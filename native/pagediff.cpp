// Native helpers for the snapshot/dirty-tracking hot path.
//
// Reference analog: the byte-granular diff loops in
// src/util/snapshot.cpp (diffWithDirtyRegions) and the XOR delta in
// src/util/delta.cpp — there C++ over mprotect'd guest memory; here C++
// over executor/host buffers, exposed to Python via ctypes (no pybind11
// in this image).
//
// Build: g++ -O3 -march=native -shared -fPIC pagediff.cpp -o libpagediff.so

#include <cstddef>
#include <cstdint>
#include <cstring>

extern "C" {

// Compare old/new buffers page-by-page; flags[i] = 1 where page i differs.
// Returns the number of dirty pages.
size_t diff_pages(const uint8_t* oldBuf, const uint8_t* newBuf, size_t len,
                  size_t pageSize, uint8_t* flags) {
    size_t nPages = (len + pageSize - 1) / pageSize;
    size_t nDirty = 0;
    for (size_t i = 0; i < nPages; i++) {
        size_t off = i * pageSize;
        size_t chunk = (off + pageSize <= len) ? pageSize : (len - off);
        uint8_t dirty = std::memcmp(oldBuf + off, newBuf + off, chunk) != 0;
        flags[i] = dirty;
        nDirty += dirty;
    }
    return nDirty;
}

// Within one page, find the changed byte ranges at `granularity`-sized
// chunks (reference compares at 128B chunks, snapshot.h:18-21). Writes up
// to maxRanges (start, length) pairs; returns the count.
size_t diff_ranges(const uint8_t* oldBuf, const uint8_t* newBuf, size_t len,
                   size_t granularity, size_t* starts, size_t* lengths,
                   size_t maxRanges) {
    size_t n = 0;
    size_t i = 0;
    while (i < len && n < maxRanges) {
        size_t chunk = (i + granularity <= len) ? granularity : (len - i);
        if (std::memcmp(oldBuf + i, newBuf + i, chunk) != 0) {
            size_t start = i;
            size_t end = i + chunk;
            i += chunk;
            // extend while consecutive chunks differ
            while (i < len) {
                size_t c2 = (i + granularity <= len) ? granularity : (len - i);
                if (std::memcmp(oldBuf + i, newBuf + i, c2) == 0) break;
                end = i + c2;
                i += c2;
            }
            starts[n] = start;
            lengths[n] = end - start;
            n++;
        } else {
            i += chunk;
        }
    }
    return n;
}

// out = a XOR b (delta encoding primitive)
void xor_buffers(const uint8_t* a, const uint8_t* b, uint8_t* out,
                 size_t len) {
    for (size_t i = 0; i < len; i++) {
        out[i] = a[i] ^ b[i];
    }
}

}  // extern "C"
