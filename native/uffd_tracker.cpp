// userfaultfd write-protect dirty tracker — the reference's
// "uffd-thread-wp" mode re-built for this runtime (reference
// src/util/dirty.cpp uffd impls, include/faabric/util/dirty.h:124-192,
// include/faabric/util/userfaultfd.h): the tracked range is registered
// with UFFDIO_REGISTER_MODE_WP and armed with UFFDIO_WRITEPROTECT; the
// FIRST write to each page parks the writer on a kernel queue and wakes
// a dedicated event thread, which records the page in a caller-owned
// flags array and clears write protection for that page (which also
// wakes the writer). Cost model is the same O(dirty) as the SIGSEGV
// tracker, with two differences the reference chose it for:
//   - faults are delivered as ordinary file events to ONE thread — no
//     process-wide signal handler, no async-signal-safety constraints,
//     no interaction with other SIGSEGV users (libtpu, faulthandler);
//   - kernel-side writes into the range (read(2), recv into the
//     buffer) fault-and-resolve normally instead of failing EFAULT.
// Requires CONFIG_USERFAULTFD + uffd-wp (kernel >= 5.7) on anonymous
// memory; uffd_install() reports absence and the Python ladder falls
// back (uffd -> segv -> native).
//
// Region table: fixed slots claimed under g_mu by uffd_start/uffd_stop;
// the event thread reads it under the same mutex (unlike a signal
// handler, it MAY take locks — that is the point of this mode).

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <linux/userfaultfd.h>
#include <mutex>
#include <poll.h>
#include <sys/ioctl.h>
#include <sys/syscall.h>
#include <thread>
#include <unistd.h>

// Newer kernel feature than this image's headers: write-protect marker
// PTEs for not-yet-populated anonymous pages (kernel >= 6.4). Without
// it the WRITEPROTECT ioctl only marks EXISTING PTEs, and writes to
// untouched pages of a fresh allocation never fault.
#ifndef UFFD_FEATURE_WP_UNPOPULATED
#define UFFD_FEATURE_WP_UNPOPULATED (1 << 13)
#endif

namespace {

constexpr int MAX_REGIONS = 128;
constexpr uintptr_t PAGE = 4096;
bool g_wp_unpopulated = false;

struct Region {
    bool active = false;
    uintptr_t start = 0;  // page-aligned
    uint64_t n_pages = 0;
    uint8_t* flags = nullptr;  // one byte per page, caller-owned
};

int g_fd = -1;
Region g_regions[MAX_REGIONS];
std::mutex g_mu;
// Heap-allocated so no global std::thread destructor can std::terminate
// the process at exit while the event loop is still parked in poll()
std::thread* g_thread = nullptr;
std::atomic<bool> g_stop{false};
int g_wake_pipe[2] = {-1, -1};

void write_unprotect(uintptr_t addr, uint64_t len)
{
    struct uffdio_writeprotect wp;
    wp.range.start = addr;
    wp.range.len = len;
    wp.mode = 0;  // clear WP; waking the parked writer is the default
    ioctl(g_fd, UFFDIO_WRITEPROTECT, &wp);
}

void event_loop()
{
    struct pollfd fds[2];
    fds[0] = {g_fd, POLLIN, 0};
    fds[1] = {g_wake_pipe[0], POLLIN, 0};
    while (!g_stop.load(std::memory_order_acquire)) {
        if (poll(fds, 2, 1000) <= 0) {
            continue;
        }
        if (fds[1].revents & POLLIN) {
            char c;
            (void)!read(g_wake_pipe[0], &c, 1);
            continue;  // re-check g_stop
        }
        struct uffd_msg msg;
        ssize_t n = read(g_fd, &msg, sizeof(msg));
        if (n != static_cast<ssize_t>(sizeof(msg))) {
            continue;
        }
        if (msg.event != UFFD_EVENT_PAGEFAULT) {
            continue;
        }
        uintptr_t addr = msg.arg.pagefault.address & ~(PAGE - 1);
        {
            std::lock_guard<std::mutex> lock(g_mu);
            for (int i = 0; i < MAX_REGIONS; i++) {
                Region& r = g_regions[i];
                if (!r.active || addr < r.start ||
                    addr >= r.start + r.n_pages * PAGE) {
                    continue;
                }
                r.flags[(addr - r.start) / PAGE] = 1;
                break;
            }
        }
        // Always resolve (even for a just-retired region) or the
        // faulting thread would park forever
        write_unprotect(addr, PAGE);
    }
}

}  // namespace

extern "C" {

// Open the userfaultfd, negotiate WP support and start the event
// thread (idempotent). 0 on success, <0 when the kernel lacks
// userfaultfd or write-protect mode.
int uffd_install()
{
    std::lock_guard<std::mutex> lock(g_mu);
    if (g_fd >= 0) {
        return 0;
    }
    int fd = static_cast<int>(
      syscall(SYS_userfaultfd, O_CLOEXEC | O_NONBLOCK));
    if (fd < 0) {
        return -1;
    }
    struct uffdio_api api;
    memset(&api, 0, sizeof(api));
    api.api = UFFD_API;
    api.features =
      UFFD_FEATURE_PAGEFAULT_FLAG_WP | UFFD_FEATURE_WP_UNPOPULATED;
    if (ioctl(fd, UFFDIO_API, &api) != 0) {
        // Retry without the newer feature (kernel < 6.4): handled by
        // pre-faulting pages in uffd_start instead
        close(fd);
        fd = static_cast<int>(
          syscall(SYS_userfaultfd, O_CLOEXEC | O_NONBLOCK));
        if (fd < 0) {
            return -1;
        }
        memset(&api, 0, sizeof(api));
        api.api = UFFD_API;
        api.features = UFFD_FEATURE_PAGEFAULT_FLAG_WP;
        if (ioctl(fd, UFFDIO_API, &api) != 0) {
            close(fd);
            return -2;
        }
    }
    if (!(api.features & UFFD_FEATURE_PAGEFAULT_FLAG_WP)) {
        close(fd);
        return -2;
    }
    g_wp_unpopulated = (api.features & UFFD_FEATURE_WP_UNPOPULATED) != 0;
    if (pipe(g_wake_pipe) != 0) {
        close(fd);
        return -3;
    }
    g_fd = fd;
    g_stop.store(false);
    g_thread = new std::thread(event_loop);
    return 0;
}

// Register + write-protect [start, start + n_pages*4096); faults route
// into `flags` (uint8 per page, caller-owned, zeroed by caller).
// `start` must be page-aligned. Returns a region id >= 0, or <0.
int uffd_start(void* start, uint64_t n_pages, void* flags)
{
    uintptr_t s = reinterpret_cast<uintptr_t>(start);
    if (g_fd < 0 || s % PAGE != 0 || n_pages == 0) {
        return -1;
    }
    struct uffdio_register reg;
    memset(&reg, 0, sizeof(reg));
    reg.range.start = s;
    reg.range.len = n_pages * PAGE;
    reg.mode = UFFDIO_REGISTER_MODE_WP;
    if (ioctl(g_fd, UFFDIO_REGISTER, &reg) != 0) {
        return -2;
    }
    if (!g_wp_unpopulated) {
        // Pre-6.4 kernels only write-protect EXISTING PTEs: touch every
        // page with a read so the zero page is mapped before arming
        volatile uint8_t sink = 0;
        for (uint64_t p = 0; p < n_pages; p++) {
            sink += *reinterpret_cast<volatile uint8_t*>(s + p * PAGE);
        }
        (void)sink;
    }
    // Claim and publish the region BEFORE arming write protection
    // (mirroring segv_start): once the WRITEPROTECT ioctl lands, a
    // concurrent writer can fault immediately, and the event thread
    // must find a live region or it resolves the fault without
    // recording the dirty bit — a silently lost page.
    int id = -1;
    {
        std::lock_guard<std::mutex> lock(g_mu);
        for (int i = 0; i < MAX_REGIONS; i++) {
            Region& r = g_regions[i];
            if (r.active) {
                continue;
            }
            r.start = s;
            r.n_pages = n_pages;
            r.flags = static_cast<uint8_t*>(flags);
            r.active = true;
            id = i;
            break;
        }
    }
    if (id < 0) {
        struct uffdio_range rng = {s, n_pages * PAGE};
        ioctl(g_fd, UFFDIO_UNREGISTER, &rng);
        return -4;  // region table full
    }
    struct uffdio_writeprotect wp;
    wp.range.start = s;
    wp.range.len = n_pages * PAGE;
    wp.mode = UFFDIO_WRITEPROTECT_MODE_WP;
    if (ioctl(g_fd, UFFDIO_WRITEPROTECT, &wp) != 0) {
        {
            std::lock_guard<std::mutex> lock(g_mu);
            g_regions[id].active = false;
        }
        struct uffdio_range rng = {s, n_pages * PAGE};
        ioctl(g_fd, UFFDIO_UNREGISTER, &rng);
        return -3;
    }
    return id;
}

// Clear write protection, unregister and retire the region. 0 on
// success.
int uffd_stop(int id)
{
    if (id < 0 || id >= MAX_REGIONS) {
        return -1;
    }
    uintptr_t s;
    uint64_t len;
    {
        std::lock_guard<std::mutex> lock(g_mu);
        Region& r = g_regions[id];
        if (!r.active) {
            return -1;
        }
        s = r.start;
        len = r.n_pages * PAGE;
        r.active = false;
    }
    write_unprotect(s, len);
    struct uffdio_range rng = {s, len};
    ioctl(g_fd, UFFDIO_UNREGISTER, &rng);
    return 0;
}

// Stop the event thread and close the fd (process teardown only).
void uffd_shutdown()
{
    {
        std::lock_guard<std::mutex> lock(g_mu);
        if (g_fd < 0) {
            return;
        }
    }
    g_stop.store(true, std::memory_order_release);
    (void)!write(g_wake_pipe[1], "x", 1);
    if (g_thread != nullptr && g_thread->joinable()) {
        g_thread->join();
    }
    delete g_thread;
    g_thread = nullptr;
    std::lock_guard<std::mutex> lock(g_mu);
    close(g_fd);
    g_fd = -1;
    close(g_wake_pipe[0]);
    close(g_wake_pipe[1]);
    g_wake_pipe[0] = g_wake_pipe[1] = -1;
}

}  // extern "C"
