// SIGSEGV write-fault dirty tracker — the reference's headline precision
// mode re-built for this runtime (reference src/util/dirty.cpp segfault
// tracker, include/faabric/util/dirty.h:12-17): mprotect the tracked
// image PROT_READ; the FIRST write to each page faults into this
// handler, which records the page in a caller-owned flags byte-array and
// restores PROT_READ|PROT_WRITE for that page only. Cost model:
//   start  = one mprotect over the range (O(VMA splits), no data touched)
//   write  = one fault per DIRTY page, ~2-4 us, then full speed
//   stop   = one mprotect restore
//   query  = read the flags array
// i.e. O(dirty) — no baseline copy, no O(image) scan per bracket.
//
// The handler must be async-signal-safe: it only reads the fixed region
// table, writes one byte, and calls mprotect (not POSIX-listed but
// kernel-atomic and used for exactly this by every fault-tracking
// runtime). Faults outside every tracked region chain to the previously
// installed handler (faulthandler / libtpu install their own).
//
// Region table: fixed slots claimed by CAS so segv_start/segv_stop from
// multiple threads never lock against the handler (a handler cannot
// take locks). active transitions 0 -> 2 (claiming, invisible to the
// handler) -> 1 (live) -> 0.

#include <atomic>
#include <csignal>
#include <cstdint>
#include <cstring>
#include <sys/mman.h>
#include <unistd.h>

namespace {

constexpr int MAX_REGIONS = 128;
constexpr uintptr_t PAGE = 4096;

struct Region {
    std::atomic<int> active{0};
    uintptr_t start = 0;  // page-aligned
    uint64_t n_pages = 0;
    uint8_t* flags = nullptr;  // one byte per page, caller-owned
};

Region g_regions[MAX_REGIONS];
struct sigaction g_prev;
std::atomic<int> g_installed{0};

void handler(int sig, siginfo_t* info, void* ctx)
{
    uintptr_t addr = reinterpret_cast<uintptr_t>(info->si_addr);
    for (int i = 0; i < MAX_REGIONS; i++) {
        Region& r = g_regions[i];
        if (r.active.load(std::memory_order_acquire) != 1) {
            continue;
        }
        if (addr < r.start || addr >= r.start + r.n_pages * PAGE) {
            continue;
        }
        uint64_t page = (addr - r.start) / PAGE;
        r.flags[page] = 1;
        mprotect(reinterpret_cast<void*>(r.start + page * PAGE),
                 PAGE,
                 PROT_READ | PROT_WRITE);
        return;
    }
    // Not a tracked fault: chain to whoever was installed before us
    if ((g_prev.sa_flags & SA_SIGINFO) && g_prev.sa_sigaction != nullptr) {
        g_prev.sa_sigaction(sig, info, ctx);
        return;
    }
    if (g_prev.sa_handler == SIG_IGN) {
        return;
    }
    if (g_prev.sa_handler != SIG_DFL && g_prev.sa_handler != nullptr) {
        g_prev.sa_handler(sig);
        return;
    }
    // Default disposition: re-deliver fatally so crashes stay crashes
    signal(SIGSEGV, SIG_DFL);
    raise(SIGSEGV);
}

}  // namespace

extern "C" {

// Install the process-wide handler (idempotent). 0 on success.
int segv_install()
{
    int expected = 0;
    if (!g_installed.compare_exchange_strong(expected, 1)) {
        return 0;
    }
    struct sigaction sa;
    memset(&sa, 0, sizeof(sa));
    sa.sa_sigaction = handler;
    sa.sa_flags = SA_SIGINFO;
    sigemptyset(&sa.sa_mask);
    if (sigaction(SIGSEGV, &sa, &g_prev) != 0) {
        g_installed.store(0);
        return -1;
    }
    return 0;
}

// Write-protect [start, start + n_pages*4096) and route its faults into
// `flags` (uint8 per page, caller-owned, zeroed by caller). `start` must
// be page-aligned. Returns a region id >= 0, or <0 on error.
int segv_start(void* start, uint64_t n_pages, void* flags)
{
    uintptr_t s = reinterpret_cast<uintptr_t>(start);
    if (s % PAGE != 0 || n_pages == 0) {
        return -1;
    }
    for (int i = 0; i < MAX_REGIONS; i++) {
        Region& r = g_regions[i];
        int expected = 0;
        if (!r.active.compare_exchange_strong(expected, 2)) {
            continue;
        }
        r.start = s;
        r.n_pages = n_pages;
        r.flags = static_cast<uint8_t*>(flags);
        // Publish the region BEFORE mprotect: no fault can occur until the
        // protection takes effect, and any write racing with the mprotect
        // must already find a live region or the handler would chain the
        // fault to the default handler and crash the process.
        r.active.store(1, std::memory_order_release);
        if (mprotect(start, n_pages * PAGE, PROT_READ) != 0) {
            r.active.store(0, std::memory_order_release);
            return -2;
        }
        return i;
    }
    return -3;  // region table full
}

// Restore write access and retire the region. 0 on success.
int segv_stop(int id)
{
    if (id < 0 || id >= MAX_REGIONS) {
        return -1;
    }
    Region& r = g_regions[id];
    if (r.active.load(std::memory_order_acquire) != 1) {
        return -1;
    }
    mprotect(reinterpret_cast<void*>(r.start),
             r.n_pages * PAGE,
             PROT_READ | PROT_WRITE);
    r.active.store(0, std::memory_order_release);
    return 0;
}

}  // extern "C"
