// Lock-free single-producer/single-consumer byte ring over a shared
// mapping — the same-machine data plane under transport/shm.py.
//
// Reference analog: faabric's in-memory MPI queues (atomic_queue /
// moodycamel SPSC, include/faabric/mpi/MpiWorld.h:29-33) carry same-host
// rank traffic without touching sockets. There ranks are threads of one
// process; here co-located ranks live in separate worker PROCESSES, so
// the queue lives in a /dev/shm mapping and the indices are C++ atomics
// on shared cache lines (Python cannot express cross-process atomics —
// this is why the hot path is native).
//
// Layout (192-byte header, then capacity bytes of data):
//   [0]   u64 magic
//   [8]   u64 capacity (power of two)
//   [64]  atomic u64 head — bytes ever written (producer-owned)
//   [72]  atomic u32 data_seq — bumped per push (futex word, consumer waits)
//   [128] atomic u64 tail — bytes ever read (consumer-owned)
//   [136] atomic u32 space_seq — bumped per pop (futex word, producer waits)
// Head and tail sit on their own cache lines: the producer writes head
// and reads tail, the consumer the reverse; sharing a line would bounce
// it between cores on every frame. Each side's futex word shares ITS
// writer's line.
//
// Frames: u64 payload length, then payload bytes, modular over the data
// region. A frame is visible to the consumer only once the head store
// (release) publishes it whole; partial writes can never be read.
//
// Blocking: waiters use shared futexes on the seq words with BOUNDED
// timeouts (the seq-vs-head visibility order is not total, so a wait
// could theoretically park just after missing its wakeup — the timeout
// turns that race into at worst one bounded stall, never a hang).
// Pushers futex-wake after every publish, poppers after every free —
// one ~µs syscall per frame is noise next to the ≥256 KiB memcpys the
// bulk plane moves, and it is what lets the other PROCESS block in the
// kernel instead of burning a core polling (the cross-process analog of
// the reference's in-process condition-variable queues, util/queue.h).

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <ctime>
#include <new>

#include <linux/futex.h>
#include <sys/syscall.h>
#include <unistd.h>

namespace {

constexpr uint64_t MAGIC = 0xFAAB51A6C0FFEE02ULL;
constexpr uint64_t HDR_BYTES = 192;

struct RingHdr {
    uint64_t magic;
    uint64_t capacity;
    char pad0[48];
    std::atomic<uint64_t> head;
    std::atomic<uint32_t> data_seq;
    char pad1[52];
    std::atomic<uint64_t> tail;
    std::atomic<uint32_t> space_seq;
    char pad2[52];
};

int futex_wait(std::atomic<uint32_t>* addr, uint32_t expected,
               uint32_t timeout_us) {
    struct timespec ts;
    ts.tv_sec = timeout_us / 1000000;
    ts.tv_nsec = (timeout_us % 1000000) * 1000L;
    // No FUTEX_PRIVATE_FLAG: the mapping is shared across processes
    return syscall(SYS_futex, reinterpret_cast<uint32_t*>(addr), FUTEX_WAIT,
                   expected, &ts, nullptr, 0);
}

void futex_wake(std::atomic<uint32_t>* addr) {
    syscall(SYS_futex, reinterpret_cast<uint32_t*>(addr), FUTEX_WAKE, 1,
            nullptr, nullptr, 0);
}

static_assert(sizeof(RingHdr) == HDR_BYTES, "header layout is the ABI");
static_assert(sizeof(std::atomic<uint64_t>) == 8,
              "atomic u64 must be plain u64 in shared memory");

inline RingHdr* hdr(void* base) { return static_cast<RingHdr*>(base); }

inline char* data(void* base) {
    return static_cast<char*>(base) + HDR_BYTES;
}

// Copy into the ring at logical position pos (modular), handling wrap.
inline void put(void* base, uint64_t cap, uint64_t pos, const void* src,
                uint64_t len) {
    uint64_t off = pos & (cap - 1);
    uint64_t first = cap - off < len ? cap - off : len;
    std::memcpy(data(base) + off, src, first);
    if (len > first) {
        std::memcpy(data(base), static_cast<const char*>(src) + first,
                    len - first);
    }
}

inline void get(void* base, uint64_t cap, uint64_t pos, void* dst,
                uint64_t len) {
    uint64_t off = pos & (cap - 1);
    uint64_t first = cap - off < len ? cap - off : len;
    std::memcpy(dst, data(base) + off, first);
    if (len > first) {
        std::memcpy(static_cast<char*>(dst) + first, data(base), len - first);
    }
}

}  // namespace

extern "C" {

// capacity must be a power of two; the mapping must be HDR_BYTES +
// capacity long and zeroed. Returns 0 on success.
int ring_init(void* base, uint64_t capacity) {
    if (capacity == 0 || (capacity & (capacity - 1)) != 0) return -1;
    RingHdr* h = new (base) RingHdr;
    h->capacity = capacity;
    h->head.store(0, std::memory_order_relaxed);
    h->tail.store(0, std::memory_order_relaxed);
    h->data_seq.store(0, std::memory_order_relaxed);
    h->space_seq.store(0, std::memory_order_relaxed);
    // Magic last: an attacher seeing it may trust the rest
    std::atomic_thread_fence(std::memory_order_release);
    h->magic = MAGIC;
    return 0;
}

// Validates an existing mapping before attach. Returns capacity, or -1.
int64_t ring_check(void* base) {
    RingHdr* h = hdr(base);
    if (h->magic != MAGIC) return -1;
    uint64_t cap = h->capacity;
    if (cap == 0 || (cap & (cap - 1)) != 0) return -1;
    return static_cast<int64_t>(cap);
}

int64_t ring_free_space(void* base) {
    RingHdr* h = hdr(base);
    uint64_t head = h->head.load(std::memory_order_relaxed);
    uint64_t tail = h->tail.load(std::memory_order_acquire);
    return static_cast<int64_t>(h->capacity - (head - tail));
}

// Push one frame gathered from nsegs segments. Returns 0 on success,
// -1 if there is not enough free space (caller retries/falls back),
// -2 if the frame can never fit this ring.
int ring_try_pushv(void* base, const void* const* segs,
                   const uint64_t* lens, uint64_t nsegs) {
    RingHdr* h = hdr(base);
    uint64_t cap = h->capacity;
    uint64_t total = 0;
    for (uint64_t i = 0; i < nsegs; i++) total += lens[i];
    uint64_t need = total + 8;
    if (need > cap) return -2;
    uint64_t head = h->head.load(std::memory_order_relaxed);
    uint64_t tail = h->tail.load(std::memory_order_acquire);
    if (need > cap - (head - tail)) return -1;
    put(base, cap, head, &total, 8);
    uint64_t pos = head + 8;
    for (uint64_t i = 0; i < nsegs; i++) {
        put(base, cap, pos, segs[i], lens[i]);
        pos += lens[i];
    }
    h->head.store(head + need, std::memory_order_release);
    h->data_seq.fetch_add(1, std::memory_order_release);
    futex_wake(&h->data_seq);
    return 0;
}

// Length of the next frame's payload without consuming it; -1 if empty.
int64_t ring_peek(void* base) {
    RingHdr* h = hdr(base);
    uint64_t tail = h->tail.load(std::memory_order_relaxed);
    uint64_t head = h->head.load(std::memory_order_acquire);
    if (head == tail) return -1;
    uint64_t len;
    get(base, h->capacity, tail, &len, 8);
    return static_cast<int64_t>(len);
}

// Pop the next frame into out (maxlen bytes). Returns the payload
// length, -1 if empty, -2 if out is too small (frame not consumed).
int64_t ring_pop(void* base, void* out, uint64_t maxlen) {
    RingHdr* h = hdr(base);
    uint64_t tail = h->tail.load(std::memory_order_relaxed);
    uint64_t head = h->head.load(std::memory_order_acquire);
    if (head == tail) return -1;
    uint64_t len;
    get(base, h->capacity, tail, &len, 8);
    if (len > maxlen) return -2;
    get(base, h->capacity, tail + 8, out, len);
    h->tail.store(tail + 8 + len, std::memory_order_release);
    h->space_seq.fetch_add(1, std::memory_order_release);
    futex_wake(&h->space_seq);
    return static_cast<int64_t>(len);
}

// Pop up to max_frames consecutive frames into out (out_len bytes),
// recording each frame's payload length in lens. Stops before a frame
// that would overflow out (a batch consumer falls back to ring_pop for
// oversized frames). The tail advances ONCE for the whole batch — one
// space-futex wake per batch instead of per frame, which is what makes
// draining a burst of small frames cheap. Returns the frame count
// (0 when empty or the next frame alone exceeds out_len).
int64_t ring_pop_batch(void* base, void* out, uint64_t out_len,
                       uint64_t* lens, uint64_t max_frames) {
    RingHdr* h = hdr(base);
    uint64_t tail = h->tail.load(std::memory_order_relaxed);
    uint64_t head = h->head.load(std::memory_order_acquire);
    uint64_t cap = h->capacity;
    uint64_t produced = 0;
    uint64_t written = 0;
    while (produced < max_frames && head != tail) {
        uint64_t len;
        get(base, cap, tail, &len, 8);
        if (written + len > out_len) break;
        get(base, cap, tail + 8, static_cast<char*>(out) + written, len);
        written += len;
        tail += 8 + len;
        lens[produced++] = len;
    }
    if (produced) {
        h->tail.store(tail, std::memory_order_release);
        h->space_seq.fetch_add(1, std::memory_order_release);
        futex_wake(&h->space_seq);
    }
    return static_cast<int64_t>(produced);
}

// Block (in the kernel) until a frame is likely available or timeout_us
// elapsed. Returns 0 when data is visible, 1 on timeout/spurious wake —
// callers loop around try_pop either way.
int ring_wait_data(void* base, uint32_t timeout_us) {
    RingHdr* h = hdr(base);
    uint32_t seq = h->data_seq.load(std::memory_order_acquire);
    uint64_t tail = h->tail.load(std::memory_order_relaxed);
    if (h->head.load(std::memory_order_acquire) != tail) return 0;
    futex_wait(&h->data_seq, seq, timeout_us);
    return h->head.load(std::memory_order_acquire) != tail ? 0 : 1;
}

// Block until >= need bytes of frame space are likely free, or timeout.
int ring_wait_space(void* base, uint64_t need, uint32_t timeout_us) {
    RingHdr* h = hdr(base);
    uint32_t seq = h->space_seq.load(std::memory_order_acquire);
    uint64_t head = h->head.load(std::memory_order_relaxed);
    uint64_t cap = h->capacity;
    if (cap - (head - h->tail.load(std::memory_order_acquire)) >= need)
        return 0;
    futex_wait(&h->space_seq, seq, timeout_us);
    return (cap - (head - h->tail.load(std::memory_order_acquire)) >= need)
               ? 0 : 1;
}

}  // extern "C"
