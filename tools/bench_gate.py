"""Round-over-round bench regression gate.

    python tools/bench_gate.py [--repo DIR] [--threshold 0.2]

Compares the newest ``BENCH_r*.json`` against the previous round and
exits non-zero when any recorded throughput/latency figure regressed by
more than the threshold (default 20%). Directionality is inferred from
the metric name: ``*_gibs`` / ``tokens_per_s`` / ``mfu`` are
higher-is-better; ``*_ms`` / ``*_s`` / ``*_ns`` (and the headline
latency ``value``) are lower-is-better. A key present in only one round
is reported as informational, never a failure — rounds grow new
sections and that must not wedge the gate.

Run as a ``slow``-marked test (tests/unit/test_bench_gate.py) so the
perf trajectory is machine-checked without taxing tier-1.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

# NOTE: _per_s (throughput rates, e.g. invocations_per_s) must be
# classified BEFORE the trailing-_s latency rule catches them.
# _bytes (ISSUE 15): accounting byte counts — device_host_copy_bytes —
# where fewer bytes moved is strictly better; direction pinned by
# tests/unit/test_bench_gate.py.
# _pct (ISSUE 18): overhead percentages — profile_overhead_pct — where
# lower is strictly better; direction pinned by the unit test.
HIGHER_BETTER = re.compile(r"(_gibs|_per_s|mfu|_speedup)")
LOWER_BETTER = re.compile(r"(_ms|_ns|_s|_ratio|_err|_bytes|_pct)$")

# Name-exact lower-is-better keys neither regex catches (ISSUE 18):
# the idle-cluster GIL pressure gauge — a [0, 1] score, not a unit —
# where any upward move means the idle path got noisier.
LOWER_BETTER_KEYS = ("gil_pressure_idle",)

# Headline figures (ISSUE 5 data plane; ISSUE 8 invocation plane): once
# a round has recorded one of these, a later round missing it is a
# FAILURE, not a note — the silent way a >20% regression escapes the
# gate is the bench section crashing and the key simply vanishing from
# the summary.
# delta_stream_gibs (ISSUE 11): the iterative repeated-payload stream
# rate over the adaptive wire-codec plane — required once recorded,
# with unit tests pinning its higher-is-better direction.
REQUIRED_KEYS = ("host_allreduce_procs_gibs", "host_sendrecv_gibs",
                 "invocations_per_s", "delta_stream_gibs")

# Invocation-plane reference figures (ISSUE 8) and the first-round
# ISSUE 10 device-plane key: tracked and printed every round but NOT
# hard-gated. The ingress headline (invocations_per_s, best-of-2 runs)
# IS gated via REQUIRED_KEYS; its serial baseline and p50 exist to make
# the same-round speedup ratio checkable, not to gate on. The
# device-plane allreduce rate gates once a round of spread exists
# (promote it like the keys below were).
#
# PROMOTED (ISSUE 9 satellite): migration_pause_ms,
# thaw_to_first_result_s and partition_heal_s moved out of this list —
# rounds r05..current showed their spread comfortably inside the 20%
# threshold, so they now gate like any other latency key.
# PROMOTED (ISSUE 10 satellite): host_allreduce_hier_gibs and
# cross_host_bytes_ratio graduated after their first recorded round —
# the deferred PR 9 promotion — and now gate like any other key.
# allreduce_quant_max_abs_err: tracked so a codec regression at least
# prints a tagged note — but data-dependent (payload-magnitude-scaled),
# so never hard-gated.
# ISSUE 11 companions to delta_stream_gibs: reference rates and the
# wall-clock/wire ratios. Wall-clock speedups saturate near 1 on this
# container (loopback outruns memcpy — no wire to win back); the wire
# ratios are the codec-controlled quantity but are workload-shaped, so
# all ride as reported-only context rather than hard gates.
# ISSUE 12 perf-introspection keys (first recorded round): the profile
# feed's per-sample cost, its metrics-off no-op floor, and the doctor's
# synthetic-cluster end-to-end runtime — reported until a round of
# spread exists, then promote like the ISSUE 9/10 keys were.
# PROMOTED (ISSUE 14 satellite): host_alltoall_gibs,
# alltoall_cross_host_bytes_ratio and alltoall_cross_host_msgs_ratio
# graduated after their first recorded round (the ISSUE 13 deferral,
# same one-round ratchet as the ISSUE 9/10 promotions) — they now gate
# like any other key.
# ISSUE 14 lifecycle keys (first recorded round, promote next):
# lifecycle_stamp_ns is the enabled per-stamp ledger cost (~100 ns
# target) and invocation_p99_ms the planner-folded admit→record e2e
# p99 under the concurrent QPS workload (log-bucket quantile —
# coarse by construction, so it rides reported-only first).
# ISSUE 15 device-resident keys (first recorded round, promote next):
# device_resident_allreduce_gibs is the zero-host-copy allreduce rate
# on payloads already living in device memory (on this CPU container
# the device_put it skips is a cheap memcpy, so no speedup is expected
# here — the figure exists for the TPU rounds where the skipped
# transfers are PCIe/DMA); device_host_copy_bytes is the asserted-zero
# copy accounting for the timed resident rounds (lower-is-better —
# _bytes direction pinned in the unit test).
# PROMOTED (ISSUE 18 satellite): state_hot_read_ns, state_pull_gibs,
# state_push_partial_gibs and statestats_record_noop_ns graduated
# after their first recorded round (the ISSUE 16 deferral, same
# one-round ratchet as the ISSUE 9/10/14 promotions) — they now gate
# like any other key. statestats_record_ns stays reported-only: the
# enabled-path feed cost is scheduler-jitter-shaped on this container.
# ISSUE 18 profiler keys (first recorded round, promote next):
# profile_sample_ns is one stack-sampler pass over the live threads
# (trie fold + /proc scan); profile_overhead_pct the sampler's
# measured drag on the invocation firehose (acceptance: ≤ 2); and
# gil_pressure_idle the drift gauge on an idle cluster (contract: ~0 —
# direction pinned via LOWER_BETTER_KEYS).
# ISSUE 19 replicated-state keys (first recorded round, promote next):
# state_replicated_push_gibs is the dirty-chunk push rate WITH the
# synchronous backup forward before each ack (compare against
# state_push_partial_gibs for the replication tax), and
# master_failover_s the measured loopback failover — planner
# remove_host to first acked write through the promoted backup.
REPORTED_ONLY = ("invocations_per_s_serial", "invocation_p50_ms",
                 "lifecycle_stamp_ns", "invocation_p99_ms",
                 "host_allreduce_device_gibs",
                 "device_resident_allreduce_gibs",
                 "device_host_copy_bytes",
                 "allreduce_quant_max_abs_err",
                 "host_allreduce_procs_raw_gibs",
                 "host_allreduce_procs_coded_gibs",
                 "allreduce_governed_speedup",
                 "allreduce_coded_wire_speedup",
                 "delta_stream_raw_gibs", "delta_stream_speedup",
                 "delta_stream_wire_speedup",
                 "perf_feed_ns", "perf_feed_noop_ns",
                 "doctor_selftest_ms",
                 "statestats_record_ns",
                 "profile_sample_ns", "profile_overhead_pct",
                 "gil_pressure_idle",
                 "state_replicated_push_gibs", "master_failover_s")

# Round-5 container drift (see ROADMAP "Recent"): ptp dispatch p50 (the
# headline "value") and delta_apply_reuse_ms read worse in ANY tree on
# the current container, including unmodified older HEADs verified via
# worktree — a gate failure there reports the container, not the code.
# Re-measured at ISSUE 11 HEAD (2026-08-03): still drifted — p50
# 0.089 ms vs the r05-recorded 0.039 (~2.3×) and apply_reuse 48 ms vs
# 15.5 (~3×), while the same run's one-pass memcpy reads 24 GiB/s —
# i.e. the regression tracks the container's fresh-page/fault behavior,
# not code. Kept out of the HARD gate (still printed as notes);
# re-baseline when a round shows them recovered.
CONTAINER_DRIFT_EXEMPT = ("value", "delta_apply_reuse_ms")


def find_rounds(repo: str) -> list[str]:
    """BENCH_r*.json paths, oldest → newest (lexicographic on the
    zero-padded round number)."""
    return sorted(glob.glob(os.path.join(repo, "BENCH_r[0-9]*.json")))


def load_metrics(path: str) -> dict[str, float]:
    """Flatten one round's comparable numbers: the headline ``value``
    (latency) plus every numeric ``summary`` entry with an inferable
    direction."""
    with open(path) as f:
        doc = json.load(f)
    parsed = doc.get("parsed") or {}
    out: dict[str, float] = {}
    if isinstance(parsed.get("value"), (int, float)):
        out["value"] = float(parsed["value"])
    for key, val in (parsed.get("summary") or {}).items():
        if isinstance(val, bool) or not isinstance(val, (int, float)):
            continue
        if (HIGHER_BETTER.search(key) or LOWER_BETTER.search(key)
                or key in LOWER_BETTER_KEYS):
            out[key] = float(val)
    return out


def direction(key: str) -> int:
    """+1 = higher is better, -1 = lower is better."""
    if key == "value" or key in LOWER_BETTER_KEYS or (
            LOWER_BETTER.search(key)
            and not HIGHER_BETTER.search(key)):
        return -1
    return 1


def compare(prev: dict[str, float], cur: dict[str, float],
            threshold: float = 0.2) -> tuple[list[str], list[str]]:
    """(regressions, notes). A regression is a >threshold move in the
    bad direction on a key both rounds recorded (zero/absent previous
    values are notes — no ratio exists). Keys in REPORTED_ONLY or
    CONTAINER_DRIFT_EXEMPT never fail the gate: their moves are printed
    as tagged notes instead."""
    regressions, notes = [], []
    soft = set(REPORTED_ONLY) | set(CONTAINER_DRIFT_EXEMPT)
    for key in sorted(set(prev) | set(cur)):
        p, c = prev.get(key), cur.get(key)
        if p is None or c is None:
            if key in REQUIRED_KEYS and c is None:
                regressions.append(
                    f"{key}: previously recorded {p}, MISSING in the "
                    "current round (data-plane bench section failed?)")
            else:
                notes.append(f"{key}: only in "
                             f"{'current' if p is None else 'previous'} "
                             f"round ({p if c is None else c})")
            continue
        if p <= 0:
            notes.append(f"{key}: previous value {p} not comparable")
            continue
        change = (c - p) / p
        if direction(key) > 0:
            bad = change < -threshold     # negative = worse
            label = "higher-is-better"
        else:
            bad = change > threshold      # positive = worse
            label = "lower-is-better"
        if bad and key in soft:
            tag = ("reported-only" if key in REPORTED_ONLY
                   else "container-drift-exempt")
            notes.append(f"{key}: {p} -> {c} ({change:+.1%}, {label}; "
                         f"{tag} — not gated)")
            continue
        if bad:
            regressions.append(f"{key}: {p} -> {c} ({change:+.1%}, "
                               f"{label})")
        else:
            notes.append(f"{key}: {p} -> {c} ({change:+.1%})")
    return regressions, notes


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="bench_gate")
    parser.add_argument("--repo", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    parser.add_argument("--threshold", type=float, default=0.2)
    parser.add_argument("--quiet", action="store_true")
    args = parser.parse_args(argv)

    rounds = find_rounds(args.repo)
    if len(rounds) < 2:
        print(f"bench_gate: need >=2 rounds, found {len(rounds)} "
              f"in {args.repo}; nothing to gate")
        return 0
    prev_path, cur_path = rounds[-2], rounds[-1]
    prev, cur = load_metrics(prev_path), load_metrics(cur_path)
    # Required keys are checked against the whole history, not just the
    # previous round — otherwise one broken round would launder both a
    # missing key (vanishes from both sides of the next comparison) and
    # a regression (the recovered round has no previous value to beat).
    # Backfill the newest historical value whenever the previous round
    # lacks the key; compare() then flags a MISSING current value or a
    # >threshold drop as usual.
    for key in REQUIRED_KEYS:
        if key in prev:
            continue
        for past in reversed(rounds[:-1]):
            val = load_metrics(past).get(key)
            if val is not None:
                prev[key] = val
                break
    regressions, notes = compare(prev, cur, args.threshold)

    print(f"bench_gate: {os.path.basename(prev_path)} -> "
          f"{os.path.basename(cur_path)} "
          f"(threshold {args.threshold:.0%})")
    if not args.quiet:
        for line in notes:
            print(f"  note: {line}")
    for line in regressions:
        print(f"  REGRESSION: {line}")
    if regressions:
        print(f"bench_gate: FAILED ({len(regressions)} regression(s))")
        return 1
    print("bench_gate: ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
