"""Tier-1 failure gate: "no worse than seed", machine-checked.

    python tools/failure_gate.py --log /tmp/_t1.log \
        [--baseline tools/tier1_baseline.txt]

Parses a pytest run log, collects every FAILED/ERROR test id from the
short summary, and diffs against the committed baseline of known
failures. Exit codes:

- 0: every failing id is in the baseline (and ids the baseline lists
  that now pass are printed as shrink-the-baseline notes);
- 1: NEW failures — test ids failing that the baseline does not carry.

The baseline is the seed's standing-failure list; as failures are fixed
their lines are deleted, ratcheting the floor down. Parametrized ids
match exactly; a bare module path (collection error) matches any id in
that module.
"""

from __future__ import annotations

import argparse
import os
import re
import sys

# Short-summary lines: "FAILED tests/x.py::test_y[param] - reason" and
# "ERROR tests/x.py::test_y - reason" (or a bare module on collection
# errors). The reason suffix is informational and stripped.
_SUMMARY_RE = re.compile(r"^(FAILED|ERROR)\s+(\S+)")


def parse_failures(log_text: str) -> set[str]:
    """Every FAILED/ERROR test id in a pytest log's short summary."""
    out: set[str] = set()
    for line in log_text.splitlines():
        m = _SUMMARY_RE.match(line.strip())
        if not m:
            continue
        test_id = m.group(2)
        # Guard against prose accidentally starting with FAILED: a test
        # id always names a file path
        if "/" in test_id or test_id.endswith(".py") or "::" in test_id:
            out.add(test_id)
    return out


def load_baseline(path: str) -> set[str]:
    if not os.path.exists(path):
        return set()
    out = set()
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line and not line.startswith("#"):
                out.add(line)
    return out


def _covered(test_id: str, baseline: set[str]) -> bool:
    if test_id in baseline:
        return True
    # A baselined module path (collection error era) covers its tests,
    # and vice versa: a baselined test id covers the module-level ERROR
    # pytest reports when that file later fails collection outright.
    module = test_id.split("::", 1)[0]
    if module in baseline:
        return True
    return any(b.split("::", 1)[0] == test_id for b in baseline)


def main(argv: list[str] | None = None) -> int:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    parser = argparse.ArgumentParser(prog="failure_gate")
    parser.add_argument("--log", default="/tmp/_t1.log",
                        help="pytest run log (tier-1 tee output)")
    parser.add_argument("--baseline",
                        default=os.path.join(repo, "tools",
                                             "tier1_baseline.txt"))
    args = parser.parse_args(argv)

    try:
        with open(args.log, errors="replace") as f:
            failures = parse_failures(f.read())
    except OSError as e:
        print(f"failure_gate: cannot read log {args.log}: {e}")
        return 1
    baseline = load_baseline(args.baseline)

    new = sorted(t for t in failures if not _covered(t, baseline))
    fixed = sorted(b for b in baseline if not _covered(b, failures))

    print(f"failure_gate: {len(failures)} failing, baseline carries "
          f"{len(baseline)} ({os.path.basename(args.baseline)})")
    for t in fixed:
        print(f"  fixed: {t} — no longer failing; delete it from the "
              "baseline to ratchet the floor down")
    for t in sorted(failures - set(new)):
        print(f"  known: {t}")
    for t in new:
        print(f"  NEW FAILURE: {t}")
    if new:
        print(f"failure_gate: FAILED ({len(new)} new failure(s) vs "
              "baseline)")
        return 1
    print("failure_gate: ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
