#!/usr/bin/env python
"""Per-key perf trajectory across the committed bench rounds.

    python tools/bench_trend.py [--repo DIR] [--json] [--key SUBSTR]

Renders the ``BENCH_r*.json`` history (oldest → newest) as one table
per metric key: first/best/latest value, the latest-vs-best delta in
the key's OWN direction (``_gibs``/``_per_s`` up is good, ``_ms``/
``_ns``/``_s`` down is good — bench_gate.py's classifier), and a
status column that highlights gated-key regressions — so perf drift
across rounds is visible at a glance instead of by hand-diffing JSON.

Status legend: ``OK`` latest within 5% of best, ``drift`` 5–20% off
best, ``REGRESSED`` >20% off best (upper-cased when the key is in
bench_gate's REQUIRED set — the ones that fail the gate), ``exempt``
for the recorded container-drift keys, ``new`` for single-round keys.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from bench_gate import (  # noqa: E402
    CONTAINER_DRIFT_EXEMPT,
    REQUIRED_KEYS,
    direction,
    find_rounds,
    load_metrics,
)

DRIFT_AT = 0.05
REGRESS_AT = 0.20


def collect(repo: str) -> dict[str, list[tuple[str, float]]]:
    """key → [(round name, value)] oldest → newest, over every
    committed round."""
    series: dict[str, list[tuple[str, float]]] = {}
    for path in find_rounds(repo):
        name = os.path.basename(path).replace("BENCH_", "").replace(
            ".json", "")
        for key, value in load_metrics(path).items():
            series.setdefault(key, []).append((name, value))
    return series


def trend_rows(series: dict[str, list[tuple[str, float]]]) -> list[dict]:
    rows = []
    for key, points in sorted(series.items()):
        values = [v for _r, v in points]
        sign = direction(key)
        best = max(values) if sign > 0 else min(values)
        best_round = points[values.index(best)][0]
        latest_round, latest = points[-1]
        first_round, first = points[0]
        if best != 0:
            # Positive = latest is WORSE than best, in the key's own
            # direction (a regression regardless of which way is up)
            off_best = (best - latest) / abs(best) * sign
        else:
            off_best = 0.0
        if key in CONTAINER_DRIFT_EXEMPT:
            status = "exempt"
        elif len(points) < 2:
            status = "new"
        elif off_best <= DRIFT_AT:
            status = "OK"
        elif off_best <= REGRESS_AT:
            status = "drift"
        else:
            status = ("REGRESSED" if key in REQUIRED_KEYS
                      else "regressed")
        rows.append({
            "key": key,
            "direction": "up" if sign > 0 else "down",
            "rounds": len(points),
            "first": first, "first_round": first_round,
            "best": best, "best_round": best_round,
            "latest": latest, "latest_round": latest_round,
            "off_best_pct": round(off_best * 100.0, 1),
            "gated": key in REQUIRED_KEYS,
            "status": status,
        })
    # Worst offenders first within gated, then the rest by drift
    rows.sort(key=lambda r: (not r["gated"], -r["off_best_pct"]))
    return rows


def _fmt(v: float) -> str:
    if abs(v) >= 1000:
        return f"{v:,.0f}"
    if abs(v) >= 10:
        return f"{v:.1f}"
    return f"{v:.3f}"


def render(rows: list[dict]) -> str:
    if not rows:
        return "bench_trend: no BENCH_r*.json rounds found"
    lines = [f"{'key':<34} {'dir':<4} {'n':>2} {'best':>10} {'@':>4} "
             f"{'latest':>10} {'Δbest':>7}  status",
             "-" * 86]
    for r in rows:
        mark = "*" if r["gated"] else " "
        lines.append(
            f"{mark}{r['key']:<33} {r['direction']:<4} {r['rounds']:>2} "
            f"{_fmt(r['best']):>10} {r['best_round'][-3:]:>4} "
            f"{_fmt(r['latest']):>10} {r['off_best_pct']:>6.1f}%  "
            f"{r['status']}")
    lines.append("-" * 86)
    lines.append("* = hard-gated key (bench_gate REQUIRED); Δbest is "
                 "how far the latest round sits off the best recorded "
                 "round, in the key's own direction")
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description="Per-key perf trajectory over BENCH_r*.json history")
    parser.add_argument("--repo", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    parser.add_argument("--json", action="store_true")
    parser.add_argument("--key", help="only keys containing SUBSTR")
    args = parser.parse_args(argv)

    series = collect(args.repo)
    if args.key:
        series = {k: v for k, v in series.items() if args.key in k}
    rows = trend_rows(series)
    if args.json:
        print(json.dumps({"rows": rows}, indent=1))
    else:
        print(render(rows))
    return 0


if __name__ == "__main__":
    sys.exit(main())
