#!/usr/bin/env bash
# One-shot repo conformance gate (ISSUE 7 satellite): ruff (when
# installed) + the concurrency conformance suite + the tier-1 failure
# gate, each against its committed baseline.
#
#   tools/check.sh [--with-tests] [--with-chaos]
#
# Without --with-tests the failure gate re-reads the last tier-1 log at
# /tmp/_t1.log (written by the canonical tier-1 command in ROADMAP.md);
# with it, the tier-1 suite runs first. --with-chaos additionally runs
# the chaos-marked state-failover proof (real worker processes
# SIGKILLed/SIGSTOPped mid-write, ISSUE 19) — slow, opt-in. Exit
# nonzero on the first failing gate.
set -u -o pipefail

repo="$(cd "$(dirname "$0")/.." && pwd)"
cd "$repo"
rc=0

echo "== ruff =="
if command -v ruff >/dev/null 2>&1; then
    if ! ruff check .; then rc=1; fi
elif python -m ruff --version >/dev/null 2>&1; then
    if ! python -m ruff check .; then rc=1; fi
else
    echo "ruff not installed; skipping (pyproject.toml pins the config" \
         "for environments that have it — do not pip install here)"
fi

echo "== concheck (guarded-by lint + protocol drift) =="
if ! python tools/concheck.py; then rc=1; fi

echo "== doctor selftest (perf introspection smoke) =="
if ! JAX_PLATFORMS=cpu python -m faabric_tpu.runner.doctor --selftest; then
    rc=1
fi

echo "== schedule verifier selftest (collective schedule compiler) =="
if ! JAX_PLATFORMS=cpu python -m faabric_tpu.mpi.schedule_compile \
        --selftest; then
    rc=1
fi

echo "== profile selftest (stack sampler attribution) =="
if ! JAX_PLATFORMS=cpu python -m faabric_tpu.runner.profile --selftest; then
    rc=1
fi

echo "== pallas ring selftest (device ring-permute p2p) =="
# On this container it validates the XLA fallback permute and reports
# the Pallas kernel as untested (no TPU granted) — fast, clean; with a
# granted TPU the same hook exercises make_async_remote_copy for real.
if ! JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=4 \
        python -m faabric_tpu.device_plane.pallas_ring --selftest; then
    rc=1
fi

for arg in "$@"; do
    if [ "$arg" = "--with-chaos" ]; then
        echo "== chaos: replicated state failover (ISSUE 19) =="
        # Zero lost acked writes across a SIGKILLed master + the
        # revived-stale-master fencing proof, against real processes
        if ! timeout -k 10 600 env JAX_PLATFORMS=cpu \
                python -m pytest tests/dist/test_state_failover.py \
                -q -m chaos -p no:cacheprovider -p no:xdist \
                -p no:randomly; then
            rc=1
        fi
    fi
done

if [ "${1:-}" = "--with-tests" ]; then
    echo "== tier-1 suite =="
    rm -f /tmp/_t1.log
    timeout -k 10 870 env JAX_PLATFORMS=cpu \
        python -m pytest tests/ -q -m 'not slow' \
        --continue-on-collection-errors -p no:cacheprovider \
        -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
    t1=${PIPESTATUS[0]}
    if [ "$t1" -ne 0 ]; then
        echo "tier-1 exited $t1 (failure gate decides pass/fail below)"
    fi
fi

echo "== failure gate (tier-1 vs baseline) =="
if [ -f /tmp/_t1.log ]; then
    if ! python tools/failure_gate.py --log /tmp/_t1.log; then rc=1; fi
else
    echo "no tier-1 log at /tmp/_t1.log; run tools/check.sh --with-tests"
    rc=1
fi

exit $rc
