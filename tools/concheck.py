"""Concurrency conformance gate: guarded-by lint + protocol drift,
ratcheted against a committed baseline (the failure_gate pattern).

    python tools/concheck.py [--baseline tools/concheck_baseline.txt]
                             [--write-baseline] [--verbose]

Runs the static passes from ``faabric_tpu/analysis`` over the package
and diffs finding *fingerprints* (path::qualname::rule::subject — no
line numbers, so unrelated edits don't churn the baseline) against the
committed baseline. Exit codes:

- 0: every finding is baselined (entries that no longer fire are
  printed as shrink-the-baseline notes);
- 1: NEW findings — concurrency-contract violations the baseline does
  not carry. Fix them, pragma them with a justification
  (``# concheck: ok(rule)``), or — for known pre-existing debt being
  tracked — add the fingerprint to the baseline.

``--write-baseline`` rewrites the baseline to exactly the current
finding set (the ratchet: run it after fixing entries so the floor
moves down and stays down).
"""

from __future__ import annotations

import argparse
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from faabric_tpu.analysis.guards import Finding, analyze_paths  # noqa: E402
from faabric_tpu.analysis.protodrift import analyze_package  # noqa: E402


def collect_findings(root: str = _REPO) -> list[Finding]:
    findings = analyze_paths(root, subdirs=("faabric_tpu",))
    findings.extend(analyze_package(root, subdirs=("faabric_tpu",)))
    return findings


def load_baseline(path: str) -> set[str]:
    if not os.path.exists(path):
        return set()
    out = set()
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line and not line.startswith("#"):
                out.add(line)
    return out


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="concheck")
    parser.add_argument("--baseline",
                        default=os.path.join(_REPO, "tools",
                                             "concheck_baseline.txt"))
    parser.add_argument("--write-baseline", action="store_true",
                        help="rewrite the baseline to the current "
                             "finding set (ratchet)")
    parser.add_argument("--verbose", action="store_true",
                        help="print every finding, baselined or not")
    args = parser.parse_args(argv)

    findings = collect_findings()
    by_fp: dict[str, Finding] = {}
    for f in findings:
        by_fp.setdefault(f.fingerprint, f)
    current = set(by_fp)
    baseline = load_baseline(args.baseline)

    if args.write_baseline:
        with open(args.baseline, "w") as f:
            f.write("# concheck baseline: known findings being tracked "
                    "as debt.\n# Fingerprints are path::qualname::rule::"
                    "subject (no line numbers).\n# Delete entries as "
                    "they are fixed — the gate prints candidates.\n")
            for fp in sorted(current):
                f.write(fp + "\n")
        print(f"concheck: baseline rewritten with {len(current)} "
              f"finding(s) -> {args.baseline}")
        return 0

    new = sorted(fp for fp in current if fp not in baseline)
    fixed = sorted(fp for fp in baseline if fp not in current)

    print(f"concheck: {len(current)} finding(s), baseline carries "
          f"{len(baseline)} ({os.path.basename(args.baseline)})")
    for fp in fixed:
        print(f"  fixed: {fp} — no longer firing; delete it from the "
              "baseline to ratchet the floor down")
    if args.verbose:
        for fp in sorted(current - set(new)):
            print(f"  known: {by_fp[fp].render()}")
    for fp in new:
        print(f"  NEW FINDING: {by_fp[fp].render()}")
        print(f"               fingerprint: {fp}")
    if new:
        print(f"concheck: FAILED ({len(new)} new finding(s) vs baseline)")
        return 1
    print("concheck: ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
