"""Merge and pretty-print flight-recorder dumps from multiple hosts.

    python -m faabric_tpu.runner.flightdump <dir> [--json] [--last N]
                                            [--kind K]

Each process that hit a dump trigger (MpiWorldAborted, planner requeue,
unhandled executor exception, SIGTERM) left one
``flight-<label>-<pid>-<ns>.json`` file in ``FAABRIC_FLIGHT_DIR``
(telemetry/flight.py). This tool merges their event rings onto one
wall-clock timeline — the black-box readout after a chaos run or a
production incident.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys


def load_dumps(directory: str) -> list[dict]:
    """Every parseable ``flight-*.json`` in ``directory`` (unreadable or
    truncated files are skipped with a note on stderr, not fatal — a
    post-mortem tool must tolerate a dump cut short by the crash)."""
    dumps = []
    for path in sorted(glob.glob(os.path.join(directory, "flight-*.json"))):
        try:
            with open(path) as f:
                body = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"skipping {path}: {e}", file=sys.stderr)
            continue
        body["_file"] = os.path.basename(path)
        dumps.append(body)
    return dumps


def merge(directory: str) -> list[dict]:
    """All dumps' events on one timeline: each event gains ``process``/
    ``pid``/``dump_reason`` provenance and the list sorts by wall-clock
    timestamp (hosts share the tracer's wall-anchored convention).

    A process that hit several dump triggers (e.g. group abort then
    SIGTERM) left overlapping ring snapshots; events dedupe on
    (process, pid, ring seq), the NEWEST dump's copy winning, so the
    merged black box reports each real event once."""
    dumps = load_dumps(directory)
    # Newest file last: its copy of a shared (pid, seq) event wins
    dumps.sort(key=lambda d: d.get("dumped_at", 0.0))
    by_key: dict[tuple, dict] = {}
    for dump in dumps:
        for e in dump.get("events", []):
            key = (dump.get("process", "?"), dump.get("pid", 0),
                   e.get("seq", -1))
            by_key[key] = {**e,
                           "process": dump.get("process", "?"),
                           "pid": dump.get("pid", 0),
                           "dump_reason": dump.get("reason", "?")}
    events = list(by_key.values())
    events.sort(key=lambda e: (e.get("ts", 0.0), e.get("seq", 0)))
    return events


def _fmt_fields(event: dict) -> str:
    skip = ("ts", "seq", "kind", "process", "pid", "dump_reason")
    return " ".join(f"{k}={event[k]}" for k in event if k not in skip)


def render(events: list[dict], last: int | None = None) -> str:
    if last is not None:
        events = events[-last:]
    if not events:
        return "(no flight events)"
    t0 = events[0].get("ts", 0.0)
    lines = []
    for e in events:
        lines.append(
            f"{e.get('ts', 0.0) - t0:+10.3f}s "
            f"{e.get('process', '?'):<22} "
            f"{e.get('kind', '?'):<20} {_fmt_fields(e)}")
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="faabric_tpu.runner.flightdump",
        description="Merge + pretty-print flight-recorder dumps")
    parser.add_argument("directory", nargs="?",
                        default=os.environ.get("FAABRIC_FLIGHT_DIR", "."))
    parser.add_argument("--json", action="store_true",
                        help="machine-readable merged event list")
    parser.add_argument("--last", type=int, default=None,
                        help="only the final N events")
    parser.add_argument("--kind", default=None,
                        help="filter by event kind (e.g. group_abort)")
    args = parser.parse_args(argv)

    events = merge(args.directory)
    if args.kind:
        events = [e for e in events if e.get("kind") == args.kind]
    if args.json:
        if args.last is not None:
            events = events[-args.last:]
        print(json.dumps(events, indent=1))
    else:
        dumps = load_dumps(args.directory)
        print(f"{len(dumps)} dump(s), {len(events)} event(s) "
              f"from {args.directory}")
        print(render(events, last=args.last))
    return 0 if events else 1


if __name__ == "__main__":
    sys.exit(main())
