"""Merge and pretty-print flight-recorder dumps from multiple hosts.

    python -m faabric_tpu.runner.flightdump <dir> [--json] [--last N]
                                            [--kind K]
    python -m faabric_tpu.runner.flightdump --url http://pl:8080 \
                                            [--url http://w0:8081] ...

Each process that hit a dump trigger (MpiWorldAborted, planner requeue,
unhandled executor exception, SIGTERM) left one
``flight-<label>-<pid>-<ns>.json`` file in ``FAABRIC_FLIGHT_DIR``
(telemetry/flight.py). This tool merges their event rings onto one
wall-clock timeline — the black-box readout after a chaos run or a
production incident.

``--url`` (repeatable; ISSUE 14 satellite) reads LIVE rings instead:
every planner/worker HTTP endpoint serves its in-memory ring at
``GET /flight``, so the black box is readable without waiting for a
crash dump. Directory and URL sources merge together.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys


def load_dumps(directory: str) -> list[dict]:
    """Every parseable ``flight-*.json`` in ``directory`` (unreadable or
    truncated files are skipped with a note on stderr, not fatal — a
    post-mortem tool must tolerate a dump cut short by the crash)."""
    dumps = []
    for path in sorted(glob.glob(os.path.join(directory, "flight-*.json"))):
        try:
            with open(path) as f:
                body = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"skipping {path}: {e}", file=sys.stderr)
            continue
        body["_file"] = os.path.basename(path)
        dumps.append(body)
    return dumps


def fetch_live_rings(urls: list[str], timeout: float = 10.0) -> list[dict]:
    """One pseudo-dump per reachable ``GET /flight`` endpoint (live
    rings have no dump trigger; ``reason`` reads ``live``). Unreachable
    endpoints are skipped with a note — a half-dead cluster is exactly
    when this tool runs."""
    import time
    import urllib.request

    dumps = []
    for url in urls:
        full = url.rstrip("/")
        if not full.endswith("/flight"):
            full += "/flight"
        try:
            with urllib.request.urlopen(full, timeout=timeout) as resp:
                body = json.loads(resp.read().decode())
        except Exception as e:  # noqa: BLE001 — degrade, never die
            print(f"skipping {full}: {e}", file=sys.stderr)
            continue
        body.setdefault("reason", "live")
        body.setdefault("dumped_at", time.time())
        body["_file"] = full
        dumps.append(body)
    return dumps


def merge_dumps(dumps: list[dict]) -> list[dict]:
    """All dumps' events on one timeline: each event gains ``process``/
    ``pid``/``dump_reason`` provenance and the list sorts by wall-clock
    timestamp (hosts share the tracer's wall-anchored convention).

    A process that hit several dump triggers (e.g. group abort then
    SIGTERM) left overlapping ring snapshots; events dedupe on
    (process, pid, ring seq), the NEWEST dump's copy winning, so the
    merged black box reports each real event once."""
    # Newest file last: its copy of a shared (pid, seq) event wins
    dumps = sorted(dumps, key=lambda d: d.get("dumped_at", 0.0))
    by_key: dict[tuple, dict] = {}
    for dump in dumps:
        for e in dump.get("events", []):
            key = (dump.get("process", "?"), dump.get("pid", 0),
                   e.get("seq", -1))
            by_key[key] = {**e,
                           "process": dump.get("process", "?"),
                           "pid": dump.get("pid", 0),
                           "dump_reason": dump.get("reason", "?")}
    events = list(by_key.values())
    events.sort(key=lambda e: (e.get("ts", 0.0), e.get("seq", 0)))
    return events


def merge(directory: str) -> list[dict]:
    """Directory-mode merge (the pre-ISSUE-14 entry point, kept for
    callers and tests)."""
    return merge_dumps(load_dumps(directory))


def _fmt_fields(event: dict) -> str:
    skip = ("ts", "seq", "kind", "process", "pid", "dump_reason")
    return " ".join(f"{k}={event[k]}" for k in event if k not in skip)


def render(events: list[dict], last: int | None = None) -> str:
    if last is not None:
        events = events[-last:]
    if not events:
        return "(no flight events)"
    t0 = events[0].get("ts", 0.0)
    lines = []
    for e in events:
        lines.append(
            f"{e.get('ts', 0.0) - t0:+10.3f}s "
            f"{e.get('process', '?'):<22} "
            f"{e.get('kind', '?'):<20} {_fmt_fields(e)}")
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="faabric_tpu.runner.flightdump",
        description="Merge + pretty-print flight-recorder dumps")
    parser.add_argument("directory", nargs="?", default=None)
    parser.add_argument("--url", action="append", default=[],
                        help="live planner/worker HTTP endpoint(s); "
                        "reads GET /flight instead of (or merged with) "
                        "dump files")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable merged event list")
    parser.add_argument("--last", type=int, default=None,
                        help="only the final N events")
    parser.add_argument("--kind", default=None,
                        help="filter by event kind (e.g. group_abort)")
    args = parser.parse_args(argv)

    directory = args.directory
    if directory is None and not args.url:
        directory = os.environ.get("FAABRIC_FLIGHT_DIR", ".")
    dumps = load_dumps(directory) if directory else []
    dumps += fetch_live_rings(args.url)
    events = merge_dumps(dumps)
    if args.kind:
        events = [e for e in events if e.get("kind") == args.kind]
    if args.json:
        if args.last is not None:
            events = events[-args.last:]
        print(json.dumps(events, indent=1))
    else:
        where = " + ".join(filter(None, [directory] + args.url))
        print(f"{len(dumps)} dump(s)/ring(s), {len(events)} event(s) "
              f"from {where}")
        print(render(events, last=args.last))
    return 0 if events else 1


if __name__ == "__main__":
    sys.exit(main())
