"""Render the cluster CPU profile (ISSUE 18).

    python -m faabric_tpu.runner.profile [--url BASE | --file DOC.json]
                                         [--top N] [--bottom-up]
                                         [--collapsed [--weight cpu]]
                                         [--diff BEFORE.json AFTER.json]
                                         [--json] [--selftest]

Fetches the planner's ``GET /profile`` — every host's stack-sampler
trie merged into ranked per-host × thread-class × collapsed-stack rows
with per-thread CPU weighting and per-process GIL pressure — and
renders it as an aligned table. Views:

* default — top-down hot stacks ranked by CPU;
* ``--bottom-up`` — per leaf-frame self totals ("which function burns
  the CPU"), complementary to the trie view;
* ``--collapsed`` — flamegraph-collapsed lines
  (``host;class;f1;...;fN weight``) feedable straight into
  flamegraph.pl / speedscope; ``--weight cpu`` weighs by cpu_ms
  instead of samples;
* ``--diff A B`` — two saved captures matched by (host, class, stack)
  ranked by CPU growth, for round-over-round regression hunting;
* ``--selftest`` — spin up a real Profiler against planted hot/idle
  threads and assert the attribution end to end (wired into
  tools/check.sh).
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time


def fetch_profile(base_url: str, timeout: float = 10.0) -> dict:
    import urllib.request

    url = base_url.rstrip("/") + "/profile"
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def _as_profile(doc: dict) -> dict:
    """A /profile response has ranked "stacks" rows; a raw telemetry
    dump (host -> {"profile": ...}) or a single-process snapshot is
    aggregated on the fly."""
    from faabric_tpu.telemetry.profiler import aggregate_profile

    if isinstance(doc.get("stacks"), list) and "hosts" in doc:
        return doc
    if "classes" in doc and "interval_ms" in doc:  # bare snapshot
        return aggregate_profile({"local": {"profile": doc}})
    return aggregate_profile(doc)


# ----------------------------------------------------------------------
# selftest

def _selftest_hot_spin(stop: threading.Event) -> None:
    """Distinctive busy-burn frame the selftest hunts for by name."""
    x = 0
    while not stop.is_set():
        for _ in range(1000):
            x = (x * 1103515245 + 12345) & 0x7FFFFFFF


def run_selftest() -> int:
    """Plant a hot spin thread + an idle thread against a real
    Profiler and assert per-class CPU attribution, frame ranking, and
    every render path. Exercises exactly what the dist acceptance test
    checks live, without sockets."""
    from faabric_tpu.telemetry.profiler import (
        Profiler,
        aggregate_profile,
        bottom_up,
        collapsed_lines,
        diff_profiles,
        render_profile,
    )

    stop = threading.Event()
    spin = threading.Thread(target=_selftest_hot_spin, args=(stop,),
                            name="selftest/spin", daemon=True)
    idle = threading.Thread(target=lambda: stop.wait(30),
                            name="selftest/idle", daemon=True)
    spin.start()
    idle.start()
    prof = Profiler(interval_s=0.005)
    prof.start()
    try:
        time.sleep(0.6)
    finally:
        prof.stop()
        stop.set()
        spin.join(timeout=5)
        idle.join(timeout=5)

    snap = prof.snapshot()
    assert snap["samples"] >= 10, f"sampler starved: {snap['samples']}"
    classes = snap["classes"]
    assert "selftest/spin" in classes, sorted(classes)
    assert "selftest/idle" in classes, sorted(classes)
    spin_cpu = classes["selftest/spin"]["cpu_ms"]
    idle_cpu = classes["selftest/idle"]["cpu_ms"]
    assert spin_cpu > 10.0, f"spin burned no CPU: {spin_cpu}"
    assert spin_cpu > 10 * max(idle_cpu, 0.1), (
        f"CPU weighting failed to separate spin ({spin_cpu} ms) from "
        f"idle ({idle_cpu} ms)")

    doc = aggregate_profile({"selfhost": {"profile": snap}})
    top = [r for r in doc["stacks"] if r["class"] == "selftest/spin"]
    assert top, doc["stacks"][:3]
    assert any("_selftest_hot_spin" in f for f in top[0]["frames"]), (
        top[0]["frames"])
    assert doc["stacks"][0]["class"] == "selftest/spin", (
        doc["stacks"][0])
    assert doc["gil"]["selfhost"]["pressure"] >= 0.0

    rendered = render_profile(doc)
    assert "selfhost" in rendered and "selftest/spin" in rendered
    lines = collapsed_lines(doc)
    assert lines and all(l.rsplit(" ", 1)[1].isdigit() for l in lines)
    assert any("selftest/spin" in l for l in lines)
    cpu_lines = collapsed_lines(doc, weight="cpu")
    assert any("selftest/spin" in l for l in cpu_lines)
    bu = bottom_up(doc)
    assert bu and bu[0]["cpu_ms"] > 0
    d = diff_profiles(doc, doc)
    assert d and all(r["cpu_ms_delta"] == 0 for r in d)

    print(f"profile selftest: OK — {snap['samples']} samples, "
          f"spin {spin_cpu:.0f} ms vs idle {idle_cpu:.0f} ms, "
          f"overhead {snap['overhead_pct']}%, "
          f"gil_pressure {doc['gil']['selfhost']['pressure']}")
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m faabric_tpu.runner.profile",
        description="Render the cluster CPU profile (GET /profile)")
    parser.add_argument("--url", default="http://127.0.0.1:8080",
                        help="planner REST base URL")
    parser.add_argument("--file", default=None, metavar="DOC.json",
                        help="render a saved /profile (or telemetry) "
                             "document instead of fetching")
    parser.add_argument("--top", type=int, default=15,
                        help="stack rows to show (default 15)")
    parser.add_argument("--bottom-up", action="store_true",
                        help="rank leaf frames by self weight")
    parser.add_argument("--collapsed", action="store_true",
                        help="emit flamegraph-collapsed lines")
    parser.add_argument("--weight", choices=("samples", "cpu"),
                        default="samples",
                        help="collapsed-line weight (default samples)")
    parser.add_argument("--diff", nargs=2, default=None,
                        metavar=("BEFORE.json", "AFTER.json"),
                        help="diff two saved captures by CPU growth")
    parser.add_argument("--json", action="store_true",
                        help="emit the machine-readable document")
    parser.add_argument("--selftest", action="store_true",
                        help="run the planted-thread attribution "
                             "selftest and exit")
    args = parser.parse_args(argv)

    if args.selftest:
        try:
            return run_selftest()
        except AssertionError as e:
            print(f"profile selftest: FAILED — {e}", file=sys.stderr)
            return 1

    from faabric_tpu.telemetry.profiler import (
        bottom_up,
        collapsed_lines,
        diff_profiles,
        render_profile,
    )

    if args.diff:
        try:
            with open(args.diff[0]) as f:
                before = _as_profile(json.load(f))
            with open(args.diff[1]) as f:
                after = _as_profile(json.load(f))
        except Exception as e:  # noqa: BLE001 — CLI surface
            print(f"profile: cannot load diff inputs: {e}",
                  file=sys.stderr)
            return 2
        rows = diff_profiles(before, after, top=args.top)
        if args.json:
            print(json.dumps(rows, indent=1))
        else:
            print(f"{'cpu_ms Δ':>10}  {'before':>10}  {'after':>10}  "
                  f"host/class · leaf")
            for r in rows:
                leaf = r["frames"][-1] if r["frames"] else "?"
                print(f"{r['cpu_ms_delta']:>10.1f}  "
                      f"{r['cpu_ms_before']:>10.1f}  "
                      f"{r['cpu_ms_after']:>10.1f}  "
                      f"{r['host']}/{r['class']} · {leaf}")
        return 0

    try:
        if args.file:
            with open(args.file) as f:
                doc = _as_profile(json.load(f))
        else:
            doc = _as_profile(fetch_profile(args.url))
    except Exception as e:  # noqa: BLE001 — CLI surface
        src = args.file or args.url
        print(f"profile: cannot load profile from {src}: {e}",
              file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps(doc, indent=1))
    elif args.collapsed:
        for line in collapsed_lines(doc, weight=args.weight):
            print(line)
    elif args.bottom_up:
        print(f"{'cpu_ms':>10}  {'smpl':>6}  frame · classes")
        for r in bottom_up(doc, top=args.top):
            print(f"{r['cpu_ms']:>10.1f}  {r['samples']:>6}  "
                  f"{r['frame']} · {', '.join(r['classes'])}")
    else:
        print(render_profile(doc, top=args.top))
    return 0 if doc.get("hosts") else 1


if __name__ == "__main__":
    sys.exit(main())
