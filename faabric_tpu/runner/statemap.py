"""Render the cluster state map (ISSUE 16).

    python -m faabric_tpu.runner.statemap [--url BASE | --file DOC.json]
                                          [--top N] [--json]

Fetches the planner's ``GET /statemap`` — every host's per-key state
access ledger merged into hot-key-ranked rows (master host, size, byte
totals by origin, locality, pull amplification, lock waits) plus
per-host mastership totals — and renders it as an aligned table.
``--file`` renders a previously saved document instead (either a
``/statemap`` response or a raw ``collect_telemetry`` dump, which is
aggregated on the fly); ``--json`` emits the machine-readable document.
"""

from __future__ import annotations

import argparse
import json
import sys

from faabric_tpu.telemetry.statestats import (
    aggregate_statemap,
    render_statemap,
)


def fetch_statemap(base_url: str, timeout: float = 10.0) -> dict:
    import urllib.request

    url = base_url.rstrip("/") + "/statemap"
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def _as_statemap(doc: dict) -> dict:
    # A /statemap response has ranked "keys" rows; anything else is
    # treated as a raw telemetry dump and aggregated here
    if isinstance(doc.get("keys"), list):
        return doc
    return aggregate_statemap(doc)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m faabric_tpu.runner.statemap",
        description="Render the cluster state map (GET /statemap)")
    parser.add_argument("--url", default="http://127.0.0.1:8080",
                        help="planner REST base URL")
    parser.add_argument("--file", default=None, metavar="DOC.json",
                        help="render a saved /statemap (or telemetry) "
                             "document instead of fetching")
    parser.add_argument("--top", type=int, default=20,
                        help="key rows to show (default 20)")
    parser.add_argument("--json", action="store_true",
                        help="emit the machine-readable document")
    args = parser.parse_args(argv)

    try:
        if args.file:
            with open(args.file) as f:
                doc = _as_statemap(json.load(f))
        else:
            doc = _as_statemap(fetch_statemap(args.url))
    except Exception as e:  # noqa: BLE001 — CLI surface
        src = args.file or args.url
        print(f"statemap: cannot load state map from {src}: {e}",
              file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps(doc, indent=1))
    else:
        print(render_statemap(doc, top=args.top))
    return 0 if doc.get("keys") else 1


if __name__ == "__main__":
    sys.exit(main())
