"""Worker runtime assembly — the FaabricMain analog
(reference src/runner/FaabricMain.cpp:19-108).

Boots one worker host: planner registration (+keep-alive), the
FunctionCallServer, and — as the layers land — state/snapshot/PTP servers.
Instantiable with an explicit host identity so two full workers can coexist
in one process on aliased port ranges (SURVEY §4.2's dist-test trick).
"""

from __future__ import annotations

from typing import Optional

from faabric_tpu.executor.factory import ExecutorFactory, set_executor_factory
from faabric_tpu.planner.client import PlannerClient
from faabric_tpu.scheduler.function_call import FunctionCallServer
from faabric_tpu.scheduler.scheduler import Scheduler
from faabric_tpu.util.config import get_system_config
from faabric_tpu.util.logging import get_logger
from faabric_tpu.util.network import get_primary_ip_for_this_host

logger = get_logger(__name__)


class WorkerRuntime:
    def __init__(self, host: str = "", slots: int | None = None,
                 n_devices: int = 0,
                 factory: Optional[ExecutorFactory] = None,
                 planner_host: str | None = None,
                 device_plane_size: int = 0) -> None:
        conf = get_system_config()
        self.host = host or get_primary_ip_for_this_host()
        # Traces from co-located worker processes merge on one Perfetto
        # timeline; the label tells their rows apart
        from faabric_tpu.telemetry import set_process_label

        set_process_label(f"worker-{self.host}")
        # Host-pair fault rules (partitions) match fire() ctx on
        # src=<this host>; the stamp is free while no rules are armed
        from faabric_tpu.faults import set_fault_identity

        set_fault_identity(self.host)
        # None = size to the machine. An EXPLICIT slots=0 is an
        # observer host (test clients, result waiters) and must
        # register as exactly 0 — the old `slots or usable_cores()`
        # silently advertised real capacity for them, so the planner
        # gang-scheduled MPI ranks onto executor-less processes (the
        # seed live-migration dist failure).
        self.slots = conf.get_usable_cores() if slots is None else slots
        self.n_devices = n_devices
        # >1: join the multi-process device plane at boot — this worker
        # contributes its local chips to ONE global jax mesh spanning
        # device_plane_size worker processes (parallel/distributed.py)
        self.device_plane_size = device_plane_size

        if factory is not None:
            set_executor_factory(factory)

        self.planner_client = PlannerClient(self.host, planner_host)
        self.scheduler = Scheduler(self.host, self.planner_client)
        self.function_server = FunctionCallServer(self.scheduler)

        # PTP group messaging (reference FaabricMain starts a
        # PointToPointServer per worker)
        from faabric_tpu.transport.point_to_point import PointToPointBroker
        from faabric_tpu.transport.ptp_remote import PointToPointServer

        self.ptp_broker = PointToPointBroker(self.host)
        # Out-of-band abort path: aborts that cannot cross a partitioned
        # worker-pair link relay through the planner's independent links
        self.ptp_broker.planner_client = self.planner_client
        self.scheduler.ptp_broker = self.ptp_broker

        # MPI worlds (reference FaabricMain's MpiWorldRegistry singleton;
        # here per runtime for in-process multi-host tests)
        from faabric_tpu.mpi.registry import MpiWorldRegistry

        self.mpi_registry = MpiWorldRegistry(self.ptp_broker,
                                             self.planner_client)
        self.scheduler.mpi_registry = self.mpi_registry

        # Snapshots (reference FaabricMain starts a SnapshotServer)
        from faabric_tpu.snapshot.registry import SnapshotRegistry
        from faabric_tpu.snapshot.remote import SnapshotServer

        self.snapshot_registry = SnapshotRegistry()
        self.scheduler.snapshot_registry = self.snapshot_registry
        self.planner_client.snapshot_registry = self.snapshot_registry

        # State KV (reference FaabricMain starts a StateServer)
        from faabric_tpu.state.state import State
        from faabric_tpu.state.remote import StateServer

        self.state = State(self.host, self.planner_client)
        self.scheduler.state = self.state

        self.extra_servers: list = [
            PointToPointServer(self.ptp_broker),
            SnapshotServer(self.snapshot_registry, self.host,
                           scheduler=self.scheduler),
            StateServer(self.state, self.host),
        ]

        # Liveness surface: GET /healthz answered locally (the chaos
        # tests and deployment probes must not infer worker liveness
        # from planner registration state). Opt-in by port — workers in
        # in-process multi-host tests would otherwise fight over it.
        import os

        try:
            http_port = int(os.environ.get("WORKER_HTTP_PORT", "0"))
        except ValueError:
            logger.warning("Ignoring malformed WORKER_HTTP_PORT=%r",
                           os.environ.get("WORKER_HTTP_PORT"))
            http_port = 0
        if http_port:
            from faabric_tpu.endpoint import WorkerHttpEndpoint

            self.extra_servers.append(
                WorkerHttpEndpoint(http_port, runtime=self))

        self._started = False

    # ------------------------------------------------------------------
    def start(self, register: bool = True) -> None:
        if self._started:
            return
        self._started = True
        self.function_server.start()
        self.scheduler.start()
        self._start_extra_servers()
        # Time-series ring (ISSUE 14): every worker samples its own
        # process gauges + executor load; the planner merges the rings
        # behind GET /timeseries. Shared, refcounted sampler thread.
        from faabric_tpu.telemetry import (
            get_timeseries,
            start_profiler,
            start_sampler,
        )

        self._executors_gauge = self.scheduler.get_executor_count
        get_timeseries().register("executors", self._executors_gauge)
        start_sampler()
        # Continuous CPU profiler (ISSUE 18): refcounted like the
        # sampler, so a co-resident planner shares the one thread
        start_profiler()
        self._profiling = True
        if register:
            self.planner_client.register_host(
                self.slots, self.n_devices, overwrite=True,
                start_keep_alive=True)
        if self.device_plane_size > 1:
            from faabric_tpu.parallel.distributed import (
                join_device_plane,
                request_device_plane,
            )

            spec = request_device_plane(self.planner_client,
                                        self.device_plane_size)
            join_device_plane(spec)
        logger.debug("Worker %s up (slots=%d chips=%d)", self.host,
                     self.slots, self.n_devices)

    def _start_extra_servers(self) -> None:
        """Hook for PTP/snapshot/state servers as those layers land. A
        bind failure part-way through must not leak the servers already
        started — a half-up worker nobody tracks poisons its port range
        for every later boot on the same aliases."""
        started = []
        try:
            for server in self.extra_servers:
                server.start()
                started.append(server)
        except Exception:
            # Each stop gets its own guard: one raising must not skip
            # the rest — a surviving listener is the very leak this
            # unwind exists to prevent
            for server in reversed(started):
                try:
                    server.stop()
                except Exception:  # noqa: BLE001 — best-effort unwind
                    pass
            try:
                self.scheduler.shutdown()
            except Exception:  # noqa: BLE001
                pass
            try:
                self.function_server.stop()
            except Exception:  # noqa: BLE001
                pass
            self._started = False
            raise

    def shutdown(self, remove_host: bool = True) -> None:
        if not self._started:
            return
        self._started = False
        from faabric_tpu.telemetry import (
            get_timeseries,
            stop_profiler,
            stop_sampler,
        )

        stop_sampler()
        if getattr(self, "_profiling", False):
            self._profiling = False
            stop_profiler()
        # Drop OUR gauge registration (fn-matched): it would pin this
        # runtime's scheduler for the rest of the process; a co-resident
        # runtime that re-registered the name keeps its series
        get_timeseries().unregister("executors",
                                    getattr(self, "_executors_gauge",
                                            None))
        if remove_host:
            # Best-effort by design: remove_host flushes any results
            # buffered during a planner outage, then deregisters; both
            # swallow RpcError internally (the planner's keep-alive
            # expiry reaps us anyway) so a dead planner cannot wedge or
            # crash worker shutdown
            try:
                self.planner_client.remove_host()
            except Exception:  # noqa: BLE001 — planner may already be gone
                logger.debug("Could not deregister %s", self.host)
        else:
            # Keeping the registration (tests, rolling restarts) must
            # still not strand results completed during an outage
            self.planner_client.flush_pending_results()
        if self.device_plane_size > 1:
            from faabric_tpu.parallel.distributed import leave_device_plane

            leave_device_plane()
        self.scheduler.shutdown()
        for server in reversed(self.extra_servers):
            server.stop()
        self.function_server.stop()
        self.ptp_broker.clear()
        self.planner_client.close()
        logger.debug("Worker %s down", self.host)
