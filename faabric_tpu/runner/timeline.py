"""Render one app's cross-host invocation lifecycle (ISSUE 14).

    python -m faabric_tpu.runner.timeline <app_id> [--url BASE]
                                          [--trace OUT.json] [--json]

Fetches the app's result messages from the planner's REST surface
(EXECUTE_BATCH_STATUS) and renders each message's phase ledger — the
monotonic stamps ``telemetry/lifecycle.py`` wrote at admit, queue exit,
schedule, journal, dispatch, executor queue exit, run start/end, result
push and planner record, across every host the message touched — as an
aligned text timeline plus, with ``--trace``, a Chrome ``trace_event``
file (one row per message; load in chrome://tracing / Perfetto).

Stamps share CLOCK_MONOTONIC on one machine, so messages line up
exactly; on a real multi-host cluster the two wire-crossing phases
absorb the clock offset (documented in docs/telemetry.md).
"""

from __future__ import annotations

import argparse
import json
import sys

from faabric_tpu.telemetry.lifecycle import PHASE_LABELS, ledger_durations

_BAR_WIDTH = 44

# Distinct single-char bar marks per phase: five labels share the
# first letter 'r' (requeue/run_prep/run/result_push/record) — exactly
# the phases this tool exists to tell apart
_BAR_MARKS = {
    "ingress_queue": "q",
    "schedule": "s",
    "journal": "j",
    "dispatch": "d",
    "requeue": "R",
    "executor_queue": "e",
    "run_prep": "p",
    "run": "r",
    "result_push": "u",
    "record": "c",
    "waiter_wake": "w",
}


def fetch_status(base_url: str, app_id: int, timeout: float = 10.0) -> dict:
    """EXECUTE_BATCH_STATUS over the planner REST surface."""
    import urllib.request

    body = json.dumps({
        "http_type": 11,  # HttpMessageType.EXECUTE_BATCH_STATUS
        "payload": json.dumps({"app_id": app_id}),
    }).encode()
    req = urllib.request.Request(
        base_url.rstrip("/"), data=body,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def _msg_rows(status: dict) -> list[dict]:
    """Per-message render rows: sorted stamps, durations, span."""
    rows = []
    for m in status.get("messageResults") or []:
        lc = m.get("lc") or {}
        stamps = sorted((int(v), k) for k, v in lc.items()
                        if isinstance(v, (int, float)))
        if not stamps:
            continue
        rows.append({
            "id": m.get("id"),
            "app_idx": m.get("app_idx", 0),
            "host": m.get("executed_host", ""),
            "return_value": m.get("return_value", 0),
            "stamps": stamps,
            "durations": ledger_durations(lc),
            "t0": stamps[0][0],
            "t1": stamps[-1][0],
        })
    rows.sort(key=lambda r: (r["t0"], r["app_idx"]))
    return rows


def render_text(app_id: int, rows: list[dict]) -> str:
    if not rows:
        return (f"app {app_id}: no messages with lifecycle ledgers "
                "(FAABRIC_METRICS=0, or results evicted)")
    t_min = min(r["t0"] for r in rows)
    t_max = max(r["t1"] for r in rows)
    span = max(1, t_max - t_min)
    lines = [f"app {app_id}: {len(rows)} message(s), "
             f"{span / 1e6:.3f} ms wall (ledger span)"]
    for r in rows:
        lines.append(
            f"  msg {r['id']} idx {r['app_idx']} on "
            f"{r['host'] or '?'} rv={r['return_value']} "
            f"({(r['t1'] - r['t0']) / 1e6:.3f} ms)")
        # Bar: each inter-stamp gap as a proportional segment
        bar = [" "] * _BAR_WIDTH
        for i in range(1, len(r["stamps"])):
            a = (r["stamps"][i - 1][0] - t_min) / span
            b = (r["stamps"][i][0] - t_min) / span
            lo = min(_BAR_WIDTH - 1, int(a * _BAR_WIDTH))
            hi = min(_BAR_WIDTH, max(lo + 1, int(b * _BAR_WIDTH)))
            key = r["stamps"][i][1]
            label = PHASE_LABELS.get(key, key)
            mark = _BAR_MARKS.get(label, label[0])
            for j in range(lo, hi):
                bar[j] = mark
        lines.append(f"    [{''.join(bar)}]")
        parts = [f"{label}={secs * 1e3:.3f}ms"
                 for label, secs in sorted(r["durations"].items(),
                                           key=lambda kv: -kv[1])]
        lines.append("    " + "  ".join(parts))
    legend = ", ".join(f"{mark}={label}"
                       for label, mark in _BAR_MARKS.items())
    lines.append(f"  (bar legend: {legend})")
    return "\n".join(lines)


def chrome_trace_events(app_id: int, rows: list[dict]) -> list[dict]:
    """Complete ('X') events per phase, one trace row (tid) per
    message; timestamps are the raw monotonic stamps in µs so multiple
    apps dumped from one cluster line up."""
    events: list[dict] = []
    for r in rows:
        tid = r["app_idx"]
        events.append({"ph": "M", "name": "thread_name", "pid": app_id,
                       "tid": tid,
                       "args": {"name": f"msg {r['id']} "
                                        f"({r['host'] or '?'})"}})
        for i in range(1, len(r["stamps"])):
            t_prev, _ = r["stamps"][i - 1]
            t, key = r["stamps"][i]
            events.append({
                "ph": "X", "pid": app_id, "tid": tid,
                "name": PHASE_LABELS.get(key, key),
                "cat": "lifecycle",
                "ts": t_prev / 1e3,
                "dur": max(0.001, (t - t_prev) / 1e3),
            })
    return events


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m faabric_tpu.runner.timeline",
        description="Render one app's cross-host invocation lifecycle")
    parser.add_argument("app_id", type=int)
    parser.add_argument("--url", default="http://127.0.0.1:8080",
                        help="planner REST base URL")
    parser.add_argument("--trace", default=None, metavar="OUT.json",
                        help="also write a Chrome trace_event file")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable rows")
    args = parser.parse_args(argv)

    try:
        status = fetch_status(args.url, args.app_id)
    except Exception as e:  # noqa: BLE001 — CLI surface
        print(f"timeline: cannot fetch app {args.app_id} from "
              f"{args.url}: {e}", file=sys.stderr)
        return 2
    rows = _msg_rows(status)
    if args.json:
        print(json.dumps({
            "app_id": args.app_id,
            "finished": status.get("finished"),
            "messages": [{k: v for k, v in r.items() if k != "stamps"}
                         for r in rows]}, indent=1))
    else:
        print(render_text(args.app_id, rows))
    if args.trace:
        with open(args.trace, "w") as f:
            json.dump({"traceEvents":
                       chrome_trace_events(args.app_id, rows),
                       "displayTimeUnit": "ms"}, f)
        print(f"chrome trace written to {args.trace}")
    return 0 if rows else 1


if __name__ == "__main__":
    sys.exit(main())
