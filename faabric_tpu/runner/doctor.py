"""Cluster doctor: ranked performance/health diagnosis from the
planner's scrape surfaces (ISSUE 12).

    python -m faabric_tpu.runner.doctor --url http://127.0.0.1:8080
    python -m faabric_tpu.runner.doctor --dir /path/to/dumps
    python -m faabric_tpu.runner.doctor --selftest

Ingests ``/perf`` (the rolling performance-profile aggregation),
``/metrics`` (Prometheus text), ``/commmatrix``, ``/healthz`` and
``/topology`` — live over HTTP, or from files dumped earlier
(``perf.json`` / ``perf-cluster.json``, ``metrics.txt``,
``commmatrix.json``, ``healthz.json``, ``topology.json``) so a
post-mortem needs no live cluster — and prints a RANKED diagnosis:

- **slow links** — per-plane, links whose measured bandwidth sits far
  below the cluster median for that plane (the HiCCL "slow rung");
- **straggler ranks** — ranks consistently arriving late at their
  collectives (entry-skew analysis over the merged per-round series,
  annotated with the rank's host via the topology);
- **codec escape storms** — full-frame escapes dwarfing coded frames
  (a link whose delta stream keeps breaking pays for nothing);
- **admission shedding** — the ingress actively 429ing sources;
- **journal fsync pressure** — the group-commit journal's write-behind
  buffer backing up or fsync falling behind its interval;
- **open circuit breakers / keep-alives at risk** — hosts the planner
  is about to give up on;
- **dominant lifecycle phase** (ISSUE 14) — which phase of the
  invocation ledger the p99 end-to-end latency is made of;
- **SLO burn** (ISSUE 14) — declared ``FAABRIC_SLO`` targets burning
  their error budget on every evaluation window;
- **queue growth / capacity exhaustion** (ISSUE 14) — trends from the
  ``/timeseries`` ring: an ingress depth that keeps growing, or free
  slots pinned at zero while a backlog holds;
- **hot-key skew** (ISSUE 16) — one state key's byte traffic dwarfing
  the median of the rest of the ``/statemap``;
- **master hotspot** (ISSUE 16) — one host serving most of the
  cluster's state bytes as master while others sit idle;
- **pull amplification** (ISSUE 16) — a key whose replicas keep
  re-pulling chunks they already pulled clean (total vs first-time
  chunk pulls);
- **lock convoy** (ISSUE 16) — global-lock waits on a key repeatedly
  stalling past ``FAABRIC_STATE_LOCK_STALL_MS``.

``--selftest`` runs the analyzers over a built-in synthetic cluster
with one planted slow link, one planted straggler, an escape storm, a
run-dominated lifecycle tail, a burning latency SLO, a growing
ingress queue, and a state map with a planted hot key, master
hotspot, amplified puller and lock convoy, and exits non-zero unless
all of them rank in the findings — the smoke gate ``tools/check.sh``
runs.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

# One median, shared with the straggler analysis this tool cross-checks
from faabric_tpu.telemetry.perfprofile import _median

SOURCES = ("perf", "metrics", "commmatrix", "healthz", "topology",
           "timeseries", "statemap", "profile")

# File-name candidates per source for --dir mode (first hit wins)
_FILE_CANDIDATES = {
    "perf": ("perf.json", "perf-cluster.json"),
    "metrics": ("metrics.txt", "metrics.prom", "metrics"),
    "commmatrix": ("commmatrix.json",),
    "healthz": ("healthz.json",),
    "topology": ("topology.json",),
    "timeseries": ("timeseries.json",),
    "statemap": ("statemap.json",),
    "profile": ("profile.json",),
}

# A link must carry this many samples before the doctor will call it
# slow — three frames of noise is not a diagnosis
MIN_LINK_MESSAGES = 5
SLOW_LINK_RATIO = 0.5     # below this × plane median → finding
ESCAPE_STORM_RATIO = 0.05  # escapes / coded frames above this → finding

# State-map analyzers (ISSUE 16)
HOT_KEY_SKEW_RATIO = 8.0      # top key bytes / median of the rest
MIN_HOT_KEY_BYTES = 1 << 20   # noise floor for skew/hotspot calls
MASTER_HOTSPOT_SHARE = 0.7    # one master serving this share of bytes
PULL_AMP_RATIO = 3.0          # total chunk pulls / first-time pulls
MIN_PULL_CHUNKS = 32          # pulls below this are not a pattern
MIN_LOCK_STALLS = 2           # one slow acquire is not a convoy

# State-replication analyzer (ISSUE 19)
REPLICATION_LAG_BYTES = 1 << 20  # acked-but-unforwarded bytes to flag

# CPU-profile analyzers (ISSUE 18)
CPU_HOTSPOT_SHARE = 0.35      # one stack's share of its host's CPU
MIN_HOTSPOT_CPU_MS = 500.0    # noise floor: below this, no hotspot call
GIL_PRESSURE_HIGH = 0.25      # sampler-drift pressure gauge threshold
# Avg runnable threads to call saturation. 0.5, not 1.0: the census
# counts threads that burned >= half a sample window, and one core can
# only sustain ~1 such thread — so on a 1-core host the LIFETIME
# average tops out near the busy fraction and never reaches 1.0.
MIN_GIL_RUNNABLE = 0.5
MIN_PROFILE_SAMPLES = 50      # samples before the profile is evidence
SAMPLER_STARVED_RATIO = 0.6   # samples/expected below this → starved


# ---------------------------------------------------------------------------
# Ingestion
# ---------------------------------------------------------------------------

def parse_prometheus(text: str) -> dict[str, list[tuple[dict, float]]]:
    """Minimal exposition-format parser: name → [(labels, value)].
    Histogram series keep their _bucket/_sum/_count suffixed names."""
    out: dict[str, list[tuple[dict, float]]] = {}
    label_re = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')
    for line in (text or "").splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            metric, value_s = line.rsplit(" ", 1)
            value = float(value_s)
        except ValueError:
            continue
        if "{" in metric:
            name, _, rest = metric.partition("{")
            labels = {m.group(1): m.group(2).replace('\\"', '"')
                      for m in label_re.finditer(rest)}
        else:
            name, labels = metric, {}
        out.setdefault(name, []).append((labels, value))
    return out


def fetch_live(base_url: str, timeout: float = 10.0) -> dict:
    """Scrape every source from a live planner endpoint. A failing
    source becomes None (the checks degrade, the doctor still runs)."""
    import urllib.request

    base = base_url.rstrip("/")
    sources: dict = {}
    for name in SOURCES:
        url = f"{base}/{name}"
        try:
            with urllib.request.urlopen(url, timeout=timeout) as resp:
                body = resp.read().decode()
        except Exception as e:  # noqa: BLE001 — diagnosis must degrade
            print(f"doctor: {url} unavailable ({e})", file=sys.stderr)
            sources[name] = None
            continue
        sources[name] = (parse_prometheus(body) if name == "metrics"
                         else json.loads(body))
    return sources


def load_dir(directory: str) -> dict:
    """Sources from dumped files (missing files → None)."""
    sources: dict = {}
    for name in SOURCES:
        sources[name] = None
        for candidate in _FILE_CANDIDATES[name]:
            path = os.path.join(directory, candidate)
            if not os.path.exists(path):
                continue
            try:
                with open(path) as f:
                    body = f.read()
            except OSError as e:
                print(f"doctor: cannot read {path}: {e}", file=sys.stderr)
                continue
            try:
                sources[name] = (parse_prometheus(body)
                                 if name == "metrics"
                                 else json.loads(body))
            except json.JSONDecodeError as e:
                print(f"doctor: bad JSON in {path}: {e}", file=sys.stderr)
            break
    return sources


# ---------------------------------------------------------------------------
# Checks — each returns findings: {"severity", "kind", "subject", "detail"}
# ---------------------------------------------------------------------------



def _link_gibs_rows(perf: dict) -> list[dict]:
    """Per-(src, dst, plane) best bandwidth evidence from the /perf link
    table: collapse codec/size-class cells onto their link, preferring
    the bytes-weighted average rate (the comm-matrix-comparable figure),
    falling back to the EWMA."""
    links: dict[tuple, dict] = {}
    for row in perf.get("links") or []:
        gibs = row.get("gibs_avg") or row.get("gibs_ewma")
        if gibs is None:
            continue
        key = (row.get("src"), row.get("dst"), row.get("plane"))
        cur = links.get(key)
        messages = row.get("messages") or 0
        nbytes = row.get("bytes") or 0
        if cur is None:
            links[key] = {"src": key[0], "dst": key[1], "plane": key[2],
                          "gibs": gibs, "messages": messages,
                          "bytes": nbytes}
        else:
            # Bytes-weighted merge across size classes/codecs
            tot = cur["bytes"] + nbytes
            if tot > 0:
                cur["gibs"] = ((cur["gibs"] * cur["bytes"]
                                + gibs * nbytes) / tot)
            cur["messages"] += messages
            cur["bytes"] = tot
    return list(links.values())


def check_slow_links(perf: dict | None) -> list[dict]:
    if not perf:
        return []
    findings = []
    rows = [r for r in _link_gibs_rows(perf)
            if (r["messages"] or 0) >= MIN_LINK_MESSAGES
            and r["dst"] not in ("mesh",)]
    by_plane: dict[str, list[dict]] = {}
    for r in rows:
        by_plane.setdefault(r["plane"], []).append(r)
    for plane, plane_rows in by_plane.items():
        if len(plane_rows) < 2:
            continue  # nothing to compare against
        med = _median([r["gibs"] for r in plane_rows])
        if med <= 0:
            continue
        for r in plane_rows:
            ratio = r["gibs"] / med
            if ratio < SLOW_LINK_RATIO:
                findings.append({
                    "kind": "slow_link",
                    "severity": min(95.0, 50.0 + 45.0 * (1.0 - ratio)),
                    "subject": f"{r['src']}→{r['dst']} ({plane})",
                    "detail": (f"{r['gibs']:.3f} GiB/s vs plane median "
                               f"{med:.3f} ({ratio:.0%}); "
                               f"{r['messages']} msgs, "
                               f"{r['bytes'] >> 20} MiB"),
                })
    return findings


def _rank_host(topology: dict | None, rank, size=None) -> str | None:
    """Weak topology fallback when the perf row carries no host: only a
    world whose size matches disambiguates (the topology's worlds are
    keyed by app id, every world has ranks 0..n-1, and a bare rank
    number matches all of them — so without a size hint that matches
    exactly one world, no attribution is honest)."""
    candidates = []
    for world in (topology or {}).get("worlds", {}).values():
        if size is not None and world.get("size") != size:
            continue
        for host, ranks in (world.get("hosts") or {}).items():
            if int(rank) in [int(r) for r in ranks]:
                candidates.append(host)
    return candidates[0] if len(candidates) == 1 else None


def check_stragglers(perf: dict | None,
                     topology: dict | None) -> list[dict]:
    if not perf:
        return []
    findings = []
    for s in perf.get("stragglers") or []:
        skew_ms = (s.get("median_skew_s") or 0.0) * 1e3
        # Exact placement rides the /perf row itself (the merge knows
        # which host's telemetry carried each rank); topology is only
        # a weak fallback for older dumps
        host = s.get("host") or _rank_host(topology, s.get("rank"))
        where = f" on {host}" if host else ""
        findings.append({
            "kind": "straggler",
            "severity": min(90.0, 40.0 + 10.0 * min(5.0, skew_ms / 10.0)
                            + 5.0 * min(4, s.get("rounds_flagged", 0))),
            "subject": (f"rank {s.get('rank')}{where} "
                        f"(world {s.get('world')}, "
                        f"{s.get('collective')})"),
            "detail": (f"arrives {skew_ms:.1f} ms late (median skew) in "
                       f"{s.get('rounds_flagged')}/"
                       f"{s.get('rounds_seen')} rounds"),
        })
    return findings


def check_codec_escapes(metrics: dict | None) -> list[dict]:
    if not metrics:
        return []
    escapes = sum(v for _l, v in
                  metrics.get("faabric_codec_escapes_total", []))
    frames = sum(v for _l, v in
                 metrics.get("faabric_codec_frames_total", []))
    if frames < 20 or escapes <= 0:
        return []
    ratio = escapes / frames
    if ratio < ESCAPE_STORM_RATIO:
        return []
    reasons: dict[str, float] = {}
    for labels, v in metrics.get("faabric_codec_escapes_total", []):
        if v > 0:
            key = labels.get("reason", "?")
            reasons[key] = reasons.get(key, 0) + v
    top = sorted(reasons.items(), key=lambda kv: -kv[1])
    return [{
        "kind": "codec_escape_storm",
        "severity": min(85.0, 30.0 + 100.0 * ratio),
        "subject": "wire-codec plane",
        "detail": (f"{int(escapes)} full-frame escapes vs {int(frames)} "
                   f"coded frames ({ratio:.1%}); top reasons: "
                   + ", ".join(f"{k}={int(v)}" for k, v in top[:3])),
    }]


def check_healthz(healthz: dict | None) -> list[dict]:
    if not healthz:
        return []
    findings = []
    ingress = healthz.get("ingress") or {}
    shed = ingress.get("shedTotal") or 0
    admitted = ingress.get("admittedTotal") or 0
    if shed > 0:
        ratio = shed / max(1, shed + admitted)
        findings.append({
            "kind": "admission_shed",
            "severity": min(80.0, 25.0 + 100.0 * ratio),
            "subject": "ingress admission",
            "detail": (f"{shed} invocations shed vs {admitted} admitted "
                       f"({ratio:.1%}); queue "
                       f"{ingress.get('queueDepth')}/"
                       f"{ingress.get('queueMax')}"),
        })
    journal = healthz.get("journal") or {}
    if journal.get("enabled"):
        buffered = journal.get("bufferedRecords") or 0
        age = journal.get("lastFsyncAgeSeconds")
        interval = journal.get("fsyncIntervalSeconds") or 0.05
        pressured = buffered > 256 or (
            journal.get("dirty") and age is not None
            and age > max(1.0, 20 * interval))
        if pressured:
            findings.append({
                "kind": "journal_fsync_pressure",
                "severity": min(75.0, 30.0 + buffered / 32.0),
                "subject": "planner journal",
                "detail": (f"{buffered} buffered records, last fsync "
                           f"{age}s ago (interval {interval}s)"),
            })
    for row in healthz.get("hosts") or []:
        breaker = row.get("breaker") or {}
        if breaker.get("state") == "open":
            findings.append({
                "kind": "breaker_open",
                "severity": 88.0,
                "subject": f"host {row.get('host')}",
                "detail": (f"circuit breaker OPEN after "
                           f"{breaker.get('consecutiveFailures')} "
                           "consecutive failures — dispatches to this "
                           "host fail fast"),
            })
        age = row.get("keepAliveAgeSeconds")
        timeout = row.get("timeoutSeconds")
        if (age is not None and timeout
                and age > 0.8 * timeout):
            findings.append({
                "kind": "keepalive_at_risk",
                "severity": 70.0,
                "subject": f"host {row.get('host')}",
                "detail": (f"last keep-alive {age:.1f}s ago "
                           f"(expiry at {timeout}s) — about to be "
                           "expired and its work requeued"),
            })
    perf_block = healthz.get("perf") or {}
    agg_age = perf_block.get("lastAggregationAgeSeconds")
    if agg_age is not None and agg_age > 600:
        findings.append({
            "kind": "perf_stale",
            "severity": 20.0,
            "subject": "performance profiles",
            "detail": (f"last /perf aggregation {agg_age:.0f}s ago — "
                       "diagnosis below may be stale"),
        })
    return findings


def check_lifecycle(healthz: dict | None) -> list[dict]:
    """Dominant-phase ranking for the p99 end-to-end tail (ISSUE 14):
    the invocation ledger's per-phase digests, ranked by their own p99
    — in the mostly-serial invocation pipeline the phase with the
    fattest tail is what the e2e p99 is made of. Always reported when
    enough invocations folded (the attribution IS the diagnosis; the
    severity scales with how dominant the leader is)."""
    lifecycle = (healthz or {}).get("lifecycle") or {}
    if (lifecycle.get("count") or 0) < 20:
        return []
    dominant = lifecycle.get("dominant_p99") or []
    e2e = lifecycle.get("e2e") or {}
    if not dominant or not e2e:
        return []
    top = dominant[0]
    share = top.get("share_of_e2e_p99") or 0.0
    runners = ", ".join(
        f"{d.get('phase')}={d.get('p99_ms')}ms" for d in dominant[1:4])
    return [{
        "kind": "dominant_phase",
        "severity": min(65.0, 25.0 + 40.0 * min(1.0, share)),
        "subject": f"lifecycle phase '{top.get('phase')}'",
        "detail": (f"p99 e2e {e2e.get('p99_ms')} ms over "
                   f"{lifecycle.get('count')} invocations; "
                   f"'{top.get('phase')}' p99 {top.get('p99_ms')} ms "
                   f"({share:.0%} of the e2e p99)"
                   + (f"; then {runners}" if runners else "")),
    }]


def check_slo(healthz: dict | None) -> list[dict]:
    """Burning SLO targets (ISSUE 14): every declared target whose burn
    rate exceeds the threshold on ALL evaluation windows."""
    slo = (healthz or {}).get("slo") or {}
    findings = []
    for t in slo.get("targets") or []:
        windows = t.get("windows") or {}
        if not t.get("burning"):
            continue
        burns = ", ".join(f"{w}×{row.get('burn')}"
                          for w, row in sorted(windows.items()))
        findings.append({
            "kind": "slo_burn",
            "severity": 92.0,
            "subject": f"SLO {t.get('name')}",
            "detail": (f"burning its error budget on every window "
                       f"(burn rates: {burns}; budget "
                       f"{t.get('budget')}"
                       + (f", threshold {t.get('threshold_ms')} ms"
                          if t.get("threshold_ms") else "") + ")"),
        })
    return findings


def _series_points(timeseries: dict | None, host: str,
                   name: str) -> list[list]:
    hosts = (timeseries or {}).get("hosts") or {}
    return ((hosts.get(host) or {}).get("series") or {}).get(name) or []


def _slope_per_s(points: list[list]) -> float:
    """Least-squares slope of [[t, v], ...] (0 with <2 points)."""
    n = len(points)
    if n < 2:
        return 0.0
    ts = [p[0] for p in points]
    vs = [p[1] for p in points]
    mt, mv = sum(ts) / n, sum(vs) / n
    var = sum((t - mt) ** 2 for t in ts)
    if var <= 0:
        return 0.0
    return sum((t - mt) * (v - mv) for t, v in points) / var


def check_queue_trend(timeseries: dict | None) -> list[dict]:
    """Trends the point-in-time counters cannot show (ISSUE 14): an
    ingress queue that keeps GROWING (backlog outrunning the ticks —
    collapse in progress, not a burst), and free slots pinned at zero
    while a backlog holds (capacity exhaustion)."""
    findings = []
    depth = _series_points(timeseries, "planner", "ingress_depth")
    if len(depth) >= 5:
        head = [v for _t, v in depth[:3]]
        tail = [v for _t, v in depth[-3:]]
        start = sum(head) / len(head)
        end = sum(tail) / len(tail)
        slope = _slope_per_s(depth)
        if end >= 10 and end >= 2 * max(1.0, start) and slope > 0:
            findings.append({
                "kind": "queue_growth",
                "severity": min(90.0, 45.0 + 5.0 * min(8.0, slope)),
                "subject": "ingress admission queue",
                "detail": (f"depth grew {start:.0f} → {end:.0f} over "
                           f"{depth[-1][0] - depth[0][0]:.0f}s "
                           f"({slope:+.1f}/s) — backlog is outrunning "
                           "the scheduling ticks"),
            })
    free = _series_points(timeseries, "planner", "free_slots")
    if len(free) >= 5 and len(depth) >= 1:
        recent = [v for _t, v in free[-5:]]
        backlog = depth[-1][1] if depth else 0
        if max(recent) <= 0 and backlog > 0:
            findings.append({
                "kind": "capacity_exhausted",
                "severity": 78.0,
                "subject": "cluster capacity",
                "detail": (f"free-slot watermark pinned at 0 for the "
                           f"last {len(recent)} samples while "
                           f"{backlog:.0f} messages queue — add "
                           "capacity or shed harder"),
            })
    return findings


def check_profile_matrix_agreement(perf: dict | None,
                                   commmatrix: dict | None) -> list[dict]:
    """Cross-check: per source host, the profile store's bytes-weighted
    bulk rate vs the comm matrix's bytes/latency for the same host's
    outbound bulk rows. Large disagreement points at a broken feed, not
    a slow link — surfaced as its own finding."""
    if not perf or not commmatrix:
        return []
    findings = []
    per_host_rows: dict[str, list[dict]] = {}
    for r in _link_gibs_rows(perf):
        if r["plane"] == "bulk-tcp":
            per_host_rows.setdefault(r["src"], []).append(r)
    for host, rows in per_host_rows.items():
        cells = (commmatrix.get("hosts") or {}).get(host) or []
        # WIRE bytes, not bytes_raw: the profile store observes what
        # crossed the wire, so a compressed link's honest comparison is
        # wire/latency on both sides (raw/latency would differ by the
        # compression ratio and cry wolf on every delta link)
        m_bytes = sum(c.get("bytes", 0)
                      for c in cells if c.get("plane") == "bulk-tcp")
        m_lat = sum(c.get("lat_sum", 0.0) for c in cells
                    if c.get("plane") == "bulk-tcp")
        if m_lat <= 0 or m_bytes <= 0:
            continue
        matrix_gibs = (m_bytes / m_lat) / (1 << 30)
        tot_bytes = sum(r["bytes"] for r in rows)
        if tot_bytes <= 0:
            continue
        profile_gibs = sum(r["gibs"] * r["bytes"]
                           for r in rows) / tot_bytes
        if matrix_gibs <= 0:
            continue
        err = abs(profile_gibs - matrix_gibs) / matrix_gibs
        if err > 0.5:
            findings.append({
                "kind": "profile_matrix_disagreement",
                "severity": 35.0,
                "subject": f"host {host} (bulk-tcp)",
                "detail": (f"profile says {profile_gibs:.2f} GiB/s, "
                           f"comm matrix {matrix_gibs:.2f} "
                           f"({err:.0%} apart) — check the feed points"),
            })
    return findings


def _statemap_keys(statemap: dict | None) -> list[dict]:
    """Ranked key rows minus the cardinality-overflow bucket."""
    return [r for r in (statemap or {}).get("keys") or []
            if r.get("key") != "other"]


def check_hot_key_skew(statemap: dict | None) -> list[dict]:
    """One key's byte traffic dwarfing the median of the rest (ISSUE
    16): the rebuild's replicate-or-repartition candidate. Needs at
    least three keys — skew against nothing is not a diagnosis."""
    rows = [r for r in _statemap_keys(statemap)
            if (r.get("bytes_total") or 0) > 0]
    if len(rows) < 3:
        return []
    top = rows[0]  # aggregate_statemap ranks by -bytes_total
    med = _median([r.get("bytes_total") or 0 for r in rows[1:]])
    if top["bytes_total"] < MIN_HOT_KEY_BYTES or med <= 0:
        return []
    ratio = top["bytes_total"] / med
    if ratio < HOT_KEY_SKEW_RATIO:
        return []
    origins = sorted((top.get("by_origin") or {}).items(),
                     key=lambda kv: -kv[1].get("bytes", 0))
    origin_s = ", ".join(f"{h}={o.get('bytes', 0) >> 20}MiB"
                         for h, o in origins[:3])
    return [{
        "kind": "hot_key_skew",
        "severity": min(82.0, 45.0 + ratio),
        "subject": f"state key {top.get('key')}",
        "detail": (f"{top['bytes_total'] >> 20} MiB of traffic vs "
                   f"{max(1, int(med)) >> 20} MiB median across "
                   f"{len(rows) - 1} other key(s) ({ratio:.0f}×); "
                   f"master {top.get('master') or '?'}"
                   + (f"; by origin: {origin_s}" if origin_s else "")),
    }]


def check_master_hotspot(statemap: dict | None) -> list[dict]:
    """One host serving most of the cluster's state bytes as master
    (ISSUE 16). Served bytes per master = the traffic of every key it
    masters; only meaningful once a second host participates."""
    rows = _statemap_keys(statemap)
    served: dict[str, int] = {}
    for r in rows:
        master = r.get("master")
        if master:
            served[master] = (served.get(master, 0)
                              + (r.get("bytes_total") or 0))
    hosts = (statemap or {}).get("hosts") or {}
    involved = set(served) | {h for h, row in hosts.items()
                              if (row.get("origin_bytes") or 0) > 0}
    total = sum(served.values())
    if len(involved) < 2 or total < MIN_HOT_KEY_BYTES:
        return []
    top_host, top_bytes = max(served.items(), key=lambda kv: kv[1])
    share = top_bytes / total
    if share < MASTER_HOTSPOT_SHARE:
        return []
    n_keys = sum(1 for r in rows if r.get("master") == top_host)
    return [{
        "kind": "master_hotspot",
        "severity": min(80.0, 40.0 + 40.0 * share),
        "subject": f"host {top_host}",
        "detail": (f"masters {n_keys} key(s) carrying "
                   f"{top_bytes >> 20} MiB of the cluster's "
                   f"{total >> 20} MiB state traffic ({share:.0%}) "
                   f"across {len(involved)} involved host(s) — "
                   "rebalance mastership or replicate the hot keys"),
    }]


def check_pull_amplification(statemap: dict | None) -> list[dict]:
    """Replicas repeatedly re-pulling chunks they already pulled clean
    (ISSUE 16): total chunk pulls far above first-time pulls means the
    full-image invalidation is throwing away clean chunks a future
    delta-pull path would keep."""
    findings = []
    for r in _statemap_keys(statemap):
        total = r.get("pull_chunks_total") or 0
        fresh = r.get("pull_chunks_fresh") or 0
        if total < MIN_PULL_CHUNKS or fresh <= 0:
            continue
        amp = total / fresh
        if amp < PULL_AMP_RATIO:
            continue
        findings.append({
            "kind": "pull_amplification",
            "severity": min(75.0, 30.0 + amp),
            "subject": f"state key {r.get('key')}",
            "detail": (f"{total} chunk pulls for {fresh} first-time "
                       f"chunks ({amp:.1f}× amplification) — replicas "
                       "keep re-pulling clean chunks; consider "
                       "version-gated or delta pulls"),
        })
    return findings


def check_lock_convoy(statemap: dict | None) -> list[dict]:
    """Global-lock waits on a key repeatedly stalling past
    FAABRIC_STATE_LOCK_STALL_MS (ISSUE 16): writers convoying on one
    lock serialise the cluster no matter how fast the links are."""
    findings = []
    for r in _statemap_keys(statemap):
        stalls = r.get("lock_stalls") or 0
        waits = r.get("lock_waits") or 0
        if stalls < MIN_LOCK_STALLS:
            continue
        ratio = stalls / max(1, waits)
        findings.append({
            "kind": "lock_convoy",
            "severity": min(85.0, 45.0 + stalls + 40.0 * ratio),
            "subject": f"state key {r.get('key')}",
            "detail": (f"{stalls} of {waits} global-lock waits stalled "
                       f"past the threshold ({ratio:.0%}) — writers are "
                       "convoying; shard the key or batch the locked "
                       "section"),
        })
    return findings


def check_state_unreplicated(statemap: dict | None) -> list[dict]:
    """A fenced key (epoch > 0 means the replication plane placed it)
    running without a live backup, or with a backup lagging the acked
    bytes (ISSUE 19): one more crash loses acknowledged writes. Epoch-0
    keys are exempt — FAABRIC_STATE_REPLICAS=0 opted them out."""
    findings = []
    for r in _statemap_keys(statemap):
        epoch = r.get("epoch") or 0
        if epoch <= 0:
            continue
        backup = r.get("backup") or ""
        lag = r.get("replication_lag") or 0
        if not backup:
            findings.append({
                "kind": "state_unreplicated",
                "severity": 78.0,
                "subject": f"state key {r.get('key')}",
                "detail": (f"fenced at epoch {epoch} on master "
                           f"{r.get('master') or '?'} with NO backup "
                           "host — acked writes have a single copy; "
                           "one more crash loses them (add hosts or "
                           "check the planner's backup election)"),
            })
        elif lag >= REPLICATION_LAG_BYTES:
            findings.append({
                "kind": "state_unreplicated",
                "severity": 60.0,
                "subject": f"state key {r.get('key')}",
                "detail": (f"backup {backup} lags the master "
                           f"{r.get('master') or '?'} by {lag >> 20} "
                           "MiB of acked bytes (anti-entropy still "
                           "streaming, or forwards failing) — the key "
                           "is not crash-safe until the lag drains"),
            })
    return findings


def check_cpu_hotspot(profile: dict | None) -> list[dict]:
    """One collapsed stack burning an outsized share of its host's
    sampled CPU (ISSUE 18): the direct evidence the planner-shard /
    native-transport ROADMAP items need — WHICH frames to move, not
    just that the process is busy."""
    findings = []
    if not profile:
        return findings
    per_host: dict[str, list[dict]] = {}
    for row in profile.get("stacks") or []:
        per_host.setdefault(row.get("host", "?"), []).append(row)
    for host, rows in sorted(per_host.items()):
        host_cpu = sum(r.get("cpu_ms") or 0.0 for r in rows)
        if host_cpu < MIN_HOTSPOT_CPU_MS:
            continue
        top = max(rows, key=lambda r: r.get("cpu_ms") or 0.0)
        share = (top.get("cpu_ms") or 0.0) / host_cpu
        if share < CPU_HOTSPOT_SHARE:
            continue
        frames = top.get("frames") or ["?"]
        findings.append({
            "kind": "cpu_hotspot",
            "severity": min(90.0, 50.0 + 40.0 * share),
            "subject": f"{host} thread {top.get('class', '?')}",
            "detail": (f"one stack burns {share:.0%} of the host's "
                       f"{host_cpu:.0f} ms sampled CPU — hot frame "
                       f"{frames[-1]}; move or shard it before adding "
                       "threads (they'd contend, not help)"),
        })
    return findings


def check_gil_saturation(profile: dict | None,
                         metrics: dict | None = None) -> list[dict]:
    """A process whose sampler wakeups drift late while multiple
    threads stay runnable (ISSUE 18): the threads are serialized on the
    interpreter, so adding more buys queueing, not throughput. Cross-
    checked against the lockcheck hold-time histogram when present — a
    lock convoy shows the same drift but names a lock site instead."""
    findings = []
    if not profile:
        return findings
    # Mean lockcheck hold time, if the metrics source carries it: long
    # holds mean the stall is A lock, not THE lock (the GIL)
    hold_note = ""
    if metrics:
        hold_sum = sum(v for _, v in
                       metrics.get("faabric_lock_hold_seconds_sum", []))
        hold_cnt = sum(v for _, v in
                       metrics.get("faabric_lock_hold_seconds_count",
                                   []))
        if hold_cnt > 0 and hold_sum / hold_cnt > 0.001:
            hold_note = (f" (lockcheck mean hold "
                         f"{1000.0 * hold_sum / hold_cnt:.1f} ms — "
                         "suspect a lock convoy before the GIL)")
    hosts_meta = profile.get("hosts") or {}
    for host, gil in sorted((profile.get("gil") or {}).items()):
        pressure = gil.get("pressure") or 0.0
        runnable = gil.get("runnable_avg") or 0.0
        samples = (hosts_meta.get(host) or {}).get("samples") or 0
        if samples < MIN_PROFILE_SAMPLES:
            continue
        if pressure < GIL_PRESSURE_HIGH or runnable < MIN_GIL_RUNNABLE:
            continue
        findings.append({
            "kind": "gil_saturation",
            "severity": min(88.0, 40.0 + 50.0 * pressure),
            "subject": f"{host} (pid {(hosts_meta.get(host) or {}).get('pid')})",
            "detail": (f"sampler wakeups drift {pressure:.0%} of the "
                       f"interval late with {runnable:.1f} threads "
                       "runnable on average — the process is "
                       "interpreter-bound; shard the work across "
                       f"processes, not threads{hold_note}"),
        })
    return findings


def check_sampler_starved(profile: dict | None) -> list[dict]:
    """The profiler itself missing most of its wakeups (ISSUE 18):
    every other profile finding from that host is undercounted, so say
    so — low severity, but it gates trust in the rest."""
    findings = []
    if not profile:
        return findings
    for host, meta in sorted((profile.get("hosts") or {}).items()):
        expected = meta.get("expected_samples") or 0
        samples = meta.get("samples") or 0
        if expected < MIN_PROFILE_SAMPLES:
            continue
        ratio = samples / expected
        if ratio >= SAMPLER_STARVED_RATIO:
            continue
        findings.append({
            "kind": "sampler_starved",
            "severity": 25.0,
            "subject": f"{host} profiler",
            "detail": (f"only {samples} of {expected} scheduled "
                       f"samples ran ({ratio:.0%}) — the box is "
                       "saturated enough to starve a 25 ms timer; "
                       "this host's profile UNDERCOUNTS its hotspots"),
        })
    return findings


def diagnose(sources: dict) -> list[dict]:
    """Every check over whatever sources are present, ranked most-severe
    first."""
    findings: list[dict] = []
    findings += check_slow_links(sources.get("perf"))
    findings += check_stragglers(sources.get("perf"),
                                 sources.get("topology"))
    findings += check_codec_escapes(sources.get("metrics"))
    findings += check_healthz(sources.get("healthz"))
    findings += check_lifecycle(sources.get("healthz"))
    findings += check_slo(sources.get("healthz"))
    findings += check_queue_trend(sources.get("timeseries"))
    findings += check_profile_matrix_agreement(sources.get("perf"),
                                               sources.get("commmatrix"))
    findings += check_hot_key_skew(sources.get("statemap"))
    findings += check_master_hotspot(sources.get("statemap"))
    findings += check_pull_amplification(sources.get("statemap"))
    findings += check_lock_convoy(sources.get("statemap"))
    findings += check_state_unreplicated(sources.get("statemap"))
    findings += check_cpu_hotspot(sources.get("profile"))
    findings += check_gil_saturation(sources.get("profile"),
                                     sources.get("metrics"))
    findings += check_sampler_starved(sources.get("profile"))
    findings.sort(key=lambda f: -f["severity"])
    return findings


def render(findings: list[dict], top: int = 0) -> str:
    if not findings:
        return "doctor: no findings — cluster looks healthy"
    rows = findings[:top] if top else findings
    lines = [f"doctor: {len(findings)} finding(s)"
             + (f", top {len(rows)}:" if top and top < len(findings)
                else ":")]
    for i, f in enumerate(rows, 1):
        lines.append(f"{i:3d}. [{f['severity']:5.1f}] "
                     f"{f['kind']:<28} {f['subject']}")
        lines.append(f"      {f['detail']}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Selftest fixture
# ---------------------------------------------------------------------------

def selftest_sources() -> dict:
    """A synthetic 3-host cluster with one planted slow link (hA→hC at
    ~1/10 of the plane median), one planted straggler (rank 5 arriving
    ~40 ms late every round), a codec escape storm, a run-dominated
    lifecycle tail, a burning p99 latency SLO, an ingress queue
    growing through the time-series window (ISSUE 14), and a state map
    with a hot key on a master hotspot, an amplified puller and a lock
    convoy (ISSUE 16)."""
    def link(src, dst, gibs, messages=200, nbytes=512 << 20):
        return {"src": src, "dst": dst, "plane": "bulk-tcp",
                "codec": "raw", "size_class": "1MiB",
                "messages": messages, "bytes": nbytes,
                "gibs_avg": gibs, "gibs_ewma": gibs}

    rounds = {}
    base_ts = 1000.0
    for i in range(8):
        rd = {}
        for rank in range(8):
            # End-aligned synchronous rounds: rank 5 idles 40 ms before
            # entering, everyone else's total absorbs the wait
            late = 0.040 if rank == 5 else 0.0
            rd[str(rank)] = {"enter_ts": base_ts + i * 0.1 + late,
                             "total": 0.055 - late}
        rounds[str(i)] = rd
    from faabric_tpu.telemetry import find_stragglers

    stragglers = [{"world": 900, "collective": "allreduce",
                   "rank": int(r), "host": "hC", **st}
                  for r, st in find_stragglers(rounds).items()]
    perf = {
        "links": [link("hA", "hB", 2.2), link("hB", "hA", 2.0),
                  link("hB", "hC", 2.4), link("hC", "hB", 2.1),
                  link("hC", "hA", 1.9),
                  link("hA", "hC", 0.21)],  # the planted slow link
        "collectives": [{"world": 900, "collective": "allreduce",
                         "completed": 64, "rounds": rounds,
                         "stragglers": {"5": stragglers[0]}
                         if stragglers else {}}],
        "stragglers": stragglers,
        "hosts": ["hA", "hB", "hC"],
    }
    metrics = {
        "faabric_codec_frames_total": [({"codec": "delta"}, 900.0)],
        "faabric_codec_escapes_total": [({"reason": "nack"}, 120.0),
                                        ({"reason": "crc"}, 30.0)],
    }
    def phase(p50, p99):
        return {"p50_ms": p50, "p90_ms": p99 * 0.8, "p99_ms": p99,
                "mean_ms": p50, "count": 4000}

    lifecycle_phases = {
        "ingress_queue": phase(0.4, 2.0),
        "schedule": phase(0.3, 1.1),
        "dispatch": phase(0.2, 0.9),
        "executor_queue": phase(1.0, 4.0),
        "run": phase(20.0, 61.0),  # the planted dominant phase
        "result_push": phase(0.3, 1.5),
        "record": phase(0.5, 2.5),
    }
    e2e = phase(24.0, 68.0)
    dominant = sorted(lifecycle_phases.items(),
                      key=lambda kv: -kv[1]["p99_ms"])
    healthz = {
        "status": "ok",
        "hosts": [{"host": h, "keepAliveAgeSeconds": 1.0,
                   "timeoutSeconds": 30, "breaker": None}
                  for h in ("hA", "hB", "hC")],
        "ingress": {"shedTotal": 0, "admittedTotal": 5000,
                    "queueDepth": 3, "queueMax": 1024},
        "journal": {"enabled": True, "bufferedRecords": 2,
                    "dirty": False, "lastFsyncAgeSeconds": 0.01,
                    "fsyncIntervalSeconds": 0.05},
        "perf": {"lastAggregationAgeSeconds": 5.0},
        "lifecycle": {
            "count": 4000, "failed": 0, "e2e": e2e,
            "phases": lifecycle_phases,
            "dominant_p99": [
                {"phase": label, "p99_ms": row["p99_ms"],
                 "share_of_e2e_p99": round(row["p99_ms"]
                                           / e2e["p99_ms"], 4)}
                for label, row in dominant],
        },
        "slo": {
            "spec": "p99_e2e_ms=50,error_rate=0.001",
            "burnThreshold": 2.0, "windowsSeconds": [60, 600],
            "ignored": [],
            "targets": [
                {"name": "p99_e2e_ms", "kind": "latency",
                 "budget": 0.01, "threshold_ms": 50.0, "burning": True,
                 "windows": {"60s": {"total": 800, "bad": 40,
                                     "burn": 5.0},
                             "600s": {"total": 4000, "bad": 160,
                                      "burn": 4.0}}},
                {"name": "error_rate", "kind": "error", "budget": 0.001,
                 "threshold_ms": None, "burning": False,
                 "windows": {"60s": {"total": 800, "bad": 0,
                                     "burn": 0.0},
                             "600s": {"total": 4000, "bad": 0,
                                      "burn": 0.0}}}],
        },
    }
    # The planted queue growth: depth ramps 2 → 60 across the window
    ts0 = 2000.0
    depth_pts = [[ts0 + i, 2.0 + 2.0 * i] for i in range(30)]
    timeseries = {"hosts": {"planner": {"series": {
        "ingress_depth": depth_pts,
        "free_slots": [[ts0 + i, max(0.0, 8.0 - i)] for i in range(30)],
    }}}}
    topology = {"hosts": {}, "worlds": {
        "900": {"size": 8,
                "hosts": {"hA": [0, 1, 2, 3], "hC": [4, 5, 6, 7]}}}}

    # ISSUE 16 plants, built through the real merge so the selftest
    # also exercises aggregate_statemap: demo/hot dominates the byte
    # traffic (hot-key skew) and is mastered on hA, which thereby
    # serves ~95% of the cluster's state bytes (master hotspot);
    # demo/amplified re-pulls its chunks 50× (pull amplification);
    # demo/locky stalls 24 of 120 lock waits (lock convoy).
    from faabric_tpu.telemetry import aggregate_statemap

    def krow(key, **kw):
        row = {"key": key, "master": "", "size": 0, "is_master": False,
               "ops_total": 0, "bytes_total": 0,
               "local_reads": 0, "remote_reads": 0,
               "pull_chunks_total": 0, "pull_chunks_fresh": 0,
               "lock_waits": 0, "lock_stalls": 0}
        row.update(kw)
        return row

    def block(*rows):
        return {"statestats": {"keys": list(rows), "snapshots": {},
                               "registry_bytes": 0, "max_keys": 256}}

    # ISSUE 19 plants: demo/fragile is fenced (epoch 3) but has no
    # backup host (state_unreplicated); demo/hot is fenced AND backed
    # up with zero lag and must NOT be flagged.
    state_tel = {
        "hA": block(
            krow("demo/hot", is_master=True, size=64 << 20,
                 ops_total=5000, bytes_total=1 << 30, local_reads=5000,
                 backup="hB", epoch=1, replication_lag=0),
            krow("demo/fragile", is_master=True, size=4 << 20,
                 ops_total=40, bytes_total=8 << 20, local_reads=40,
                 backup="", epoch=3, replication_lag=4 << 20),
            krow("demo/amplified", is_master=True, size=8 << 20,
                 ops_total=50, bytes_total=32 << 20, local_reads=50)),
        "hB": block(
            krow("demo/hot", master="hA", size=64 << 20, ops_total=3000,
                 bytes_total=1 << 30, remote_reads=3000,
                 pull_chunks_total=600, pull_chunks_fresh=580),
            krow("demo/amplified", master="hA", ops_total=900,
                 bytes_total=200 << 20, remote_reads=900,
                 pull_chunks_total=5000, pull_chunks_fresh=100),
            krow("demo/locky", master="hC", ops_total=120,
                 bytes_total=1 << 20, lock_waits=120, lock_stalls=24)),
        "hC": block(
            krow("demo/locky", is_master=True, size=1 << 20,
                 ops_total=10, bytes_total=1 << 20, local_reads=10),
            krow("demo/cold0", is_master=True, size=1 << 20,
                 ops_total=20, bytes_total=2 << 20, local_reads=20),
            krow("demo/cold1", is_master=True, size=1 << 20,
                 ops_total=20, bytes_total=2 << 20, local_reads=20)),
    }
    statemap = aggregate_statemap(state_tel)

    # ISSUE 18 plants, built through the real aggregate_profile so the
    # selftest also exercises the profile merge: hA burns ~70% of its
    # CPU in one planner/tick stack (cpu_hotspot) while its sampler
    # drifts 60% late with 3 runnable threads (gil_saturation); hB is
    # idle and must yield ZERO profile findings; hC's sampler ran only
    # 300 of 1000 scheduled wakeups (sampler_starved).
    from faabric_tpu.telemetry import aggregate_profile

    def pstack(cls, frames, samples, cpu_ms):
        return {"class": cls, "frames": frames, "samples": samples,
                "cpu_ms": cpu_ms}

    def psnap(samples, expected, stacks, pressure, runnable_avg,
              pid=100):
        return {
            "enabled": True, "pid": pid, "interval_ms": 25.0,
            "samples": samples, "expected_samples": expected,
            "wall_s": expected * 0.025, "sample_cost_ms": 0.1,
            "overhead_pct": 0.4, "nodes": 64, "max_nodes": 4096,
            "dropped_frames": 0,
            "classes": {s["class"]: {"samples": s["samples"],
                                     "cpu_ms": s["cpu_ms"],
                                     "threads_now": 1}
                        for s in stacks},
            "stacks": stacks,
            "gil": {"pressure": pressure,
                    "drift_ratio_avg": pressure,
                    "drift_ratio_max": pressure * 2,
                    "runnable_now": int(runnable_avg),
                    "runnable_avg": runnable_avg,
                    "late_samples": int(samples * pressure)},
        }

    hot_frames = ["_tick_loop (ingress/tick.py:330)",
                  "call_batch_group (planner/planner.py:700)",
                  "_pack_decision (planner/planner.py:812)"]
    profile_tel = {
        "hA": {"profile": psnap(
            1200, 1250,
            [pstack("planner/tick", hot_frames, 900, 2100.0),
             pstack("transport/worker@planner-server-sync",
                    ["_worker_loop (transport/server.py:160)"],
                    200, 600.0),
             pstack("main", ["serve (runner/runtime.py:40)"],
                    100, 80.0)],
            pressure=0.6, runnable_avg=3.2, pid=101)},
        # Idle host: tiny CPU, calm sampler — must stay finding-free
        "hB": {"profile": psnap(
            1200, 1220,
            [pstack("main", ["wait (threading.py:320)"], 1150, 40.0),
             pstack("telemetry/sampler",
                    ["do_work (telemetry/timeseries.py:200)"],
                    50, 12.0)],
            pressure=0.02, runnable_avg=0.1, pid=102)},
        "hC": {"profile": psnap(
            300, 1000,
            [pstack("executor/pool@e1-0",
                    ["run (executor/executor.py:250)"], 280, 260.0)],
            pressure=0.1, runnable_avg=0.5, pid=103)},
    }
    profile = aggregate_profile(profile_tel)
    return {"perf": perf, "metrics": metrics, "commmatrix": None,
            "healthz": healthz, "topology": topology,
            "timeseries": timeseries, "statemap": statemap,
            "profile": profile}


def run_selftest() -> int:
    findings = diagnose(selftest_sources())
    print(render(findings, top=14))
    top_kinds = [f["kind"] for f in findings[:7]]
    all_kinds = [f["kind"] for f in findings]
    problems = []
    slow = [f for f in findings if f["kind"] == "slow_link"]
    if not slow or "hA→hC" not in slow[0]["subject"]:
        problems.append("planted slow link hA→hC not found")
    stragglers = [f for f in findings if f["kind"] == "straggler"]
    if not stragglers or "rank 5" not in stragglers[0]["subject"]:
        problems.append("planted straggler rank 5 not found")
    if "hC" not in (stragglers[0]["subject"] if stragglers else ""):
        problems.append("straggler not attributed to its host hC")
    if "codec_escape_storm" not in all_kinds:
        problems.append("planted escape storm not found")
    if "slow_link" not in top_kinds or "straggler" not in top_kinds:
        problems.append(f"planted faults not in top findings: {top_kinds}")
    # ISSUE 14 analyzers: the run-dominated lifecycle tail, the burning
    # latency SLO and the growing ingress queue must all be found
    dominant = [f for f in findings if f["kind"] == "dominant_phase"]
    if not dominant or "'run'" not in dominant[0]["subject"]:
        problems.append("planted dominant phase 'run' not found")
    slo_burns = [f for f in findings if f["kind"] == "slo_burn"]
    if not slo_burns or "p99_e2e_ms" not in slo_burns[0]["subject"]:
        problems.append("planted burning SLO p99_e2e_ms not found")
    if "slo_burn" not in top_kinds:
        problems.append(f"slo_burn not in top findings: {top_kinds}")
    if "queue_growth" not in all_kinds:
        problems.append("planted ingress queue growth not found")
    if "capacity_exhausted" not in all_kinds:
        problems.append("planted capacity exhaustion not found")
    # ISSUE 16 analyzers: the hot key, its master hotspot, the
    # amplified puller and the lock convoy must all be found
    hot = [f for f in findings if f["kind"] == "hot_key_skew"]
    if not hot or "demo/hot" not in hot[0]["subject"]:
        problems.append("planted hot key demo/hot not found")
    hotspot = [f for f in findings if f["kind"] == "master_hotspot"]
    if not hotspot or "hA" not in hotspot[0]["subject"]:
        problems.append("planted master hotspot hA not found")
    amp = [f for f in findings if f["kind"] == "pull_amplification"]
    if not amp or "demo/amplified" not in amp[0]["subject"]:
        problems.append("planted pull amplification not found")
    convoy = [f for f in findings if f["kind"] == "lock_convoy"]
    if not convoy or "demo/locky" not in convoy[0]["subject"]:
        problems.append("planted lock convoy demo/locky not found")
    # ISSUE 19 analyzer: the fenced-but-backupless key must be found;
    # the fenced-and-replicated key must not produce a false positive
    unrep = [f for f in findings if f["kind"] == "state_unreplicated"]
    if not unrep or "demo/fragile" not in unrep[0]["subject"]:
        problems.append("planted unreplicated key demo/fragile "
                        "not found")
    if any("demo/hot" in f["subject"] for f in unrep):
        problems.append("replicated key demo/hot wrongly flagged "
                        "as unreplicated")
    # ISSUE 18 analyzers: the hA tick hotspot, hA's GIL saturation and
    # hC's starved sampler must be found; idle hB must stay clean
    hotspots = [f for f in findings if f["kind"] == "cpu_hotspot"]
    if not hotspots or "hA" not in hotspots[0]["subject"]:
        problems.append("planted cpu hotspot on hA not found")
    elif "planner/tick" not in hotspots[0]["subject"]:
        problems.append("hotspot not attributed to planner/tick: "
                        + hotspots[0]["subject"])
    gil = [f for f in findings if f["kind"] == "gil_saturation"]
    if not gil or "hA" not in gil[0]["subject"]:
        problems.append("planted GIL saturation on hA not found")
    starved = [f for f in findings if f["kind"] == "sampler_starved"]
    if not starved or "hC" not in starved[0]["subject"]:
        problems.append("planted starved sampler on hC not found")
    profile_kinds = ("cpu_hotspot", "gil_saturation", "sampler_starved")
    hb_noise = [f for f in findings
                if f["kind"] in profile_kinds and "hB" in f["subject"]]
    if hb_noise:
        problems.append(f"idle host hB produced profile findings: "
                        f"{[f['kind'] for f in hb_noise]}")
    if problems:
        print("doctor selftest FAILED:", "; ".join(problems))
        return 1
    print("doctor selftest OK")
    return 0


# ---------------------------------------------------------------------------

def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m faabric_tpu.runner.doctor",
        description="Ranked cluster performance/health diagnosis")
    parser.add_argument("--url", help="live planner endpoint base URL "
                        "(e.g. http://127.0.0.1:8080)")
    parser.add_argument("--dir", help="directory of dumped sources "
                        "(perf.json, metrics.txt, commmatrix.json, "
                        "healthz.json, topology.json)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable findings")
    parser.add_argument("--top", type=int, default=12,
                        help="show only the top N findings (0 = all)")
    parser.add_argument("--selftest", action="store_true",
                        help="run on the built-in synthetic cluster and "
                        "verify the planted faults are found")
    args = parser.parse_args(argv)

    if args.selftest:
        return run_selftest()
    if args.url:
        sources = fetch_live(args.url)
    elif args.dir:
        sources = load_dir(args.dir)
    else:
        parser.error("one of --url, --dir or --selftest is required")
        return 2
    findings = diagnose(sources)
    if args.json:
        print(json.dumps({"findings": findings}, indent=1))
    else:
        print(render(findings, top=args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
