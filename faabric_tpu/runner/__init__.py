"""Worker runtime assembly (reference src/runner)."""

from faabric_tpu.runner.runtime import WorkerRuntime

__all__ = ["WorkerRuntime"]
