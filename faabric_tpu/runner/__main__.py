"""Deployment entry points — the planner_server / worker binaries analog
(reference src/planner/planner_server.cpp:9-43, src/runner/FaabricMain.cpp).

    python -m faabric_tpu.runner planner [--port-offset N] [--http-port P]
    python -m faabric_tpu.runner worker --host IP [--slots N] [--devices N]
    python -m faabric_tpu.runner redis [--port P]

The planner role serves RPC + its snapshot server + the REST endpoint; the
worker boots a full WorkerRuntime (function/PTP/snapshot/state servers,
keep-alive registration); the redis role runs the in-repo RESP server
(the docker-compose `redis` service analog for STATE_MODE=redis
deployments without an external Redis). All run until SIGTERM/SIGINT.
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading

from faabric_tpu.util.crash import install_crash_handler
from faabric_tpu.util.logging import get_logger

logger = get_logger("faabric_tpu.runner")


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="faabric_tpu.runner")
    sub = parser.add_subparsers(dest="role", required=True)

    p_planner = sub.add_parser("planner")
    p_planner.add_argument("--port-offset", type=int, default=0)
    p_planner.add_argument("--http-port", type=int, default=0,
                           help="REST endpoint port (0 = config default)")

    p_redis = sub.add_parser("redis")
    p_redis.add_argument("--port", type=int, default=6379)
    p_redis.add_argument("--bind", default="127.0.0.1")

    p_worker = sub.add_parser("worker")
    p_worker.add_argument("--host", default="",
                          help="this worker's identity (default: primary IP)")
    p_worker.add_argument("--slots", type=int, default=None,
                          help="execution slots (default: one per usable core; 0 = observer host)")
    p_worker.add_argument("--devices", type=int, default=0)
    p_worker.add_argument("--planner-host", default=None)

    args = parser.parse_args(argv)
    install_crash_handler()

    stop = threading.Event()

    def _on_signal(signum, _frame):
        # Dump the flight ring while the process state is still intact —
        # but the shutdown signal must survive ANY flight failure. The
        # recorded kind names the ACTUAL signal (a post-mortem must not
        # claim a SIGTERM for an operator's Ctrl-C).
        try:
            from faabric_tpu.telemetry import flight_dump, flight_record

            name = signal.Signals(signum).name.lower()
            flight_record(name, role=args.role)
            flight_dump(name)
        except Exception:  # noqa: BLE001 — never lose the shutdown
            pass
        finally:
            stop.set()

    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, _on_signal)

    if args.role == "planner":
        from faabric_tpu.endpoint import PlannerHttpEndpoint
        from faabric_tpu.planner import PlannerServer

        server = PlannerServer(port_offset=args.port_offset)
        server.start()
        endpoint = PlannerHttpEndpoint(
            port=args.http_port or None)
        endpoint.start()
        logger.info("Planner up (rpc offset %d, http :%d)", args.port_offset,
                    endpoint.port)
        stop.wait()
        endpoint.stop()
        server.stop()
    elif args.role == "redis":
        from faabric_tpu.redis import MiniRedisServer

        srv = MiniRedisServer(host=args.bind, port=args.port)
        srv.start()
        logger.info("Mini redis up on %s:%d", args.bind, srv.port)
        stop.wait()
        srv.stop()
    else:
        from faabric_tpu.runner import WorkerRuntime

        runtime = WorkerRuntime(host=args.host, slots=args.slots,
                                n_devices=args.devices,
                                planner_host=args.planner_host)
        runtime.start()
        logger.info("Worker %s up", runtime.host)
        stop.wait()
        runtime.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
