"""Pretty-print / verify a planner write-ahead journal.

    python -m faabric_tpu.runner.journaldump <dir> [--json] [--last N]
                                             [--kind K] [--verify]

The companion of ``flightdump`` for the control plane: reads the
journal directory a planner wrote (``FAABRIC_PLANNER_JOURNAL_DIR`` —
``planner.journal`` + the compaction snapshot ``planner.snapshot.json``,
see planner/journal.py) and renders the snapshot summary plus every
valid record on one timeline. ``--verify`` exits non-zero when the
journal has a torn tail or an unreadable snapshot — the CI hook for
"the black box itself is intact".
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from faabric_tpu.planner.journal import load_journal_dir


def _fmt_fields(rec: dict) -> str:
    skip = ("k", "ts")
    out = []
    for key in rec:
        if key in skip:
            continue
        val = rec[key]
        if isinstance(val, dict):
            # Nested payloads (req/decision/msg) render as summaries:
            # the point of the dump is the timeline, not a JSON wall
            n = val.get("messages")
            ident = val.get("app_id", val.get("id", ""))
            size = len(n) if isinstance(n, list) else len(val)
            out.append(f"{key}=<{ident}:{size}>")
        else:
            out.append(f"{key}={val}")
    return " ".join(out)


def render(records: list[dict], last: int | None = None) -> str:
    if last is not None:
        records = records[-last:]
    if not records:
        return "(no journal records)"
    t0 = records[0].get("ts", 0.0)
    lines = []
    for rec in records:
        if rec.get("k") == "group":
            # Group commit (ISSUE 8): one scheduling tick's records in
            # one on-disk record — render the envelope plus each
            # coalesced sub-record indented under it
            subs = rec.get("recs") or []
            lines.append(f"{rec.get('ts', 0.0) - t0:+10.3f}s "
                         f"{'group':<18} n={len(subs)}")
            for sub in subs:
                lines.append(f"{'':>12} └ {sub.get('k', '?'):<16} "
                             f"{_fmt_fields(sub)}")
            continue
        lines.append(f"{rec.get('ts', 0.0) - t0:+10.3f}s "
                     f"{rec.get('k', '?'):<18} {_fmt_fields(rec)}")
    return "\n".join(lines)


def filter_kind(records: list[dict], kind: str) -> list[dict]:
    """--kind filter that understands group commits: a group record
    matches when its own kind matches, or when any coalesced sub-record
    does (the group is then narrowed to the matching subs)."""
    out = []
    for rec in records:
        if rec.get("k") == kind:
            out.append(rec)
            continue
        if rec.get("k") == "group":
            subs = [s for s in (rec.get("recs") or [])
                    if s.get("k") == kind]
            if subs:
                out.append({**rec, "recs": subs, "n": len(subs)})
    return out


def snapshot_summary(state: dict | None) -> str:
    if state is None:
        return "no snapshot"
    in_flight = state.get("in_flight") or {}
    results = state.get("results") or {}
    return (f"snapshot: {len(in_flight)} in-flight app(s), "
            f"{sum(len(r) for r in results.values())} result(s), "
            f"{len(state.get('state_masters') or {})} state master(s), "
            f"{len(state.get('evicted') or {})} frozen, "
            f"last known hosts {state.get('known_hosts') or []}")


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="faabric_tpu.runner.journaldump",
        description="Pretty-print / verify a planner write-ahead journal")
    parser.add_argument(
        "directory", nargs="?",
        default=os.environ.get("FAABRIC_PLANNER_JOURNAL_DIR", "."))
    parser.add_argument("--json", action="store_true",
                        help="machine-readable records + snapshot + meta")
    parser.add_argument("--last", type=int, default=None,
                        help="only the final N records")
    parser.add_argument("--kind", default=None,
                        help="filter by record kind (e.g. result)")
    parser.add_argument("--verify", action="store_true",
                        help="exit non-zero on a torn tail or a "
                             "corrupt/unreadable snapshot")
    args = parser.parse_args(argv)

    snapshot, records, meta = load_journal_dir(args.directory)
    if args.kind:
        records = filter_kind(records, args.kind)

    if args.json:
        body = {"meta": meta, "snapshot": snapshot, "records":
                records[-args.last:] if args.last is not None else records}
        print(json.dumps(body, indent=1, default=str))
    else:
        print(f"{len(records)} record(s) from {args.directory} "
              f"(generation {meta.get('generation', '?')})")
        print(snapshot_summary(snapshot))
        if meta.get("skipped_bytes"):
            print(f"skipped {meta['skipped_bytes']} journal byte(s) "
                  "already folded into the snapshot")
        if meta.get("torn"):
            print(f"TORN TAIL: {meta.get('torn_bytes', 0)} trailing "
                  "byte(s) failed length/CRC checks", file=sys.stderr)
        if meta.get("snapshot_error"):
            print(f"SNAPSHOT UNREADABLE: {meta['snapshot_error']}",
                  file=sys.stderr)
        print(render(records, last=args.last))

    if args.verify and (meta.get("torn") or meta.get("snapshot_error")):
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
