"""Device-resident state handles: pass HBM arrays by reference (ISSUE 15).

The executor-chain workload SURVEY §2.1/§2.5 describes — faabric-style
batch functions chained over shared arrays — today moves every
intermediate through the host state KV (``StateKeyValue``:
device_get → host image → device_put). For arrays that never leave the
chip between steps both transfers are pure waste. This module is the
zero-copy tier:

- ``push(world_id, rank, name, arr)`` registers a **live, committed,
  single-device jax.Array** under a compact, JSON-serializable
  :class:`DeviceStateHandle` (world / rank / name / shape / dtype /
  device id / generation / uid) — **no host staging**: the registry
  holds a reference to the array exactly where it lives in HBM.
- ``pull(handle)`` hands the array back **by reference** — zero
  copies, the lazy-materialization contract: nothing moves until a
  consumer explicitly asks for host bytes via ``pull_host`` (one
  counted device→host copy) or queues a device snapshot diff.
- Handles ride executor chains as plain dicts (``to_dict`` /
  ``from_dict``) — what crosses the invocation boundary is ~100 bytes
  of metadata, never the payload.

Safety contract (the ISSUE 15 small-fix): a migrated rank must never
pull a stale HBM reference. ``MpiWorld.prepare_migration`` calls
:func:`invalidate_world` — the world's generation bumps and every
outstanding handle drops (flight-recorded); a pull of an invalidated
handle raises :class:`StaleDeviceHandle` instead of returning a buffer
whose chip assignment no longer matches the world. After the
re-handshake (``activate_device_plane``) the executor re-pushes its
arrays, minting fresh handles under the new generation — "drop +
re-handshake re-registers them".

Snapshot bridge: ``snapshot_of(handle)`` wraps the live array in a
:class:`~faabric_tpu.snapshot.device_snapshot.DeviceSnapshot`, so
dirty-page diffing runs ON the chip and only the diff bytes ever cross
to the host (SURVEY §7).

Memory note: the registry pins pushed arrays (that is its job — a
handle must stay pullable), bounded by ``FAABRIC_DEVICE_HANDLES_MAX``
(default 256 per process); pushing past the cap evicts nothing and
raises — silent eviction would turn a valid handle stale, which is
exactly the bug class the generation check exists to make loud.
"""

from __future__ import annotations

import threading
from dataclasses import asdict, dataclass

import numpy as np

from faabric_tpu.util.config import _env_int
from faabric_tpu.util.logging import get_logger

logger = get_logger(__name__)

DEFAULT_MAX_HANDLES = 256


class StaleDeviceHandle(KeyError):
    """The handle's HBM reference is gone or from a pre-migration
    generation — re-push after the re-handshake."""


class DeviceHandleError(ValueError):
    """The pushed value is not a committed single-device jax.Array (or
    the registry is at capacity)."""


@dataclass(frozen=True)
class DeviceStateHandle:
    """Compact by-reference name for one HBM array. Serializable —
    executor chains pass the dict, never the payload."""

    world_id: int
    rank: int
    name: str
    shape: tuple
    dtype: str
    device_id: int
    gen: int
    uid: int

    def to_dict(self) -> dict:
        d = asdict(self)
        d["shape"] = list(self.shape)
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "DeviceStateHandle":
        return cls(world_id=int(d["world_id"]), rank=int(d["rank"]),
                   name=str(d["name"]), shape=tuple(d["shape"]),
                   dtype=str(d["dtype"]), device_id=int(d["device_id"]),
                   gen=int(d["gen"]), uid=int(d["uid"]))

    @property
    def nbytes(self) -> int:
        n = 1
        for s in self.shape:
            n *= int(s)
        return n * np.dtype(self.dtype).itemsize


class DeviceHandleRegistry:
    """Per-process HBM handle table."""

    # Concurrency contract (tools/concheck.py): executor threads push/
    # pull concurrently while migrations invalidate; one lock covers
    # the table (operations are dict hits — no compile, no transfer
    # under the lock).
    GUARDS = {
        "_entries": "_lock",
        "_world_gen": "_lock",
        "_by_world": "_lock",
        "_next_uid": "_lock",
    }

    def __init__(self, max_handles: int | None = None) -> None:
        self.max_handles = (max_handles if max_handles is not None else
                            _env_int("FAABRIC_DEVICE_HANDLES_MAX",
                                     DEFAULT_MAX_HANDLES))
        self._lock = threading.Lock()
        self._entries: dict[int, tuple[DeviceStateHandle, object]] = {}
        self._by_world: dict[int, set[int]] = {}
        self._world_gen: dict[int, int] = {}
        self._next_uid = 1

    # ------------------------------------------------------------------
    def push(self, world_id: int, rank: int, name: str,
             arr) -> DeviceStateHandle:
        """Register a device-resident array; NO host staging — the
        array object itself is pinned, exactly where it lives."""
        from faabric_tpu.device_plane.plane import is_device_payload

        if not is_device_payload(arr):
            raise DeviceHandleError(
                "push() needs a jax.Array (host values belong in the "
                "state KV; device_put first to pin a host buffer)")
        try:
            committed = bool(getattr(arr, "committed", False))
            devs = arr.sharding.device_set
        except Exception as e:  # noqa: BLE001 — exotic array types
            raise DeviceHandleError(f"unsupported array type: {e!r}")
        if not committed or len(devs) != 1:
            raise DeviceHandleError(
                "push() needs a COMMITTED single-device array "
                f"(committed={committed}, devices={len(devs)})")
        (dev,) = devs
        with self._lock:
            if len(self._entries) >= self.max_handles:
                raise DeviceHandleError(
                    f"device handle registry at capacity "
                    f"({self.max_handles}); drop handles or raise "
                    "FAABRIC_DEVICE_HANDLES_MAX")
            gen = self._world_gen.setdefault(world_id, 0)
            uid = self._next_uid
            self._next_uid += 1
            handle = DeviceStateHandle(
                world_id=int(world_id), rank=int(rank), name=str(name),
                shape=tuple(int(s) for s in arr.shape),
                dtype=str(np.dtype(arr.dtype)), device_id=int(dev.id),
                gen=gen, uid=uid)
            self._entries[uid] = (handle, arr)
            self._by_world.setdefault(world_id, set()).add(uid)
        return handle

    def _resolve(self, handle: DeviceStateHandle):
        if isinstance(handle, dict):
            handle = DeviceStateHandle.from_dict(handle)
        with self._lock:
            gen = self._world_gen.get(handle.world_id, 0)
            entry = self._entries.get(handle.uid)
        if handle.gen != gen or entry is None:
            raise StaleDeviceHandle(
                f"device handle {handle.uid} "
                f"({handle.world_id}/{handle.rank}/{handle.name}) is "
                f"stale: generation {handle.gen} vs {gen} — the rank "
                "migrated; re-handshake and re-push")
        return entry

    def pull(self, handle):
        """The live HBM array, by reference — zero transfers."""
        return self._resolve(handle)[1]

    def pull_host(self, handle) -> np.ndarray:
        """Materialize on host: the ONE counted device→host copy."""
        from faabric_tpu.device_plane.copies import D2H, count_copy

        arr = self._resolve(handle)[1]
        out = np.asarray(arr)
        count_copy(D2H, int(out.nbytes), "state")
        return out

    def push_from_host(self, world_id: int, rank: int, name: str,
                       host_arr, device) -> DeviceStateHandle:
        """Escape hatch for host values entering the HBM tier: one
        counted host→device placement, then a normal push."""
        import jax

        host_arr = np.asarray(host_arr)
        from faabric_tpu.device_plane.copies import H2D, count_copy

        arr = jax.device_put(host_arr, device)
        count_copy(H2D, int(host_arr.nbytes), "state")
        return self.push(world_id, rank, name, arr)

    def snapshot_of(self, handle):
        """A DeviceSnapshot tracking the handle's live array: dirty
        detection and diff extraction stay ON the chip."""
        from faabric_tpu.snapshot.device_snapshot import DeviceSnapshot

        return DeviceSnapshot(self.pull(handle))

    # ------------------------------------------------------------------
    def drop(self, handle) -> bool:
        if isinstance(handle, dict):
            handle = DeviceStateHandle.from_dict(handle)
        with self._lock:
            entry = self._entries.pop(handle.uid, None)
            if entry is not None:
                self._by_world.get(handle.world_id, set()).discard(
                    handle.uid)
        return entry is not None

    def invalidate_world(self, world_id: int) -> int:
        """Migration hook (``MpiWorld.prepare_migration``): bump the
        world's generation and drop every outstanding handle — a
        migrated rank can never pull a stale HBM reference. Flight-
        recorded so post-mortems can tie a StaleDeviceHandle burst to
        the remap that caused it."""
        with self._lock:
            self._world_gen[world_id] = \
                self._world_gen.get(world_id, 0) + 1
            gen = self._world_gen[world_id]
            uids = self._by_world.pop(world_id, set())
            dropped = 0
            nbytes = 0
            for uid in uids:
                entry = self._entries.pop(uid, None)
                if entry is not None:
                    dropped += 1
                    nbytes += entry[0].nbytes
        if dropped:
            from faabric_tpu.telemetry.flight import flight_record

            flight_record("device_handle_invalidate", world=world_id,
                          gen=gen, dropped=dropped, bytes=nbytes)
        if dropped:
            logger.info(
                "Invalidated %d device state handle(s) (%d bytes) for "
                "world %s (generation %d)", dropped, nbytes, world_id,
                gen)
        return dropped

    def world_generation(self, world_id: int) -> int:
        with self._lock:
            return self._world_gen.get(world_id, 0)

    def summary(self) -> dict:
        with self._lock:
            handles = [h.to_dict() for h, _a in self._entries.values()]
            gens = dict(self._world_gen)
        return {"count": len(handles),
                "bytes": sum(DeviceStateHandle.from_dict(h).nbytes
                             for h in handles),
                "world_generations": gens,
                "handles": handles}

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._by_world.clear()
            self._world_gen.clear()


_registry: DeviceHandleRegistry | None = None
_registry_lock = threading.Lock()


def get_device_handle_registry() -> DeviceHandleRegistry:
    global _registry
    if _registry is None:
        with _registry_lock:
            if _registry is None:
                _registry = DeviceHandleRegistry()
    return _registry


def invalidate_world(world_id: int) -> int:
    """Module-level convenience for the migration path: invalidate
    without instantiating a registry nobody used."""
    with _registry_lock:
        reg = _registry
    if reg is None:
        return 0
    return reg.invalidate_world(world_id)


def reset_device_handles() -> None:
    """Test hook: drop the singleton."""
    global _registry
    with _registry_lock:
        _registry = None
