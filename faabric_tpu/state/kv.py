"""Distributed state key-value.

Reference analog: include/faabric/state/StateKeyValue.h:105-226 and
src/state/InMemoryStateKeyValue.cpp:90-260. One master host per key;
non-masters hold a local image with lazy **chunked pull** (pulled mask),
a **dirty-chunk mask** with partial push (only dirty chunks travel),
appends with remote retrieval, and read/write locks hosted by the master.

TPU deltas from the reference: values are numpy byte buffers (the device
round-trip is ``jax.device_put(kv.get_array(...))`` / ``kv.set(device_
get(...))`` — state stays host-resident, chips pull what they need); no
Redis backend — master election goes through the planner (the cluster
metadata service) and all data movement is master↔replica RPC.
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np

from faabric_tpu.util.logging import get_logger

logger = get_logger(__name__)

STATE_CHUNK_SIZE = 4096


def n_chunks(size: int) -> int:
    return max(1, (size + STATE_CHUNK_SIZE - 1) // STATE_CHUNK_SIZE)


class StateKeyValue:
    def __init__(self, user: str, key: str, size: int,
                 is_master: bool, master_host: str,
                 client_factory=None) -> None:
        self.user = user
        self.key = key
        self.size = size
        self.is_master = is_master
        self.master_host = master_host
        self._client_factory = client_factory

        self._lock = threading.RLock()
        self._data = np.zeros(size, dtype=np.uint8)
        chunks = n_chunks(size)
        # Masters own authoritative data: everything is "pulled"
        self._pulled = np.full(chunks, is_master, dtype=bool)
        self._dirty = np.zeros(chunks, dtype=bool)

        self._appended: list[bytes] = []

        # Master-side value lock (reference read/write locks; writers over
        # RPC serialise on this)
        self._value_lock = threading.Lock()

    # ------------------------------------------------------------------
    def _client(self):
        if self._client_factory is None:
            raise RuntimeError(
                f"No state client for non-master access to {self.user}/{self.key}")
        return self._client_factory(self.master_host)

    def _chunk_range(self, offset: int, length: int) -> tuple[int, int]:
        first = offset // STATE_CHUNK_SIZE
        last = (offset + max(1, length) - 1) // STATE_CHUNK_SIZE
        return first, last + 1

    def _ensure_pulled(self, offset: int, length: int) -> None:
        if self.is_master:
            return
        first, last = self._chunk_range(offset, length)
        with self._lock:
            missing = [c for c in range(first, min(last, self._pulled.size))
                       if not self._pulled[c]]
        if not missing:
            return
        client = self._client()
        for c in missing:
            lo = c * STATE_CHUNK_SIZE
            hi = min(self.size, lo + STATE_CHUNK_SIZE)
            data = client.pull_chunk(self.user, self.key, lo, hi - lo)
            with self._lock:
                self._data[lo:lo + len(data)] = np.frombuffer(data, np.uint8)
                self._pulled[c] = True

    # ------------------------------------------------------------------
    # Reads
    # ------------------------------------------------------------------
    def get(self) -> bytes:
        self._ensure_pulled(0, self.size)
        with self._lock:
            return self._data.tobytes()

    def get_array(self) -> np.ndarray:
        self._ensure_pulled(0, self.size)
        with self._lock:
            return self._data.copy()

    def get_chunk(self, offset: int, length: int) -> bytes:
        if offset + length > self.size:
            raise ValueError(
                f"Chunk [{offset}, {offset + length}) out of bounds "
                f"(size {self.size})")
        self._ensure_pulled(offset, length)
        with self._lock:
            return self._data[offset:offset + length].tobytes()

    # ------------------------------------------------------------------
    # Writes
    # ------------------------------------------------------------------
    def set(self, data: bytes) -> None:
        if len(data) != self.size:
            raise ValueError(f"set() needs {self.size} bytes, got {len(data)}")
        with self._lock:
            self._data[:] = np.frombuffer(data, np.uint8)
            self._pulled[:] = True
            self._dirty[:] = True

    def set_chunk(self, offset: int, data: bytes) -> None:
        if offset + len(data) > self.size:
            raise ValueError("Chunk write out of bounds")
        first, last = self._chunk_range(offset, len(data))
        with self._lock:
            self._data[offset:offset + len(data)] = np.frombuffer(data,
                                                                  np.uint8)
            self._dirty[first:last] = True
            self._pulled[first:last] = True

    # ------------------------------------------------------------------
    # Push / pull (non-master ↔ master)
    # ------------------------------------------------------------------
    def push_full(self) -> None:
        if self.is_master:
            with self._lock:
                self._dirty[:] = False
            return
        self._client().push_chunk(self.user, self.key, 0, self.get())
        with self._lock:
            self._dirty[:] = False

    def push_partial(self) -> None:
        """Push only the dirty chunks (reference pushPartial)."""
        if self.is_master:
            with self._lock:
                self._dirty[:] = False
            return
        with self._lock:
            dirty = [int(c) for c in np.where(self._dirty)[0]]
        if not dirty:
            return
        client = self._client()
        for c in dirty:
            lo = c * STATE_CHUNK_SIZE
            hi = min(self.size, lo + STATE_CHUNK_SIZE)
            with self._lock:
                payload = self._data[lo:hi].tobytes()
            client.push_chunk(self.user, self.key, lo, payload)
            with self._lock:
                self._dirty[c] = False

    def pull(self) -> None:
        """Re-pull the whole value from the master."""
        if self.is_master:
            return
        with self._lock:
            self._pulled[:] = False
        self._ensure_pulled(0, self.size)

    def n_dirty_chunks(self) -> int:
        with self._lock:
            return int(self._dirty.sum())

    # ------------------------------------------------------------------
    # Appends (reference append/getAppended/clearAppended)
    # ------------------------------------------------------------------
    def append(self, data: bytes) -> None:
        if self.is_master:
            with self._lock:
                self._appended.append(bytes(data))
        else:
            self._client().append(self.user, self.key, data)

    def get_appended(self, n_values: int) -> list[bytes]:
        if self.is_master:
            with self._lock:
                if len(self._appended) < n_values:
                    raise ValueError(
                        f"Only {len(self._appended)} appended values")
                return list(self._appended[:n_values])
        return self._client().pull_appended(self.user, self.key, n_values)

    def clear_appended(self) -> None:
        if self.is_master:
            with self._lock:
                self._appended.clear()
        else:
            self._client().clear_appended(self.user, self.key)

    # ------------------------------------------------------------------
    # Locks (master-hosted)
    # ------------------------------------------------------------------
    # Master-side acquire bound: slightly under the client socket timeout,
    # so a contended lock surfaces as an RPC error on the requester rather
    # than an orphaned server thread that acquires for a dead client
    LOCK_ACQUIRE_TIMEOUT = 30.0

    def lock_global(self) -> None:
        if self.is_master:
            if not self._value_lock.acquire(timeout=self.LOCK_ACQUIRE_TIMEOUT):
                raise TimeoutError(
                    f"Timed out acquiring global lock on {self.user}/{self.key}")
        else:
            # Lock/unlock use one-shot connections: the shared cached
            # client serialises its sync socket, so a blocked lock request
            # would block the holder's unlock behind it (deadlock)
            self._oneshot_lock_call("lock")

    def unlock_global(self) -> None:
        if self.is_master:
            self._value_lock.release()
        else:
            self._oneshot_lock_call("unlock")

    def _oneshot_lock_call(self, op: str) -> None:
        from faabric_tpu.state.remote import StateClient

        client = StateClient(self.master_host)
        try:
            getattr(client, op)(self.user, self.key)
        finally:
            client.close()

    # -- master-side entry points used by the StateServer ---------------
    def server_pull_chunk(self, offset: int, length: int) -> bytes:
        with self._lock:
            return self._data[offset:offset + length].tobytes()

    def server_push_chunk(self, offset: int, data: bytes) -> None:
        first, last = self._chunk_range(offset, len(data))
        with self._lock:
            if offset + len(data) > self.size:
                raise ValueError("Pushed chunk out of bounds")
            self._data[offset:offset + len(data)] = np.frombuffer(data,
                                                                  np.uint8)
            self._pulled[first:last] = True

    def server_append(self, data: bytes) -> None:
        with self._lock:
            self._appended.append(bytes(data))
