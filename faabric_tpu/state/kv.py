"""Distributed state key-value.

Reference analog: include/faabric/state/StateKeyValue.h:105-226 and
src/state/InMemoryStateKeyValue.cpp:90-260. One master host per key;
non-masters hold a local image with lazy **chunked pull** (pulled mask),
a **dirty-chunk mask** with partial push (only dirty chunks travel),
appends with remote retrieval, and read/write locks hosted by the master.

TPU deltas from the reference: values are numpy byte buffers (the device
round-trip is ``jax.device_put(kv.get_array(...))`` / ``kv.set(device_
get(...))`` — state stays host-resident, chips pull what they need).
Authority interactions (where the authoritative bytes live) go through a
pluggable :mod:`faabric_tpu.state.backend` — planner-elected in-memory
masters by default, shared-memory files with ``STATE_MODE=file``.
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np

from faabric_tpu.state.backend import (
    MasterMemoryAuthority,
    RemoteAuthority,
    StateAuthority,
)
from faabric_tpu.util.logging import get_logger

logger = get_logger(__name__)

STATE_CHUNK_SIZE = 4096


def n_chunks(size: int) -> int:
    return max(1, (size + STATE_CHUNK_SIZE - 1) // STATE_CHUNK_SIZE)


class StateKeyValue:
    def __init__(self, user: str, key: str, size: int,
                 is_master: bool, master_host: str,
                 client_factory=None,
                 authority: Optional[StateAuthority] = None) -> None:
        self.user = user
        self.key = key
        self.size = size
        self.master_host = master_host

        if authority is None:
            authority = (MasterMemoryAuthority(user, key) if is_master
                         else RemoteAuthority(user, key, master_host,
                                              client_factory))
        self.authority = authority
        # "Master" now means: the authoritative bytes are THIS process's
        # image (the StateServer serves them from here)
        self.is_master = authority.local

        self._lock = threading.RLock()
        self._data = np.zeros(size, dtype=np.uint8)
        # Device-view cache keyed by (dtype, sharding), invalidated by
        # host-image mutation (get_device_array)
        self._version = 0
        self._device_cache: dict = {}
        chunks = n_chunks(size)
        # Local-authority data is authoritative: everything is "pulled"
        self._pulled = np.full(chunks, self.is_master, dtype=bool)
        self._dirty = np.zeros(chunks, dtype=bool)

    # ------------------------------------------------------------------
    def _chunk_range(self, offset: int, length: int) -> tuple[int, int]:
        first = offset // STATE_CHUNK_SIZE
        last = (offset + max(1, length) - 1) // STATE_CHUNK_SIZE
        return first, last + 1

    def _ensure_pulled(self, offset: int, length: int) -> None:
        if self.is_master:
            return
        first, last = self._chunk_range(offset, length)
        with self._lock:
            missing = [c for c in range(first, min(last, self._pulled.size))
                       if not self._pulled[c]]
        if not missing:
            return
        for c in missing:
            lo = c * STATE_CHUNK_SIZE
            hi = min(self.size, lo + STATE_CHUNK_SIZE)
            data = self.authority.pull_chunk(lo, hi - lo)
            with self._lock:
                self._data[lo:lo + len(data)] = np.frombuffer(data, np.uint8)
                self._pulled[c] = True
                self._bump_version()

    # ------------------------------------------------------------------
    # Reads
    # ------------------------------------------------------------------
    def get(self) -> bytes:
        self._ensure_pulled(0, self.size)
        with self._lock:
            return self._data.tobytes()

    def get_array(self) -> np.ndarray:
        self._ensure_pulled(0, self.size)
        with self._lock:
            return self._data.copy()

    def get_chunk(self, offset: int, length: int) -> bytes:
        if offset + length > self.size:
            raise ValueError(
                f"Chunk [{offset}, {offset + length}) out of bounds "
                f"(size {self.size})")
        self._ensure_pulled(offset, length)
        with self._lock:
            return self._data[offset:offset + length].tobytes()

    # ------------------------------------------------------------------
    # Writes
    # ------------------------------------------------------------------
    def set(self, data: bytes) -> None:
        if len(data) != self.size:
            raise ValueError(f"set() needs {self.size} bytes, got {len(data)}")
        with self._lock:
            self._data[:] = np.frombuffer(data, np.uint8)
            self._pulled[:] = True
            self._dirty[:] = True
            self._bump_version()

    def set_chunk(self, offset: int, data: bytes) -> None:
        if offset + len(data) > self.size:
            raise ValueError("Chunk write out of bounds")
        first, last = self._chunk_range(offset, len(data))
        with self._lock:
            self._data[offset:offset + len(data)] = np.frombuffer(data,
                                                                  np.uint8)
            self._dirty[first:last] = True
            self._pulled[first:last] = True
            self._bump_version()

    # ------------------------------------------------------------------
    # Push / pull (non-master ↔ master)
    # ------------------------------------------------------------------
    def push_full(self) -> None:
        if self.is_master:
            with self._lock:
                self._dirty[:] = False
            return
        self.authority.push_chunk(0, self.get())
        with self._lock:
            self._dirty[:] = False

    def push_partial(self) -> None:
        """Push only the dirty chunks (reference pushPartial)."""
        if self.is_master:
            with self._lock:
                self._dirty[:] = False
            return
        with self._lock:
            dirty = [int(c) for c in np.where(self._dirty)[0]]
        if not dirty:
            return
        # Batched pushes (backends that can pipeline — redis — send each
        # group in one round-trip), bounded to a few MiB per group so a
        # fully-dirty multi-GiB value neither doubles peak memory nor
        # holds the kv lock for the whole payload copy
        group_chunks = max(1, (4 << 20) // STATE_CHUNK_SIZE)
        for g in range(0, len(dirty), group_chunks):
            group = dirty[g:g + group_chunks]
            with self._lock:
                writes = []
                for c in group:
                    lo = c * STATE_CHUNK_SIZE
                    hi = min(self.size, lo + STATE_CHUNK_SIZE)
                    writes.append((lo, self._data[lo:hi].tobytes()))
            self.authority.push_chunks(writes)
            with self._lock:
                for c in group:
                    self._dirty[c] = False

    def pull(self) -> None:
        """Re-pull the whole value from the master."""
        if self.is_master:
            return
        with self._lock:
            self._pulled[:] = False
        self._ensure_pulled(0, self.size)

    def n_dirty_chunks(self) -> int:
        with self._lock:
            return int(self._dirty.sum())

    # ------------------------------------------------------------------
    # Appends (reference append/getAppended/clearAppended)
    # ------------------------------------------------------------------
    def append(self, data: bytes) -> None:
        self.authority.append(data)

    def get_appended(self, n_values: int) -> list[bytes]:
        return self.authority.get_appended(n_values)

    def clear_appended(self) -> None:
        self.authority.clear_appended()

    # ------------------------------------------------------------------
    # Locks (authority-hosted)
    # ------------------------------------------------------------------
    def lock_global(self) -> None:
        self.authority.lock()

    def unlock_global(self) -> None:
        self.authority.unlock()

    # ------------------------------------------------------------------
    # Device view (SURVEY §7 stage 6: "HBM-backed values with host↔device
    # sync") — the host image stays authoritative; chips hold a cached
    # jax array that refreshes when the host image changes
    # ------------------------------------------------------------------
    def get_device_array(self, dtype=None, sharding=None):
        """The value as a device-resident jax array (optionally viewed as
        ``dtype`` and placed with ``sharding``). Cached per (dtype,
        sharding) and invalidated whenever the host image mutates — a
        training step reading unchanged state pays zero transfers."""
        import jax

        self._ensure_pulled(0, self.size)
        with self._lock:
            version = self._version
            # Normalized dtype + the (hashable) sharding itself: equal
            # shardings hit one entry, and the dict keeps the sharding
            # alive so a recycled object id can never alias a stale entry
            key = (np.dtype(dtype).str if dtype is not None else None,
                   sharding)
            cached = self._device_cache.get(key)
            if cached is not None and cached[0] == version:
                return cached[1]
            host = self._data.copy()
        arr = host if dtype is None else host.view(dtype)
        dev = jax.device_put(arr, sharding)
        with self._lock:
            self._device_cache[key] = (version, dev)
        return dev

    def set_from_device(self, arr) -> None:
        """Write a device array's bytes back into the host image (device
        → host sync); push_partial/push_full then moves it to the
        authority."""
        host = np.asarray(arr).reshape(-1).view(np.uint8)
        if host.size != self.size:
            raise ValueError(
                f"device value is {host.size} bytes, KV holds {self.size}")
        self.set(host.tobytes())

    def _bump_version(self) -> None:
        self._version += 1
        self._device_cache.clear()

    # -- master-side entry points used by the StateServer ---------------
    def server_pull_chunk(self, offset: int, length: int) -> bytes:
        with self._lock:
            return self._data[offset:offset + length].tobytes()

    def server_push_chunk(self, offset: int, data: bytes) -> None:
        first, last = self._chunk_range(offset, len(data))
        with self._lock:
            if offset + len(data) > self.size:
                raise ValueError("Pushed chunk out of bounds")
            self._data[offset:offset + len(data)] = np.frombuffer(data,
                                                                  np.uint8)
            self._pulled[first:last] = True
            self._bump_version()

    def server_append(self, data: bytes) -> None:
        self.authority.append(data)
