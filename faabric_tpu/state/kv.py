"""Distributed state key-value.

Reference analog: include/faabric/state/StateKeyValue.h:105-226 and
src/state/InMemoryStateKeyValue.cpp:90-260. One master host per key;
non-masters hold a local image with lazy **chunked pull** (pulled mask),
a **dirty-chunk mask** with partial push (only dirty chunks travel),
appends with remote retrieval, and read/write locks hosted by the master.

TPU deltas from the reference: values are numpy byte buffers (the device
round-trip is ``jax.device_put(kv.get_array(...))`` / ``kv.set(device_
get(...))`` — state stays host-resident, chips pull what they need).
Authority interactions (where the authoritative bytes live) go through a
pluggable :mod:`faabric_tpu.state.backend` — planner-elected in-memory
masters by default, shared-memory files with ``STATE_MODE=file``.

Observability (ISSUE 16): every op feeds the per-key access ledger
(:mod:`faabric_tpu.telemetry.statestats` — op counts, bytes, chunk
counts, dirty ratios, latency quantiles, pull amplification), remote
chunk traffic rides ``plane=state`` comm-matrix rows and ``state/*``
spans, pull/push failures and global-lock stalls flight-record, and
in-run state time charges the invocation's lifecycle ledger
(``charge_state_time``) so ``/healthz`` can attribute state-bound
invocations. With ``FAABRIC_METRICS=0`` every handle is the shared
no-op singleton and the hot paths skip even the clock reads.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

import numpy as np

from faabric_tpu.faults.registry import (
    DROP,
    FaultConnectionError,
    fault_point,
    faults_enabled,
)
from faabric_tpu.state.backend import (
    MasterMemoryAuthority,
    RemoteAuthority,
    StaleStateEpoch,
    StateAuthority,
)
from faabric_tpu.telemetry.commmatrix import get_comm_matrix
from faabric_tpu.telemetry.flight import flight_record
from faabric_tpu.telemetry.lifecycle import charge_state_time
from faabric_tpu.telemetry.statestats import (
    get_state_stats,
    lock_stall_threshold_s,
)
from faabric_tpu.telemetry.tracer import span
from faabric_tpu.util.logging import get_logger
from faabric_tpu.util.retry import RetryPolicy

logger = get_logger(__name__)

STATE_CHUNK_SIZE = 4096

# Fault points at the state wire (ISSUE 19 satellite): chaos tests
# inject delays/drops/conn-kills where state bytes travel instead of
# only at the transport layer. Same boot-time capture idiom as the
# transport call sites — FAABRIC_FAULTS unset keeps these at one
# module-global bool check.
_FAULTS = faults_enabled()
_FP_PULL = fault_point("state.pull")
_FP_PUSH = fault_point("state.push")
_FP_REPLICATE = fault_point("state.replicate")

# Bounded client-side retry after a failover: one re-resolve through
# the planner per attempt (the long wait for keep-alive expiry is the
# CALLER's loop — this only bridges already-promoted placements)
_PLACEMENT_RETRY = RetryPolicy(max_attempts=3, backoff=0.05)


def _fire_fault(point, **ctx) -> None:
    """A DROP verdict at the state wire surfaces as a peer failure (a
    dropped state RPC and a dead peer are indistinguishable here), so
    the retry/no-ack machinery engages instead of bytes silently
    vanishing."""
    if point.fire(**ctx) is DROP:
        raise FaultConnectionError(
            f"fault rule dropped {point.name} for {ctx.get('key')}")


def n_chunks(size: int) -> int:
    return max(1, (size + STATE_CHUNK_SIZE - 1) // STATE_CHUNK_SIZE)


class StateKeyValue:
    # Concurrency contract (tools/concheck.py): the image and every
    # mask/cache derived from it mutate under the one RLock. Telemetry
    # handles (_stats, _comm) are write-once in __init__ and internally
    # locked — plain attribute reads thereafter. NOT listed: epoch
    # (monotone int; writes serialized by the server's fencing path and
    # the single-threaded resolver retry, GIL-atomic reads),
    # backup_host (whole-str swap, GIL-atomic), _stale (one-way bool
    # latch — a late reader just fences one op later).
    GUARDS = {
        "_data": "_lock",
        "_pulled": "_lock",
        "_ever_pulled": "_lock",
        "_dirty": "_lock",
        "_n_dirty": "_lock",
        "_version": "_lock",
        "_device_cache": "_lock",
    }

    def __init__(self, user: str, key: str, size: int,
                 is_master: bool, master_host: str,
                 client_factory=None,
                 authority: Optional[StateAuthority] = None,
                 local_host: str = "", backup_host: str = "",
                 epoch: int = 0, resolver=None) -> None:
        self.user = user
        self.key = key
        self.size = size
        self.master_host = master_host
        self.full_key = f"{user}/{key}"
        self.local_host = local_host or "local"
        # Replication + fencing (ISSUE 19). backup_host is where a
        # MASTER forwards acked writes; epoch fences ops after a
        # failover; resolver re-resolves (master, backup, epoch) through
        # the planner. All optional: direct constructions (benches,
        # tests, file/redis modes) run exactly as before.
        self.backup_host = backup_host
        self.epoch = epoch
        self._resolver = resolver
        self._stale = False
        self._client_factory = client_factory

        if authority is None:
            authority = (MasterMemoryAuthority(user, key) if is_master
                         else RemoteAuthority(user, key, master_host,
                                              client_factory, epoch=epoch))
        self.authority = authority
        # "Master" now means: the authoritative bytes are THIS process's
        # image (the StateServer serves them from here)
        self.is_master = authority.local

        self._lock = threading.RLock()
        self._data = np.zeros(size, dtype=np.uint8)
        # Device-view cache keyed by (dtype, sharding), invalidated by
        # host-image mutation (get_device_array)
        self._version = 0
        self._device_cache: dict = {}
        chunks = n_chunks(size)
        # Local-authority data is authoritative: everything is "pulled"
        self._pulled = np.full(chunks, self.is_master, dtype=bool)
        # Monotone: chunks pulled at least once, ever — the denominator
        # of the pull-amplification signal (pull() resets _pulled but
        # never this, so a re-pull of a clean chunk reads as repeat)
        self._ever_pulled = np.full(chunks, self.is_master, dtype=bool)
        self._dirty = np.zeros(chunks, dtype=bool)
        self._n_dirty = 0

        self._stats = get_state_stats()
        self._comm = get_comm_matrix()
        self._stats.note_key(self.full_key, master=master_host,
                             size=size, is_master=self.is_master,
                             backup=backup_host, epoch=epoch)

    # ------------------------------------------------------------------
    def _chunk_range(self, offset: int, length: int) -> tuple[int, int]:
        first = offset // STATE_CHUNK_SIZE
        last = (offset + max(1, length) - 1) // STATE_CHUNK_SIZE
        return first, last + 1

    # ------------------------------------------------------------------
    # Epoch fencing + replication (ISSUE 19)
    # ------------------------------------------------------------------
    def check_epoch(self, req_epoch: int) -> None:
        """Master-side fence, called by the StateServer on every op:
        reject requests older than our epoch, adopt newer ones (the
        planner re-blessed this host), reject EVERYTHING once this
        master learned it was fenced out — only the journaled epoch
        owner acks."""
        if self._stale:
            raise StaleStateEpoch(
                f"StaleStateEpoch: {self.full_key} master at "
                f"{self.local_host} has been fenced out (a failover "
                "promoted its backup)")
        if not req_epoch:
            return
        if req_epoch < self.epoch:
            raise StaleStateEpoch(
                f"StaleStateEpoch: op at epoch {req_epoch} rejected by "
                f"{self.full_key} master (epoch {self.epoch})")
        if req_epoch > self.epoch:
            self.epoch = req_epoch

    def mark_stale(self) -> None:
        """One-way latch: this process's mastership of the key has been
        superseded (demotion observed a higher-epoch replicate)."""
        self._stale = True

    def adopt_placement(self, backup: str, epoch: int) -> None:
        """Master-side placement refresh (promotion anti-entropy thread
        learned the backup from the planner)."""
        self.backup_host = backup
        if epoch > self.epoch:
            self.epoch = epoch
        self._stats.note_key(self.full_key, master=self.master_host,
                             backup=backup, epoch=self.epoch)

    def load_image(self, data: bytes, appended: list[bytes]) -> None:
        """Seed a freshly-promoted master from its replica snapshot:
        the image IS the set of acknowledged writes."""
        with self._lock:
            self._data[:len(data)] = np.frombuffer(data, np.uint8)
            self._pulled[:] = True
            self._ever_pulled[:] = True
            self._dirty[:] = False
            self._n_dirty = 0
            self._bump_version_locked()
        if hasattr(self.authority, "seed_appended"):
            self.authority.seed_appended(appended)

    def _has_backup(self) -> bool:
        return bool(self.is_master and self.backup_host
                    and self._client_factory is not None)

    def _remote_retry(self, fn):
        """Run one remote-authority op, re-resolving placement through
        the planner and retrying (bounded) when it fails: covers a
        client whose cached master died after the planner already
        promoted the backup. StaleStateEpoch surfaces through the
        transport as an RpcError whose text carries the class name, so
        a plain re-resolve-on-any-failure is both necessary (connection
        errors during failover) and sufficient."""
        attempt = 0
        while True:
            try:
                return fn()
            except Exception:  # noqa: BLE001 — rethrown unless rebound
                attempt += 1
                if (attempt >= _PLACEMENT_RETRY.max_attempts
                        or not self._reresolve_placement()):
                    raise
                _PLACEMENT_RETRY.sleep(attempt - 1)

    def _reresolve_placement(self) -> bool:
        """Non-master side: re-claim through the planner; True when the
        placement actually changed (worth retrying the op)."""
        if self.is_master or self._resolver is None:
            return False
        try:
            master, backup, epoch = self._resolver()
        except Exception:  # noqa: BLE001 — planner unreachable
            return False
        auth = self.authority
        changed = (master != self.master_host
                   or epoch > getattr(auth, "epoch", 0))
        if not changed:
            return False
        if master == self.local_host:
            # Total-loss re-election landed mastership on US, but this
            # object is a remote-image KV and cannot convert in place —
            # surface the original failure to the caller
            return False
        flight_record("state_reresolve", key=self.full_key,
                      old_master=self.master_host, master=master,
                      epoch=epoch)
        self.master_host = master
        self.backup_host = backup
        if epoch > self.epoch:
            self.epoch = epoch
        if isinstance(auth, RemoteAuthority):
            auth.master_host = master
            auth.epoch = epoch
        self._stats.note_key(self.full_key, master=master, backup=backup,
                             epoch=epoch)
        return True

    def _replicate_writes(self, writes: list[tuple[int, bytes]]) -> None:
        """Synchronously forward chunk writes to the backup BEFORE the
        mutation is acked — the invariant the whole design rests on: an
        acked write exists on two hosts (or the ack never happened)."""
        if not writes or not self._has_backup():
            return
        if _FAULTS:
            _fire_fault(_FP_REPLICATE, key=self.full_key,
                        host=self.backup_host)
        recording = self._stats.enabled
        t0 = time.monotonic_ns() if recording else 0
        nbytes = sum(len(d) for _o, d in writes)
        with span("state", "replicate", key=self.full_key,
                  chunks=len(writes)):
            try:
                self._client_factory(self.backup_host).replicate_chunks(
                    self.user, self.key, self.epoch, self.size, writes)
            except Exception as e:  # noqa: BLE001
                self._replication_failed(e)
        if recording:
            dt_ns = time.monotonic_ns() - t0
            charge_state_time(dt_ns)
            self._stats.record(self.full_key, "replicate", nbytes=nbytes,
                               chunks=len(writes), seconds=dt_ns / 1e9,
                               remote=True)
            self._comm.record(self.local_host, self.backup_host, "state",
                              nbytes, seconds=dt_ns / 1e9,
                              raw_bytes=nbytes)

    def _replicate_append(self, values: list[bytes],
                          replace: bool = False) -> None:
        if (not values and not replace) or not self._has_backup():
            return
        if _FAULTS:
            _fire_fault(_FP_REPLICATE, key=self.full_key,
                        host=self.backup_host)
        recording = self._stats.enabled
        t0 = time.monotonic_ns() if recording else 0
        nbytes = sum(len(v) for v in values)
        with span("state", "replicate_append", key=self.full_key,
                  nbytes=nbytes):
            try:
                self._client_factory(self.backup_host).replicate_append(
                    self.user, self.key, self.epoch, self.size, values,
                    replace=replace)
            except Exception as e:  # noqa: BLE001
                self._replication_failed(e)
        if recording:
            dt_ns = time.monotonic_ns() - t0
            charge_state_time(dt_ns)
            self._stats.record(self.full_key, "replicate", nbytes=nbytes,
                               seconds=dt_ns / 1e9, remote=True)
            self._comm.record(self.local_host, self.backup_host, "state",
                              nbytes, seconds=dt_ns / 1e9,
                              raw_bytes=nbytes)

    def _replication_failed(self, err: Exception) -> None:
        """A backup forward failed. StaleStateEpoch (possibly re-raised
        through the transport error channel) means WE were fenced out —
        a failover already promoted our backup — so this master must
        never ack again. Anything else: re-resolve placement; a newly
        elected backup gets a full anti-entropy sync (which covers the
        failed bytes — the local image already holds them); the same
        unreachable backup propagates the failure (an acked write is
        never silently unreplicated while a backup is assigned); no
        eligible backup left runs unreplicated, loudly."""
        if (isinstance(err, StaleStateEpoch)
                or "StaleStateEpoch" in str(err)):
            self._stale = True
            flight_record("state_fenced", key=self.full_key,
                          host=self.local_host, epoch=self.epoch)
            raise StaleStateEpoch(
                f"StaleStateEpoch: {self.full_key} master at "
                f"{self.local_host} was fenced out during failover"
            ) from err
        flight_record("state_replicate_fail", key=self.full_key,
                      backup=self.backup_host, error=repr(err))
        old_backup = self.backup_host
        if not self._reresolve_master_placement():
            if self._stale:
                raise StaleStateEpoch(
                    f"StaleStateEpoch: {self.full_key} master at "
                    f"{self.local_host} was fenced out during failover"
                ) from err
            raise err
        if self.backup_host and self.backup_host != old_backup:
            self.full_sync_backup()
        elif self.backup_host:
            raise err
        else:
            flight_record("state_unreplicated", key=self.full_key,
                          host=self.local_host)
            self._stats.set_replication_lag(self.full_key, self.size)

    def _reresolve_master_placement(self) -> bool:
        """Master side: re-claim through the planner after a failed
        forward; False when unresolvable or when the planner says we
        are no longer the master (→ fenced)."""
        if self._resolver is None:
            return False
        try:
            master, backup, epoch = self._resolver()
        except Exception:  # noqa: BLE001 — planner unreachable
            return False
        if master != self.local_host:
            self._stale = True
            flight_record("state_fenced", key=self.full_key,
                          host=self.local_host, epoch=epoch)
            return False
        self.backup_host = backup
        if epoch > self.epoch:
            self.epoch = epoch
        self._stats.note_key(self.full_key, master=master, backup=backup,
                             epoch=self.epoch)
        return True

    def full_sync_backup(self) -> None:
        """Anti-entropy: stream the whole image + append log to the
        current backup (fresh backup after a failover or a replicate-
        failure re-election). Replication lag — bytes the backup is
        still missing — is visible in statestats until the stream
        completes; byte-exact including the append log (replace, not
        additive)."""
        backup = self.backup_host
        if not self._has_backup():
            return
        client = self._client_factory(backup)
        self._stats.set_replication_lag(self.full_key, self.size)
        group_bytes = max(1, (4 << 20) // STATE_CHUNK_SIZE) \
            * STATE_CHUNK_SIZE
        sent = 0
        with span("state", "anti_entropy", key=self.full_key,
                  nbytes=self.size):
            for lo in range(0, self.size, group_bytes):
                hi = min(self.size, lo + group_bytes)
                with self._lock:
                    data = self._data[lo:hi].tobytes()
                client.replicate_chunks(self.user, self.key, self.epoch,
                                        self.size, [(lo, data)])
                sent += hi - lo
                self._stats.set_replication_lag(
                    self.full_key, max(0, self.size - sent))
            appended = (self.authority.all_appended()
                        if hasattr(self.authority, "all_appended") else [])
            client.replicate_append(self.user, self.key, self.epoch,
                                    self.size, appended, replace=True)
        self._stats.set_replication_lag(self.full_key, 0)
        flight_record("state_anti_entropy", key=self.full_key,
                      backup=backup, nbytes=self.size)

    def _flush_replication(self) -> None:
        """Master-local write path (set/set_chunk staged dirty chunks,
        then push_full/push_partial): forward the dirty chunks to the
        backup before they are acked/cleared."""
        if not self._has_backup():
            return
        with self._lock:
            dirty = [int(c) for c in np.where(self._dirty)[0]]
        if not dirty:
            return
        group_chunks = max(1, (4 << 20) // STATE_CHUNK_SIZE)
        for g in range(0, len(dirty), group_chunks):
            group = dirty[g:g + group_chunks]
            with self._lock:
                writes = []
                for c in group:
                    lo = c * STATE_CHUNK_SIZE
                    hi = min(self.size, lo + STATE_CHUNK_SIZE)
                    writes.append((lo, self._data[lo:hi].tobytes()))
            self._replicate_writes(writes)

    def _ensure_pulled(self, offset: int, length: int) -> int:
        """Pull any not-yet-pulled chunks covering the range from the
        authority; returns how many chunks travelled (0 = the read was
        served entirely from the local image)."""
        if self.is_master:
            return 0
        first, last = self._chunk_range(offset, length)
        with self._lock:
            missing = [c for c in range(first, min(last, self._pulled.size))
                       if not self._pulled[c]]
            fresh = sum(1 for c in missing if not self._ever_pulled[c])
        if not missing:
            return 0
        recording = self._stats.enabled
        t0 = time.monotonic_ns() if recording else 0
        nbytes = 0
        with span("state", "pull", key=self.full_key,
                  chunks=len(missing)):
            if _FAULTS:
                _fire_fault(_FP_PULL, key=self.full_key,
                            host=self.master_host)
            for c in missing:
                lo = c * STATE_CHUNK_SIZE
                hi = min(self.size, lo + STATE_CHUNK_SIZE)
                try:
                    data = self._remote_retry(
                        lambda lo=lo, hi=hi:
                        self.authority.pull_chunk(lo, hi - lo))
                except Exception as e:  # noqa: BLE001 — record, re-raise
                    flight_record("state_pull_fail", key=self.full_key,
                                  master=self.master_host, offset=lo,
                                  error=repr(e))
                    raise
                nbytes += len(data)
                with self._lock:
                    self._data[lo:lo + len(data)] = np.frombuffer(
                        data, np.uint8)
                    self._pulled[c] = True
                    self._ever_pulled[c] = True
                    self._bump_version_locked()
        if recording:
            dt_ns = time.monotonic_ns() - t0
            charge_state_time(dt_ns)
            self._stats.record(self.full_key, "pull", nbytes=nbytes,
                               chunks=len(missing), fresh_chunks=fresh,
                               seconds=dt_ns / 1e9, remote=True)
            # Master→client chunk traffic on the state plane: raw ==
            # wire today; a future delta-push path diverges them
            self._comm.record(self.master_host, self.local_host,
                              "state", nbytes, seconds=dt_ns / 1e9,
                              raw_bytes=nbytes)
        return len(missing)

    # ------------------------------------------------------------------
    # Reads
    # ------------------------------------------------------------------
    def get(self) -> bytes:
        pulled = self._ensure_pulled(0, self.size)
        self._stats.record(self.full_key, "get", nbytes=self.size,
                           remote=pulled > 0)
        with self._lock:
            return self._data.tobytes()

    def get_array(self) -> np.ndarray:
        pulled = self._ensure_pulled(0, self.size)
        self._stats.record(self.full_key, "get", nbytes=self.size,
                           remote=pulled > 0)
        with self._lock:
            return self._data.copy()

    def get_chunk(self, offset: int, length: int) -> bytes:
        if offset + length > self.size:
            raise ValueError(
                f"Chunk [{offset}, {offset + length}) out of bounds "
                f"(size {self.size})")
        pulled = self._ensure_pulled(offset, length)
        self._stats.record(self.full_key, "get_chunk", nbytes=length,
                           remote=pulled > 0)
        with self._lock:
            return self._data[offset:offset + length].tobytes()

    # ------------------------------------------------------------------
    # Writes
    # ------------------------------------------------------------------
    def set(self, data: bytes) -> None:
        if len(data) != self.size:
            raise ValueError(f"set() needs {self.size} bytes, got {len(data)}")
        with self._lock:
            self._data[:] = np.frombuffer(data, np.uint8)
            self._pulled[:] = True
            self._dirty[:] = True
            self._n_dirty = int(self._dirty.size)
            self._bump_version_locked()
            n_dirty = self._n_dirty
        self._stats.record(self.full_key, "set", nbytes=self.size,
                           chunks=n_chunks(self.size))
        self._stats.set_dirty_outstanding(self.full_key, n_dirty)

    def set_chunk(self, offset: int, data: bytes) -> None:
        if offset + len(data) > self.size:
            raise ValueError("Chunk write out of bounds")
        first, last = self._chunk_range(offset, len(data))
        with self._lock:
            self._data[offset:offset + len(data)] = np.frombuffer(data,
                                                                  np.uint8)
            self._n_dirty += int((~self._dirty[first:last]).sum())
            self._dirty[first:last] = True
            self._pulled[first:last] = True
            self._bump_version_locked()
            n_dirty = self._n_dirty
        self._stats.record(self.full_key, "set_chunk",
                           nbytes=len(data), chunks=last - first)
        self._stats.set_dirty_outstanding(self.full_key, n_dirty)

    # ------------------------------------------------------------------
    # Push / pull (non-master ↔ master)
    # ------------------------------------------------------------------
    def push_full(self) -> None:
        if self.is_master:
            # Replicated write path (ISSUE 19): forward the dirty chunks
            # to the backup BEFORE clearing them — returning from here
            # is the master-local ack
            self._flush_replication()
            with self._lock:
                self._dirty[:] = False
                self._n_dirty = 0
            self._stats.set_dirty_outstanding(self.full_key, 0)
            return
        recording = self._stats.enabled
        t0 = time.monotonic_ns() if recording else 0
        with self._lock:
            dirty_before = self._n_dirty
        payload = self.get()
        with span("state", "push_full", key=self.full_key,
                  nbytes=len(payload)):
            if _FAULTS:
                _fire_fault(_FP_PUSH, key=self.full_key,
                            host=self.master_host)
            try:
                self._remote_retry(
                    lambda: self.authority.push_chunk(0, payload))
            except Exception as e:  # noqa: BLE001 — record, re-raise
                flight_record("state_push_fail", key=self.full_key,
                              master=self.master_host, op="push_full",
                              error=repr(e))
                raise
        with self._lock:
            self._dirty[:] = False
            self._n_dirty = 0
        if recording:
            dt_ns = time.monotonic_ns() - t0
            charge_state_time(dt_ns)
            self._stats.record(self.full_key, "push_full",
                               nbytes=len(payload),
                               chunks=n_chunks(self.size),
                               dirty_chunks=dirty_before,
                               seconds=dt_ns / 1e9, remote=True)
            self._comm.record(self.local_host, self.master_host,
                              "state", len(payload),
                              seconds=dt_ns / 1e9,
                              raw_bytes=len(payload))
            self._stats.set_dirty_outstanding(self.full_key, 0)

    def push_partial(self) -> None:
        """Push only the dirty chunks (reference pushPartial)."""
        if self.is_master:
            # Replicated write path (ISSUE 19): dirty chunks reach the
            # backup before the master-local ack clears them
            self._flush_replication()
            with self._lock:
                self._dirty[:] = False
                self._n_dirty = 0
            self._stats.set_dirty_outstanding(self.full_key, 0)
            return
        with self._lock:
            dirty = [int(c) for c in np.where(self._dirty)[0]]
        if not dirty:
            return
        recording = self._stats.enabled
        t0 = time.monotonic_ns() if recording else 0
        nbytes = 0
        # Batched pushes (backends that can pipeline — redis — send each
        # group in one round-trip), bounded to a few MiB per group so a
        # fully-dirty multi-GiB value neither doubles peak memory nor
        # holds the kv lock for the whole payload copy
        group_chunks = max(1, (4 << 20) // STATE_CHUNK_SIZE)
        with span("state", "push_partial", key=self.full_key,
                  chunks=len(dirty)):
            if _FAULTS:
                _fire_fault(_FP_PUSH, key=self.full_key,
                            host=self.master_host)
            for g in range(0, len(dirty), group_chunks):
                group = dirty[g:g + group_chunks]
                with self._lock:
                    writes = []
                    for c in group:
                        lo = c * STATE_CHUNK_SIZE
                        hi = min(self.size, lo + STATE_CHUNK_SIZE)
                        writes.append((lo, self._data[lo:hi].tobytes()))
                try:
                    self._remote_retry(
                        lambda w=writes: self.authority.push_chunks(w))
                except Exception as e:  # noqa: BLE001 — record, re-raise
                    flight_record("state_push_fail", key=self.full_key,
                                  master=self.master_host,
                                  op="push_partial", error=repr(e))
                    raise
                nbytes += sum(len(d) for _off, d in writes)
                with self._lock:
                    for c in group:
                        self._dirty[c] = False
                    self._n_dirty = int(self._dirty.sum())
        if recording:
            dt_ns = time.monotonic_ns() - t0
            charge_state_time(dt_ns)
            self._stats.record(self.full_key, "push_partial",
                               nbytes=nbytes,
                               chunks=n_chunks(self.size),
                               dirty_chunks=len(dirty),
                               seconds=dt_ns / 1e9, remote=True)
            self._comm.record(self.local_host, self.master_host,
                              "state", nbytes, seconds=dt_ns / 1e9,
                              raw_bytes=nbytes)
            with self._lock:
                n_dirty = self._n_dirty
            self._stats.set_dirty_outstanding(self.full_key, n_dirty)

    def pull(self) -> None:
        """Re-pull the whole value from the master."""
        if self.is_master:
            return
        with self._lock:
            self._pulled[:] = False
        self._ensure_pulled(0, self.size)

    def n_dirty_chunks(self) -> int:
        with self._lock:
            return int(self._dirty.sum())

    # ------------------------------------------------------------------
    # Appends (reference append/getAppended/clearAppended)
    # ------------------------------------------------------------------
    def append(self, data: bytes) -> None:
        recording = self._stats.enabled
        t0 = time.monotonic_ns() if recording else 0
        with span("state", "append", key=self.full_key,
                  nbytes=len(data)):
            if self.is_master:
                self.authority.append(data)
                # Forward before returning: returning IS the ack
                self._replicate_append([bytes(data)])
            else:
                self._remote_retry(lambda: self.authority.append(data))
        if recording:
            dt_ns = time.monotonic_ns() - t0
            charge_state_time(dt_ns)
            self._stats.record(self.full_key, "append",
                               nbytes=len(data), seconds=dt_ns / 1e9,
                               remote=not self.is_master)

    def get_appended(self, n_values: int) -> list[bytes]:
        return self.authority.get_appended(n_values)

    def clear_appended(self) -> None:
        self.authority.clear_appended()
        if self.is_master:
            # Keep the replica's append log byte-exact (replace with
            # the now-empty log)
            self._replicate_append([], replace=True)

    # ------------------------------------------------------------------
    # Locks (authority-hosted)
    # ------------------------------------------------------------------
    def lock_global(self) -> None:
        recording = self._stats.enabled
        t0 = time.monotonic_ns() if recording else 0
        with span("state", "lock_global", key=self.full_key):
            self.authority.lock()
        if recording:
            wait_s = (time.monotonic_ns() - t0) / 1e9
            charge_state_time(int(wait_s * 1e9))
            stalled = wait_s >= lock_stall_threshold_s()
            self._stats.record(self.full_key, "lock_global",
                               seconds=wait_s,
                               remote=not self.is_master)
            self._stats.lock_wait(self.full_key, wait_s, stalled=stalled)
            if stalled:
                flight_record("state_lock_stall", key=self.full_key,
                              master=self.master_host,
                              wait_ms=round(wait_s * 1e3, 3))

    def unlock_global(self) -> None:
        self.authority.unlock()

    # ------------------------------------------------------------------
    # Device view (SURVEY §7 stage 6: "HBM-backed values with host↔device
    # sync") — the host image stays authoritative; chips hold a cached
    # jax array that refreshes when the host image changes
    # ------------------------------------------------------------------
    def get_device_array(self, dtype=None, sharding=None):
        """The value as a device-resident jax array (optionally viewed as
        ``dtype`` and placed with ``sharding``). Cached per (dtype,
        sharding) and invalidated whenever the host image mutates — a
        training step reading unchanged state pays zero transfers."""
        import jax

        self._ensure_pulled(0, self.size)
        with self._lock:
            version = self._version
            # Normalized dtype + the (hashable) sharding itself: equal
            # shardings hit one entry, and the dict keeps the sharding
            # alive so a recycled object id can never alias a stale entry
            key = (np.dtype(dtype).str if dtype is not None else None,
                   sharding)
            cached = self._device_cache.get(key)
            if cached is not None and cached[0] == version:
                return cached[1]
            host = self._data.copy()
        arr = host if dtype is None else host.view(dtype)
        dev = jax.device_put(arr, sharding)
        with self._lock:
            self._device_cache[key] = (version, dev)
        return dev

    def set_from_device(self, arr) -> None:
        """Write a device array's bytes back into the host image (device
        → host sync); push_partial/push_full then moves it to the
        authority."""
        host = np.asarray(arr).reshape(-1).view(np.uint8)
        if host.size != self.size:
            raise ValueError(
                f"device value is {host.size} bytes, KV holds {self.size}")
        self.set(host.tobytes())

    def _bump_version_locked(self) -> None:
        self._version += 1
        self._device_cache.clear()

    # -- master-side entry points used by the StateServer ---------------
    # Serving traffic is deliberately NOT ledgered here: each client
    # records its own pulls/pushes, and the statemap merge attributes
    # origin from those client-side rows — a server-side record would
    # double-count every remote byte in the cluster totals.
    def server_pull_chunk(self, offset: int, length: int) -> bytes:
        with self._lock:
            return self._data[offset:offset + length].tobytes()

    def server_push_chunk(self, offset: int, data: bytes) -> None:
        first, last = self._chunk_range(offset, len(data))
        with self._lock:
            if offset + len(data) > self.size:
                raise ValueError("Pushed chunk out of bounds")
            self._data[offset:offset + len(data)] = np.frombuffer(data,
                                                                  np.uint8)
            self._pulled[first:last] = True
            self._bump_version_locked()
        # Synchronous backup forward BEFORE the RPC response (the ack):
        # raising here means the client never sees success (ISSUE 19)
        self._replicate_writes([(offset, bytes(data))])

    def server_append(self, data: bytes) -> None:
        self.authority.append(data)
        self._replicate_append([bytes(data)])
