"""Distributed state key-value.

Reference analog: include/faabric/state/StateKeyValue.h:105-226 and
src/state/InMemoryStateKeyValue.cpp:90-260. One master host per key;
non-masters hold a local image with lazy **chunked pull** (pulled mask),
a **dirty-chunk mask** with partial push (only dirty chunks travel),
appends with remote retrieval, and read/write locks hosted by the master.

TPU deltas from the reference: values are numpy byte buffers (the device
round-trip is ``jax.device_put(kv.get_array(...))`` / ``kv.set(device_
get(...))`` — state stays host-resident, chips pull what they need).
Authority interactions (where the authoritative bytes live) go through a
pluggable :mod:`faabric_tpu.state.backend` — planner-elected in-memory
masters by default, shared-memory files with ``STATE_MODE=file``.

Observability (ISSUE 16): every op feeds the per-key access ledger
(:mod:`faabric_tpu.telemetry.statestats` — op counts, bytes, chunk
counts, dirty ratios, latency quantiles, pull amplification), remote
chunk traffic rides ``plane=state`` comm-matrix rows and ``state/*``
spans, pull/push failures and global-lock stalls flight-record, and
in-run state time charges the invocation's lifecycle ledger
(``charge_state_time``) so ``/healthz`` can attribute state-bound
invocations. With ``FAABRIC_METRICS=0`` every handle is the shared
no-op singleton and the hot paths skip even the clock reads.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

import numpy as np

from faabric_tpu.state.backend import (
    MasterMemoryAuthority,
    RemoteAuthority,
    StateAuthority,
)
from faabric_tpu.telemetry.commmatrix import get_comm_matrix
from faabric_tpu.telemetry.flight import flight_record
from faabric_tpu.telemetry.lifecycle import charge_state_time
from faabric_tpu.telemetry.statestats import (
    get_state_stats,
    lock_stall_threshold_s,
)
from faabric_tpu.telemetry.tracer import span
from faabric_tpu.util.logging import get_logger

logger = get_logger(__name__)

STATE_CHUNK_SIZE = 4096


def n_chunks(size: int) -> int:
    return max(1, (size + STATE_CHUNK_SIZE - 1) // STATE_CHUNK_SIZE)


class StateKeyValue:
    # Concurrency contract (tools/concheck.py): the image and every
    # mask/cache derived from it mutate under the one RLock. Telemetry
    # handles (_stats, _comm) are write-once in __init__ and internally
    # locked — plain attribute reads thereafter.
    GUARDS = {
        "_data": "_lock",
        "_pulled": "_lock",
        "_ever_pulled": "_lock",
        "_dirty": "_lock",
        "_n_dirty": "_lock",
        "_version": "_lock",
        "_device_cache": "_lock",
    }

    def __init__(self, user: str, key: str, size: int,
                 is_master: bool, master_host: str,
                 client_factory=None,
                 authority: Optional[StateAuthority] = None,
                 local_host: str = "") -> None:
        self.user = user
        self.key = key
        self.size = size
        self.master_host = master_host
        self.full_key = f"{user}/{key}"
        self.local_host = local_host or "local"

        if authority is None:
            authority = (MasterMemoryAuthority(user, key) if is_master
                         else RemoteAuthority(user, key, master_host,
                                              client_factory))
        self.authority = authority
        # "Master" now means: the authoritative bytes are THIS process's
        # image (the StateServer serves them from here)
        self.is_master = authority.local

        self._lock = threading.RLock()
        self._data = np.zeros(size, dtype=np.uint8)
        # Device-view cache keyed by (dtype, sharding), invalidated by
        # host-image mutation (get_device_array)
        self._version = 0
        self._device_cache: dict = {}
        chunks = n_chunks(size)
        # Local-authority data is authoritative: everything is "pulled"
        self._pulled = np.full(chunks, self.is_master, dtype=bool)
        # Monotone: chunks pulled at least once, ever — the denominator
        # of the pull-amplification signal (pull() resets _pulled but
        # never this, so a re-pull of a clean chunk reads as repeat)
        self._ever_pulled = np.full(chunks, self.is_master, dtype=bool)
        self._dirty = np.zeros(chunks, dtype=bool)
        self._n_dirty = 0

        self._stats = get_state_stats()
        self._comm = get_comm_matrix()
        self._stats.note_key(self.full_key, master=master_host,
                             size=size, is_master=self.is_master)

    # ------------------------------------------------------------------
    def _chunk_range(self, offset: int, length: int) -> tuple[int, int]:
        first = offset // STATE_CHUNK_SIZE
        last = (offset + max(1, length) - 1) // STATE_CHUNK_SIZE
        return first, last + 1

    def _ensure_pulled(self, offset: int, length: int) -> int:
        """Pull any not-yet-pulled chunks covering the range from the
        authority; returns how many chunks travelled (0 = the read was
        served entirely from the local image)."""
        if self.is_master:
            return 0
        first, last = self._chunk_range(offset, length)
        with self._lock:
            missing = [c for c in range(first, min(last, self._pulled.size))
                       if not self._pulled[c]]
            fresh = sum(1 for c in missing if not self._ever_pulled[c])
        if not missing:
            return 0
        recording = self._stats.enabled
        t0 = time.monotonic_ns() if recording else 0
        nbytes = 0
        with span("state", "pull", key=self.full_key,
                  chunks=len(missing)):
            for c in missing:
                lo = c * STATE_CHUNK_SIZE
                hi = min(self.size, lo + STATE_CHUNK_SIZE)
                try:
                    data = self.authority.pull_chunk(lo, hi - lo)
                except Exception as e:  # noqa: BLE001 — record, re-raise
                    flight_record("state_pull_fail", key=self.full_key,
                                  master=self.master_host, offset=lo,
                                  error=repr(e))
                    raise
                nbytes += len(data)
                with self._lock:
                    self._data[lo:lo + len(data)] = np.frombuffer(
                        data, np.uint8)
                    self._pulled[c] = True
                    self._ever_pulled[c] = True
                    self._bump_version_locked()
        if recording:
            dt_ns = time.monotonic_ns() - t0
            charge_state_time(dt_ns)
            self._stats.record(self.full_key, "pull", nbytes=nbytes,
                               chunks=len(missing), fresh_chunks=fresh,
                               seconds=dt_ns / 1e9, remote=True)
            # Master→client chunk traffic on the state plane: raw ==
            # wire today; a future delta-push path diverges them
            self._comm.record(self.master_host, self.local_host,
                              "state", nbytes, seconds=dt_ns / 1e9,
                              raw_bytes=nbytes)
        return len(missing)

    # ------------------------------------------------------------------
    # Reads
    # ------------------------------------------------------------------
    def get(self) -> bytes:
        pulled = self._ensure_pulled(0, self.size)
        self._stats.record(self.full_key, "get", nbytes=self.size,
                           remote=pulled > 0)
        with self._lock:
            return self._data.tobytes()

    def get_array(self) -> np.ndarray:
        pulled = self._ensure_pulled(0, self.size)
        self._stats.record(self.full_key, "get", nbytes=self.size,
                           remote=pulled > 0)
        with self._lock:
            return self._data.copy()

    def get_chunk(self, offset: int, length: int) -> bytes:
        if offset + length > self.size:
            raise ValueError(
                f"Chunk [{offset}, {offset + length}) out of bounds "
                f"(size {self.size})")
        pulled = self._ensure_pulled(offset, length)
        self._stats.record(self.full_key, "get_chunk", nbytes=length,
                           remote=pulled > 0)
        with self._lock:
            return self._data[offset:offset + length].tobytes()

    # ------------------------------------------------------------------
    # Writes
    # ------------------------------------------------------------------
    def set(self, data: bytes) -> None:
        if len(data) != self.size:
            raise ValueError(f"set() needs {self.size} bytes, got {len(data)}")
        with self._lock:
            self._data[:] = np.frombuffer(data, np.uint8)
            self._pulled[:] = True
            self._dirty[:] = True
            self._n_dirty = int(self._dirty.size)
            self._bump_version_locked()
            n_dirty = self._n_dirty
        self._stats.record(self.full_key, "set", nbytes=self.size,
                           chunks=n_chunks(self.size))
        self._stats.set_dirty_outstanding(self.full_key, n_dirty)

    def set_chunk(self, offset: int, data: bytes) -> None:
        if offset + len(data) > self.size:
            raise ValueError("Chunk write out of bounds")
        first, last = self._chunk_range(offset, len(data))
        with self._lock:
            self._data[offset:offset + len(data)] = np.frombuffer(data,
                                                                  np.uint8)
            self._n_dirty += int((~self._dirty[first:last]).sum())
            self._dirty[first:last] = True
            self._pulled[first:last] = True
            self._bump_version_locked()
            n_dirty = self._n_dirty
        self._stats.record(self.full_key, "set_chunk",
                           nbytes=len(data), chunks=last - first)
        self._stats.set_dirty_outstanding(self.full_key, n_dirty)

    # ------------------------------------------------------------------
    # Push / pull (non-master ↔ master)
    # ------------------------------------------------------------------
    def push_full(self) -> None:
        if self.is_master:
            with self._lock:
                self._dirty[:] = False
                self._n_dirty = 0
            self._stats.set_dirty_outstanding(self.full_key, 0)
            return
        recording = self._stats.enabled
        t0 = time.monotonic_ns() if recording else 0
        with self._lock:
            dirty_before = self._n_dirty
        payload = self.get()
        with span("state", "push_full", key=self.full_key,
                  nbytes=len(payload)):
            try:
                self.authority.push_chunk(0, payload)
            except Exception as e:  # noqa: BLE001 — record, re-raise
                flight_record("state_push_fail", key=self.full_key,
                              master=self.master_host, op="push_full",
                              error=repr(e))
                raise
        with self._lock:
            self._dirty[:] = False
            self._n_dirty = 0
        if recording:
            dt_ns = time.monotonic_ns() - t0
            charge_state_time(dt_ns)
            self._stats.record(self.full_key, "push_full",
                               nbytes=len(payload),
                               chunks=n_chunks(self.size),
                               dirty_chunks=dirty_before,
                               seconds=dt_ns / 1e9, remote=True)
            self._comm.record(self.local_host, self.master_host,
                              "state", len(payload),
                              seconds=dt_ns / 1e9,
                              raw_bytes=len(payload))
            self._stats.set_dirty_outstanding(self.full_key, 0)

    def push_partial(self) -> None:
        """Push only the dirty chunks (reference pushPartial)."""
        if self.is_master:
            with self._lock:
                self._dirty[:] = False
                self._n_dirty = 0
            self._stats.set_dirty_outstanding(self.full_key, 0)
            return
        with self._lock:
            dirty = [int(c) for c in np.where(self._dirty)[0]]
        if not dirty:
            return
        recording = self._stats.enabled
        t0 = time.monotonic_ns() if recording else 0
        nbytes = 0
        # Batched pushes (backends that can pipeline — redis — send each
        # group in one round-trip), bounded to a few MiB per group so a
        # fully-dirty multi-GiB value neither doubles peak memory nor
        # holds the kv lock for the whole payload copy
        group_chunks = max(1, (4 << 20) // STATE_CHUNK_SIZE)
        with span("state", "push_partial", key=self.full_key,
                  chunks=len(dirty)):
            for g in range(0, len(dirty), group_chunks):
                group = dirty[g:g + group_chunks]
                with self._lock:
                    writes = []
                    for c in group:
                        lo = c * STATE_CHUNK_SIZE
                        hi = min(self.size, lo + STATE_CHUNK_SIZE)
                        writes.append((lo, self._data[lo:hi].tobytes()))
                try:
                    self.authority.push_chunks(writes)
                except Exception as e:  # noqa: BLE001 — record, re-raise
                    flight_record("state_push_fail", key=self.full_key,
                                  master=self.master_host,
                                  op="push_partial", error=repr(e))
                    raise
                nbytes += sum(len(d) for _off, d in writes)
                with self._lock:
                    for c in group:
                        self._dirty[c] = False
                    self._n_dirty = int(self._dirty.sum())
        if recording:
            dt_ns = time.monotonic_ns() - t0
            charge_state_time(dt_ns)
            self._stats.record(self.full_key, "push_partial",
                               nbytes=nbytes,
                               chunks=n_chunks(self.size),
                               dirty_chunks=len(dirty),
                               seconds=dt_ns / 1e9, remote=True)
            self._comm.record(self.local_host, self.master_host,
                              "state", nbytes, seconds=dt_ns / 1e9,
                              raw_bytes=nbytes)
            with self._lock:
                n_dirty = self._n_dirty
            self._stats.set_dirty_outstanding(self.full_key, n_dirty)

    def pull(self) -> None:
        """Re-pull the whole value from the master."""
        if self.is_master:
            return
        with self._lock:
            self._pulled[:] = False
        self._ensure_pulled(0, self.size)

    def n_dirty_chunks(self) -> int:
        with self._lock:
            return int(self._dirty.sum())

    # ------------------------------------------------------------------
    # Appends (reference append/getAppended/clearAppended)
    # ------------------------------------------------------------------
    def append(self, data: bytes) -> None:
        recording = self._stats.enabled
        t0 = time.monotonic_ns() if recording else 0
        with span("state", "append", key=self.full_key,
                  nbytes=len(data)):
            self.authority.append(data)
        if recording:
            dt_ns = time.monotonic_ns() - t0
            charge_state_time(dt_ns)
            self._stats.record(self.full_key, "append",
                               nbytes=len(data), seconds=dt_ns / 1e9,
                               remote=not self.is_master)

    def get_appended(self, n_values: int) -> list[bytes]:
        return self.authority.get_appended(n_values)

    def clear_appended(self) -> None:
        self.authority.clear_appended()

    # ------------------------------------------------------------------
    # Locks (authority-hosted)
    # ------------------------------------------------------------------
    def lock_global(self) -> None:
        recording = self._stats.enabled
        t0 = time.monotonic_ns() if recording else 0
        with span("state", "lock_global", key=self.full_key):
            self.authority.lock()
        if recording:
            wait_s = (time.monotonic_ns() - t0) / 1e9
            charge_state_time(int(wait_s * 1e9))
            stalled = wait_s >= lock_stall_threshold_s()
            self._stats.record(self.full_key, "lock_global",
                               seconds=wait_s,
                               remote=not self.is_master)
            self._stats.lock_wait(self.full_key, wait_s, stalled=stalled)
            if stalled:
                flight_record("state_lock_stall", key=self.full_key,
                              master=self.master_host,
                              wait_ms=round(wait_s * 1e3, 3))

    def unlock_global(self) -> None:
        self.authority.unlock()

    # ------------------------------------------------------------------
    # Device view (SURVEY §7 stage 6: "HBM-backed values with host↔device
    # sync") — the host image stays authoritative; chips hold a cached
    # jax array that refreshes when the host image changes
    # ------------------------------------------------------------------
    def get_device_array(self, dtype=None, sharding=None):
        """The value as a device-resident jax array (optionally viewed as
        ``dtype`` and placed with ``sharding``). Cached per (dtype,
        sharding) and invalidated whenever the host image mutates — a
        training step reading unchanged state pays zero transfers."""
        import jax

        self._ensure_pulled(0, self.size)
        with self._lock:
            version = self._version
            # Normalized dtype + the (hashable) sharding itself: equal
            # shardings hit one entry, and the dict keeps the sharding
            # alive so a recycled object id can never alias a stale entry
            key = (np.dtype(dtype).str if dtype is not None else None,
                   sharding)
            cached = self._device_cache.get(key)
            if cached is not None and cached[0] == version:
                return cached[1]
            host = self._data.copy()
        arr = host if dtype is None else host.view(dtype)
        dev = jax.device_put(arr, sharding)
        with self._lock:
            self._device_cache[key] = (version, dev)
        return dev

    def set_from_device(self, arr) -> None:
        """Write a device array's bytes back into the host image (device
        → host sync); push_partial/push_full then moves it to the
        authority."""
        host = np.asarray(arr).reshape(-1).view(np.uint8)
        if host.size != self.size:
            raise ValueError(
                f"device value is {host.size} bytes, KV holds {self.size}")
        self.set(host.tobytes())

    def _bump_version_locked(self) -> None:
        self._version += 1
        self._device_cache.clear()

    # -- master-side entry points used by the StateServer ---------------
    # Serving traffic is deliberately NOT ledgered here: each client
    # records its own pulls/pushes, and the statemap merge attributes
    # origin from those client-side rows — a server-side record would
    # double-count every remote byte in the cluster totals.
    def server_pull_chunk(self, offset: int, length: int) -> bytes:
        with self._lock:
            return self._data[offset:offset + length].tobytes()

    def server_push_chunk(self, offset: int, data: bytes) -> None:
        first, last = self._chunk_range(offset, len(data))
        with self._lock:
            if offset + len(data) > self.size:
                raise ValueError("Pushed chunk out of bounds")
            self._data[offset:offset + len(data)] = np.frombuffer(data,
                                                                  np.uint8)
            self._pulled[first:last] = True
            self._bump_version_locked()

    def server_append(self, data: bytes) -> None:
        self.authority.append(data)
