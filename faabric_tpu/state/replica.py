"""Passive per-key replicas — the backup side of the replicated write
path (ISSUE 19).

A master forwards every acked mutation (dirty chunks, appends) to its
planner-placed backup host BEFORE acking the client; the backup applies
them into a :class:`StateReplica` — a byte image plus append log plus
the epoch the bytes were forwarded under. No reads are ever served from
a replica: it exists only to be promoted. On failover the planner bumps
the epoch and tells the backup to promote (or the first fenced client op
triggers self-promotion); promotion converts the replica into a real
master :class:`~faabric_tpu.state.kv.StateKeyValue` seeded with the
replica's image, which is exactly the set of acknowledged writes.

Epoch fencing lives here too: a forward carrying an epoch older than the
replica's is a write from a fenced-out ex-master and raises
:class:`~faabric_tpu.state.backend.StaleStateEpoch` — the rejection that
makes it impossible for a stale master to ack (its ack path requires
this forward to succeed).
"""

from __future__ import annotations

import threading

import numpy as np

from faabric_tpu.state.backend import StaleStateEpoch


class StateReplica:
    # Concurrency contract (tools/concheck.py): image, append log, size
    # and epoch all mutate together under one lock (a forward must be
    # applied atomically against the fence check).
    GUARDS = {
        "_data": "_lock",
        "_appended": "_lock",
        "_epoch": "_lock",
        "_size": "_lock",
    }

    def __init__(self, user: str, key: str, size: int,
                 epoch: int = 0) -> None:
        self.user = user
        self.key = key
        self.full_key = f"{user}/{key}"
        self._lock = threading.Lock()
        self._size = size
        self._data = np.zeros(size, dtype=np.uint8)
        self._appended: list[bytes] = []
        self._epoch = epoch

    @property
    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    @property
    def size(self) -> int:
        with self._lock:
            return self._size

    def _fence_locked(self, epoch: int, size: int) -> None:
        if epoch < self._epoch:
            raise StaleStateEpoch(
                f"StaleStateEpoch: replicate of {self.full_key} at epoch "
                f"{epoch} rejected (replica at epoch {self._epoch})")
        self._epoch = epoch
        if size > self._size:
            grown = np.zeros(size, dtype=np.uint8)
            grown[:self._size] = self._data
            self._data = grown
            self._size = size

    def apply_chunks(self, epoch: int, size: int,
                     writes: list[tuple[int, bytes]]) -> None:
        with self._lock:
            self._fence_locked(epoch, size)
            for offset, data in writes:
                if offset + len(data) > self._size:
                    raise ValueError(
                        f"Replicated chunk [{offset}, "
                        f"{offset + len(data)}) out of bounds "
                        f"(size {self._size})")
                self._data[offset:offset + len(data)] = np.frombuffer(
                    data, np.uint8)

    def apply_append(self, epoch: int, size: int, values: list[bytes],
                     replace: bool = False) -> None:
        """Forwarded appends; ``replace=True`` swaps the whole log
        (anti-entropy full sync — byte-exact, not additive)."""
        with self._lock:
            self._fence_locked(epoch, size)
            if replace:
                self._appended[:] = [bytes(v) for v in values]
            else:
                self._appended.extend(bytes(v) for v in values)

    def snapshot(self) -> tuple[bytes, list[bytes], int]:
        """(image, appended values, epoch) — the promotion payload."""
        with self._lock:
            return (self._data.tobytes(), list(self._appended),
                    self._epoch)
