"""Host-wide state: user/key → StateKeyValue.

Reference analog: include/faabric/state/State.h:23-59 and
src/state/State.cpp:100-160. ``get_kv`` resolves the key's master through
the planner (first caller claims mastership) and caches the KV locally.

ISSUE 19: this object also hosts the BACKUP side of the replicated
write path — passive :class:`~faabric_tpu.state.replica.StateReplica`
images that masters forward acked writes into, and the promotion paths
(planner PROMOTE RPC or fenced-op self-promotion) that convert a replica
into a real master KV after failover.
"""

from __future__ import annotations

import threading
from typing import Optional

from faabric_tpu.state.backend import StaleStateEpoch
from faabric_tpu.state.kv import StateKeyValue
from faabric_tpu.state.replica import StateReplica
from faabric_tpu.util.logging import get_logger

logger = get_logger(__name__)


class State:
    # Concurrency contract (tools/concheck.py)
    GUARDS = {
        "_kvs": "_lock",
        "_replicas": "_lock",
    }

    def __init__(self, host: str, planner_client=None) -> None:
        self.host = host
        self.planner_client = planner_client
        self._lock = threading.Lock()
        self._kvs: dict[str, StateKeyValue] = {}
        # Passive replicas this host backs for OTHER hosts' masters
        # (ISSUE 19) — never read from, only promoted
        self._replicas: dict[str, StateReplica] = {}

        from faabric_tpu.state.remote import StateClient
        from faabric_tpu.transport.client_pool import ClientPool

        self._state_clients = ClientPool(StateClient)

    # ------------------------------------------------------------------
    def _client_factory(self, master_host: str):
        return self._state_clients.get(master_host)

    def close_clients(self) -> None:
        """Close every pooled outbound state connection (runtime
        teardown). Safe mid-life: the pool re-dials lazily on the next
        remote op."""
        self._state_clients.close_all()

    def get_kv(self, user: str, key: str, size: int = 0) -> StateKeyValue:
        full = f"{user}/{key}"
        with self._lock:
            kv = self._kvs.get(full)
        if kv is not None:
            return kv

        from faabric_tpu.util.config import get_system_config

        conf = get_system_config()
        mode = conf.state_mode
        if mode in ("file", "shm"):
            kv = self._make_file_kv(user, key, size, conf)
        elif mode == "redis":
            from faabric_tpu.state.backend import RedisAuthority

            authority = RedisAuthority(user, key, size)
            kv = StateKeyValue(user, key, authority.size, False, "<redis>",
                               authority=authority, local_host=self.host)
        elif mode != "inmemory":
            raise ValueError(f"Unknown STATE_MODE {mode!r}")
        else:
            kv = self._make_inmemory_kv(user, key, size)

        with self._lock:
            # Another thread may have raced us; first one wins
            existing = self._kvs.get(full)
            if existing is not None:
                return existing
            self._kvs[full] = kv
        logger.debug("%s created KV %s (mode=%s master=%s size=%d)",
                     self.host, full, mode, kv.master_host, kv.size)
        return kv

    def _make_file_kv(self, user: str, key: str, size: int,
                      conf) -> StateKeyValue:
        from faabric_tpu.state.backend import SharedFileAuthority

        if size <= 0:
            size = SharedFileAuthority.existing_size(user, key,
                                                     conf.state_dir)
            if size <= 0:
                raise ValueError(
                    f"State key {user}/{key} does not exist yet; creation "
                    "needs an explicit size")
        authority = SharedFileAuthority(user, key, size, conf.state_dir)
        return StateKeyValue(user, key, authority.size, False, "<file>",
                             authority=authority, local_host=self.host)

    def _resolver_for(self, user: str, key: str):
        """Placement re-resolution closure handed to each in-memory KV:
        one planner claim returning (master, backup, epoch)."""
        if self.planner_client is None:
            return None

        def resolve() -> tuple[str, str, int]:
            return self.planner_client.claim_state_master(user, key)

        return resolve

    def _make_inmemory_kv(self, user: str, key: str,
                          size: int) -> StateKeyValue:
        from faabric_tpu.telemetry import flight_record

        full = f"{user}/{key}"
        if self.planner_client is not None:
            master, backup, epoch = \
                self.planner_client.claim_state_master(user, key)
        else:
            master, backup, epoch = self.host, "", 0
        is_master = master == self.host
        if is_master:
            flight_record("state_master_claim", key=full, host=self.host,
                          size=max(size, 0), backup=backup, epoch=epoch)

        if size <= 0:
            if is_master:
                # We just claimed a key we cannot create (no size): release
                # the claim so the eventual creator can become master
                # instead of the key being poisoned cluster-wide
                if self.planner_client is not None:
                    try:
                        self.planner_client.drop_state_master(user, key)
                        flight_record("state_master_drop", key=full,
                                      host=self.host, reason="no_size")
                    except Exception:  # noqa: BLE001
                        logger.warning("Could not release claim on %s", full)
                raise ValueError(
                    f"Master creation of {full} needs an explicit size")
            size = self._client_factory(master).state_size(user, key,
                                                           epoch=epoch)

        return StateKeyValue(user, key, size, is_master, master,
                             client_factory=self._client_factory,
                             local_host=self.host, backup_host=backup,
                             epoch=epoch,
                             resolver=self._resolver_for(user, key))

    def try_get_kv(self, user: str, key: str) -> Optional[StateKeyValue]:
        with self._lock:
            return self._kvs.get(f"{user}/{key}")

    def delete_kv(self, user: str, key: str) -> None:
        with self._lock:
            kv = self._kvs.pop(f"{user}/{key}", None)
            self._replicas.pop(f"{user}/{key}", None)
        if kv is not None and kv.is_master \
                and self.planner_client is not None:
            try:
                self.planner_client.drop_state_master(user, key)
                from faabric_tpu.telemetry import flight_record

                flight_record("state_master_drop", key=f"{user}/{key}",
                              host=self.host, reason="delete")
            except Exception:  # noqa: BLE001
                logger.debug("Could not drop master for %s/%s", user, key)

    def get_kv_count(self) -> int:
        with self._lock:
            return len(self._kvs)

    def clear(self) -> None:
        with self._lock:
            self._kvs.clear()
            self._replicas.clear()
        self._state_clients.close_all()

    # ------------------------------------------------------------------
    # Backup side of the replicated write path (ISSUE 19): masters
    # forward acked writes here; the planner (or a fenced client op)
    # promotes the replica after the master dies.
    # ------------------------------------------------------------------
    def _get_replica(self, full: str, size: int, epoch: int) -> StateReplica:
        with self._lock:
            rep = self._replicas.get(full)
            if rep is None:
                user, _, key = full.partition("/")
                rep = StateReplica(user, key, size, epoch=epoch)
                self._replicas[full] = rep
            return rep

    def replica_count(self) -> int:
        with self._lock:
            return len(self._replicas)

    def apply_replica_chunks(self, user: str, key: str, epoch: int,
                             size: int,
                             writes: list[tuple[int, bytes]]) -> None:
        full = f"{user}/{key}"
        self._fence_or_demote_master(full, epoch)
        self._get_replica(full, size, epoch).apply_chunks(
            epoch, size, writes)

    def apply_replica_append(self, user: str, key: str, epoch: int,
                             size: int, values: list[bytes],
                             replace: bool = False) -> None:
        full = f"{user}/{key}"
        self._fence_or_demote_master(full, epoch)
        self._get_replica(full, size, epoch).apply_append(
            epoch, size, values, replace=replace)

    def _fence_or_demote_master(self, full: str, epoch: int) -> None:
        """A replicate forward arrived for a key THIS host masters.
        Older-or-equal epoch: the sender is a fenced-out ex-master still
        trying to ack — reject (this rejection is what makes a stale
        ack structurally impossible). Newer epoch: WE are the stale
        ex-master and a legitimately promoted master is replicating to
        us — demote our KV into a replica seeded with its image."""
        user, _, key = full.partition("/")
        kv = self.try_get_kv(user, key)
        if kv is None or not kv.is_master:
            return
        if epoch <= kv.epoch:
            raise StaleStateEpoch(
                f"StaleStateEpoch: replicate of {full} at epoch {epoch} "
                f"rejected by its master at {self.host} "
                f"(epoch {kv.epoch})")
        from faabric_tpu.telemetry import flight_record

        logger.warning(
            "Demoting stale master %s at %s: epoch %d replicate arrived "
            "(local epoch %d)", full, self.host, epoch, kv.epoch)
        kv.mark_stale()
        image = kv.get()
        appended = (kv.authority.all_appended()
                    if hasattr(kv.authority, "all_appended") else [])
        rep = self._get_replica(full, kv.size, kv.epoch)
        rep.apply_chunks(kv.epoch, kv.size, [(0, image)])
        rep.apply_append(kv.epoch, kv.size, appended, replace=True)
        with self._lock:
            self._kvs.pop(full, None)
        flight_record("state_demoted", key=full, host=self.host,
                      old_epoch=kv.epoch, new_epoch=epoch)

    def maybe_self_promote(self, user: str, key: str,
                           req_epoch: int) -> Optional[StateKeyValue]:
        """A fenced client op landed here but no master KV exists: if we
        back a replica at an older epoch, the planner's journal made us
        the owner (clients only learn epochs from planner claims) and
        the PROMOTE notify was lost or has not arrived yet — promote
        now. Returns the new master KV, or None."""
        full = f"{user}/{key}"
        with self._lock:
            rep = self._replicas.get(full)
        if rep is None or req_epoch <= rep.epoch:
            return None
        if self.promote_replica(user, key, req_epoch, ""):
            return self.try_get_kv(user, key)
        return None

    def promote_replica(self, user: str, key: str, epoch: int,
                        backup: str) -> bool:
        """Convert this host's replica into the authoritative master
        copy at ``epoch`` (failover). Idempotent: a duplicate PROMOTE
        for an already-promoted key just returns True. False = no
        replica here (the planner drops the mastership so the next
        claim re-elects). The new backup anti-entropy-syncs from the
        promoted image on a background thread."""
        full = f"{user}/{key}"
        from faabric_tpu.telemetry import flight_record

        with self._lock:
            existing = self._kvs.get(full)
            if (existing is not None and existing.is_master
                    and existing.epoch >= epoch):
                return True
            rep = self._replicas.get(full)
        if rep is None:
            return False
        image, appended, _rep_epoch = rep.snapshot()
        kv = StateKeyValue(user, key, len(image), True, self.host,
                           client_factory=self._client_factory,
                           local_host=self.host, backup_host=backup,
                           epoch=epoch,
                           resolver=self._resolver_for(user, key))
        kv.load_image(image, appended)
        with self._lock:
            # Replace any stale non-master KV for the key (a demoted
            # ex-master was already removed by _fence_or_demote_master)
            self._kvs[full] = kv
            self._replicas.pop(full, None)
        logger.warning("Promoted replica %s to master at %s (epoch %d, "
                       "new backup %r)", full, self.host, epoch, backup)
        flight_record("state_promoted", key=full, host=self.host,
                      epoch=epoch, backup=backup, size=kv.size)
        self._start_anti_entropy(kv)
        return True

    def _start_anti_entropy(self, kv: StateKeyValue) -> None:
        """Post-promotion: learn the new backup from the planner if the
        PROMOTE carried none, then stream the full image to it. Off the
        server thread — promotion must ack fast; the replication-lag
        gauge stays honest (== size) until the sync lands."""
        def run() -> None:
            try:
                if not kv.backup_host and self.planner_client is not None:
                    master, backup, epoch = \
                        self.planner_client.claim_state_master(kv.user,
                                                               kv.key)
                    if master != self.host:
                        return  # superseded by a newer failover
                    kv.adopt_placement(backup, epoch)
                kv.full_sync_backup()
            except Exception as e:  # noqa: BLE001 — retried by the next
                # replicate-failure re-resolve; the lag gauge stays loud
                logger.warning("Anti-entropy sync of %s to %r failed: %s",
                               kv.full_key, kv.backup_host, e)

        threading.Thread(target=run, daemon=True,
                         name=f"state/anti-entropy@{kv.full_key}").start()
