"""Host-wide state: user/key → StateKeyValue.

Reference analog: include/faabric/state/State.h:23-59 and
src/state/State.cpp:100-160. ``get_kv`` resolves the key's master through
the planner (first caller claims mastership) and caches the KV locally.
"""

from __future__ import annotations

import threading
from typing import Optional

from faabric_tpu.state.kv import StateKeyValue
from faabric_tpu.util.logging import get_logger

logger = get_logger(__name__)


class State:
    # Concurrency contract (tools/concheck.py)
    GUARDS = {
        "_kvs": "_lock",
    }

    def __init__(self, host: str, planner_client=None) -> None:
        self.host = host
        self.planner_client = planner_client
        self._lock = threading.Lock()
        self._kvs: dict[str, StateKeyValue] = {}

        from faabric_tpu.state.remote import StateClient
        from faabric_tpu.transport.client_pool import ClientPool

        self._state_clients = ClientPool(StateClient)

    # ------------------------------------------------------------------
    def _client_factory(self, master_host: str):
        return self._state_clients.get(master_host)

    def get_kv(self, user: str, key: str, size: int = 0) -> StateKeyValue:
        full = f"{user}/{key}"
        with self._lock:
            kv = self._kvs.get(full)
        if kv is not None:
            return kv

        from faabric_tpu.util.config import get_system_config

        conf = get_system_config()
        mode = conf.state_mode
        if mode in ("file", "shm"):
            kv = self._make_file_kv(user, key, size, conf)
        elif mode == "redis":
            from faabric_tpu.state.backend import RedisAuthority

            authority = RedisAuthority(user, key, size)
            kv = StateKeyValue(user, key, authority.size, False, "<redis>",
                               authority=authority, local_host=self.host)
        elif mode != "inmemory":
            raise ValueError(f"Unknown STATE_MODE {mode!r}")
        else:
            kv = self._make_inmemory_kv(user, key, size)

        with self._lock:
            # Another thread may have raced us; first one wins
            existing = self._kvs.get(full)
            if existing is not None:
                return existing
            self._kvs[full] = kv
        logger.debug("%s created KV %s (mode=%s master=%s size=%d)",
                     self.host, full, mode, kv.master_host, kv.size)
        return kv

    def _make_file_kv(self, user: str, key: str, size: int,
                      conf) -> StateKeyValue:
        from faabric_tpu.state.backend import SharedFileAuthority

        if size <= 0:
            size = SharedFileAuthority.existing_size(user, key,
                                                     conf.state_dir)
            if size <= 0:
                raise ValueError(
                    f"State key {user}/{key} does not exist yet; creation "
                    "needs an explicit size")
        authority = SharedFileAuthority(user, key, size, conf.state_dir)
        return StateKeyValue(user, key, authority.size, False, "<file>",
                             authority=authority, local_host=self.host)

    def _make_inmemory_kv(self, user: str, key: str,
                          size: int) -> StateKeyValue:
        from faabric_tpu.telemetry import flight_record

        full = f"{user}/{key}"
        if self.planner_client is not None:
            master = self.planner_client.claim_state_master(user, key)
        else:
            master = self.host
        is_master = master == self.host
        if is_master:
            flight_record("state_master_claim", key=full, host=self.host,
                          size=max(size, 0))

        if size <= 0:
            if is_master:
                # We just claimed a key we cannot create (no size): release
                # the claim so the eventual creator can become master
                # instead of the key being poisoned cluster-wide
                if self.planner_client is not None:
                    try:
                        self.planner_client.drop_state_master(user, key)
                        flight_record("state_master_drop", key=full,
                                      host=self.host, reason="no_size")
                    except Exception:  # noqa: BLE001
                        logger.warning("Could not release claim on %s", full)
                raise ValueError(
                    f"Master creation of {full} needs an explicit size")
            size = self._client_factory(master).state_size(user, key)

        return StateKeyValue(user, key, size, is_master, master,
                             client_factory=self._client_factory,
                             local_host=self.host)

    def try_get_kv(self, user: str, key: str) -> Optional[StateKeyValue]:
        with self._lock:
            return self._kvs.get(f"{user}/{key}")

    def delete_kv(self, user: str, key: str) -> None:
        with self._lock:
            kv = self._kvs.pop(f"{user}/{key}", None)
        if kv is not None and kv.is_master \
                and self.planner_client is not None:
            try:
                self.planner_client.drop_state_master(user, key)
                from faabric_tpu.telemetry import flight_record

                flight_record("state_master_drop", key=f"{user}/{key}",
                              host=self.host, reason="delete")
            except Exception:  # noqa: BLE001
                logger.debug("Could not drop master for %s/%s", user, key)

    def get_kv_count(self) -> int:
        with self._lock:
            return len(self._kvs)

    def clear(self) -> None:
        with self._lock:
            self._kvs.clear()
        self._state_clients.close_all()
