"""Distributed state KV (reference src/state)."""

from faabric_tpu.state.backend import (
    MasterMemoryAuthority,
    RedisAuthority,
    RemoteAuthority,
    SharedFileAuthority,
    StaleStateEpoch,
    StateAuthority,
)
from faabric_tpu.state.device_handle import (
    DeviceHandleError,
    DeviceHandleRegistry,
    DeviceStateHandle,
    StaleDeviceHandle,
    get_device_handle_registry,
    reset_device_handles,
)
from faabric_tpu.state.kv import STATE_CHUNK_SIZE, StateKeyValue
from faabric_tpu.state.placement import place_backup, ring_order
from faabric_tpu.state.replica import StateReplica
from faabric_tpu.state.state import State
from faabric_tpu.state.remote import (
    StateCalls,
    StateClient,
    StateServer,
    clear_mock_state_requests,
    get_mock_state_pushes,
)

__all__ = [
    "DeviceHandleError",
    "DeviceHandleRegistry",
    "DeviceStateHandle",
    "StaleDeviceHandle",
    "get_device_handle_registry",
    "reset_device_handles",
    "MasterMemoryAuthority",
    "RedisAuthority",
    "RemoteAuthority",
    "STATE_CHUNK_SIZE",
    "SharedFileAuthority",
    "StaleStateEpoch",
    "State",
    "StateAuthority",
    "StateCalls",
    "StateClient",
    "StateServer",
    "StateKeyValue",
    "StateReplica",
    "clear_mock_state_requests",
    "get_mock_state_pushes",
    "place_backup",
    "ring_order",
]
