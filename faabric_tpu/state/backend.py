"""Pluggable state authorities — where a key's authoritative bytes live.

Reference analog: the StateKeyValue virtuals with two backends,
in-memory master (src/state/InMemoryStateKeyValue.cpp:90-260) and Redis
(src/state/RedisStateKeyValue.cpp). Selected by ``STATE_MODE``:

- ``inmemory`` (default): one master host per key (planner-elected); the
  master's process memory is the authority, replicas sync over the
  StateServer RPC. Split here into :class:`MasterMemoryAuthority` (this
  process IS the authority) and :class:`RemoteAuthority` (RPC to it).
- ``file`` (alias ``shm``): the authority is an mmap'd file under
  ``STATE_DIR`` (default /dev/shm) — every process on the machine maps
  the same bytes, locks ride fcntl.flock, appends are length-prefixed
  records in a side file. No master election, no RPC: the TPU-pod
  single-host analog of the reference's Redis mode (an authority
  outside any worker process that survives worker restarts).
- ``redis``: a Redis server is the authority (GETRANGE/SETRANGE for
  chunks, a list for appends, SET-NX-PX token for the lock) via the
  pure-Python RESP client in :mod:`faabric_tpu.redis`; tests and
  single-host runs use the in-repo MiniRedisServer, production points
  ``REDIS_STATE_HOST`` at a real Redis.

StateKeyValue keeps the chunked lazy-pull / dirty-push / append protocol
and delegates every authority interaction to one of these objects — the
protocol code is backend-agnostic, which is what makes the backend
actually pluggable.
"""

from __future__ import annotations

import os
import struct
import threading
from typing import Optional

from faabric_tpu.util.logging import get_logger

logger = get_logger(__name__)

_APPEND_REC = struct.Struct("<I")


class StaleStateEpoch(RuntimeError):
    """A state RPC carried an epoch older than the receiver's — the
    sender's placement is stale (a failover happened). Clients re-resolve
    through the planner and retry; a fenced-out ex-master stops acking
    (ISSUE 19). Raised with the class name in the message so it survives
    the transport error channel (clients detect it by substring on the
    re-raised RpcError)."""


class StateAuthority:
    """Authoritative-store accessor for one user/key."""

    #: True when the authoritative bytes live in THIS process (the
    #: StateServer serves them to replicas)
    local = False

    def pull_chunk(self, offset: int, length: int) -> bytes:
        raise NotImplementedError

    def push_chunk(self, offset: int, data: bytes) -> None:
        raise NotImplementedError

    def push_chunks(self, writes: list[tuple[int, bytes]]) -> None:
        """Batched multi-chunk push; backends with a wire protocol that
        supports it (redis pipelining) override to one round-trip."""
        for offset, data in writes:
            self.push_chunk(offset, data)

    def append(self, data: bytes) -> None:
        raise NotImplementedError

    def get_appended(self, n_values: int) -> list[bytes]:
        raise NotImplementedError

    def clear_appended(self) -> None:
        raise NotImplementedError

    def lock(self) -> None:
        raise NotImplementedError

    def unlock(self) -> None:
        raise NotImplementedError


class MasterMemoryAuthority(StateAuthority):
    """This process holds the key (inmemory mode, master side). The value
    bytes themselves stay in the StateKeyValue's local image (its local
    fast paths and the StateServer entry points act on one buffer under
    one lock); the authority owns what ISN'T the image: the append log
    and the global value lock."""

    local = True

    # Concurrency contract (tools/concheck.py): the append log mutates
    # under _lock; _value_lock IS the datum clients contend on (held
    # across their critical sections), never a guard for attributes.
    GUARDS = {
        "_appended": "_lock",
    }

    # Slightly under the client socket timeout so a contended lock
    # surfaces as an RPC error on the requester rather than an orphaned
    # server thread that acquires for a dead client
    LOCK_ACQUIRE_TIMEOUT = 30.0

    def __init__(self, user: str, key: str) -> None:
        self.user = user
        self.key = key
        self._lock = threading.Lock()
        self._appended: list[bytes] = []
        self._value_lock = threading.Lock()

    def pull_chunk(self, offset: int, length: int) -> bytes:
        raise RuntimeError("local authority: data lives in the KV image")

    def push_chunk(self, offset: int, data: bytes) -> None:
        raise RuntimeError("local authority: data lives in the KV image")

    def append(self, data: bytes) -> None:
        with self._lock:
            self._appended.append(bytes(data))

    def all_appended(self) -> list[bytes]:
        """Every appended value — the anti-entropy full-sync source."""
        with self._lock:
            return list(self._appended)

    def seed_appended(self, values: list[bytes]) -> None:
        """Replace the append log wholesale (replica promotion)."""
        with self._lock:
            self._appended[:] = [bytes(v) for v in values]

    def get_appended(self, n_values: int) -> list[bytes]:
        with self._lock:
            if len(self._appended) < n_values:
                raise ValueError(
                    f"Only {len(self._appended)} appended values")
            return list(self._appended[:n_values])

    def clear_appended(self) -> None:
        with self._lock:
            self._appended.clear()

    def lock(self) -> None:
        if not self._value_lock.acquire(timeout=self.LOCK_ACQUIRE_TIMEOUT):
            raise TimeoutError(
                f"Timed out acquiring global lock on {self.user}/{self.key}")

    def unlock(self) -> None:
        self._value_lock.release()


class RemoteAuthority(StateAuthority):
    """The key's master lives on another host (inmemory mode, replica
    side): every op is an RPC to its StateServer."""

    def __init__(self, user: str, key: str, master_host: str,
                 client_factory, epoch: int = 0) -> None:
        self.user = user
        self.key = key
        self.master_host = master_host
        self._client_factory = client_factory
        # Fencing epoch stamped on every RPC (ISSUE 19); 0 = unfenced
        # (replication off / pre-failover-era key). The owning
        # StateKeyValue bumps it when it re-resolves after a failover.
        self.epoch = epoch

    def _client(self):
        if self._client_factory is None:
            raise RuntimeError(
                f"No state client for non-master access to "
                f"{self.user}/{self.key}")
        return self._client_factory(self.master_host)

    def pull_chunk(self, offset: int, length: int) -> bytes:
        return self._client().pull_chunk(self.user, self.key, offset,
                                         length, epoch=self.epoch)

    def push_chunk(self, offset: int, data: bytes) -> None:
        self._client().push_chunk(self.user, self.key, offset, data,
                                  epoch=self.epoch)

    def append(self, data: bytes) -> None:
        self._client().append(self.user, self.key, data, epoch=self.epoch)

    def get_appended(self, n_values: int) -> list[bytes]:
        return self._client().pull_appended(self.user, self.key, n_values,
                                            epoch=self.epoch)

    def clear_appended(self) -> None:
        self._client().clear_appended(self.user, self.key,
                                      epoch=self.epoch)

    # Lock/unlock use one-shot connections: the shared cached client
    # serialises its sync socket, so a blocked lock request would block
    # the holder's unlock behind it (deadlock)
    def lock(self) -> None:
        self._oneshot("lock")

    def unlock(self) -> None:
        self._oneshot("unlock")

    def _oneshot(self, op: str) -> None:
        from faabric_tpu.state.remote import StateClient

        client = StateClient(self.master_host)
        try:
            getattr(client, op)(self.user, self.key, epoch=self.epoch)
        finally:
            client.close()


class SharedFileAuthority(StateAuthority):
    """The authority is an mmap'd file every process on the machine can
    open (``file``/``shm`` mode). Value bytes in ``<safe>.bin``, appends
    as length-prefixed records in ``<safe>.append``, the global lock is
    flock on ``<safe>.lock``."""

    local = False  # nothing for the StateServer to serve

    # Concurrency contract (tools/concheck.py). NOT listed: _lock_fd —
    # lock()/unlock() mutate it outside _iolock on purpose, because the
    # flock handoff itself serialises them (one holder at a time) and
    # taking _iolock there would stall every reader behind a 30 s
    # contended-lock poll loop.
    GUARDS = {
        "_mm": "_iolock",
    }

    def __init__(self, user: str, key: str, size: int,
                 state_dir: str) -> None:
        import mmap

        self.user = user
        self.key = key
        os.makedirs(state_dir, exist_ok=True)
        safe = f"{user}__{key}".replace("/", "_")
        self._path = os.path.join(state_dir, safe + ".bin")
        self._append_path = os.path.join(state_dir, safe + ".append")
        self._lock_path = os.path.join(state_dir, safe + ".lock")
        self._iolock = threading.Lock()
        self._lock_fd: Optional[int] = None

        # Create-or-open at the requested size (first creator sizes it)
        flags = os.O_RDWR | os.O_CREAT
        fd = os.open(self._path, flags, 0o644)
        try:
            cur = os.fstat(fd).st_size
            if cur < size:
                os.ftruncate(fd, size)
            self.size = max(cur, size)
            self._mm = mmap.mmap(fd, self.size) if self.size else None
        finally:
            os.close(fd)

    @staticmethod
    def existing_size(user: str, key: str, state_dir: str) -> int:
        safe = f"{user}__{key}".replace("/", "_")
        try:
            return os.stat(os.path.join(state_dir, safe + ".bin")).st_size
        except OSError:
            return 0

    def pull_chunk(self, offset: int, length: int) -> bytes:
        with self._iolock:
            return bytes(self._mm[offset:offset + length])

    def push_chunk(self, offset: int, data: bytes) -> None:
        if offset + len(data) > self.size:
            raise ValueError("Pushed chunk out of bounds")
        with self._iolock:
            self._mm[offset:offset + len(data)] = bytes(data)

    def append(self, data: bytes) -> None:
        import fcntl

        with self._iolock, open(self._append_path, "ab") as f:
            fcntl.flock(f, fcntl.LOCK_EX)
            try:
                f.write(_APPEND_REC.pack(len(data)))
                f.write(data)
                f.flush()  # record fully on disk before the lock drops
            finally:
                fcntl.flock(f, fcntl.LOCK_UN)

    def get_appended(self, n_values: int) -> list[bytes]:
        import fcntl

        out: list[bytes] = []
        try:
            with self._iolock, open(self._append_path, "rb") as f:
                # Shared lock against in-flight appends / truncates
                fcntl.flock(f, fcntl.LOCK_SH)
                try:
                    while len(out) < n_values:
                        head = f.read(_APPEND_REC.size)
                        if len(head) < _APPEND_REC.size:
                            break
                        (n,) = _APPEND_REC.unpack(head)
                        body = f.read(n)
                        if len(body) < n:
                            raise ValueError(
                                f"Torn append record in {self._append_path}")
                        out.append(body)
                finally:
                    fcntl.flock(f, fcntl.LOCK_UN)
        except FileNotFoundError:
            pass
        if len(out) < n_values:
            raise ValueError(f"Only {len(out)} appended values")
        return out

    def clear_appended(self) -> None:
        import fcntl

        with self._iolock:
            try:
                with open(self._append_path, "r+b") as f:
                    fcntl.flock(f, fcntl.LOCK_EX)
                    try:
                        f.truncate(0)
                    finally:
                        fcntl.flock(f, fcntl.LOCK_UN)
            except OSError:
                pass

    # Same bound as MasterMemoryAuthority: a contended lock must surface
    # as an error, not wedge the worker silently
    LOCK_ACQUIRE_TIMEOUT = 30.0

    def lock(self) -> None:
        import fcntl
        import time

        fd = os.open(self._lock_path, os.O_RDWR | os.O_CREAT, 0o644)
        deadline = time.monotonic() + self.LOCK_ACQUIRE_TIMEOUT
        while True:
            try:
                fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                break
            except OSError:
                if time.monotonic() >= deadline:
                    os.close(fd)
                    raise TimeoutError(
                        f"Timed out acquiring global lock on "
                        f"{self.user}/{self.key}")
                time.sleep(0.01)
        self._lock_fd = fd

    def unlock(self) -> None:
        import fcntl

        fd, self._lock_fd = self._lock_fd, None
        if fd is None:
            raise RuntimeError("unlock without lock")
        fcntl.flock(fd, fcntl.LOCK_UN)
        os.close(fd)

    def delete_files(self) -> None:
        for p in (self._path, self._append_path, self._lock_path):
            try:
                os.unlink(p)
            except OSError:
                pass


class RedisAuthority(StateAuthority):
    """The authority is a Redis server (``redis`` mode): value bytes in a
    string key (GETRANGE/SETRANGE — the reference's pull/push mapping,
    src/state/RedisStateKeyValue.cpp), appends in a list key, the global
    lock a SET-NX-PX token key with TTL (so a crashed holder cannot wedge
    the cluster). Speaks RESP via :mod:`faabric_tpu.redis` — works
    against a real Redis or the in-repo MiniRedisServer."""

    local = False

    LOCK_ACQUIRE_TIMEOUT = 30.0
    LOCK_TTL_MS = 60_000

    def __init__(self, user: str, key: str, size: int) -> None:
        self.user = user
        self.key = key
        self._key = f"fstate:{user}/{key}".encode()
        self._append_key = self._key + b":append"
        self._lock_key = self._key + b":lock"
        # Token is thread-local: authorities are shared across threads
        # through the cached StateKeyValue, and a TTL expiry means two
        # threads can hold (what they think is) the lock concurrently —
        # a shared token slot would let one thread's unlock delete the
        # other's live lock
        self._lock_tls = threading.local()

        cli = self._cli()
        cur = cli.strlen(self._key)
        if size > cur:
            # Grow to the requested size (zero-fill, first creator sizes)
            cli.setrange(self._key, size - 1, b"\x00")
            cur = size
        elif size <= 0 and cur <= 0:
            raise ValueError(
                f"State key {user}/{key} does not exist in redis yet; "
                "creation needs an explicit size")
        self.size = cur

    @staticmethod
    def _cli():
        from faabric_tpu.redis import get_redis

        return get_redis("state")

    def pull_chunk(self, offset: int, length: int) -> bytes:
        return self._cli().getrange(self._key, offset, offset + length - 1)

    def push_chunk(self, offset: int, data: bytes) -> None:
        if offset + len(data) > self.size:
            raise ValueError("Pushed chunk out of bounds")
        self._cli().setrange(self._key, offset, data)

    def push_chunks(self, writes: list[tuple[int, bytes]]) -> None:
        """Pipelined multi-chunk push, one round-trip (reference
        setRangePipeline); kv.push_partial sends all dirty chunks here."""
        for offset, data in writes:
            if offset + len(data) > self.size:
                raise ValueError("Pushed chunk out of bounds")
        self._cli().setrange_pipeline(self._key, writes)

    def append(self, data: bytes) -> None:
        self._cli().rpush(self._append_key, data)

    def get_appended(self, n_values: int) -> list[bytes]:
        if n_values <= 0:
            return []  # LRANGE 0 -1 would mean "whole list"
        vals = self._cli().lrange(self._append_key, 0, n_values - 1)
        if len(vals) < n_values:
            raise ValueError(f"Only {len(vals)} appended values")
        return vals

    def clear_appended(self) -> None:
        self._cli().delete(self._append_key)

    def lock(self) -> None:
        import time as _time
        import uuid

        token = uuid.uuid4().bytes
        cli = self._cli()
        deadline = _time.monotonic() + self.LOCK_ACQUIRE_TIMEOUT
        while not cli.set_nx_px(self._lock_key, token, self.LOCK_TTL_MS):
            if _time.monotonic() >= deadline:
                raise TimeoutError(
                    f"Timed out acquiring global lock on "
                    f"{self.user}/{self.key}")
            _time.sleep(0.01)
        self._lock_tls.token = token

    def unlock(self) -> None:
        token = getattr(self._lock_tls, "token", None)
        self._lock_tls.token = None
        if token is None:
            raise RuntimeError("unlock without lock")
        self._cli().del_if_eq(self._lock_key, token)

    def delete_keys(self) -> None:
        self._cli().delete(self._key, self._append_key, self._lock_key)


def make_redis_authority(user: str, key: str, size: int) -> RedisAuthority:
    return RedisAuthority(user, key, size)
