"""Consistent-hash replica placement for the state plane (ISSUE 19).

The planner places each key's **backup** host on a consistent-hash ring
so that host churn reshuffles the minimum number of keys: when a host
leaves, only the keys whose backup WAS that host move (to the next host
clockwise); when a host joins, it takes over only the ring arcs its
virtual nodes land on. Masters stay first-claimer-elected (locality:
the first writer is usually the hottest writer); the ring only decides
where the synchronous replica lives.

Pure functions over ``hashlib`` — deterministic across processes and
Python runs (``hash()`` is salted per process and would make the
planner and a replayed journal disagree about placement).
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Iterable, Sequence

# Virtual nodes per host: enough to keep per-host key share within a few
# percent of uniform on small clusters without making ring construction
# (O(hosts * VNODES log) per claim) noticeable.
VNODES = 64


def _hash(token: str) -> int:
    """Stable 64-bit ring coordinate for a token."""
    digest = hashlib.blake2b(token.encode("utf-8"), digest_size=8).digest()
    return int.from_bytes(digest, "big")


def ring_order(full_key: str, hosts: Iterable[str]) -> list[str]:
    """Distinct hosts in ring order starting at the key's point — the
    key's placement preference list. Deterministic for a given
    (key, host-set) regardless of input ordering."""
    uniq = sorted(set(hosts))
    if not uniq:
        return []
    points: list[tuple[int, str]] = []
    for h in uniq:
        for v in range(VNODES):
            points.append((_hash(f"{h}#{v}"), h))
    points.sort()
    coords = [p for p, _ in points]
    start = bisect.bisect_right(coords, _hash(full_key))
    order: list[str] = []
    seen: set[str] = set()
    for j in range(len(points)):
        h = points[(start + j) % len(points)][1]
        if h not in seen:
            seen.add(h)
            order.append(h)
            if len(order) == len(uniq):
                break
    return order


def place_backup(full_key: str, hosts: Iterable[str],
                 exclude: Sequence[str] | set[str] = ()) -> str:
    """The backup host for a key: first ring candidate not excluded
    (callers exclude at least the master — master ≠ backup always).
    Empty string when no eligible host exists (single-host cluster,
    planner-only test setups): the caller runs unreplicated."""
    for h in ring_order(full_key, hosts):
        if h not in exclude:
            return h
    return ""
