"""State RPC: server (ports 8003/8004) + client with mock recording.

Reference analog: src/state/StateServer.cpp (191 lines) with ops
Pull/Push/Size/Append/PullAppended/ClearAppended/Delete/Lock/Unlock
(include/faabric/state/State.h:11-21). Chunk bytes ride the binary tail.
"""

from __future__ import annotations

import enum
import threading
from typing import TYPE_CHECKING

from faabric_tpu.telemetry import flight_record, span
from faabric_tpu.transport.client import MessageEndpointClient
from faabric_tpu.transport.common import (
    STATE_ASYNC_PORT,
    STATE_SYNC_PORT,
    get_host_alias_offset,
)
from faabric_tpu.transport.message import TransportMessage
from faabric_tpu.transport.server import MessageEndpointServer, handler_response
from faabric_tpu.util.config import get_system_config
from faabric_tpu.util.logging import get_logger
from faabric_tpu.util.testing import is_mock_mode

if TYPE_CHECKING:  # pragma: no cover
    from faabric_tpu.state.state import State

logger = get_logger(__name__)


class StateCalls(enum.IntEnum):
    PULL = 1
    PUSH = 2
    SIZE = 3
    APPEND = 4
    PULL_APPENDED = 5
    CLEAR_APPENDED = 6
    DELETE = 7
    LOCK = 8
    UNLOCK = 9


_OP_NAMES = {int(c): c.name.lower() for c in StateCalls}

_mock_lock = threading.Lock()
# (host, user, key, offset, data)
_mock_pushes: list[tuple[str, str, str, int, bytes]] = []


def get_mock_state_pushes() -> list[tuple[str, str, str, int, bytes]]:
    with _mock_lock:
        return list(_mock_pushes)


def clear_mock_state_requests() -> None:
    with _mock_lock:
        _mock_pushes.clear()


class StateClient(MessageEndpointClient):
    def __init__(self, host: str) -> None:
        super().__init__(host, STATE_ASYNC_PORT, STATE_SYNC_PORT)

    def pull_chunk(self, user: str, key: str, offset: int,
                   length: int) -> bytes:
        resp = self.sync_send(int(StateCalls.PULL), {
            "user": user, "key": key, "offset": offset, "length": length,
        }, idempotent=True)
        return resp.payload

    def push_chunk(self, user: str, key: str, offset: int,
                   data: bytes) -> None:
        if is_mock_mode():
            with _mock_lock:
                _mock_pushes.append((self.host, user, key, offset, data))
            return
        # Idempotent: pushing the same chunk bytes twice converges
        self.sync_send(int(StateCalls.PUSH),
                       {"user": user, "key": key, "offset": offset}, data,
                       idempotent=True)

    def state_size(self, user: str, key: str) -> int:
        resp = self.sync_send(int(StateCalls.SIZE),
                              {"user": user, "key": key}, idempotent=True)
        return int(resp.header["size"])

    def append(self, user: str, key: str, data: bytes) -> None:
        self.sync_send(int(StateCalls.APPEND),
                       {"user": user, "key": key}, data)

    def pull_appended(self, user: str, key: str,
                      n_values: int) -> list[bytes]:
        resp = self.sync_send(int(StateCalls.PULL_APPENDED), {
            "user": user, "key": key, "n_values": n_values,
        }, idempotent=True)
        lengths = resp.header.get("lengths", [])
        out, off = [], 0
        for n in lengths:
            out.append(resp.payload[off:off + n])
            off += n
        return out

    def clear_appended(self, user: str, key: str) -> None:
        self.sync_send(int(StateCalls.CLEAR_APPENDED),
                       {"user": user, "key": key}, idempotent=True)

    def delete(self, user: str, key: str) -> None:
        self.sync_send(int(StateCalls.DELETE),
                       {"user": user, "key": key}, idempotent=True)

    def lock(self, user: str, key: str) -> None:
        self.sync_send(int(StateCalls.LOCK), {"user": user, "key": key})

    def unlock(self, user: str, key: str) -> None:
        self.sync_send(int(StateCalls.UNLOCK), {"user": user, "key": key})


class StateServer(MessageEndpointServer):
    def __init__(self, state: "State", host: str = "") -> None:
        conf = get_system_config()
        offset = get_host_alias_offset(host or state.host)
        super().__init__(
            STATE_ASYNC_PORT + offset,
            STATE_SYNC_PORT + offset,
            label=f"state-server-{host or state.host}",
            n_threads=conf.state_server_threads,
        )
        self.state = state

    def do_async_recv(self, msg: TransportMessage) -> None:
        logger.warning("Unknown async state call %d", msg.code)

    def do_sync_recv(self, msg: TransportMessage) -> TransportMessage:
        code = msg.code
        h = msg.header
        user, key = h["user"], h["key"]
        op = _OP_NAMES.get(code, str(code))

        kv = self.state.try_get_kv(user, key)
        if kv is None or not kv.is_master:
            # A replica asked the wrong host: stale master routing. Worth a
            # black-box record — a burst of these means the planner's master
            # table and the clients' cached masters have diverged.
            flight_record("state_not_master", key=f"{user}/{key}",
                          host=self.state.host, op=op)
            raise KeyError(f"Host is not master for state {user}/{key}")

        with span("state", f"serve_{op}", key=f"{user}/{key}"):
            if code == int(StateCalls.PULL):
                data = kv.server_pull_chunk(h["offset"], h["length"])
                return handler_response(payload=data)

            if code == int(StateCalls.PUSH):
                kv.server_push_chunk(h["offset"], msg.payload)
                return handler_response()

            if code == int(StateCalls.SIZE):
                return handler_response(header={"size": kv.size})

            if code == int(StateCalls.APPEND):
                kv.server_append(msg.payload)
                return handler_response()

            if code == int(StateCalls.PULL_APPENDED):
                values = kv.get_appended(h["n_values"])
                return handler_response(
                    header={"lengths": [len(v) for v in values]},
                    payload=b"".join(values))

            if code == int(StateCalls.CLEAR_APPENDED):
                kv.clear_appended()
                return handler_response()

            if code == int(StateCalls.DELETE):
                self.state.delete_kv(user, key)
                return handler_response()

            if code == int(StateCalls.LOCK):
                kv.lock_global()
                return handler_response()

            if code == int(StateCalls.UNLOCK):
                kv.unlock_global()
                return handler_response()

        raise ValueError(f"Unknown sync state call {code}")
