"""State RPC: server (ports 8003/8004) + client with mock recording.

Reference analog: src/state/StateServer.cpp (191 lines) with ops
Pull/Push/Size/Append/PullAppended/ClearAppended/Delete/Lock/Unlock
(include/faabric/state/State.h:11-21). Chunk bytes ride the binary tail.

ISSUE 19 additions: every op carries the key's fencing ``epoch`` (0 =
unfenced, the FAABRIC_STATE_REPLICAS=0 / legacy wire shape), and three
replication ops — REPLICATE / REPLICATE_APPEND (master → backup dirty
forwards, applied into the host's passive :class:`StateReplica`) and
PROMOTE (planner → new master after failover: convert the replica into
the authoritative copy). A master op whose epoch is older than the
receiver's raises :class:`StaleStateEpoch`; the message text crosses the
transport error channel so clients detect it by substring, re-resolve
placement through the planner, and retry.
"""

from __future__ import annotations

import enum
import threading
from typing import TYPE_CHECKING

from faabric_tpu.telemetry import flight_record, span
from faabric_tpu.transport.client import MessageEndpointClient
from faabric_tpu.transport.common import (
    STATE_ASYNC_PORT,
    STATE_SYNC_PORT,
    get_host_alias_offset,
)
from faabric_tpu.transport.message import TransportMessage
from faabric_tpu.transport.server import MessageEndpointServer, handler_response
from faabric_tpu.util.config import get_system_config
from faabric_tpu.util.logging import get_logger
from faabric_tpu.util.testing import is_mock_mode

if TYPE_CHECKING:  # pragma: no cover
    from faabric_tpu.state.state import State

logger = get_logger(__name__)


class StateCalls(enum.IntEnum):
    PULL = 1
    PUSH = 2
    SIZE = 3
    APPEND = 4
    PULL_APPENDED = 5
    CLEAR_APPENDED = 6
    DELETE = 7
    LOCK = 8
    UNLOCK = 9
    # Replication plane (ISSUE 19): master → backup synchronous forwards
    # and the planner's post-failover promotion nudge
    REPLICATE = 10
    REPLICATE_APPEND = 11
    PROMOTE = 12


_OP_NAMES = {int(c): c.name.lower() for c in StateCalls}

_mock_lock = threading.Lock()
# (host, user, key, offset, data)
_mock_pushes: list[tuple[str, str, str, int, bytes]] = []


def get_mock_state_pushes() -> list[tuple[str, str, str, int, bytes]]:
    with _mock_lock:
        return list(_mock_pushes)


def clear_mock_state_requests() -> None:
    with _mock_lock:
        _mock_pushes.clear()


def _with_epoch(header: dict, epoch: int) -> dict:
    # Epoch 0 stays OFF the wire: the REPLICAS=0 path keeps the exact
    # legacy header shape
    if epoch:
        header["epoch"] = epoch
    return header


class StateClient(MessageEndpointClient):
    def __init__(self, host: str) -> None:
        super().__init__(host, STATE_ASYNC_PORT, STATE_SYNC_PORT)

    def pull_chunk(self, user: str, key: str, offset: int,
                   length: int, epoch: int = 0) -> bytes:
        resp = self.sync_send(int(StateCalls.PULL), _with_epoch({
            "user": user, "key": key, "offset": offset, "length": length,
        }, epoch), idempotent=True)
        return resp.payload

    def push_chunk(self, user: str, key: str, offset: int,
                   data: bytes, epoch: int = 0) -> None:
        if is_mock_mode():
            with _mock_lock:
                _mock_pushes.append((self.host, user, key, offset, data))
            return
        # Idempotent: pushing the same chunk bytes twice converges
        self.sync_send(int(StateCalls.PUSH), _with_epoch(
            {"user": user, "key": key, "offset": offset}, epoch), data,
            idempotent=True)

    def state_size(self, user: str, key: str, epoch: int = 0) -> int:
        resp = self.sync_send(int(StateCalls.SIZE), _with_epoch(
            {"user": user, "key": key}, epoch), idempotent=True)
        return int(resp.header["size"])

    def append(self, user: str, key: str, data: bytes,
               epoch: int = 0) -> None:
        self.sync_send(int(StateCalls.APPEND), _with_epoch(
            {"user": user, "key": key}, epoch), data)

    def pull_appended(self, user: str, key: str,
                      n_values: int, epoch: int = 0) -> list[bytes]:
        resp = self.sync_send(int(StateCalls.PULL_APPENDED), _with_epoch({
            "user": user, "key": key, "n_values": n_values,
        }, epoch), idempotent=True)
        lengths = resp.header.get("lengths", [])
        out, off = [], 0
        for n in lengths:
            out.append(resp.payload[off:off + n])
            off += n
        return out

    def clear_appended(self, user: str, key: str, epoch: int = 0) -> None:
        self.sync_send(int(StateCalls.CLEAR_APPENDED), _with_epoch(
            {"user": user, "key": key}, epoch), idempotent=True)

    def delete(self, user: str, key: str) -> None:
        self.sync_send(int(StateCalls.DELETE),
                       {"user": user, "key": key}, idempotent=True)

    def lock(self, user: str, key: str, epoch: int = 0) -> None:
        self.sync_send(int(StateCalls.LOCK), _with_epoch(
            {"user": user, "key": key}, epoch))

    def unlock(self, user: str, key: str, epoch: int = 0) -> None:
        self.sync_send(int(StateCalls.UNLOCK), _with_epoch(
            {"user": user, "key": key}, epoch))

    # -- replication plane (master/planner side, ISSUE 19) --------------
    def replicate_chunks(self, user: str, key: str, epoch: int,
                         size: int, writes: list[tuple[int, bytes]]) -> None:
        """Forward dirty chunks to the backup. Idempotent: re-applying
        the same bytes at the same epoch converges."""
        if is_mock_mode():
            return
        offsets = [int(o) for o, _d in writes]
        lengths = [len(d) for _o, d in writes]
        self.sync_send(int(StateCalls.REPLICATE), {
            "user": user, "key": key, "epoch": epoch, "size": size,
            "offsets": offsets, "lengths": lengths,
        }, b"".join(d for _o, d in writes), idempotent=True)

    def replicate_append(self, user: str, key: str, epoch: int, size: int,
                         values: list[bytes], replace: bool = False) -> None:
        """Forward appended values; ``replace`` swaps the whole log
        (anti-entropy full sync) and is therefore idempotent — the
        additive form is not."""
        if is_mock_mode():
            return
        self.sync_send(int(StateCalls.REPLICATE_APPEND), {
            "user": user, "key": key, "epoch": epoch, "size": size,
            "lengths": [len(v) for v in values], "replace": bool(replace),
        }, b"".join(values), idempotent=bool(replace))

    def promote(self, user: str, key: str, epoch: int,
                backup: str) -> bool:
        """Planner → new master after failover: convert the local
        replica into the authoritative copy at ``epoch`` and start
        anti-entropy towards ``backup``. False = no replica here."""
        if is_mock_mode():
            return True
        resp = self.sync_send(int(StateCalls.PROMOTE), {
            "user": user, "key": key, "epoch": epoch, "backup": backup,
        }, idempotent=True)
        return bool(resp.header.get("ok"))


class StateServer(MessageEndpointServer):
    def __init__(self, state: "State", host: str = "") -> None:
        conf = get_system_config()
        offset = get_host_alias_offset(host or state.host)
        super().__init__(
            STATE_ASYNC_PORT + offset,
            STATE_SYNC_PORT + offset,
            label=f"state-server-{host or state.host}",
            n_threads=conf.state_server_threads,
        )
        self.state = state

    def do_async_recv(self, msg: TransportMessage) -> None:
        logger.warning("Unknown async state call %d", msg.code)

    def do_sync_recv(self, msg: TransportMessage) -> TransportMessage:
        code = msg.code
        h = msg.header
        user, key = h["user"], h["key"]
        op = _OP_NAMES.get(code, str(code))

        # Replication-plane ops target the BACKUP side (no master KV
        # here by design) — dispatch before the master guard
        if code == int(StateCalls.REPLICATE):
            with span("state", "serve_replicate", key=f"{user}/{key}"):
                writes, off = [], 0
                for offset, length in zip(h["offsets"], h["lengths"]):
                    writes.append(
                        (int(offset), msg.payload[off:off + length]))
                    off += length
                self.state.apply_replica_chunks(
                    user, key, int(h["epoch"]), int(h["size"]), writes)
            return handler_response()

        if code == int(StateCalls.REPLICATE_APPEND):
            with span("state", "serve_replicate_append",
                      key=f"{user}/{key}"):
                values, off = [], 0
                for length in h["lengths"]:
                    values.append(msg.payload[off:off + length])
                    off += length
                self.state.apply_replica_append(
                    user, key, int(h["epoch"]), int(h["size"]), values,
                    replace=bool(h.get("replace")))
            return handler_response()

        if code == int(StateCalls.PROMOTE):
            with span("state", "serve_promote", key=f"{user}/{key}"):
                ok = self.state.promote_replica(
                    user, key, int(h["epoch"]), h.get("backup", ""))
            return handler_response(header={"ok": ok})

        req_epoch = int(h.get("epoch", 0))
        kv = self.state.try_get_kv(user, key)
        if kv is None or not kv.is_master:
            # A fenced client op can land here right after a failover,
            # before (or instead of — the notify is best-effort) the
            # planner's PROMOTE arrives: a replica at epoch < req_epoch
            # is the journaled owner's data, so promote it now
            if req_epoch:
                kv = self.state.maybe_self_promote(user, key, req_epoch)
            else:
                kv = None
        if kv is None or not kv.is_master:
            # A replica asked the wrong host: stale master routing. Worth a
            # black-box record — a burst of these means the planner's master
            # table and the clients' cached masters have diverged.
            flight_record("state_not_master", key=f"{user}/{key}",
                          host=self.state.host, op=op)
            raise KeyError(f"Host is not master for state {user}/{key}")

        # Epoch fence (ISSUE 19): reject ops older than our epoch, adopt
        # newer ones (the planner re-blessed us), reject everything once
        # this master knows it has been fenced out
        kv.check_epoch(req_epoch)

        with span("state", f"serve_{op}", key=f"{user}/{key}"):
            if code == int(StateCalls.PULL):
                data = kv.server_pull_chunk(h["offset"], h["length"])
                return handler_response(payload=data)

            if code == int(StateCalls.PUSH):
                kv.server_push_chunk(h["offset"], msg.payload)
                return handler_response()

            if code == int(StateCalls.SIZE):
                return handler_response(header={"size": kv.size})

            if code == int(StateCalls.APPEND):
                kv.server_append(msg.payload)
                return handler_response()

            if code == int(StateCalls.PULL_APPENDED):
                values = kv.get_appended(h["n_values"])
                return handler_response(
                    header={"lengths": [len(v) for v in values]},
                    payload=b"".join(values))

            if code == int(StateCalls.CLEAR_APPENDED):
                kv.clear_appended()
                return handler_response()

            if code == int(StateCalls.DELETE):
                self.state.delete_kv(user, key)
                return handler_response()

            if code == int(StateCalls.LOCK):
                kv.lock_global()
                return handler_response()

            if code == int(StateCalls.UNLOCK):
                kv.unlock_global()
                return handler_response()

        raise ValueError(f"Unknown sync state call {code}")
