"""Batched scheduling ticks: the core of the high-QPS invocation path.

Every PR before ISSUE 8 priced one invocation at one planner RPC + one
synchronous journal write + one full policy run + one dispatch RPC.
This module amortises all four: batchable NEW invocations accumulate in
a queue and a tick thread (period ``FAABRIC_PLANNER_TICK_MS``) hands
the whole batch to ``Planner.call_batch_group`` — ONE planner-lock
pass, ONE host-map build + expiry sweep, the decision cache as an
admission fast path (repeat signatures skip the policy), ONE
group-commit journal record, batched mapping distribution and ONE
dispatch RPC per (host, tick).

Immediate-path cutover: when the queue is idle a submission runs the
classic synchronous ``call_batch`` inline — a lone invocation never
waits out a tick, so single-invocation latency does not regress. The
batched path only engages once submissions actually overlap.

Backpressure composition with admission.py: an invocation holds its
admission credits from ``try_admit`` until it resolves (scheduled,
failed, or deadline-shed). When the cluster is out of slots the batch
stays queued — capacity frees as results land — and only the queue
bound itself sheds new arrivals. A queued invocation that outlives
``FAABRIC_INGRESS_QUEUE_TIMEOUT`` resolves as NOT_ENOUGH_SLOTS (sync
waiters) or FAILED results (fire-and-forget submissions) so callers
never hang on a full cluster.

Ineligible requests — anything that is not a plain NEW FUNCTIONS/
PROCESSES batch (MPI worlds, THREADS forks, migrations, scale changes,
preloaded or frozen apps) — bypass the queue entirely and keep the
classic synchronous path; ticks are for the invocation firehose, not
for control-plane surgery.
"""

from __future__ import annotations

import threading
import time
from typing import TYPE_CHECKING, Optional

from faabric_tpu.batch_scheduler.decision import (
    NOT_ENOUGH_SLOTS,
    SchedulingDecision,
    not_enough_slots_decision,
)
from faabric_tpu.ingress.admission import (
    AdmissionController,
    IngressShedError,
)
from faabric_tpu.telemetry import get_lifecycle, get_metrics
from faabric_tpu.telemetry.lifecycle import (
    PHASE_ADMIT,
    PHASE_QUEUE_EXIT,
)
from faabric_tpu.util.logging import get_logger

if TYPE_CHECKING:  # pragma: no cover
    from faabric_tpu.planner.planner import Planner
    from faabric_tpu.proto import BatchExecuteRequest

logger = get_logger(__name__)

_metrics = get_metrics()
_TICKS = _metrics.counter(
    "faabric_ingress_ticks_total",
    "Scheduling ticks that processed at least one queued invocation")
_TICK_BATCH = _metrics.histogram(
    "faabric_ingress_tick_batch_requests",
    "Queued invocation requests scheduled per tick")
_IMMEDIATE = _metrics.counter(
    "faabric_ingress_immediate_total",
    "Invocations that took the immediate (tickless) cutover path")
_BATCHED = _metrics.counter(
    "faabric_ingress_batched_total",
    "Invocations scheduled through a batched tick")
_QUEUE_WAIT = _metrics.histogram(
    "faabric_ingress_queue_wait_seconds",
    "Enqueue to decision latency for tick-batched invocations")

# Lifecycle ledger (ISSUE 14): admission + queue-exit stamps ride the
# messages themselves (no-op singleton when FAABRIC_METRICS=0)
_LC = get_lifecycle()


class _Pending:
    __slots__ = ("req", "source", "deadline", "shed_deadline", "event",
                 "result", "enq_ts", "wait")

    def __init__(self, req, source: str, deadline: float,
                 wait: bool, grace: float = 0.0) -> None:
        self.req = req
        self.source = source
        self.deadline = deadline
        # Ticks must not shed an entry its sync waiter would still
        # accept: the waiter only withdraws at deadline + its grace, so
        # shedding at the bare deadline would return spurious
        # NOT_ENOUGH_SLOTS from a busy (not full) cluster. Fire-and-
        # forget entries (grace=0) shed at the queue-timeout policy
        # deadline itself.
        self.shed_deadline = deadline + grace
        self.wait = wait
        self.event = threading.Event()
        self.result: Optional[SchedulingDecision] = None
        self.enq_ts = time.monotonic()


class IngressCoordinator:
    """Admission + tick batching between the endpoints and the planner
    core. One per Planner; the tick thread starts lazily on the first
    batched submission and stops with the owning PlannerServer."""

    # Concurrency contract (tools/concheck.py): queue + tick state under
    # one leaf lock, held only for list/dict ops — scheduling itself
    # (call_batch_group: planner lock + network) always runs lock-free
    # here. _immediate_total/_batched_total/_ticks/_last_tick_batch are
    # also guarded for a consistent stats() snapshot.
    GUARDS = {
        "_queue": "_lock",
        "_inline": "_lock",
        "_tick_busy": "_lock",
        "_thread": "_lock",
        "_stop": "_lock",
        "_stopped": "_lock",
        "_immediate_total": "_lock",
        "_batched_total": "_lock",
        "_ticks": "_lock",
        "_last_tick_batch": "_lock",
        "_last_tick_s": "_lock",
    }

    def __init__(self, planner: "Planner",
                 admission: AdmissionController | None = None) -> None:
        self._planner = planner
        self.admission = admission or AdmissionController()
        # Per-coordinator tick-thread name (ISSUE 18): the class prefix
        # ``ingress/tick`` keeps profiler attribution stable while the
        # ``@instance`` suffix lets a test (or doctor) scope thread
        # queries to THIS coordinator — under full-suite load another
        # test's still-draining coordinator must not alias ours.
        self._tick_name = f"ingress/tick@{id(self):x}"
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._queue: list[_Pending] = []
        self._inline = 0  # submissions currently on the immediate path
        self._tick_busy = False
        self._thread: Optional[threading.Thread] = None
        self._stop = False
        # Latched by stop(), cleared only by an explicit start():
        # submissions racing a server shutdown must shed, not silently
        # re-arm a fresh tick thread that dispatches into the closing
        # server (and outlives it)
        self._stopped = False
        self._immediate_total = 0
        self._batched_total = 0
        self._ticks = 0
        self._last_tick_batch = 0
        self._last_tick_s = 0.0

    # ------------------------------------------------------------------
    def submit(self, req: "BatchExecuteRequest", source: str = "",
               wait: bool = True,
               timeout: float | None = None) -> Optional[SchedulingDecision]:
        """Run one invocation through admission + the tick machinery.

        Returns the scheduling decision (``wait=True``) or ``None``
        after a successful enqueue (``wait=False`` — results flow back
        through the normal result plane). Raises ``IngressShedError``
        when admission sheds the invocation."""
        from faabric_tpu.util.config import get_system_config

        # Ledger t0: everything entering the planner through the
        # ingress — batchable or not — stamps admit here
        _LC.stamp_many(req.messages, PHASE_ADMIT)

        # Shape check only — lock-free. Requests with existing planner
        # state (scale changes, thaws, preloads) that slip through are
        # deferred to the classic path by the tick's stateful re-check.
        if not self._planner.is_batchable_shape(req):
            return self._planner.call_batch(req)

        # concheck: ok(guard-unlocked) — latched flag, racy read by
        # design: the post-stop enqueue race is closed by the second
        # check under the lock below
        if self._stopped:
            raise IngressShedError(0.5, "ingress stopped")

        n = req.n_messages()
        verdict = self.admission.try_admit(source, n)
        if not verdict.admitted:
            raise IngressShedError(verdict.retry_after, verdict.reason)

        # Immediate-path cutover: with nothing queued and nothing in
        # flight, this invocation IS the tick — run it inline so a
        # single caller pays classic call_batch latency, not tick_ms.
        with self._lock:
            idle = (not self._queue and self._inline == 0
                    and not self._tick_busy)
            if idle:
                self._inline += 1
                self._immediate_total += 1
        if idle:
            try:
                # The queue was never entered: zero-wait queue exit
                _LC.stamp_many(req.messages, PHASE_QUEUE_EXIT)
                return self._planner.call_batch(req)
            finally:
                with self._lock:
                    self._inline -= 1
                self.admission.release(source, n)
                _IMMEDIATE.inc()

        conf = get_system_config()
        if timeout is None:
            timeout = conf.ingress_queue_timeout
        # The extra grace covers the scheduling latency of the tick
        # that fires the deadline (ticks run tens of ms under load).
        # Kept short: the RPC plane calls this with sub-second timeouts
        # from a small sync worker pool.
        grace = max(0.5, conf.planner_tick_ms / 100)
        pending = _Pending(req, source, time.monotonic() + timeout, wait,
                           grace=grace)
        with self._lock:
            if self._stopped:
                self.admission.release(source, n)
                raise IngressShedError(0.5, "ingress stopped")
            self._queue.append(pending)
            self._ensure_thread_locked()
        if not wait:
            return None
        if not pending.event.wait(timeout + grace):
            # Timed out. If the request is still QUEUED, withdraw it —
            # returning NOT_ENOUGH_SLOTS while leaving it schedulable
            # would let a later tick dispatch work the caller already
            # gave up on (duplicate execution on retry). If a tick is
            # mid-flight with it, the decision is imminent and may
            # already be dispatched: wait it out rather than lie.
            with self._lock:
                withdrawn = pending in self._queue
                if withdrawn:
                    self._queue.remove(pending)
            if withdrawn:
                self.admission.release(source, n)
                return not_enough_slots_decision()
            # A tick holds the entry: its decision (or its deadline
            # shed — ticks pre-filter expired entries) is coming, and
            # the work may ALREADY be dispatched, so returning
            # NOT_ENOUGH_SLOTS here would invite a duplicating retry.
            # Wait it out up to the system-wide message timeout — a
            # tick stalled past that means a wedged planner, where the
            # caller's own RPC socket timeout governs anyway.
            pending.event.wait(max(
                conf.global_message_timeout,
                pending.deadline - time.monotonic() + 1.0))
        result = pending.result
        if result is None:
            # The tick loop died or stop() raced us: resolve locally so
            # the caller never hangs (credits were released by whoever
            # removed us from the queue, or will be by stop()).
            return not_enough_slots_decision()
        return result

    def submit_many(self, reqs: list["BatchExecuteRequest"],
                    source: str = "") -> None:
        """Bulk fire-and-forget submission: admit the whole set under
        one credit grant (all-or-nothing) and enqueue every batchable
        request for the next tick; results flow back through the
        normal result plane. The rare non-batchable request in a bulk
        submission takes the classic synchronous path inline."""
        from faabric_tpu.util.config import get_system_config

        for r in reqs:
            _LC.stamp_many(r.messages, PHASE_ADMIT)
        batchable: list = []
        direct: list = []
        for r in reqs:
            (batchable if self._planner.is_batchable_shape(r)
             else direct).append(r)
        # concheck: ok(guard-unlocked) — latched flag, racy read by
        # design; the enqueue below re-checks under the lock
        if self._stopped:
            raise IngressShedError(0.5, "ingress stopped")
        total = sum(r.n_messages() for r in batchable)
        if total:
            verdict = self.admission.try_admit(source, total)
            if not verdict.admitted:
                raise IngressShedError(verdict.retry_after, verdict.reason)
            deadline = (time.monotonic()
                        + get_system_config().ingress_queue_timeout)
            pendings = [_Pending(r, source, deadline, wait=False)
                        for r in batchable]
            # Credits were granted as one block; release per-request as
            # each pending resolves — hand each its own share
            with self._lock:
                if self._stopped:
                    self.admission.release(source, total)
                    raise IngressShedError(0.5, "ingress stopped")
                self._queue.extend(pendings)
                self._ensure_thread_locked()
        for req in direct:
            # Fire-and-forget contract: the bulk was ACCEPTED, so every
            # request must reach a terminal state the submitter's
            # batch-status polls can see — a dropped NOT_ENOUGH_SLOTS
            # (or a raising call_batch) would leave its app finishing
            # never, and propagating the error would make the client
            # retry (and duplicate) the already-enqueued batchables.
            try:
                d = self._planner.call_batch(req)
                if d.app_id == NOT_ENOUGH_SLOTS:
                    self._planner.fail_unscheduled(
                        req, b"Shed: no capacity for non-batchable "
                        b"bulk submission")
            except Exception:  # noqa: BLE001
                logger.exception("Direct call_batch failed for bulk-"
                                 "submitted app %d", req.app_id)
                try:
                    self._planner.fail_unscheduled(
                        req, b"Bulk submission failed")
                except Exception:  # noqa: BLE001
                    logger.exception("Failing bulk app %d", req.app_id)

    # ------------------------------------------------------------------
    def _ensure_thread_locked(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop = False
        t = threading.Thread(target=self._tick_loop,
                             name=self._tick_name, daemon=True)
        self._thread = t
        t.start()

    def _tick_loop(self) -> None:
        from faabric_tpu.util.config import get_system_config

        while True:
            tick_s = max(0.0005, get_system_config().planner_tick_ms
                         / 1000.0)
            self._wake.wait(tick_s)
            self._wake.clear()
            with self._lock:
                # Identity check, not just the flag: a stop() whose 5s
                # join expired on a network-stalled tick leaves this
                # thread a zombie, and a later start() + submission
                # resets _stop for its NEW thread — the zombie must see
                # it no longer owns the loop and exit, not resurrect.
                if self._stop or self._thread is not threading.current_thread():
                    return
                batch = self._queue
                self._queue = []
                self._tick_busy = bool(batch)
            if not batch:
                continue
            try:
                self._run_tick(batch)
            except Exception:  # noqa: BLE001 — a tick must never kill
                # the loop. Unresolved entries are FAILED, not requeued:
                # call_batch_group may have registered some of them
                # in-flight before raising (e.g. ENOSPC from the group
                # journal commit), and a re-run would classify those as
                # SCALE_CHANGE and dispatch the same messages twice.
                # fail_unscheduled no-ops for apps that did register —
                # they are in the same stranded-but-consistent state a
                # raising classic call_batch leaves behind.
                logger.exception("Ingress tick failed (%d requests)",
                                 len(batch))
                for pending in batch:
                    if pending.result is None:
                        self._shed_at_deadline(pending)
            finally:
                with self._lock:
                    self._tick_busy = False

    def _run_tick(self, batch: list[_Pending]) -> None:
        t0 = time.monotonic()
        # Deadline pre-filter: an entry past its deadline must never be
        # scheduled — its sync waiter may already have given up with
        # NOT_ENOUGH_SLOTS, and dispatching it now would execute work
        # the caller believes was rejected (duplicate on retry).
        live: list[_Pending] = []
        expired = 0
        for pending in batch:
            if t0 >= pending.shed_deadline:
                self._shed_at_deadline(pending)
                expired += 1
            else:
                live.append(pending)
        batch = live
        if expired:
            logger.debug("Tick shed %d expired queue entr(ies) before "
                         "scheduling", expired)
        if not batch:
            return
        for pending in batch:
            _LC.stamp_many(pending.req.messages, PHASE_QUEUE_EXIT)
        results, deferred = self._planner.call_batch_group(
            [p.req for p in batch])
        backlog: list[_Pending] = []
        resolved = 0
        resolved_msgs = 0
        now = time.monotonic()
        for i, pending in enumerate(batch):
            if i in deferred:
                # Raced out of batch eligibility (e.g. a scale-change
                # arriving as its app went in-flight): classic path.
                try:
                    d = self._planner.call_batch(pending.req)
                except Exception:  # noqa: BLE001
                    logger.exception("Deferred ingress call_batch failed "
                                     "(app %d)", pending.req.app_id)
                    d = not_enough_slots_decision()
                if d.app_id == NOT_ENOUGH_SLOTS and not pending.wait:
                    # Fire-and-forget contract: an unplaceable deferred
                    # submission still needs terminal results or its
                    # batch-status poller hangs forever
                    try:
                        self._planner.fail_unscheduled(
                            pending.req, b"Shed: deferred submission "
                            b"could not be scheduled")
                    except Exception:  # noqa: BLE001
                        logger.exception("Failing deferred app %d",
                                         pending.req.app_id)
                self._resolve(pending, d)
                resolved += 1
                resolved_msgs += pending.req.n_messages()
                continue
            decision = results[i]
            if decision is None:
                # No capacity this tick: requeue unless the deadline
                # passed — slots free as results land.
                if now >= pending.shed_deadline:
                    self._shed_at_deadline(pending)
                    resolved += 1
                    resolved_msgs += pending.req.n_messages()
                else:
                    backlog.append(pending)
                continue
            _QUEUE_WAIT.observe(now - pending.enq_ts)
            self._resolve(pending, decision)
            resolved += 1
            resolved_msgs += pending.req.n_messages()
        with self._lock:
            stopped = self._stopped
            if not stopped:
                # Backlog keeps FIFO order ahead of newer arrivals
                self._queue[:0] = backlog
            self._ticks += 1
            self._last_tick_batch = resolved
            self._last_tick_s = time.monotonic() - t0
            self._batched_total += resolved
        if stopped:
            # stop() already drained the queue (its 5s join can expire
            # while a tick is stalled in network): re-inserting would
            # strand these entries with their credits in a latched-
            # closed coordinator — shed them like the rest
            for pending in backlog:
                self._shed_at_deadline(pending)
        _TICKS.inc()
        _TICK_BATCH.observe(resolved)
        _BATCHED.inc(resolved)
        if resolved_msgs:
            # MESSAGE count, not request count: admission depth and the
            # retry_after hint are accounted in messages
            self.admission.note_drained(resolved_msgs,
                                        time.monotonic() - t0)

    def _resolve(self, pending: _Pending,
                 decision: SchedulingDecision) -> None:
        self.admission.release(pending.source, pending.req.n_messages())
        pending.result = decision
        pending.event.set()

    def _shed_at_deadline(self, pending: _Pending) -> None:
        """A queued invocation outlived its deadline without capacity:
        sync waiters get NOT_ENOUGH_SLOTS; fire-and-forget submissions
        get terminal FAILED results so batch-status pollers finish."""
        logger.warning(
            "Shedding app %d after %.1fs in the ingress queue (no "
            "capacity)", pending.req.app_id,
            time.monotonic() - pending.enq_ts)
        if not pending.wait:
            try:
                self._planner.fail_unscheduled(
                    pending.req, b"Shed: no capacity within the ingress "
                    b"queue timeout")
            except Exception:  # noqa: BLE001
                logger.exception("Failing shed app %d", pending.req.app_id)
        self._resolve(pending, not_enough_slots_decision())

    # ------------------------------------------------------------------
    def start(self) -> None:
        """Re-arm a stopped coordinator (in-process PlannerServer
        start/stop cycles). The tick thread itself still starts lazily
        on the first batched submission."""
        with self._lock:
            self._stopped = False

    def stop(self) -> None:
        """Stop the tick thread, latch the coordinator closed (new
        submissions shed until start()), and resolve everything still
        queued as unschedulable — nothing will ever schedule it."""
        with self._lock:
            self._stop = True
            self._stopped = True
            thread = self._thread
            self._thread = None
        self._wake.set()
        if thread is not None and thread.is_alive():
            thread.join(timeout=5.0)
        self.shed_all("ingress stopped")

    def shed_all(self, reason: str) -> None:
        """Resolve every queued entry as unschedulable (planner reset /
        shutdown). Fire-and-forget submissions additionally get
        terminal FAILED results — their submitters poll batch status
        and would otherwise hang on apps nobody will ever place."""
        with self._lock:
            drained = self._queue
            self._queue = []
        for pending in drained:
            logger.warning("Shedding queued app %d: %s",
                           pending.req.app_id, reason)
            if not pending.wait:
                try:
                    self._planner.fail_unscheduled(
                        pending.req, b"Shed: " + reason.encode())
                except Exception:  # noqa: BLE001
                    logger.exception("Failing shed app %d",
                                     pending.req.app_id)
            self._resolve(pending, not_enough_slots_decision())

    def last_tick_ms(self) -> float:
        """Duration of the most recent non-empty tick (time-series
        gauge: a tick trending toward the tick period is the planner
        saturating)."""
        with self._lock:
            return self._last_tick_s * 1000.0

    # ------------------------------------------------------------------
    def stats(self) -> dict:
        out = self.admission.stats()
        with self._lock:
            queued_msgs = sum(p.req.n_messages() for p in self._queue)
            out.update({
                "queuedRequests": len(self._queue),
                "queuedMessages": queued_msgs,
                "lastTickMs": round(self._last_tick_s * 1000.0, 3),
                "immediateTotal": self._immediate_total,
                "batchedTotal": self._batched_total,
                "ticks": self._ticks,
                "lastTickBatch": self._last_tick_batch,
                "avgTickOccupancy": (
                    round(self._batched_total / self._ticks, 2)
                    if self._ticks else 0.0),
                "tickThreadAlive": (self._thread is not None
                                    and self._thread.is_alive()),
            })
        return out
