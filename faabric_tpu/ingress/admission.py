"""Admission control for the high-QPS invocation ingress.

The endpoint-side half of ISSUE 8: every batchable invocation passes
through a bounded admission queue before it may join a planner
scheduling tick. Two limits protect the planner:

- a **global queue bound** (``FAABRIC_INGRESS_QUEUE_MAX``, counted in
  messages): when the scheduling tick falls behind arrivals, the queue
  absorbs the burst up to the bound and then SHEDS — callers get an
  explicit retry-after (HTTP 429 + ``Retry-After``) instead of the
  planner collapsing under an unbounded backlog (collapse → shed,
  never OOM);
- a **per-source credit cap** (``FAABRIC_INGRESS_SOURCE_CREDITS``):
  each source (tenant/user on the REST surface, submitting host on the
  RPC surface) may hold at most this many queued messages, so one
  runaway client saturating the queue cannot starve every other
  source's admission even while global headroom remains.

Credits are taken at admission and released when the invocation leaves
the queue (scheduled, failed, or shed at its deadline). The
``retry_after`` hint scales with the backlog: an EWMA of the recent
per-message drain time (fed back by the tick loop) times the current
depth, clamped to [0.05s, 5s].

Depth/shed/admit counters are exported through the metrics registry and
surfaced on the planner's ``/healthz`` (ingress block).
"""

from __future__ import annotations

import dataclasses
import threading

from faabric_tpu.telemetry import get_metrics

_metrics = get_metrics()
_ADMITTED = _metrics.counter(
    "faabric_ingress_admitted_total",
    "Invocation messages admitted into the ingress queue or immediate "
    "path")
_SHED = _metrics.counter(
    "faabric_ingress_shed_total",
    "Invocation messages shed at admission (queue full or source over "
    "its credit cap)")
_DEPTH = _metrics.gauge(
    "faabric_ingress_queue_depth",
    "Messages currently holding admission credits (queued or being "
    "scheduled)")


@dataclasses.dataclass(frozen=True)
class AdmissionVerdict:
    admitted: bool
    retry_after: float = 0.0
    reason: str = ""


class IngressShedError(Exception):
    """Raised to a synchronous submitter whose invocation was shed.
    Carries the backlog-scaled retry hint the REST surface maps to
    ``429`` + ``Retry-After``."""

    def __init__(self, retry_after: float, reason: str = "overloaded"):
        super().__init__(f"invocation shed ({reason}); "
                         f"retry after {retry_after:.2f}s")
        self.retry_after = retry_after
        self.reason = reason


class AdmissionController:
    # Concurrency contract (tools/concheck.py): all mutable accounting
    # under one leaf lock; try_admit/release are O(1) dict ops and the
    # lock is never held across blocking calls.
    GUARDS = {
        "_depth": "_lock",
        "_credits": "_lock",
        "_shed_total": "_lock",
        "_admitted_total": "_lock",
        "_drain_ewma_s": "_lock",
    }

    # retry_after clamp bounds (seconds)
    RETRY_AFTER_MIN = 0.05
    RETRY_AFTER_MAX = 5.0
    # drain-time EWMA seed before the first tick feedback arrives
    DEFAULT_DRAIN_S = 0.002

    def __init__(self, queue_max: int | None = None,
                 source_credits: int | None = None) -> None:
        from faabric_tpu.util.config import get_system_config

        conf = get_system_config()
        self.queue_max = (queue_max if queue_max is not None
                          else conf.ingress_queue_max)
        self.source_credits = (source_credits if source_credits is not None
                               else conf.ingress_source_credits)
        self._lock = threading.Lock()
        self._depth = 0  # messages holding credits
        self._credits: dict[str, int] = {}  # source → messages held
        self._shed_total = 0
        self._admitted_total = 0
        self._drain_ewma_s = self.DEFAULT_DRAIN_S

    # ------------------------------------------------------------------
    def try_admit(self, source: str, n_msgs: int) -> AdmissionVerdict:
        """Take ``n_msgs`` credits for ``source``, or shed with a
        retry-after hint. All-or-nothing per request."""
        n_msgs = max(1, n_msgs)
        with self._lock:
            held = self._credits.get(source, 0)
            if self._depth + n_msgs > self.queue_max:
                reason = "admission queue full"
            elif held + n_msgs > self.source_credits:
                reason = f"source {source or '<anon>'} over credit cap"
            else:
                self._depth += n_msgs
                self._credits[source] = held + n_msgs
                self._admitted_total += n_msgs
                _ADMITTED.inc(n_msgs)
                _DEPTH.set(self._depth)
                return AdmissionVerdict(True)
            self._shed_total += n_msgs
            retry = min(self.RETRY_AFTER_MAX,
                        max(self.RETRY_AFTER_MIN,
                            self._depth * self._drain_ewma_s))
        _SHED.inc(n_msgs)
        return AdmissionVerdict(False, retry_after=retry, reason=reason)

    def release(self, source: str, n_msgs: int) -> None:
        """Return ``n_msgs`` credits (the invocation left the queue:
        scheduled, failed, or deadline-shed)."""
        n_msgs = max(1, n_msgs)
        with self._lock:
            self._depth = max(0, self._depth - n_msgs)
            held = self._credits.get(source, 0) - n_msgs
            if held > 0:
                self._credits[source] = held
            else:
                self._credits.pop(source, None)
            _DEPTH.set(self._depth)

    def note_drained(self, n_msgs: int, elapsed_s: float) -> None:
        """Tick-loop feedback: ``n_msgs`` resolved in ``elapsed_s`` —
        refreshes the per-message drain EWMA behind retry_after."""
        if n_msgs <= 0 or elapsed_s <= 0:
            return
        per_msg = elapsed_s / n_msgs
        with self._lock:
            self._drain_ewma_s = 0.8 * self._drain_ewma_s + 0.2 * per_msg

    def depth(self) -> int:
        """Current queue depth (messages holding credits) — the
        time-series ring's ingress-depth gauge."""
        with self._lock:
            return self._depth

    def shed_total(self) -> int:
        """Cumulative shed count (the ring stores the raw counter; the
        doctor differentiates to get a shed RATE trend)."""
        with self._lock:
            return self._shed_total

    # ------------------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {
                "queueDepth": self._depth,
                "queueMax": self.queue_max,
                "sourceCredits": self.source_credits,
                "sourcesHolding": len(self._credits),
                "admittedTotal": self._admitted_total,
                "shedTotal": self._shed_total,
                "drainEwmaMs": round(self._drain_ewma_s * 1000.0, 4),
            }
