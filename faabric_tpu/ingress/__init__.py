"""High-QPS invocation ingress (ISSUE 8).

Admission control + batched scheduling ticks between the endpoints
(HTTP REST, planner RPC) and the planner core. See admission.py and
tick.py module docs, and docs/invocation_path.md for the architecture.
"""

from faabric_tpu.ingress.admission import (
    AdmissionController,
    AdmissionVerdict,
    IngressShedError,
)
from faabric_tpu.ingress.tick import IngressCoordinator

__all__ = [
    "AdmissionController",
    "AdmissionVerdict",
    "IngressCoordinator",
    "IngressShedError",
]
