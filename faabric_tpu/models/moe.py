"""Mixture-of-Experts transformer: the second model family, exercising
expert parallelism over the ``ep`` mesh axis.

Top-k routing (switch-style top-1 by default, GShard-style top-2+ via
``router_top_k``) with fixed expert capacity, in the einsum-dispatch
formulation: a one-hot dispatch tensor scatters tokens into per-expert
buffers, experts run as one batched matmul pair, and the combine einsum
gathers results weighted by the router gates (renormalized over the
selected experts for k > 1). Capacity is allocated slot-major — every
token's first choice outranks any token's second choice, the standard
priority rule. Experts shard over ``ep``; with the dispatch/combine
sharding constraints XLA inserts the token all_to_alls over ICI — the
MoE analog of the MPI world's alltoall (SURVEY §2.4), expressed entirely
through shardings.

Static shapes throughout: capacity is fixed, overflow tokens drop (their
residual passes through), standard for TPU switch routing.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from faabric_tpu.models.transformer import (
    ModelConfig,
    _rms_norm,
    attention_sublayer,
)


@dataclasses.dataclass(frozen=True)
class MoEConfig(ModelConfig):
    n_experts: int = 4
    capacity_factor: float = 1.25
    # Experts per token: 1 = switch routing (gate = raw top prob),
    # >1 = GShard-style with gates renormalized over the selected experts
    router_top_k: int = 1
    # Auxiliary load-balancing loss weight (switch transformer)
    aux_loss_weight: float = 0.01


def init_moe_params(key: jax.Array, cfg: MoEConfig) -> dict:
    keys = jax.random.split(key, cfg.n_layers + 2)

    def dense(k, shape, fan_in):
        return (jax.random.normal(k, shape, cfg.param_dtype)
                / np.sqrt(fan_in))

    blocks = []
    for i in range(cfg.n_layers):
        bk = jax.random.split(keys[i], 5)
        blocks.append({
            "ln1": jnp.ones((cfg.d_model,), cfg.param_dtype),
            "wqkv": dense(bk[0], (cfg.d_model, 3, cfg.n_heads, cfg.head_dim),
                          cfg.d_model),
            "wo": dense(bk[1], (cfg.n_heads, cfg.head_dim, cfg.d_model),
                        cfg.d_model),
            "ln2": jnp.ones((cfg.d_model,), cfg.param_dtype),
            "router": dense(bk[2], (cfg.d_model, cfg.n_experts), cfg.d_model),
            "w1": dense(bk[3], (cfg.n_experts, cfg.d_model, cfg.d_ff),
                        cfg.d_model),
            "w2": dense(bk[4], (cfg.n_experts, cfg.d_ff, cfg.d_model),
                        cfg.d_ff),
        })
    return {
        "embed": dense(keys[-2], (cfg.vocab_size, cfg.d_model), cfg.d_model),
        "blocks": blocks,
        "ln_f": jnp.ones((cfg.d_model,), cfg.param_dtype),
        "lm_head": dense(keys[-1], (cfg.d_model, cfg.vocab_size), cfg.d_model),
    }


def moe_param_shardings(mesh: Mesh, cfg: MoEConfig) -> dict:
    def ns(*spec):
        return NamedSharding(mesh, P(*spec))

    block = {
        "ln1": ns(),
        "wqkv": ns(None, None, "tp", None),
        "wo": ns("tp", None, None),
        "ln2": ns(),
        "router": ns(),
        # Experts shard over ep; each expert's hidden over tp
        "w1": ns("ep", None, "tp"),
        "w2": ns("ep", "tp", None),
    }
    return {
        "embed": ns("tp", None),
        "blocks": [dict(block) for _ in range(cfg.n_layers)],
        "ln_f": ns(),
        "lm_head": ns(None, "tp"),
    }


def _capacity(cfg: MoEConfig, seq: int) -> int:
    return max(1, int(np.ceil(
        seq * cfg.router_top_k * cfg.capacity_factor / cfg.n_experts)))


def moe_dispatch_combine(x: jax.Array, router: jax.Array, cfg: MoEConfig
                         ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Routing + slot-major capacity allocation shared by the single-mesh
    layer below and the pipeline's ep-local path
    (parallel/pipeline.py:_pp_moe_ffn): x (B, S, D) →
    (dispatch (B, S, E, C), combine_w (B, S, E, C), aux scalar).
    Pure jnp — identical results wherever it runs, which is what keeps
    the two paths loss-parity-exact."""
    b, s, d = x.shape
    e = cfg.n_experts
    k = cfg.router_top_k
    c = _capacity(cfg, s)

    logits = x.astype(jnp.float32) @ router.astype(jnp.float32)  # (B, S, E)
    probs = jax.nn.softmax(logits, axis=-1)
    topk_probs, topk_idx = jax.lax.top_k(probs, k)   # (B, S, K)
    if k == 1:
        gates = topk_probs                           # switch: raw prob
    else:
        gates = topk_probs / jnp.sum(topk_probs, axis=-1, keepdims=True)

    # Switch load-balancing aux loss over FIRST choices: E · Σ_e f_e · p_e
    top1_hot = jax.nn.one_hot(topk_idx[..., 0], e, dtype=jnp.float32)
    density = top1_hot.mean(axis=1)                  # fraction per expert
    density_proxy = probs.mean(axis=1)
    aux = (density * density_proxy).sum(axis=-1).mean() * e

    # Capacity allocation, slot-major: flatten (K, S) assignments so all
    # first choices outrank any second choice, cumsum positions within
    # each expert's buffer, drop past capacity
    oh = jax.nn.one_hot(topk_idx, e, dtype=jnp.float32)      # (B, S, K, E)
    oh_flat = oh.transpose(0, 2, 1, 3).reshape(b, k * s, e)  # slot-major
    pos_flat = ((jnp.cumsum(oh_flat, axis=1) - 1.0) * oh_flat).sum(axis=-1)
    keep = (pos_flat < c).astype(jnp.float32)
    disp_flat = (oh_flat * keep[..., None])[..., None] \
        * jax.nn.one_hot(pos_flat.astype(jnp.int32), c,
                         dtype=jnp.float32)[:, :, None, :]
    disp = disp_flat.reshape(b, k, s, e, c)                  # per slot
    dispatch = disp.sum(axis=1)                              # (B, S, E, C)
    combine_w = (disp
                 * gates.transpose(0, 2, 1)[..., None, None]).sum(axis=1)
    return dispatch, combine_w, aux


def _moe_layer(x: jax.Array, blk: dict, cfg: MoEConfig,
               mesh: Optional[Mesh]) -> tuple[jax.Array, jax.Array]:
    """x (B, S, D) → (out, aux_loss)."""
    dispatch, combine_w, aux = moe_dispatch_combine(x, blk["router"], cfg)

    def constrain(arr, *spec):
        if mesh is not None:
            return jax.lax.with_sharding_constraint(
                arr, NamedSharding(mesh, P(*spec)))
        return arr

    xf = x.astype(jnp.float32)
    expert_in = jnp.einsum("bsec,bsd->ebcd", dispatch, xf)
    # Token buffers shard over ep with the experts → XLA all_to_alls the
    # tokens to their expert's chips
    expert_in = constrain(expert_in, "ep", "dp", None, None)

    w1 = blk["w1"].astype(jnp.float32)
    w2 = blk["w2"].astype(jnp.float32)
    h = jax.nn.gelu(jnp.einsum("ebcd,edf->ebcf", expert_in, w1))
    out_e = jnp.einsum("ebcf,efd->ebcd", h, w2)
    out_e = constrain(out_e, "ep", "dp", None, None)

    out = jnp.einsum("bsec,ebcd->bsd", combine_w, out_e)
    return out.astype(x.dtype), aux.astype(jnp.float32)


def moe_forward(params: dict, tokens: jax.Array, cfg: MoEConfig,
                mesh: Optional[Mesh] = None
                ) -> tuple[jax.Array, jax.Array]:
    """tokens (B, S) → (logits (B, S, V), aux_loss scalar)."""
    def constrain(arr, *spec):
        if mesh is not None:
            return jax.lax.with_sharding_constraint(
                arr, NamedSharding(mesh, P(*spec)))
        return arr

    b, s = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(s)[None], (b, s))
    x = params["embed"].astype(cfg.compute_dtype)[tokens]
    x = constrain(x, "dp", None, None)

    # Resolve "auto" kernels + mesh downgrades (flash shard_maps over
    # (dp, tp); the fused norm stays single-stream)
    from faabric_tpu.models.transformer import resolve_impls
    cfg = resolve_impls(cfg, mesh)

    aux_total = jnp.zeros((), jnp.float32)
    for blk in params["blocks"]:
        x = attention_sublayer(x, blk, positions, cfg, mesh)
        h = _rms_norm(x, blk["ln2"])
        moe_out, aux = _moe_layer(h, blk, cfg, mesh)
        aux_total = aux_total + aux
        x = x + moe_out
        x = constrain(x, "dp", None, None)

    x = _rms_norm(x, params["ln_f"])
    logits = (x @ params["lm_head"].astype(cfg.compute_dtype)
              ).astype(jnp.float32)
    return logits, aux_total / max(1, cfg.n_layers)


def moe_loss_fn(params: dict, tokens: jax.Array, targets: jax.Array,
                cfg: MoEConfig, mesh: Optional[Mesh] = None) -> jax.Array:
    from faabric_tpu.models.transformer import token_nll

    logits, aux = moe_forward(params, tokens, cfg, mesh)
    return jnp.mean(token_nll(logits, targets)) + cfg.aux_loss_weight * aux


def make_moe_train_step(cfg: MoEConfig, mesh: Optional[Mesh] = None,
                        optimizer=None):
    import optax

    from faabric_tpu.models.train import make_optimizer

    optimizer = optimizer or make_optimizer()

    def step(params, opt_state, tokens, targets):
        loss, grads = jax.value_and_grad(moe_loss_fn)(params, tokens,
                                                      targets, cfg, mesh)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return jax.jit(step, donate_argnums=(0, 1))
