"""Autoregressive decoding with a KV cache.

Static shapes end-to-end: the cache is pre-allocated at ``max_seq`` and
filled with ``lax.dynamic_update_slice``; attention masks by position, so
prefill and every decode step compile once each. The whole greedy loop is
one ``lax.scan`` under jit — no host round-trips between tokens, which is
what keeps a TPU busy at small batch.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from faabric_tpu.models.transformer import (
    ModelConfig,
    _norm,
    _rope,
)


def init_kv_cache(cfg: ModelConfig, batch: int) -> list[dict]:
    return [{
        "k": jnp.zeros((batch, cfg.max_seq, cfg.n_heads, cfg.head_dim),
                       cfg.compute_dtype),
        "v": jnp.zeros((batch, cfg.max_seq, cfg.n_heads, cfg.head_dim),
                       cfg.compute_dtype),
    } for _ in range(cfg.n_layers)]


def _cached_attention(q, cache_k, cache_v, length):
    """q (B, S_q, H, D) against the cache's first ``length`` positions
    (q's last position is length-1)."""
    scale = 1.0 / np.sqrt(q.shape[-1])
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, cache_k
                        ).astype(jnp.float32) * scale
    s_q = q.shape[1]
    max_seq = cache_k.shape[1]
    q_pos = (length - s_q) + jnp.arange(s_q)
    k_pos = jnp.arange(max_seq)
    mask = q_pos[:, None] >= k_pos[None, :]
    logits = jnp.where(mask[None, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, cache_v)


def _block_with_cache(x, blk, cache, start, length, cfg: ModelConfig):
    """One transformer block over tokens at positions [start, start+S);
    updates the cache in place (functionally) and attends over
    [0, length)."""
    b, s, _ = x.shape
    h = _norm(x, blk["ln1"], cfg)
    qkv = jnp.einsum("bsd,dthe->tbshe", h,
                     blk["wqkv"].astype(cfg.compute_dtype))
    q, k, v = qkv[0], qkv[1], qkv[2]
    positions = jnp.broadcast_to(start + jnp.arange(s)[None], (b, s))
    q = _rope(q, positions, cfg.rope_theta)
    k = _rope(k, positions, cfg.rope_theta)

    cache_k = jax.lax.dynamic_update_slice(cache["k"], k, (0, start, 0, 0))
    cache_v = jax.lax.dynamic_update_slice(cache["v"], v, (0, start, 0, 0))

    attn = _cached_attention(q, cache_k, cache_v, length)
    x = x + jnp.einsum("bshe,hed->bsd", attn,
                       blk["wo"].astype(cfg.compute_dtype))
    h = _norm(x, blk["ln2"], cfg)
    ff = jax.nn.gelu(h @ blk["w1"].astype(cfg.compute_dtype))
    x = x + ff @ blk["w2"].astype(cfg.compute_dtype)
    return x, {"k": cache_k, "v": cache_v}


def forward_with_cache(params, tokens, cache, start, cfg: ModelConfig):
    """tokens (B, S) entering at position ``start`` → (logits (B, S, V),
    new cache). length = start + S."""
    from faabric_tpu.models.transformer import resolve_impls

    cfg = resolve_impls(cfg)
    b, s = tokens.shape
    length = start + s
    x = params["embed"].astype(cfg.compute_dtype)[tokens]
    new_cache = []
    for blk, layer_cache in zip(params["blocks"], cache):
        x, updated = _block_with_cache(x, blk, layer_cache, start, length,
                                       cfg)
        new_cache.append(updated)
    x = _norm(x, params["ln_f"], cfg)
    logits = (x @ params["lm_head"].astype(cfg.compute_dtype)
              ).astype(jnp.float32)
    return logits, new_cache


def _pick_token(logits, key, greedy: bool, temperature, top_k: int,
                use_top_p: bool, top_p) -> jax.Array:
    """One sampling step over (B, V) logits. Static structure (greedy vs
    sample, top-k size, top-p enabled) picks the program; temperature and
    top_p themselves are TRACED operands, so a serving loop varying them
    per request reuses one compiled decode."""
    if greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][:, -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if use_top_p:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # Keep the smallest prefix with cumulative mass >= top_p (the
        # first token always survives)
        cutoff_idx = jnp.sum(cum < top_p, axis=-1, keepdims=True)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


@partial(jax.jit, static_argnums=(2, 3, 6, 7, 9, 10, 11))
def _generate_impl(params, prompt, cfg: ModelConfig, n_tokens: int,
                   key, temperature, greedy: bool, top_k: int, top_p,
                   use_top_p: bool, mesh, prefill_chunk: int = 0):
    from jax.sharding import NamedSharding, PartitionSpec as P

    b, s_p = prompt.shape
    cache = init_kv_cache(cfg, b)
    if mesh is not None:
        kv_sharding = NamedSharding(mesh, P("dp", None, "tp", None))
        cache = [{k: jax.lax.with_sharding_constraint(v, kv_sharding)
                  for k, v in layer.items()} for layer in cache]

    if prefill_chunk and prefill_chunk < s_p:
        # Chunked prefill: attention during prefill peaks at
        # (chunk × max_seq) scores instead of (S_p × max_seq) — the
        # long-prompt memory bound. Chunk boundaries are static.
        pos = 0
        logits = None
        while pos < s_p:
            hi = min(pos + prefill_chunk, s_p)
            logits, cache = forward_with_cache(
                params, prompt[:, pos:hi], cache, pos, cfg)
            pos = hi
    else:
        logits, cache = forward_with_cache(params, prompt, cache, 0, cfg)
    key, sub = jax.random.split(key)
    next_tok = _pick_token(logits[:, -1], sub, greedy, temperature,
                           top_k, use_top_p, top_p)

    def step(carry, _):
        tok, pos, cache, key = carry
        logits, cache = forward_with_cache(params, tok[:, None], cache,
                                           pos, cfg)
        key, sub = jax.random.split(key)
        nxt = _pick_token(logits[:, -1], sub, greedy, temperature,
                          top_k, use_top_p, top_p)
        return (nxt, pos + 1, cache, key), tok

    (_, _, _, _), toks = jax.lax.scan(step, (next_tok, s_p, cache, key),
                                      None, length=n_tokens)
    return toks.T  # (B, n_tokens)


def generate(params, prompt, cfg: ModelConfig, n_tokens: int,
             key: jax.Array | None = None, temperature: float = 0.0,
             top_k: int = 0, top_p: float = 1.0, mesh=None,
             prefill_chunk: int = 0):
    """Decode: prompt (B, S_p) int32 → (B, n_tokens) int32. Prefill + a
    scanned single-token decode loop, all one program. Default is greedy
    (temperature 0); pass a PRNG ``key`` with ``temperature``/``top_k``/
    ``top_p`` for sampling (varying temperature/top_p does NOT
    recompile; varying top_k does — it's a shape). With ``mesh``, the KV
    cache shards batch over ``dp`` and heads over ``tp`` (matching
    tp-sharded params), so decode runs tensor-parallel with XLA
    inserting the activation collectives. ``prefill_chunk`` processes
    long prompts in fixed-size chunks, bounding prefill attention
    memory."""
    greedy = temperature == 0.0
    if key is None:
        key = jax.random.PRNGKey(0)
    return _generate_impl(
        params, prompt, cfg, n_tokens, key,
        jnp.float32(temperature if not greedy else 1.0), greedy,
        int(top_k), jnp.float32(top_p), top_p < 1.0, mesh,
        int(prefill_chunk))
