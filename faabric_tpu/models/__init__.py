"""Flagship models exercising the mesh substrate."""

from faabric_tpu.models.transformer import (
    ModelConfig,
    forward,
    init_params,
    loss_fn,
    param_shardings,
    shard_params,
)
from faabric_tpu.models.train import (
    data_sharding,
    init_train_state,
    make_optimizer,
    make_train_step,
)

__all__ = [
    "ModelConfig",
    "data_sharding",
    "forward",
    "init_params",
    "init_train_state",
    "loss_fn",
    "make_optimizer",
    "make_train_step",
    "param_shardings",
    "shard_params",
]
