"""Flagship models exercising the mesh substrate."""

from faabric_tpu.models.transformer import (
    ModelConfig,
    forward,
    init_params,
    loss_fn,
    param_shardings,
    shard_params,
)
from faabric_tpu.models.train import (
    data_sharding,
    init_train_state,
    make_multi_step,
    make_optimizer,
    make_train_step,
)

__all__ = [
    "ModelConfig",
    "data_sharding",
    "forward",
    "init_params",
    "init_train_state",
    "loss_fn",
    "make_multi_step",
    "make_optimizer",
    "make_train_step",
    "param_shardings",
    "shard_params",
]

from faabric_tpu.models.checkpoint import (  # noqa: E402
    restore_train_state,
    save_train_state,
)
from faabric_tpu.models.generate import generate, init_kv_cache  # noqa: E402
from faabric_tpu.models.moe import (  # noqa: E402
    MoEConfig,
    init_moe_params,
    make_moe_train_step,
    moe_forward,
    moe_loss_fn,
    moe_param_shardings,
)

__all__ += [
    "MoEConfig",
    "generate",
    "init_kv_cache",
    "init_moe_params",
    "make_moe_train_step",
    "moe_forward",
    "moe_loss_fn",
    "moe_param_shardings",
    "restore_train_state",
    "save_train_state",
]
