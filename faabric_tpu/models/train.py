"""Training step over a device mesh.

The full step — forward, backward, optimizer — compiles as ONE XLA program
over the mesh: gradient allreduce over ``dp``, tensor-parallel collectives
over ``tp``, sequence gathers over ``sp``, all inserted by XLA from the
sharding annotations. Params are donated so the update is in-place in HBM.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from faabric_tpu.models.transformer import (
    ModelConfig,
    init_params,
    loss_fn,
    param_shardings,
)


def make_optimizer(lr: float = 3e-4, weight_decay: float = 0.01,
                   warmup_steps: int = 0, total_steps: int | None = None,
                   clip_norm: float | None = None):
    """AdamW with optional warmup-cosine schedule and global-norm
    gradient clipping — the standard large-model training recipe."""
    if total_steps:
        schedule = optax.warmup_cosine_decay_schedule(
            init_value=0.0, peak_value=lr, warmup_steps=max(1, warmup_steps),
            decay_steps=max(total_steps, warmup_steps + 1))
    elif warmup_steps:
        # No horizon given: warm up then HOLD at peak (never silently
        # decay to zero on an invented horizon)
        schedule = optax.join_schedules(
            [optax.linear_schedule(0.0, lr, warmup_steps),
             optax.constant_schedule(lr)], [warmup_steps])
    else:
        schedule = lr
    tx = optax.adamw(schedule, weight_decay=weight_decay)
    if clip_norm is not None:
        tx = optax.chain(optax.clip_by_global_norm(clip_norm), tx)
    return tx


def _build_step(cfg: ModelConfig, mesh: Optional[Mesh],
                optimizer, accum_steps: int):
    """The un-jitted step body shared by :func:`make_train_step` (one
    dispatch per step) and :func:`make_multi_step` (n steps per
    dispatch)."""

    def grads_of(params, tokens, targets):
        return jax.value_and_grad(loss_fn)(params, tokens, targets,
                                           cfg, mesh)

    def step(params, opt_state, tokens, targets):
        if accum_steps > 1:
            b = tokens.shape[0]
            if b % accum_steps:
                raise ValueError(
                    f"batch {b} not divisible by accum_steps={accum_steps}")
            tok = tokens.reshape(accum_steps, b // accum_steps,
                                 *tokens.shape[1:])
            tgt = targets.reshape(accum_steps, b // accum_steps,
                                  *targets.shape[1:])
            if mesh is not None:
                # Each microbatch must stay dp-sharded (the contiguous
                # reshape would otherwise park whole microbatches on a
                # subset of dp shards, idling the rest of the mesh)
                mb_sharding = NamedSharding(mesh, P(None, "dp", "sp"))
                tok = jax.lax.with_sharding_constraint(tok, mb_sharding)
                tgt = jax.lax.with_sharding_constraint(tgt, mb_sharding)

            def acc(carry, mb):
                loss_sum, g_sum = carry
                loss, g = grads_of(params, mb[0], mb[1])
                return (loss_sum + loss,
                        jax.tree.map(jnp.add, g_sum, g)), None

            zeros = jax.tree.map(jnp.zeros_like, params)
            (loss_sum, g_sum), _ = jax.lax.scan(
                acc, (jnp.zeros(()), zeros), (tok, tgt))
            loss = loss_sum / accum_steps
            grads = jax.tree.map(lambda g: g / accum_steps, g_sum)
        else:
            loss, grads = grads_of(params, tokens, targets)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step


def make_train_step(cfg: ModelConfig, mesh: Optional[Mesh] = None,
                    optimizer=None, accum_steps: int = 1):
    """Returns jitted ``step(params, opt_state, tokens, targets) →
    (params, opt_state, loss)``. ``accum_steps > 1`` splits the batch
    into that many microbatches and accumulates gradients with a
    ``lax.scan`` before the single optimizer update — big effective
    batches without the activation memory (means over equal microbatches
    equal the full-batch gradient exactly)."""
    optimizer = optimizer or make_optimizer()
    return jax.jit(_build_step(cfg, mesh, optimizer, accum_steps),
                   donate_argnums=(0, 1))


def make_multi_step(cfg: ModelConfig, mesh: Optional[Mesh] = None,
                    optimizer=None, accum_steps: int = 1):
    """Returns jitted ``run(params, opt_state, tokens, targets, n) →
    (params, opt_state, last_loss)`` executing ``n`` whole train steps
    inside ONE compiled program (``lax.scan`` over the step body).

    This puts the training loop itself on the device: one dispatch —
    and, on a remote PJRT client, one network round-trip — per n steps
    instead of per step. ``tokens``/``targets`` carry a leading step
    axis of length n (a fresh batch per step), or the plain batch shape
    to reuse one batch every step (benchmarking)."""
    optimizer = optimizer or make_optimizer()
    step = _build_step(cfg, mesh, optimizer, accum_steps)

    @partial(jax.jit, static_argnums=(4,), donate_argnums=(0, 1))
    def run(params, opt_state, tokens, targets, n: int):
        per_step = tokens.ndim == 3
        if per_step and tokens.shape[0] != n:
            raise ValueError(
                f"tokens carry {tokens.shape[0]} per-step batches, n={n}")

        def body(carry, xs):
            p, o = carry
            tok, tgt = xs if per_step else (tokens, targets)
            p, o, loss = step(p, o, tok, tgt)
            return (p, o), loss

        (params, opt_state), losses = jax.lax.scan(
            body, (params, opt_state),
            (tokens, targets) if per_step else None, length=n)
        return params, opt_state, losses[-1]

    return run


def init_train_state(key: jax.Array, cfg: ModelConfig,
                     mesh: Optional[Mesh] = None, optimizer=None):
    """Params + optimizer state, laid out over the mesh when given."""
    optimizer = optimizer or make_optimizer()
    params = init_params(key, cfg)
    if mesh is not None:
        params = jax.device_put(params, param_shardings(mesh, cfg))
    opt_state = optimizer.init(params)
    return params, opt_state


def data_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P("dp", "sp"))
