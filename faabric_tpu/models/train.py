"""Training step over a device mesh.

The full step — forward, backward, optimizer — compiles as ONE XLA program
over the mesh: gradient allreduce over ``dp``, tensor-parallel collectives
over ``tp``, sequence gathers over ``sp``, all inserted by XLA from the
sharding annotations. Params are donated so the update is in-place in HBM.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from faabric_tpu.models.transformer import (
    ModelConfig,
    init_params,
    loss_fn,
    param_shardings,
)


def make_optimizer(lr: float = 3e-4, weight_decay: float = 0.01):
    return optax.adamw(lr, weight_decay=weight_decay)


def make_train_step(cfg: ModelConfig, mesh: Optional[Mesh] = None,
                    optimizer=None):
    """Returns jitted ``step(params, opt_state, tokens, targets) →
    (params, opt_state, loss)``."""
    optimizer = optimizer or make_optimizer()

    def step(params, opt_state, tokens, targets):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, targets,
                                                  cfg, mesh)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return jax.jit(step, donate_argnums=(0, 1))


def init_train_state(key: jax.Array, cfg: ModelConfig,
                     mesh: Optional[Mesh] = None, optimizer=None):
    """Params + optimizer state, laid out over the mesh when given."""
    optimizer = optimizer or make_optimizer()
    params = init_params(key, cfg)
    if mesh is not None:
        params = jax.device_put(params, param_shardings(mesh, cfg))
    opt_state = optimizer.init(params)
    return params, opt_state


def data_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P("dp", "sp"))
