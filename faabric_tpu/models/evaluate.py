"""Evaluation: token-level NLL / perplexity over a batch stream."""

from __future__ import annotations

from functools import partial
from typing import Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from faabric_tpu.models.transformer import ModelConfig, forward, token_nll


@partial(jax.jit, static_argnums=(3, 4))
def _eval_step(params, tokens, targets, cfg: ModelConfig, mesh):
    nll = token_nll(forward(params, tokens, cfg, mesh), targets)
    return jnp.sum(nll), nll.size


def evaluate_perplexity(params, cfg: ModelConfig,
                        batches: Iterable, mesh=None,
                        max_batches: Optional[int] = None) -> dict:
    """Mean token NLL and perplexity over (tokens, targets) batches
    (e.g. a :class:`faabric_tpu.data.DataLoader`)."""
    import itertools

    total_nll = 0.0
    total_tokens = 0
    if max_batches is not None:
        batches = itertools.islice(iter(batches), max_batches)
    for tokens, targets in batches:
        nll_sum, count = _eval_step(params, tokens, targets, cfg, mesh)
        total_nll += float(nll_sum)
        total_tokens += int(count)
    if total_tokens == 0:
        raise ValueError("evaluate_perplexity got no batches")
    mean_nll = total_nll / total_tokens
    return {"nll": mean_nll, "perplexity": float(np.exp(mean_nll)),
            "tokens": total_tokens}
