"""Flagship model: decoder-only transformer, TPU-first.

Pure-JAX pytree params (no framework indirection between the model and
XLA), written for the MXU and the mesh:
- matmuls stay large and batched, activations compute in bfloat16 while
  params/optimizer stay float32 (classic mixed precision);
- every weight has an explicit PartitionSpec: attention heads and MLP
  hidden shard over ``tp``, batch over ``dp``, sequence over ``sp``
  (Megatron-style sequence parallelism on the norm/MLP path — XLA inserts
  the gathers around attention);
- blocks are ``jax.checkpoint``-wrapped so long-context activations
  rematerialise instead of living in HBM;
- static shapes and a Python-unrolled layer loop: everything under jit
  traces once.

The reference is a serverless runtime with no models; this is the
framework's own flagship workload (SURVEY §5.7: the deliverable substrate
must carry DP/TP/SP strategies), exercised by __graft_entry__ and bench.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    d_ff: int = 2048
    max_seq: int = 2048
    rope_theta: float = 10000.0
    compute_dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True
    # "reference" = plain jnp attention; "flash" = the Pallas fused kernel
    # (ops/flash_attention.py) — identical numerics, no (S, S) scores in
    # HBM; "ring" = sequence-parallel ring attention over the sp axis
    # (parallel/ring_attention.py) — the long-context path that never
    # gathers the sequence; "auto" (default) = flash on TPU, reference on
    # CPU (interpret-mode Pallas is for tests, not speed)
    attention_impl: str = "auto"
    # "reference" = inline jnp RMS norm; "fused" = the Pallas kernel
    # (ops/rms_norm.py); "auto" = fused on TPU, reference on CPU
    norm_impl: str = "auto"

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


# ---------------------------------------------------------------------------
# Parameters
# ---------------------------------------------------------------------------

def init_params(key: jax.Array, cfg: ModelConfig) -> dict:
    keys = jax.random.split(key, cfg.n_layers + 2)

    def dense(k, shape, fan_in):
        return (jax.random.normal(k, shape, cfg.param_dtype)
                / np.sqrt(fan_in))

    blocks = []
    for i in range(cfg.n_layers):
        bk = jax.random.split(keys[i], 4)
        blocks.append({
            "ln1": jnp.ones((cfg.d_model,), cfg.param_dtype),
            "wqkv": dense(bk[0], (cfg.d_model, 3, cfg.n_heads, cfg.head_dim),
                          cfg.d_model),
            "wo": dense(bk[1], (cfg.n_heads, cfg.head_dim, cfg.d_model),
                        cfg.d_model),
            "ln2": jnp.ones((cfg.d_model,), cfg.param_dtype),
            "w1": dense(bk[2], (cfg.d_model, cfg.d_ff), cfg.d_model),
            "w2": dense(bk[3], (cfg.d_ff, cfg.d_model), cfg.d_ff),
        })
    return {
        "embed": dense(keys[-2], (cfg.vocab_size, cfg.d_model), cfg.d_model),
        "blocks": blocks,
        "ln_f": jnp.ones((cfg.d_model,), cfg.param_dtype),
        "lm_head": dense(keys[-1], (cfg.d_model, cfg.vocab_size), cfg.d_model),
    }


def param_shardings(mesh: Mesh, cfg: ModelConfig) -> dict:
    """PartitionSpecs per weight: heads/hidden over tp, vocab over tp for
    the embedding table halves (keeps the biggest tables sharded)."""
    def ns(*spec):
        return NamedSharding(mesh, P(*spec))

    block = {
        "ln1": ns(),
        "wqkv": ns(None, None, "tp", None),
        "wo": ns("tp", None, None),
        "ln2": ns(),
        "w1": ns(None, "tp"),
        "w2": ns("tp", None),
    }
    return {
        "embed": ns("tp", None),
        "blocks": [dict(block) for _ in range(cfg.n_layers)],
        "ln_f": ns(),
        "lm_head": ns(None, "tp"),
    }


def shard_params(params: dict, mesh: Mesh, cfg: ModelConfig) -> dict:
    return jax.device_put(params, param_shardings(mesh, cfg))


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def _rms_norm(x: jax.Array, scale: jax.Array) -> jax.Array:
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + 1e-6).astype(x.dtype)) * scale.astype(x.dtype)


def _rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embeddings over the head dim: x (B, S, H, D)."""
    d = x.shape[-1]
    freqs = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    angles = positions[:, :, None, None].astype(jnp.float32) \
        * freqs[None, None, None, :]
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    x1, x2 = x[..., 0::2], x[..., 1::2]
    out = jnp.stack([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.reshape(x.shape).astype(x.dtype)


def _attention(q: jax.Array, k: jax.Array, v: jax.Array) -> jax.Array:
    """Causal attention, (B, S, H, D); fp32 softmax accumulators."""
    scale = 1.0 / np.sqrt(q.shape[-1])
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    s = q.shape[1]
    mask = jnp.tril(jnp.ones((s, s), dtype=bool))
    logits = jnp.where(mask[None, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def resolve_impls(cfg: ModelConfig, mesh: Optional[Mesh] = None) -> ModelConfig:
    """Resolve "auto" kernel choices for the current backend, and downgrade
    combinations the mesh can't run: flash under a mesh runs shard_mapped
    over (dp, tp), so a sequence-sharded model (sp > 1) routes to ring
    attention, which keeps the sequence distributed; the fused norm kernel
    stays single-stream."""
    att, norm = cfg.attention_impl, cfg.norm_impl
    on_tpu = jax.default_backend() == "tpu"
    if att == "auto":
        att = "flash" if on_tpu else "reference"
    if norm == "auto":
        norm = "fused" if on_tpu else "reference"
    if mesh is not None:
        if att == "flash" and mesh.shape.get("sp", 1) > 1:
            att = "ring"
        if norm == "fused":
            norm = "reference"
    if (att, norm) != (cfg.attention_impl, cfg.norm_impl):
        cfg = dataclasses.replace(cfg, attention_impl=att, norm_impl=norm)
    return cfg


def _norm(x: jax.Array, scale: jax.Array, cfg: ModelConfig) -> jax.Array:
    if cfg.norm_impl == "fused":
        from faabric_tpu.ops.rms_norm import rms_norm

        return rms_norm(x, scale)
    return _rms_norm(x, scale)


def _sharded_flash(q, k, v, mesh: Mesh):
    """Flash attention under a mesh: batch (dp) and heads (tp) are
    embarrassingly parallel for attention, so each shard runs the Pallas
    kernel on its local (B/dp, S, H/tp, D) slab — no collectives."""
    from faabric_tpu.ops.flash_attention import flash_attention
    from faabric_tpu.parallel.collectives import shard_map_compat

    spec = P("dp", None, "tp", None)
    # check off (check_vma / check_rep by JAX version): pallas_call's
    # out_shape carries no varying-mesh-axes annotation, and this
    # wrapper is trivially per-shard anyway
    return shard_map_compat(lambda q, k, v: flash_attention(q, k, v, True),
                            mesh=mesh, in_specs=(spec, spec, spec),
                            out_specs=spec, check_vma=False)(q, k, v)


def attention_sublayer(x: jax.Array, blk: dict, positions: jax.Array,
                       cfg: ModelConfig,
                       mesh: Optional[Mesh] = None) -> jax.Array:
    """Pre-norm attention + residual — shared by the dense and MoE
    families (honours cfg.attention_impl / norm_impl)."""
    h = _norm(x, blk["ln1"], cfg)
    qkv = jnp.einsum("bsd,dthe->tbshe", h,
                     blk["wqkv"].astype(cfg.compute_dtype))
    q, k, v = qkv[0], qkv[1], qkv[2]
    q = _rope(q, positions, cfg.rope_theta)
    k = _rope(k, positions, cfg.rope_theta)
    if cfg.attention_impl == "flash":
        from faabric_tpu.ops.flash_attention import flash_attention

        if mesh is not None:
            attn = _sharded_flash(q, k, v, mesh)
        else:
            attn = flash_attention(q, k, v, True)
    elif cfg.attention_impl == "ring" and mesh is not None:
        from faabric_tpu.parallel.ring_attention import ring_attention

        attn = ring_attention(q, k, v, mesh, axis="sp",
                              batch_axis="dp", head_axis="tp")
    else:
        attn = _attention(q, k, v)
    return x + jnp.einsum("bshe,hed->bsd", attn,
                          blk["wo"].astype(cfg.compute_dtype))


def _block(x: jax.Array, blk: dict, positions: jax.Array,
           cfg: ModelConfig, mesh: Optional[Mesh] = None) -> jax.Array:
    x = attention_sublayer(x, blk, positions, cfg, mesh)
    h = _norm(x, blk["ln2"], cfg)
    ff = jax.nn.gelu(h @ blk["w1"].astype(cfg.compute_dtype))
    return x + ff @ blk["w2"].astype(cfg.compute_dtype)


def forward(params: dict, tokens: jax.Array, cfg: ModelConfig,
            mesh: Optional[Mesh] = None) -> jax.Array:
    """tokens (B, S) int32 → logits (B, S, V)."""
    def maybe_constrain(x, *spec):
        if mesh is not None:
            return jax.lax.with_sharding_constraint(
                x, NamedSharding(mesh, P(*spec)))
        return x

    cfg = resolve_impls(cfg, mesh)

    b, s = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(s)[None], (b, s))
    x = params["embed"].astype(cfg.compute_dtype)[tokens]
    x = maybe_constrain(x, "dp", "sp", None)

    block_fn = _block
    if cfg.remat:
        block_fn = jax.checkpoint(_block, static_argnums=(3, 4))
    for blk in params["blocks"]:
        x = block_fn(x, blk, positions, cfg, mesh)
        x = maybe_constrain(x, "dp", "sp", None)

    x = _norm(x, params["ln_f"], cfg)
    logits = x @ params["lm_head"].astype(cfg.compute_dtype)
    return maybe_constrain(logits.astype(jnp.float32), "dp", "sp", None)


def token_nll(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Per-token negative log-likelihood — THE loss definition, shared by
    training (dense + MoE) and evaluation so they can never diverge."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]


def loss_fn(params: dict, tokens: jax.Array, targets: jax.Array,
            cfg: ModelConfig, mesh: Optional[Mesh] = None) -> jax.Array:
    return jnp.mean(token_nll(forward(params, tokens, cfg, mesh), targets))
