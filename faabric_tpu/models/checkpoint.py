"""Training-state checkpoint/resume.

The reference's checkpoint story is its snapshot stack (guest memory
images, §5.4); the TPU-native equivalent for model state is orbax over the
params/optimizer pytree: device arrays stream HBM→host→disk, and restore
re-lays them out over the mesh via the model's param shardings. Runtime
(executor-memory) checkpointing stays with faabric_tpu.snapshot — the two
cover the reference capability from both sides.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np

from faabric_tpu.util.logging import get_logger

logger = get_logger(__name__)


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def save_train_state(path: str, params: Any, opt_state: Any,
                     step: int = 0) -> None:
    """Write params + optimizer state + step to ``path`` (a directory)."""
    path = os.path.abspath(path)
    state = {"params": params, "opt_state": opt_state,
             "step": np.asarray(step)}
    # No silent fallback: an unrestorable "checkpoint" is worse than a
    # loud save failure
    ckpt = _checkpointer()
    ckpt.save(path, state, force=True)
    logger.debug("Checkpoint saved to %s (step %d)", path, step)


def restore_train_state(path: str, mesh=None, cfg=None,
                        optimizer=None) -> tuple[Any, Any, int]:
    """Restore (params, opt_state, step). With ``cfg`` (+``optimizer``) the
    pytree restores into the real optax/param structure rather than raw
    dicts; with ``mesh`` the arrays are placed back onto the mesh with the
    model's shardings."""
    path = os.path.abspath(path)
    ckpt = _checkpointer()

    # MoE configs carry expert/router params: dispatch init/sharding helpers
    # on config type once for both the template and placement blocks
    init_fn = shardings_fn = None
    if cfg is not None:
        from faabric_tpu.models.moe import (MoEConfig, init_moe_params,
                                            moe_param_shardings)
        from faabric_tpu.models.transformer import init_params, param_shardings

        is_moe = isinstance(cfg, MoEConfig)
        init_fn = init_moe_params if is_moe else init_params
        shardings_fn = moe_param_shardings if is_moe else param_shardings

    template = None  # noqa: assigned below when cfg+optimizer given
    if cfg is not None and optimizer is not None:
        # Zero-weight template gives orbax the exact target structure
        t_params = jax.tree.map(
            lambda s: np.zeros(s.shape, s.dtype),
            jax.eval_shape(lambda: init_fn(jax.random.PRNGKey(0), cfg)))
        template = {"params": t_params,
                    "opt_state": optimizer.init(t_params),
                    "step": np.asarray(0)}

    state = ckpt.restore(path, item=template) if template is not None \
        else ckpt.restore(path)

    params = state["params"]
    opt_state = state["opt_state"]
    step = int(np.asarray(state["step"]))

    if mesh is not None and cfg is not None:
        params = jax.device_put(params, shardings_fn(mesh, cfg))
        if optimizer is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            # Moment leaves inherit the params' shardings (mu/nu mirror the
            # param tree); anything whose fresh sharding doesn't span the
            # mesh (e.g. the adam step-count scalar) replicates over it.
            fresh = jax.jit(optimizer.init)(params)
            mesh_devs = set(np.asarray(mesh.devices).flat)
            replicated = NamedSharding(mesh, PartitionSpec())

            def place(ref, val):
                sh = getattr(ref, "sharding", None)
                if sh is not None and set(sh.device_set) == mesh_devs:
                    return jax.device_put(np.asarray(val), sh)
                return jax.device_put(np.asarray(val), replicated)

            opt_state = jax.tree.map(place, fresh, opt_state)
    return params, opt_state, step

