"""Message schema — the analog of the reference's protobuf definitions
(src/proto/faabric.proto, 242 lines).

Implemented as dataclasses with a compact wire form: dict/JSON for the
control plane (small messages), with large binary payloads (input/output
data, snapshot contents, MPI buffers) carried out-of-band in the transport
frame's binary tail — the flatbuffers analog (src/flat/faabric.fbs).
"""

from __future__ import annotations

import dataclasses
import enum
import json
import time
from typing import Any

from faabric_tpu.util.gids import generate_gid


class BatchExecuteType(enum.IntEnum):
    # faabric.proto:26-31
    FUNCTIONS = 0
    THREADS = 1
    PROCESSES = 2
    MIGRATION = 3


class MessageType(enum.IntEnum):
    # faabric.proto:93-99
    CALL = 0
    KILL = 1
    EMPTY = 2
    FLUSH = 3


class ReturnValue(enum.IntEnum):
    SUCCESS = 0
    FAILED = 1
    MIGRATED = -99  # MIGRATED_FUNCTION_RETURN_VALUE
    FROZEN = -98


@dataclasses.dataclass
class Message:
    """A single function invocation (faabric.proto:91-151)."""

    id: int = 0
    app_id: int = 0
    app_idx: int = 0
    main_host: str = ""
    type: int = int(MessageType.CALL)

    user: str = ""
    function: str = ""

    input_data: bytes = b""
    output_data: bytes = b""

    timestamp: float = 0.0
    executed_host: str = ""
    finish_timestamp: float = 0.0

    return_value: int = 0

    # Snapshots
    snapshot_key: str = ""

    # Function groups (PTP)
    group_id: int = 0
    group_idx: int = 0
    group_size: int = 0

    # MPI
    is_mpi: bool = False
    mpi_world_id: int = 0
    mpi_rank: int = 0
    mpi_world_size: int = 0

    # OpenMP-style shared-memory parallelism
    is_omp: bool = False
    omp_num_threads: int = 0

    # Exec-graph
    record_exec_graph: bool = False
    exec_graph_details: dict[str, str] = dataclasses.field(default_factory=dict)
    int_exec_graph_details: dict[str, int] = dataclasses.field(default_factory=dict)
    chained_msg_ids: list[int] = dataclasses.field(default_factory=list)

    # Migration
    is_migration: bool = False

    # Invocation lifecycle ledger (ISSUE 14): phase → monotonic ns
    # stamp, written by telemetry/lifecycle.py at admit/schedule/
    # dispatch/run/result boundaries. Carried on the wire so the ledger
    # accumulates ACROSS hosts; empty when FAABRIC_METRICS=0.
    lc: dict[str, int] = dataclasses.field(default_factory=dict)

    def to_dict(self) -> dict[str, Any]:
        """REST/journal form: payloads hex-encoded in place. Built on
        the one hand-rolled field list (to_wire_dict)."""
        d = self.to_wire_dict()
        d["input_data"] = self.input_data.hex()
        d["output_data"] = self.output_data.hex()
        return d

    def to_wire_dict(self) -> dict[str, Any]:
        """THE hand-rolled field dict (the list must track the
        dataclass): payload fields carry LENGTHS — the bytes ride the
        transport frame's binary tail. Hand-rolled, not
        dataclasses.asdict (which deep-copies at ~22 µs/message): this
        sits on every dispatch, result push and journal append."""
        return {
            "id": self.id,
            "app_id": self.app_id,
            "app_idx": self.app_idx,
            "main_host": self.main_host,
            "type": self.type,
            "user": self.user,
            "function": self.function,
            "input_data": len(self.input_data),
            "output_data": len(self.output_data),
            "timestamp": self.timestamp,
            "executed_host": self.executed_host,
            "finish_timestamp": self.finish_timestamp,
            "return_value": self.return_value,
            "snapshot_key": self.snapshot_key,
            "group_id": self.group_id,
            "group_idx": self.group_idx,
            "group_size": self.group_size,
            "is_mpi": self.is_mpi,
            "mpi_world_id": self.mpi_world_id,
            "mpi_rank": self.mpi_rank,
            "mpi_world_size": self.mpi_world_size,
            "is_omp": self.is_omp,
            "omp_num_threads": self.omp_num_threads,
            "record_exec_graph": self.record_exec_graph,
            "exec_graph_details": dict(self.exec_graph_details),
            "int_exec_graph_details": dict(self.int_exec_graph_details),
            "chained_msg_ids": list(self.chained_msg_ids),
            "is_migration": self.is_migration,
            "lc": dict(self.lc),
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "Message":
        d = dict(d)
        d["input_data"] = bytes.fromhex(d.get("input_data", ""))
        d["output_data"] = bytes.fromhex(d.get("output_data", ""))
        field_names = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in field_names})


@dataclasses.dataclass
class HostResources:
    # faabric.proto:75-78
    slots: int = 0
    used_slots: int = 0

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "HostResources":
        return cls(slots=d.get("slots", 0), used_slots=d.get("used_slots", 0))


@dataclasses.dataclass
class BatchExecuteRequest:
    """A batch of messages executed as one app (faabric.proto:21-60)."""

    app_id: int = 0
    group_id: int = 0
    user: str = ""
    function: str = ""
    type: int = int(BatchExecuteType.FUNCTIONS)
    # Tenant/user tag for multi-tenant scheduling (reference wedges this into
    # the protobuf subtype field; CompactScheduler.cpp filterHosts).
    subtype: int = 0
    messages: list[Message] = dataclasses.field(default_factory=list)

    # Single-host optimisations
    single_host_hint: bool = False
    single_host: bool = False

    # Elastic scaling hint (OpenMP fork grows to free slots on main host)
    elastic_scale_hint: bool = False

    # Main-thread snapshot for THREADS batches
    snapshot_key: str = ""

    # Migration / spot
    evicted_host: str = ""

    def n_messages(self) -> int:
        return len(self.messages)

    def to_dict(self) -> dict[str, Any]:
        return {
            "app_id": self.app_id,
            "group_id": self.group_id,
            "user": self.user,
            "function": self.function,
            "type": self.type,
            "subtype": self.subtype,
            "messages": [m.to_dict() for m in self.messages],
            "single_host_hint": self.single_host_hint,
            "single_host": self.single_host,
            "elastic_scale_hint": self.elastic_scale_hint,
            "snapshot_key": self.snapshot_key,
            "evicted_host": self.evicted_host,
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "BatchExecuteRequest":
        req = cls(
            app_id=d.get("app_id", 0),
            group_id=d.get("group_id", 0),
            user=d.get("user", ""),
            function=d.get("function", ""),
            type=d.get("type", 0),
            subtype=d.get("subtype", 0),
            single_host_hint=d.get("single_host_hint", False),
            single_host=d.get("single_host", False),
            elastic_scale_hint=d.get("elastic_scale_hint", False),
            snapshot_key=d.get("snapshot_key", ""),
            evicted_host=d.get("evicted_host", ""),
        )
        req.messages = [Message.from_dict(m) for m in d.get("messages", [])]
        return req


@dataclasses.dataclass
class BatchExecuteRequestStatus:
    # faabric.proto:62-73
    app_id: int = 0
    finished: bool = False
    message_results: list[Message] = dataclasses.field(default_factory=list)
    expected_num_messages: int = 0

    def to_dict(self) -> dict[str, Any]:
        return {
            "app_id": self.app_id,
            "finished": self.finished,
            "message_results": [m.to_dict() for m in self.message_results],
            "expected_num_messages": self.expected_num_messages,
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "BatchExecuteRequestStatus":
        s = cls(
            app_id=d.get("app_id", 0),
            finished=d.get("finished", False),
            expected_num_messages=d.get("expected_num_messages", 0),
        )
        s.message_results = [Message.from_dict(m) for m in d.get("message_results", [])]
        return s


@dataclasses.dataclass
class PointToPointMessage:
    # faabric.proto:208-219 — payload travels in the transport binary tail
    app_id: int = 0
    group_id: int = 0
    send_idx: int = 0
    recv_idx: int = 0

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "PointToPointMessage":
        return cls(
            app_id=d.get("app_id", 0),
            group_id=d.get("group_id", 0),
            send_idx=d.get("send_idx", 0),
            recv_idx=d.get("recv_idx", 0),
        )


@dataclasses.dataclass
class PointToPointMapping:
    # faabric.proto:221-230 (one entry of PointToPointMappings, + mpiPort)
    host: str = ""
    message_id: int = 0
    app_idx: int = 0
    group_idx: int = 0
    mpi_port: int = 0
    device_ids: list[int] = dataclasses.field(default_factory=list)

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "PointToPointMapping":
        return cls(
            host=d.get("host", ""),
            message_id=d.get("message_id", 0),
            app_idx=d.get("app_idx", 0),
            group_idx=d.get("group_idx", 0),
            mpi_port=d.get("mpi_port", 0),
            device_ids=list(d.get("device_ids", [])),
        )


@dataclasses.dataclass
class PointToPointMappings:
    app_id: int = 0
    group_id: int = 0
    mappings: list[PointToPointMapping] = dataclasses.field(default_factory=list)

    def to_dict(self) -> dict[str, Any]:
        return {
            "app_id": self.app_id,
            "group_id": self.group_id,
            "mappings": [m.to_dict() for m in self.mappings],
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "PointToPointMappings":
        out = cls(app_id=d.get("app_id", 0), group_id=d.get("group_id", 0))
        out.mappings = [PointToPointMapping.from_dict(m) for m in d.get("mappings", [])]
        return out


@dataclasses.dataclass
class PendingMigration:
    # faabric.proto:236-242
    app_id: int = 0
    group_id: int = 0
    group_idx: int = 0
    src_host: str = ""
    dst_host: str = ""

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "PendingMigration":
        return cls(**{k: d.get(k, v) for k, v in
                      (("app_id", 0), ("group_id", 0), ("group_idx", 0),
                       ("src_host", ""), ("dst_host", ""))})


# ---------------------------------------------------------------------------
# Factories (reference: include/faabric/util/batch.h:11-39, func.h:29-57)
# ---------------------------------------------------------------------------

def message_factory(user: str, function: str) -> Message:
    msg = Message(
        id=generate_gid(),
        app_id=generate_gid(),
        user=user,
        function=function,
        timestamp=time.time(),
    )
    return msg


def batch_exec_factory(user: str, function: str, count: int = 1) -> BatchExecuteRequest:
    req = BatchExecuteRequest(app_id=generate_gid(), user=user, function=function)
    for i in range(count):
        msg = message_factory(user, function)
        msg.app_id = req.app_id
        msg.app_idx = i
        req.messages.append(msg)
    return req


def func_to_string(msg: Message, include_id: bool = False) -> str:
    base = f"{msg.user}/{msg.function}"
    if include_id:
        base += f":{msg.id}"
    return base


def get_main_thread_snapshot_key(msg: Message) -> str:
    # reference src/util/func.cpp:152 — key must include the app id so two
    # concurrent apps of the same function never share a main-thread snapshot
    if msg.app_id <= 0:
        raise ValueError(f"Invalid app id for snapshot key: {msg.app_id}")
    return f"{msg.user}/{msg.function}_{msg.app_id}"


def is_batch_exec_request_valid(req: BatchExecuteRequest | None) -> bool:
    if req is None:
        return False
    if not req.user or not req.function:
        return False
    return req.n_messages() > 0


def update_batch_exec_app_id(req: BatchExecuteRequest, app_id: int) -> None:
    req.app_id = app_id
    for m in req.messages:
        m.app_id = app_id


def update_batch_exec_group_id(req: BatchExecuteRequest, group_id: int) -> None:
    req.group_id = group_id
    for m in req.messages:
        m.group_id = group_id


def message_to_json(msg: Message) -> str:
    return json.dumps(msg.to_dict())


def message_from_json(s: str) -> Message:
    return Message.from_dict(json.loads(s))


# ---------------------------------------------------------------------------
# Wire form: binary-tail payload convention.
#
# Hex-in-JSON (to_dict/from_dict) is reserved for the human-facing REST
# surface. RPC transport uses these helpers instead: message control fields
# travel as JSON, while input/output payloads are concatenated into the
# transport frame's binary tail (the flatbuffers analog, src/flat/faabric.fbs)
# so bulk data never passes through JSON.
# ---------------------------------------------------------------------------

def messages_to_wire(msgs: list[Message]) -> tuple[list[dict[str, Any]], bytes]:
    tail = bytearray()
    dicts: list[dict[str, Any]] = []
    for m in msgs:
        # to_wire_dict, not dataclasses.asdict: asdict deep-copies
        # recursively (~22 µs/message) and this sits on every dispatch
        # and result push — at invocation-plane QPS that was a top-three
        # per-message cost (ISSUE 8)
        dicts.append(m.to_wire_dict())
        tail += m.input_data
        tail += m.output_data
    return dicts, bytes(tail)


def messages_from_wire(dicts: list[dict[str, Any]], tail: bytes) -> list[Message]:
    field_names = {f.name for f in dataclasses.fields(Message)}
    msgs: list[Message] = []
    off = 0
    for d in dicts:
        d = dict(d)
        in_len = int(d.get("input_data", 0))
        out_len = int(d.get("output_data", 0))
        if in_len < 0 or out_len < 0 or off + in_len + out_len > len(tail):
            raise ValueError(
                f"Wire message payload lengths ({in_len}, {out_len}) do not "
                f"fit the binary tail (offset {off}, tail {len(tail)})"
            )
        d["input_data"] = tail[off:off + in_len]
        off += in_len
        d["output_data"] = tail[off:off + out_len]
        off += out_len
        msgs.append(Message(**{k: v for k, v in d.items() if k in field_names}))
    if off != len(tail):
        raise ValueError(f"Binary tail has {len(tail) - off} trailing bytes")
    return msgs


def ber_to_wire(req: BatchExecuteRequest) -> tuple[dict[str, Any], bytes]:
    # Build the header directly — req.to_dict() would hex-encode every
    # payload only for it to be discarded, which is exactly what the binary
    # tail exists to avoid.
    msg_dicts, tail = messages_to_wire(req.messages)
    header = {
        "app_id": req.app_id,
        "group_id": req.group_id,
        "user": req.user,
        "function": req.function,
        "type": req.type,
        "subtype": req.subtype,
        "messages": msg_dicts,
        "single_host_hint": req.single_host_hint,
        "single_host": req.single_host,
        "elastic_scale_hint": req.elastic_scale_hint,
        "snapshot_key": req.snapshot_key,
        "evicted_host": req.evicted_host,
    }
    return header, tail


def bers_to_wire(reqs: list[BatchExecuteRequest]
                 ) -> tuple[dict[str, Any], bytes]:
    """Pipelined wire form (ISSUE 8): many independent batches in one
    frame — per-request headers under ``bers`` with per-request tail
    lengths under ``tails``, binary tails concatenated in order. Shared
    by EXECUTE_BATCHES dispatch and bulk SUBMIT_BATCH so the offset
    arithmetic exists exactly once per direction."""
    headers: list[dict[str, Any]] = []
    tails: list[bytes] = []
    for req in reqs:
        header, tail = ber_to_wire(req)
        headers.append(header)
        tails.append(tail)
    return ({"bers": headers, "tails": [len(t) for t in tails]},
            b"".join(tails))


def bers_from_wire(header: dict[str, Any],
                   payload: bytes) -> list[BatchExecuteRequest]:
    """Inverse of ``bers_to_wire``."""
    bers = header.get("bers", [])
    lengths = [int(n) for n in header.get("tails", [])]
    if len(bers) != len(lengths):
        raise ValueError(
            f"Wire batch list has {len(bers)} headers but "
            f"{len(lengths)} tail lengths")
    if sum(lengths) != len(payload):
        raise ValueError(
            f"Wire batch tails declare {sum(lengths)} bytes but the "
            f"payload carries {len(payload)}")
    out: list[BatchExecuteRequest] = []
    off = 0
    for h, n in zip(bers, lengths):
        out.append(ber_from_wire(h, payload[off:off + n]))
        off += n
    return out


def ber_from_wire(header: dict[str, Any], tail: bytes) -> BatchExecuteRequest:
    d = dict(header)
    msg_dicts = d.pop("messages", [])
    req = BatchExecuteRequest.from_dict({**d, "messages": []})
    req.messages = messages_from_wire(msg_dicts, tail)
    return req
