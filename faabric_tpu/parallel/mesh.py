"""Device-mesh substrate: axis layout, shardings, gang scheduling glue.

This is where the framework's scheduling layer meets XLA's compilation
model. A gang-scheduled group (planner decision → device ids) becomes a
``jax.sharding.Mesh`` whose axes carry the parallelism strategy:

    dp — data parallel (batch)           → gradients allreduce over ICI
    tp — tensor parallel (heads/hidden)  → activation collectives
    sp — sequence parallel (long ctx)    → ring attention / all-to-all
    pp — pipeline parallel (stages)      → ppermute between stages
    ep — expert parallel (MoE)           → all_to_all token routing

The reference has no mesh concept — its analog is the MPI world's rank↔
host mapping (src/mpi/MpiWorld.cpp:318-366). Here the mesh IS the
interconnect topology and XLA inserts the collectives.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MESH_AXES = ("dp", "tp", "sp", "pp", "ep")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Axis sizes; -1 on dp means 'absorb remaining devices'."""

    dp: int = -1
    tp: int = 1
    sp: int = 1
    pp: int = 1
    ep: int = 1

    def resolve(self, n_devices: int) -> dict[str, int]:
        fixed = self.tp * self.sp * self.pp * self.ep
        if n_devices % fixed != 0:
            raise ValueError(
                f"{n_devices} devices not divisible by tp*sp*pp*ep={fixed}")
        dp = self.dp if self.dp > 0 else n_devices // fixed
        if dp * fixed != n_devices:
            raise ValueError(
                f"dp*tp*sp*pp*ep={dp * fixed} != n_devices={n_devices}")
        return {"dp": dp, "tp": self.tp, "sp": self.sp, "pp": self.pp,
                "ep": self.ep}


def build_mesh(devices: Optional[Sequence] = None,
               config: MeshConfig | None = None) -> Mesh:
    """Lay a (dp, tp, sp, pp, ep) mesh over the devices. Axis order puts tp
    innermost-adjacent so tensor-parallel collectives ride the shortest ICI
    hops (the scaling-book recipe: fastest-varying axis ↔ nearest
    neighbours)."""
    devices = list(devices if devices is not None else jax.devices())
    config = config or MeshConfig()
    sizes = config.resolve(len(devices))
    grid = np.array(devices).reshape(
        sizes["dp"], sizes["sp"], sizes["pp"], sizes["ep"], sizes["tp"])
    # Present axes in canonical (dp, tp, sp, pp, ep) name order
    grid = np.moveaxis(grid, 4, 1)
    return Mesh(grid, ("dp", "tp", "sp", "pp", "ep"))


def named(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def constraint(x, mesh: Mesh, *spec):
    """Activation sharding hint inside jit (XLA propagates the rest)."""
    return jax.lax.with_sharding_constraint(x, named(mesh, *spec))


def mesh_from_group(broker, group_id: int, ranks: Sequence[int],
                    config: MeshConfig | None = None) -> Mesh:
    """Build a mesh from a gang-scheduled group's chip placement: rank i's
    planner-assigned device id (carried in the PTP mappings) becomes mesh
    position i."""
    from faabric_tpu.parallel.collectives import local_devices_for_ids

    broker.wait_for_mappings(group_id)
    device_ids = [broker.get_device_for_idx(group_id, r) for r in ranks]
    devices = local_devices_for_ids(device_ids)
    return build_mesh(devices, config)
