"""Ring attention: causal attention over a sequence-sharded mesh axis.

Long-context first-class: for sequences too large for one chip's HBM, Q/K/V
shard along the sequence over the ``sp`` mesh axis. Each device keeps its Q
shard resident and the K/V shards rotate around the ring with
``jax.lax.ppermute`` — ICI neighbour hops. Each rotation step computes its
(Q-block, KV-block) attention through the **Pallas flash kernel**
(ops/flash_attention.py) and folds the partial result in with the
flash-decoding (out, lse) merge: the diagonal block runs the causal
kernel, fully-visible past blocks the non-causal kernel, and fully-masked
future blocks skip both matmuls entirely via ``lax.switch`` (the previous
jnp path materialized an (S_l, S_l) fp32 score block per step and spent
half the ring's FLOPs computing scores it then masked). Communication
overlaps compute in XLA's pipeline; the full (S, S) score matrix never
exists anywhere, and per-step peak memory is the kernel's O(S_l·D).

This is the sequence-parallel analog of the reference's "scale memory
beyond one host" capability (SURVEY §5.7); same recurrence as the Pallas
flash kernel, one level up the hierarchy.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Single source for the shard_map version shim (check_vma vs check_rep)
from faabric_tpu.parallel.collectives import (
    _SHARD_MAP_NO_CHECK_KW as _NO_CHECK_KW,
    shard_map,
)

NEG_INF = -1e30


def _mark_varying(x, axes: tuple[str, ...]):
    """Tag a locally-built array as device-varying over the given mesh
    axes (loop-carry / cond-branch types must match shard-derived
    values). Only the axes the value isn't already varying over are
    added — pcast rejects re-marking. API moved pvary →
    pcast(to='varying') across JAX versions."""
    have = getattr(getattr(x, "aval", None), "vma", frozenset())
    missing = tuple(a for a in axes if a not in have)
    if not missing:
        return x
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, missing, to="varying")
    if hasattr(jax.lax, "pvary"):  # pragma: no cover — older JAX
        return jax.lax.pvary(x, missing)
    return x  # pragma: no cover — oldest JAX has no varying check


def ring_attention(q, k, v, mesh: Mesh, axis: str = "sp",
                   causal: bool = True, batch_axis: str | None = None,
                   head_axis: str | None = None):
    """q/k/v (B, S, H, D) with S sharded over ``axis``; B and H may
    additionally shard over ``batch_axis``/``head_axis`` (attention is
    independent across batch and heads, so those axes never communicate).
    Returns the same sharding.

    Within each rotation step, device i holds Q block i and K/V block
    ((i - step) mod n); causal masking uses the blocks' global positions,
    so fully-masked future blocks contribute nothing.
    """
    n = mesh.shape[axis]
    if n == 1:
        from faabric_tpu.ops.flash_attention import _reference_attention

        return _reference_attention(q, k, v, causal)
    return _compiled_ring(mesh, axis, causal, batch_axis, head_axis)(q, k, v)


@functools.lru_cache(maxsize=64)
def _compiled_ring(mesh: Mesh, axis: str, causal: bool,
                   batch_axis: str | None = None,
                   head_axis: str | None = None):
    """One jitted shard_map per signature — eager callers must hit the jit
    cache, not retrace per invocation."""
    n = mesh.shape[axis]
    perm = [(j, (j + 1) % n) for j in range(n)]

    def local_fn(q_blk, k_blk, v_blk):
        from faabric_tpu.ops.flash_attention import (
            flash_attention_with_lse,
            merge_attention_blocks,
        )

        # shapes (B, S_l, H, D)
        b, s_l, h, d = q_blk.shape
        my_idx = jax.lax.axis_index(axis)

        # (the shard_map runs with the varying check off, so fresh
        # constants need no pcast marking here)
        acc0 = jnp.zeros((b, s_l, h, d), jnp.float32)
        lse0 = jnp.full((b * h, s_l), NEG_INF, jnp.float32)

        # Per-block attention: each branch returns (out (B,S_l,H,D) in
        # the input dtype, lse (B·H, S_l) fp32)
        def diag_block(q, k, v):
            return flash_attention_with_lse(q, k, v, True)

        def full_block(q, k, v):
            return flash_attention_with_lse(q, k, v, False)

        def skip_block(q, k, v):
            # Fully-masked future block: neutral element of the merge
            return (jnp.zeros_like(q),
                    jnp.full((b * h, s_l), NEG_INF, jnp.float32))

        def fold(i, acc, lse_acc, k_cur, v_cur):
            kv_idx = (my_idx - i) % n
            if causal:
                # 0: diagonal (causal kernel), 1: past (full kernel),
                # 2: future (skip — no matmuls at all)
                rel = jnp.where(kv_idx == my_idx, 0,
                                jnp.where(kv_idx < my_idx, 1, 2))
                out_blk, lse_blk = jax.lax.switch(
                    rel, [diag_block, full_block, skip_block],
                    q_blk, k_cur, v_cur)
            else:
                out_blk, lse_blk = full_block(q_blk, k_cur, v_cur)
            # Flash-decoding combine (acc stays fp32: it's outs[0], and
            # merge_attention_blocks casts to the first operand's dtype)
            return merge_attention_blocks([acc, out_blk],
                                          [lse_acc, lse_blk])

        def step(i, carry):
            acc, lse_acc, k_cur, v_cur = carry
            acc, lse_acc = fold(i, acc, lse_acc, k_cur, v_cur)
            # Rotate K/V to the next ring neighbour (ICI hop)
            k_nxt = jax.lax.ppermute(k_cur, axis, perm)
            v_nxt = jax.lax.ppermute(v_cur, axis, perm)
            return acc, lse_acc, k_nxt, v_nxt

        # Steps 0..n-2 fold-then-rotate; the final block folds outside the
        # loop so no rotation result is ever discarded (2 ICI hops saved)
        acc, lse_acc, k_last, v_last = jax.lax.fori_loop(
            0, n - 1, step, (acc0, lse0, k_blk, v_blk))
        acc, _ = fold(n - 1, acc, lse_acc, k_last, v_last)
        # acc is the normalized union already (merge of normalized
        # partials); causal rows always see their diagonal, so no
        # fully-masked rows exist
        return acc.astype(q_blk.dtype)

    spec = P(batch_axis, axis, head_axis, None)
    # Varying-check off: pallas_call's out_shape carries no varying-mesh-
    # axes annotation (same trade as the model's flash path,
    # models/transformer.py)
    return jax.jit(shard_map(local_fn, mesh=mesh,
                             in_specs=(spec, spec, spec),
                             out_specs=spec,
                             **{_NO_CHECK_KW: False}))


def shard_sequence(x, mesh: Mesh, axis: str = "sp"):
    """Place (B, S, ...) with S sharded over the axis."""
    spec = [None] * x.ndim
    spec[1] = axis
    return jax.device_put(x, NamedSharding(mesh, P(*spec)))
