"""Ring attention: causal attention over a sequence-sharded mesh axis.

Long-context first-class: for sequences too large for one chip's HBM, Q/K/V
shard along the sequence over the ``sp`` mesh axis. Each device keeps its Q
shard resident and the K/V shards rotate around the ring with
``jax.lax.ppermute`` — ICI neighbour hops — while an online-softmax
accumulator (running max / sum / weighted values, all fp32) folds each
block in. Communication overlaps compute in XLA's pipeline; the full
(S, S) score matrix never exists anywhere.

This is the sequence-parallel analog of the reference's "scale memory
beyond one host" capability (SURVEY §5.7); same recurrence as the Pallas
flash kernel (ops/flash_attention.py), one level up the hierarchy.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

NEG_INF = -1e30


def _mark_varying(x, axes: tuple[str, ...]):
    """Tag a locally-built array as device-varying over the given mesh
    axes (loop-carry / cond-branch types must match shard-derived
    values). Only the axes the value isn't already varying over are
    added — pcast rejects re-marking. API moved pvary →
    pcast(to='varying') across JAX versions."""
    have = getattr(getattr(x, "aval", None), "vma", frozenset())
    missing = tuple(a for a in axes if a not in have)
    if not missing:
        return x
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, missing, to="varying")
    if hasattr(jax.lax, "pvary"):  # pragma: no cover — older JAX
        return jax.lax.pvary(x, missing)
    return x  # pragma: no cover — oldest JAX has no varying check


def ring_attention(q, k, v, mesh: Mesh, axis: str = "sp",
                   causal: bool = True, batch_axis: str | None = None,
                   head_axis: str | None = None):
    """q/k/v (B, S, H, D) with S sharded over ``axis``; B and H may
    additionally shard over ``batch_axis``/``head_axis`` (attention is
    independent across batch and heads, so those axes never communicate).
    Returns the same sharding.

    Within each rotation step, device i holds Q block i and K/V block
    ((i - step) mod n); causal masking uses the blocks' global positions,
    so fully-masked future blocks contribute nothing.
    """
    n = mesh.shape[axis]
    if n == 1:
        from faabric_tpu.ops.flash_attention import _reference_attention

        return _reference_attention(q, k, v, causal)
    return _compiled_ring(mesh, axis, causal, batch_axis, head_axis)(q, k, v)


@functools.lru_cache(maxsize=64)
def _compiled_ring(mesh: Mesh, axis: str, causal: bool,
                   batch_axis: str | None = None,
                   head_axis: str | None = None):
    """One jitted shard_map per signature — eager callers must hit the jit
    cache, not retrace per invocation."""
    n = mesh.shape[axis]
    perm = [(j, (j + 1) % n) for j in range(n)]

    def local_fn(q_blk, k_blk, v_blk):
        # shapes (B, S_l, H, D)
        s_l = q_blk.shape[1]
        my_idx = jax.lax.axis_index(axis)
        scale = 1.0 / np.sqrt(q_blk.shape[-1])
        qf = q_blk.astype(jnp.float32) * scale

        b, _, h, d = q_blk.shape
        m0 = jnp.full((b, h, s_l), NEG_INF, dtype=jnp.float32)
        l0 = jnp.zeros((b, h, s_l), dtype=jnp.float32)
        acc0 = jnp.zeros((b, s_l, h, d), dtype=jnp.float32)
        varying_axes = tuple(a for a in (axis, batch_axis, head_axis)
                             if a is not None)
        m0, l0, acc0 = (_mark_varying(x, varying_axes)
                        for x in (m0, l0, acc0))

        def fold(i, m_prev, l_prev, acc, k_cur, v_cur):
            kv_idx = (my_idx - i) % n

            scores = jnp.einsum("bqhd,bkhd->bhqk", qf,
                                k_cur.astype(jnp.float32))
            if causal:
                q_pos = my_idx * s_l + jax.lax.broadcasted_iota(
                    jnp.int32, (s_l, s_l), 0)
                k_pos = kv_idx * s_l + jax.lax.broadcasted_iota(
                    jnp.int32, (s_l, s_l), 1)
                mask = q_pos >= k_pos
                scores = jnp.where(mask[None, None], scores, NEG_INF)

            m_cur = jnp.max(scores, axis=-1)
            m_new = jnp.maximum(m_prev, m_cur)
            correction = jnp.exp(m_prev - m_new)
            p = jnp.exp(scores - m_new[..., None])
            l_new = l_prev * correction + jnp.sum(p, axis=-1)
            acc_new = acc * correction.transpose(0, 2, 1)[..., None] \
                + jnp.einsum("bhqk,bkhd->bqhd", p,
                             v_cur.astype(jnp.float32))
            return m_new, l_new, acc_new

        def step(i, carry):
            m_prev, l_prev, acc, k_cur, v_cur = carry
            m_new, l_new, acc_new = fold(i, m_prev, l_prev, acc, k_cur, v_cur)
            # Rotate K/V to the next ring neighbour (ICI hop)
            k_nxt = jax.lax.ppermute(k_cur, axis, perm)
            v_nxt = jax.lax.ppermute(v_cur, axis, perm)
            return m_new, l_new, acc_new, k_nxt, v_nxt

        # Steps 0..n-2 fold-then-rotate; the final block folds outside the
        # loop so no rotation result is ever discarded (2 ICI hops saved)
        m, l, acc, k_last, v_last = jax.lax.fori_loop(
            0, n - 1, step, (m0, l0, acc0, k_blk, v_blk))
        m, l, acc = fold(n - 1, m, l, acc, k_last, v_last)
        # Guard fully-masked rows (l == 0 cannot happen causally for row 0
        # of block 0 since the diagonal is unmasked, but stay safe)
        l = jnp.maximum(l, 1e-30)
        out = acc / l.transpose(0, 2, 1)[..., None]
        return out.astype(q_blk.dtype)

    spec = P(batch_axis, axis, head_axis, None)
    return jax.jit(shard_map(local_fn, mesh=mesh,
                             in_specs=(spec, spec, spec),
                             out_specs=spec))


def shard_sequence(x, mesh: Mesh, axis: str = "sp"):
    """Place (B, S, ...) with S sharded over the axis."""
    spec = [None] * x.ndim
    spec[1] = axis
    return jax.device_put(x, NamedSharding(mesh, P(*spec)))
