"""Multi-process device plane: ONE jax mesh spanning worker processes.

The reference spans hosts with a per-rank-pair TCP mesh inside MpiWorld
(src/mpi/MpiWorld.cpp:1789-1934) over its docker-compose worker topology
(docker-compose.yml:42-62). The TPU-native equivalent is JAX's
multi-controller SPMD model: every worker process joins one
``jax.distributed`` coordination service, contributes its local chips,
and ``jax.devices()`` becomes the GLOBAL device set — collectives
compiled over a mesh of those devices ride ICI within a slice and DCN
across slices, scheduled by XLA rather than hand-built socket pairs.

Formation is planner-coordinated (``Planner.join_device_plane``): each
worker asks the planner to join at boot, the planner assigns process ids
in join order and elects the FIRST joiner's host to run the coordination
service on a planner-claimed port (the same pool that backs MPI
base-port claims). This mirrors how the planner already forms MPI gangs
— the device plane is one more gang, sized by configuration rather than
per-batch because ``jax.distributed.initialize`` is once-per-process:
a pod slice is claimed for the worker's lifetime, exactly like a real
TPU pod.

Single-machine testing: N worker processes × M virtual CPU devices
(``XLA_FLAGS=--xla_force_host_platform_device_count=M``) form an N·M
device global mesh over the Gloo CPU backend — the driver-style dryrun
for multi-host without multi-host hardware.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Optional, Sequence

from faabric_tpu.util.logging import get_logger

logger = get_logger(__name__)

# Timeout for the whole plane to assemble (all processes must reach
# jax.distributed.initialize together; stragglers block everyone)
DEFAULT_INIT_TIMEOUT_S = 120.0

_state_lock = threading.Lock()
_joined_spec: Optional["DevicePlaneSpec"] = None


@dataclasses.dataclass(frozen=True)
class DevicePlaneSpec:
    """Everything a worker needs to join the plane. ``coordinator_host``
    is a LOGICAL host name — the dialable ip:port comes from the alias
    table (transport/common.py), so single-machine clusters on aliased
    loopback ports and real multi-host clusters use the same spec."""

    coordinator_host: str
    coordinator_port: int
    num_processes: int
    process_id: int

    def coordinator_address(self) -> str:
        from faabric_tpu.transport.common import resolve_host

        ip, port = resolve_host(self.coordinator_host,
                                self.coordinator_port)
        return f"{ip}:{port}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "DevicePlaneSpec":
        return cls(coordinator_host=d["coordinator_host"],
                   coordinator_port=int(d["coordinator_port"]),
                   num_processes=int(d["num_processes"]),
                   process_id=int(d["process_id"]))


def request_device_plane(planner_client, n_processes: int,
                         timeout: float = 60.0,
                         poll_interval: float = 0.2) -> DevicePlaneSpec:
    """Ask the planner to join the device plane, polling until the
    roster is full (every expected worker has asked). The planner
    assigns process ids in join order — deterministic and stable because
    each host's slot is remembered across polls."""
    deadline = time.monotonic() + timeout
    while True:
        spec = planner_client.join_device_plane(n_processes)
        if spec is not None:
            return spec
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"device plane of {n_processes} never assembled "
                f"within {timeout}s (workers missing?)")
        time.sleep(poll_interval)


def join_device_plane(spec: DevicePlaneSpec,
                      local_device_ids: Optional[Sequence[int]] = None,
                      init_timeout_s: float = DEFAULT_INIT_TIMEOUT_S,
                      ) -> None:
    """Join the coordination service and initialise the global backend.

    Must run before anything initialises a JAX backend in this process
    (``jax.distributed.initialize`` is once-per-process). After it,
    ``jax.devices()`` is the plane-wide device list and
    ``jax.local_devices()`` this process's contribution.
    """
    global _joined_spec
    import jax

    with _state_lock:
        if _joined_spec is not None:
            if _joined_spec == spec:
                return  # idempotent re-join with the same spec
            raise RuntimeError(
                f"process already joined plane {_joined_spec}; "
                f"cannot join {spec}")
        addr = spec.coordinator_address()
        logger.info("Joining device plane: %s as process %d/%d",
                    addr, spec.process_id, spec.num_processes)
        # Cross-process collectives on the CPU backend need the gloo
        # implementation opted in BEFORE the backend initialises; newer
        # JAX defaults to it, 0.4.x raises "Multiprocess computations
        # aren't implemented on the CPU backend" without it (the seed
        # device-plane dist failure). Real TPU/GPU backends ignore it.
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:  # noqa: BLE001 — unknown config on some versions
            logger.debug("jax_cpu_collectives_implementation not settable",
                         exc_info=True)
        kwargs = {}
        if local_device_ids is not None:
            kwargs["local_device_ids"] = list(local_device_ids)
        try:
            jax.distributed.initialize(
                coordinator_address=addr,
                num_processes=spec.num_processes,
                process_id=spec.process_id,
                initialization_timeout=int(init_timeout_s),
                **kwargs)
        except TypeError:  # older jax without initialization_timeout
            jax.distributed.initialize(
                coordinator_address=addr,
                num_processes=spec.num_processes,
                process_id=spec.process_id, **kwargs)
        _joined_spec = spec


def leave_device_plane() -> None:
    """Tear down this process's membership (idempotent)."""
    global _joined_spec
    import jax

    with _state_lock:
        if _joined_spec is None:
            return
        try:
            jax.distributed.shutdown()
        except Exception:  # noqa: BLE001 — peers may already be gone
            logger.debug("jax.distributed.shutdown raised", exc_info=True)
        _joined_spec = None


def current_plane() -> Optional[DevicePlaneSpec]:
    with _state_lock:
        return _joined_spec


def plane_summary() -> dict:
    """Observability: what this process sees of the plane."""
    import jax

    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "global_devices": len(jax.devices()),
        "local_devices": len(jax.local_devices()),
        "platform": jax.default_backend(),
    }


def force_cpu_virtual_devices(n: int) -> None:
    """Single-machine plane testing: give this process EXACTLY ``n``
    virtual CPU devices, replacing any inherited device-count flag (a
    test harness parent exports its own). Must run before any JAX
    backend initialises; composes with the sitecustomize override the
    same way util/device_env.py does."""
    flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append(f"--xla_force_host_platform_device_count={n}")
    os.environ["XLA_FLAGS"] = " ".join(flags)
    import jax

    jax.config.update("jax_platforms", "cpu")
