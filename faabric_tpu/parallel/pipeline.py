"""Pipeline parallelism over the ``pp`` mesh axis.

TPU-first design — no per-stage processes, no host-driven schedule. The
whole pipeline is ONE compiled SPMD program:

- Block params stack into leading-``n_layers`` arrays sharded over ``pp``
  (each stage holds a contiguous slab of ``n_layers / pp`` layers and
  runs them with ``lax.scan``).
- The GPipe microbatch schedule is a differentiable ``lax.scan`` over
  ``M + S − 1`` ticks under ``shard_map``: at tick ``t`` stage ``s``
  processes microbatch ``t − s``; activations hop stage→stage+1 with a
  single ``lax.ppermute`` (one ICI neighbour transfer per tick).
- Reverse-mode AD through the scan + ppermute gives the backward
  pipeline for free — XLA schedules it as the mirrored permute chain,
  so ``jax.grad`` of the pipelined loss is itself pipelined.
- Within a stage, tensor parallelism is Megatron-style: heads/hidden
  shard over ``tp`` with an explicit ``psum`` after the attention output
  and MLP down projections (a size-1 ``tp`` axis makes them no-ops).
- Embedding / final norm / LM head are replicated over ``pp`` (they are
  small next to the blocks). The schedule is deliberately branch-free —
  collectives near device-varying ``lax.cond`` deadlock — so every stage
  embeds each tick (a cheap gather) and selects against the hopped-in
  activation; stage outputs stream out as scan ys (the last stage's
  microbatch m is the static slice at tick m + S − 1) and the
  LM-head/loss runs once after the loop, scanned one microbatch at a
  time, masked to the last stage by the final psum.

The reference has no pipeline concept — its "scale the big thing" analog
is gang-scheduled MPI worlds (SURVEY §5.7); this is the mesh-axis
incarnation the TPU build must carry.

Schedule math: ``n_ticks(S, M) = M + S − 1``; bubble fraction
``(S − 1) / (M + S − 1)`` — exposed for tests.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from faabric_tpu.parallel.collectives import (
    SHARD_MAP_HAS_VMA,
    shard_map_compat,
)

# Replication checking for the pipeline shard_maps. On current JAX the
# vma machinery (pcast in _mark_varying) lets the default check pass and
# catch real mistakes, so keep it on (None = library default, True). On
# 0.4.x the older check_rep inference cannot see through the schedule
# bodies (scan-carried accumulators, in-body vjp) and rejects the
# statically-correct P() loss out-spec — run those shard_maps unchecked
# there; the schedule tests pin the numerics against dense/autodiff
# references, which is the stronger check anyway.
_PP_CHECK = None if SHARD_MAP_HAS_VMA else False

from faabric_tpu.models.transformer import (
    ModelConfig,
    _rms_norm,
    _rope,
)
from faabric_tpu.parallel.ring_attention import _mark_varying


# ---------------------------------------------------------------------------
# Schedule math (unit-testable without devices)
# ---------------------------------------------------------------------------

def n_ticks(n_stages: int, n_microbatches: int) -> int:
    """GPipe ticks to drain the pipeline."""
    return n_microbatches + n_stages - 1


def bubble_fraction(n_stages: int, n_microbatches: int) -> float:
    """Fraction of stage-ticks idle in the fill/drain bubble."""
    total = n_stages * n_ticks(n_stages, n_microbatches)
    useful = n_stages * n_microbatches
    return (total - useful) / total


def schedule(n_stages: int, n_microbatches: int) -> list[list[int | None]]:
    """``schedule(S, M)[t][s]`` = microbatch stage ``s`` works on at tick
    ``t`` (None = bubble). Mirrors the on-device arithmetic exactly."""
    out = []
    for t in range(n_ticks(n_stages, n_microbatches)):
        row = []
        for s in range(n_stages):
            m = t - s
            row.append(m if 0 <= m < n_microbatches else None)
        out.append(row)
    return out


# ---------------------------------------------------------------------------
# Param layout: blocks stacked over a leading layer axis, sharded over pp
# ---------------------------------------------------------------------------

def stack_block_params(params: dict) -> dict:
    """Transformer param tree (blocks as a list of dicts) → pipeline tree
    with each block leaf stacked on a leading (n_layers,) axis."""
    blocks = params["blocks"]
    stacked = {k: jnp.stack([blk[k] for blk in blocks])
               for k in blocks[0]}
    return {"embed": params["embed"], "stacked": stacked,
            "ln_f": params["ln_f"], "lm_head": params["lm_head"]}


def unstack_block_params(pp_params: dict) -> dict:
    """Inverse of :func:`stack_block_params` (checkpoint interop)."""
    stacked = pp_params["stacked"]
    n_layers = next(iter(stacked.values())).shape[0]
    blocks = [{k: stacked[k][i] for k in stacked} for i in range(n_layers)]
    return {"embed": pp_params["embed"], "blocks": blocks,
            "ln_f": pp_params["ln_f"], "lm_head": pp_params["lm_head"]}


def pp_param_shardings(mesh: Mesh, cfg: ModelConfig) -> dict:
    """Layer axis over ``pp``; heads/hidden over ``tp``; embed/ln_f/
    lm_head replicated (small next to the blocks). MoE configs add the
    expert axis: router replicated, expert slabs over ``ep`` with each
    expert's hidden over ``tp``."""
    def ns(*spec):
        return NamedSharding(mesh, P(*spec))

    if getattr(cfg, "n_experts", 0) > 0:
        stacked = {
            "ln1": ns("pp", None),
            "wqkv": ns("pp", None, None, "tp", None),
            "wo": ns("pp", "tp", None, None),
            "ln2": ns("pp", None),
            "router": ns("pp", None, None),
            "w1": ns("pp", "ep", None, "tp"),
            "w2": ns("pp", "ep", "tp", None),
        }
    else:
        stacked = {
            "ln1": ns("pp", None),
            "wqkv": ns("pp", None, None, "tp", None),
            "wo": ns("pp", "tp", None, None),
            "ln2": ns("pp", None),
            "w1": ns("pp", None, "tp"),
            "w2": ns("pp", "tp", None),
        }
    return {
        "embed": ns(),
        "stacked": stacked,
        "ln_f": ns(),
        "lm_head": ns(),
    }


def pp_data_sharding(mesh: Mesh) -> NamedSharding:
    """(M, B, S) microbatched tokens: batch over dp, sequence over sp
    (identical to the pre-sp layout when sp=1), microbatch axis
    replicated (every stage sees every microbatch's tokens; only stage 0
    embeds them)."""
    return NamedSharding(mesh, P(None, "dp", "sp"))


# ---------------------------------------------------------------------------
# In-stage compute (Megatron tp inside a pipeline stage)
# ---------------------------------------------------------------------------

def _head_nll(y, ln_f, lm_head, targets_m, cfg: ModelConfig):
    """LM-head NLL for one microbatch — the single definition both
    schedules (GPipe's loss_one, 1F1B's head) differentiate. The local
    token mean is pmean'd over sp (equal shard sizes; no-op at sp=1) so
    a sequence-sharded pipeline reports the global mean."""
    h = _rms_norm(y, ln_f)
    logits = (h @ lm_head.astype(cfg.compute_dtype)).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets_m[..., None], axis=-1)[..., 0]
    return jax.lax.pmean(jnp.mean(nll), "sp")


def _global_positions(b_local: int, seq: int):
    """GLOBAL row ids for this device's sequence shard — the single
    definition of 'global row = axis_index(sp) · seq_local + local'
    shared by both schedule bodies (and consistent with the row0 offset
    in _pp_attention_sublayer). A no-op offset at sp=1."""
    return jnp.broadcast_to(
        jax.lax.axis_index("sp") * seq + jnp.arange(seq)[None],
        (b_local, seq))


def _validate_pp_mesh(cfg: ModelConfig, mesh: Mesh) -> int:
    n_stages = mesh.shape["pp"]
    if cfg.n_layers % n_stages:
        raise ValueError(
            f"n_layers={cfg.n_layers} not divisible by pp={n_stages}")
    if mesh.shape.get("sp", 1) > 1 and getattr(cfg, "n_experts", 0):
        raise ValueError(
            "MoE pipeline stages don't compose with sp (per-shard "
            "capacity would diverge from the global routing)")
    ep = mesh.shape.get("ep", 1)
    if ep > 1:
        n_experts = getattr(cfg, "n_experts", 0)
        if not n_experts:
            raise ValueError("ep>1 needs a MoE config (n_experts)")
        if n_experts % ep:
            raise ValueError(
                f"n_experts={n_experts} not divisible by ep={ep}")
    return n_stages


def _pp_specs(cfg: ModelConfig, mesh: Mesh):
    param_specs = jax.tree.map(lambda s: s.spec,
                               pp_param_shardings(mesh, cfg))
    return param_specs, P(None, "dp", "sp")


def _pp_attention_offset(q, k, v, row_offset):
    """Causal attention where q covers the GLOBAL rows [row_offset,
    row_offset + Sq) of a sequence whose K/V span all Skv rows. Reduces
    to models/transformer._attention exactly at row_offset=0, Skv==Sq
    (same op order and fp32 softmax accumulators)."""
    scale = 1.0 / (q.shape[-1] ** 0.5)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    sq, skv = q.shape[1], k.shape[1]
    rows = row_offset + jnp.arange(sq)[:, None]
    mask = jnp.arange(skv)[None, :] <= rows
    logits = jnp.where(mask[None, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _pp_attention_sublayer(x, blk, positions, cfg: ModelConfig):
    """Megatron attention on tp-local shards (qkv column-parallel, wo
    row-parallel + psum) — shared by the dense and MoE pp blocks.

    Sequence parallelism composes here: activations/Q stay sharded over
    ``sp`` and K/V are (transiently) all-gathered for the causal
    offset-masked attention — the DeepSpeed-Ulysses-flavoured gather
    variant, chosen over the ring inside the pipeline because the tick
    scan already owns the ppermute schedule. Both collectives are
    no-ops at sp=1, so this is ONE code path, not a branch. (The
    dedicated non-pp sp path keeps full ring attention with flash
    kernels — parallel/ring_attention.py.)"""
    h = _rms_norm(x, blk["ln1"])
    qkv = jnp.einsum("bsd,dthe->tbshe", h,
                     blk["wqkv"].astype(cfg.compute_dtype))
    q, k, v = qkv[0], qkv[1], qkv[2]
    q = _rope(q, positions, cfg.rope_theta)
    k = _rope(k, positions, cfg.rope_theta)
    k_full = jax.lax.all_gather(k, "sp", axis=1, tiled=True)
    v_full = jax.lax.all_gather(v, "sp", axis=1, tiled=True)
    row0 = jax.lax.axis_index("sp") * q.shape[1]
    attn = _pp_attention_offset(q, k_full, v_full, row0)
    attn_out = jnp.einsum("bshe,hed->bsd", attn,
                          blk["wo"].astype(cfg.compute_dtype))
    return x + jax.lax.psum(attn_out, "tp")


def _pp_block(x, blk, positions, cfg: ModelConfig):
    """One transformer block on tp-local shards: qkv/w1 column-parallel,
    wo/w2 row-parallel with a psum over ``tp`` after each."""
    x = _pp_attention_sublayer(x, blk, positions, cfg)
    h = _rms_norm(x, blk["ln2"])
    ff = jax.nn.gelu(h @ blk["w1"].astype(cfg.compute_dtype))
    ff_out = ff @ blk["w2"].astype(cfg.compute_dtype)
    return x + jax.lax.psum(ff_out, "tp")


# ---------------------------------------------------------------------------
# The pipelined loss
# ---------------------------------------------------------------------------

def _is_moe(cfg: ModelConfig) -> bool:
    return getattr(cfg, "n_experts", 0) > 0


def _pp_moe_ffn(h, blk, cfg):
    """Switch-MoE feed-forward on (tp, ep)-local shards: the routing +
    capacity math is replicated (every member computes the same
    dispatch/combine from the same activations, exactly the global
    formulation in models/moe.py:_moe_layer), the expert FFN runs only
    this member's experts (ep-local slab, tp-sharded hidden), and two
    psums reassemble: tp for the row-parallel expert matmul, ep to sum
    each member's contribution for its own experts' tokens. The switch
    aux load-balancing loss is NOT computed on the pipeline path (the
    head-anchored schedules carry one scalar loss; capacity dispatch
    still bounds imbalance) — train with aux via the single-mesh MoE
    step, or accept aux_loss_weight=0 semantics under pp."""
    from faabric_tpu.models.moe import moe_dispatch_combine

    e = cfg.n_experts
    h32 = h.astype(jnp.float32)
    # Routing + capacity allocation: the SHARED pure-jnp definition from
    # models/moe.py — one implementation is what keeps this path
    # loss-parity-exact with the single-mesh layer (aux is discarded
    # here; see docstring)
    dispatch, combine_w, _aux = moe_dispatch_combine(h, blk["router"], cfg)

    # This member's expert slab
    ep_size = jax.lax.psum(1, "ep")
    e_loc = e // ep_size
    lo = jax.lax.axis_index("ep") * e_loc
    disp_loc = jax.lax.dynamic_slice_in_dim(dispatch, lo, e_loc, axis=2)
    comb_loc = jax.lax.dynamic_slice_in_dim(combine_w, lo, e_loc, axis=2)

    expert_in = jnp.einsum("bsec,bsd->ebcd", disp_loc, h32)
    w1 = blk["w1"].astype(jnp.float32)                     # (E_loc, D, F_tp)
    w2 = blk["w2"].astype(jnp.float32)                     # (E_loc, F_tp, D)
    mid = jax.nn.gelu(jnp.einsum("ebcd,edf->ebcf", expert_in, w1))
    out_e = jax.lax.psum(jnp.einsum("ebcf,efd->ebcd", mid, w2), "tp")
    out = jnp.einsum("bsec,ebcd->bsd", comb_loc, out_e)
    return jax.lax.psum(out, "ep").astype(h.dtype)


def _pp_moe_block(x, blk, positions, cfg):
    """MoE transformer block on (tp, ep)-local shards: the shared
    Megatron attention sublayer + the ep-local switch-MoE FFN above."""
    x = _pp_attention_sublayer(x, blk, positions, cfg)
    h = _rms_norm(x, blk["ln2"])
    return x + _pp_moe_ffn(h, blk, cfg)


def _block_fn(cfg: ModelConfig):
    return _pp_moe_block if _is_moe(cfg) else _pp_block


def _pipeline_loss_local(pp_params, tokens_mb, targets_mb,
                         cfg: ModelConfig, n_stages: int):
    """Per-device body (under shard_map over dp/tp/pp). tokens_mb/
    targets_mb: (M, b_local, S)."""
    s_idx = jax.lax.axis_index("pp")
    m_count, b_local, seq = tokens_mb.shape
    d_model = cfg.d_model
    ticks = n_ticks(n_stages, m_count)

    positions = _global_positions(b_local, seq)
    embed = pp_params["embed"]
    stacked = pp_params["stacked"]

    def stage_fn(x):
        """Run my slab of layers (scan over the local layer axis)."""
        def body(h, blk):
            return _block_fn(cfg)(h, blk, positions, cfg), None

        if cfg.remat:
            body = jax.checkpoint(body)
        x, _ = jax.lax.scan(body, x, stacked)
        return x

    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    # Branch-free schedule (collectives under device-varying lax.cond
    # deadlock — every device must run the same collective sequence):
    # every stage embeds (a cheap gather) and selects between that and
    # the hopped-in activation. Stage outputs stream out as scan ys —
    # the last stage's microbatch m output is simply tick m + S − 1, a
    # STATIC slice after the loop — so the backward saves O(T) per-tick
    # activations, not the O(T·M) an in-carry output buffer would.
    def tick(x_in, t):
        m = jnp.clip(t - s_idx, 0, m_count - 1)
        tokens_m = tokens_mb[m]

        emb = _mark_varying(embed.astype(cfg.compute_dtype)[tokens_m],
                            ("dp", "pp", "sp"))
        x = jnp.where(s_idx == 0, emb, x_in)
        y = stage_fn(x)

        # One ICI neighbour hop moves every stage's output forward
        return jax.lax.ppermute(y, "pp", perm), y

    x0 = _mark_varying(jnp.zeros((b_local, seq, d_model), cfg.compute_dtype),
                       ("dp", "pp", "sp"))
    _, ys = jax.lax.scan(tick, x0, jnp.arange(ticks))
    # Last stage produced microbatch m at tick m + (S − 1); every other
    # stage's slice is garbage and is masked out by the final psum
    outputs = ys[n_stages - 1:n_stages - 1 + m_count]

    # Loss head scanned one microbatch at a time so peak logits memory
    # stays (b, S, V) — not M× that. Real data only on the last stage;
    # other stages' buffers are garbage and get masked out below.
    # The accumulator is shape (1,), NOT scalar: JAX 0.4.x shard_map
    # partial-eval fails to promote a scalar scan-carry residual
    # (rank-0 output vs the {0: all_names} residual spec — the seed
    # test_pipeline _SpecError), and a singleton axis costs nothing.
    def loss_one(acc, y_t):
        y, targets_m = y_t
        return acc + _head_nll(y, pp_params["ln_f"], pp_params["lm_head"],
                               targets_m, cfg), None

    loss_sum, _ = jax.lax.scan(
        loss_one, _mark_varying(jnp.zeros((1,), jnp.float32), ("dp", "pp")),
        (outputs, targets_mb))
    local_loss = loss_sum[0] / m_count

    loss = jax.lax.psum(
        jnp.where(s_idx == n_stages - 1, local_loss, 0.0), "pp")
    loss = jax.lax.pmean(loss, "dp")
    return jax.lax.pmean(loss, "tp")  # tp replicas agree; mark it so


def make_pp_loss(cfg: ModelConfig, mesh: Mesh):
    """Jittable ``loss(pp_params, tokens_mb, targets_mb)`` where tokens_mb
    is (n_microbatches, batch, seq)."""
    n_stages = _validate_pp_mesh(cfg, mesh)
    param_specs, data_spec = _pp_specs(cfg, mesh)

    local = partial(_pipeline_loss_local, cfg=cfg, n_stages=n_stages)
    return shard_map_compat(local, mesh=mesh,
                            in_specs=(param_specs, data_spec, data_spec),
                            out_specs=P(), check_vma=_PP_CHECK)


# ---------------------------------------------------------------------------
# 1F1B: hand-scheduled interleaved forward/backward
# ---------------------------------------------------------------------------

def n_ticks_1f1b(n_stages: int, n_microbatches: int) -> int:
    """Wall ticks for the 1F1B schedule below (each tick = one fwd unit
    + one bwd unit per stage)."""
    return n_microbatches + 2 * (n_stages - 1)


def ring_slots(n_stages: int) -> int:
    """Saved-input slots a stage needs: in-flight microbatches are
    bounded by the schedule depth 2(S−1)+1 — NOT by M (the GPipe-by-grad
    path's backward holds O(M + S) per-tick activations)."""
    return 2 * (n_stages - 1) + 1


def _pipeline_1f1b_local(pp_params, tokens_mb, targets_mb,
                         cfg: ModelConfig, n_stages: int, dp_size: int,
                         unmentioned=None, ad_overcount: float = 1.0):
    """Per-device 1F1B body: a FORWARD-ONLY scan that carries gradient
    accumulators — no outer jax.grad, so XLA never materialises per-tick
    saved activations. Schedule (branch-free, both units every tick):

    - fwd: stage ``s`` forwards microbatch ``mf = t − s`` (GPipe fill),
      saving its post-select INPUT in a ring buffer (recompute-style
      residual — the cheapest carryable VJP state).
    - bwd: stage ``s`` backwards ``mb = t − 2(S−1) + s``; for the last
      stage ``mb == mf``, so the loss head's dy feeds its own vjp the
      same tick. Invalid units run on clamped garbage with a ZERO dy —
      vjp is linear in the cotangent, so their grad contribution is
      exactly zero without a branch (collectives under device-varying
      lax.cond deadlock).
    - hops: activations ppermute forward, input-cotangents ppermute
      backward; one tick = one ICI hop each way.

    Returns (loss, grads) with grads in the pp-sharded param layout.
    """
    s_idx = jax.lax.axis_index("pp")
    m_count, b_local, seq = tokens_mb.shape
    d_model = cfg.d_model
    ticks = n_ticks_1f1b(n_stages, m_count)
    n_slots = ring_slots(n_stages)

    positions = _global_positions(b_local, seq)
    embed = pp_params["embed"]
    stacked = pp_params["stacked"]
    is_first = s_idx == 0
    is_last = s_idx == n_stages - 1

    def stage_fn(slab, x):
        def body(h, blk):
            return _block_fn(cfg)(h, blk, positions, cfg), None

        if cfg.remat:
            body = jax.checkpoint(body)
        x, _ = jax.lax.scan(body, x, slab)
        return x

    perm_fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    perm_bwd = [(i, (i - 1) % n_stages) for i in range(n_stages)]

    def tick(carry, t):
        x_hop, dy_hop, ring, g_stacked, g_embed, g_lnf, g_lmh, loss_acc = \
            carry

        # ---- forward unit -------------------------------------------
        mf = t - s_idx
        fwd_valid = (mf >= 0) & (mf < m_count)
        mf_c = jnp.clip(mf, 0, m_count - 1)
        tokens_f = tokens_mb[mf_c]
        emb = _mark_varying(embed.astype(cfg.compute_dtype)[tokens_f],
                            ("dp", "pp", "sp"))
        x_in = jnp.where(is_first, emb, x_hop)
        slot_f = mf_c % n_slots
        ring = ring.at[slot_f].set(
            jnp.where(fwd_valid, x_in, ring[slot_f]))
        y = stage_fn(stacked, x_in)

        # Loss head each tick. The validity mask is INSIDE the
        # differentiated function: ln_f/lm_head are invariant over dp AND
        # pp, so the in-body vjp auto-psums their cotangents over both
        # axes (transpose of the implicit invariant→varying casts) — an
        # outside-the-grad mask would let other stages' garbage heads
        # into that sum. Masked inside, the auto-psum delivers exactly
        # the valid last-stage contribution, Σ'd over dp shards.
        head_mask = fwd_valid & is_last
        hm = jnp.where(head_mask, 1.0, 0.0)
        (masked_loss, (dy_own, d_lnf, d_lmh)) = jax.value_and_grad(
            lambda y_, lnf_, lmh_: hm * _head_nll(y_, lnf_, lmh_,
                                                  targets_mb[mf_c], cfg),
            argnums=(0, 1, 2))(y, pp_params["ln_f"], pp_params["lm_head"])
        loss_acc = loss_acc + masked_loss
        g_lnf = g_lnf + d_lnf
        g_lmh = g_lmh + d_lmh

        # ---- backward unit ------------------------------------------
        mb = t - 2 * (n_stages - 1) + s_idx
        bwd_valid = (mb >= 0) & (mb < m_count)
        mb_c = jnp.clip(mb, 0, m_count - 1)
        x_saved = ring[mb_c % n_slots]
        dy_in = jnp.where(is_last, dy_own.astype(cfg.compute_dtype), dy_hop)
        dy_eff = jnp.where(bwd_valid, dy_in, jnp.zeros_like(dy_in))
        _, vjp = jax.vjp(stage_fn, stacked, x_saved)
        d_slab, dx = vjp(dy_eff)
        g_stacked = jax.tree.map(jnp.add, g_stacked, d_slab)
        # dx is already the FULL input cotangent: under the vma-checked
        # shard_map, transposing the invariant→tp-varying casts where x
        # meets the tp-sharded matmuls inserts the psum('tp') (the
        # Megatron f/g pattern) — an explicit psum here would double-
        # count the tp-invariant residual path
        # Stage 0's dx is the embedding-gather cotangent
        tokens_b = tokens_mb[mb_c]
        g_embed = g_embed.at[tokens_b].add(
            jnp.where(is_first, dx, jnp.zeros_like(dx)).astype(g_embed.dtype))

        # ---- hops ---------------------------------------------------
        x_hop = jax.lax.ppermute(y, "pp", perm_fwd)
        dy_hop = jax.lax.ppermute(dx, "pp", perm_bwd)
        return (x_hop, dy_hop, ring, g_stacked, g_embed, g_lnf, g_lmh,
                loss_acc), None

    zeros_act = _mark_varying(
        jnp.zeros((b_local, seq, d_model), cfg.compute_dtype),
        ("dp", "pp", "sp"))
    ring0 = _mark_varying(
        jnp.zeros((n_slots, b_local, seq, d_model), cfg.compute_dtype),
        ("dp", "pp", "sp"))
    # Accumulator vma types mirror what lands in them: g_stacked /
    # g_lnf / g_lmh receive vjp cotangents already auto-psum'd over the
    # axes their params are invariant on (zeros_like inherits the
    # param's own type); g_embed takes the dp-local dx scatter and the
    # loss the pp/dp-local masked head value
    g_stacked0 = jax.tree.map(jnp.zeros_like, stacked)
    g_embed0 = _mark_varying(jnp.zeros_like(embed), ("dp", "pp", "sp"))
    g_lnf0 = jnp.zeros_like(pp_params["ln_f"])
    g_lmh0 = jnp.zeros_like(pp_params["lm_head"])
    loss0 = _mark_varying(jnp.zeros((), jnp.float32), ("dp", "pp"))

    (x_hop, dy_hop, ring, g_stacked, g_embed, g_lnf, g_lmh,
     loss_acc), _ = jax.lax.scan(
        tick, (zeros_act, zeros_act, ring0, g_stacked0, g_embed0, g_lnf0,
               g_lmh0, loss0), jnp.arange(ticks))

    inv_m = 1.0 / m_count
    # Loss value lives on the last stage (values are device-local, only
    # cotangents of invariant leaves get auto-psum'd)
    loss = jax.lax.psum(loss_acc * inv_m, "pp")
    loss = jax.lax.pmean(loss, "dp")
    loss = jax.lax.pmean(loss, "tp")

    # Gradient normalization — two regimes:
    # - manually-accumulated g_embed (scatter of the dp-LOCAL dx): combine
    #   stages with psum('pp'), dp-average with pmean;
    # - vjp-produced g_stacked / g_lnf / g_lmh: on vma-era JAX the
    #   in-body vjp already psum'd them over every axis their param is
    #   invariant on (dp; pp too for the head leaves) — they arrive as
    #   Σ over dp shards, so the dp MEAN is a static division, and
    #   another psum/pmean would double-count.
    if unmentioned is not None:
        # Old JAX (check_rep era): the in-body vjp inserts NO automatic
        # collectives — every vjp-produced cotangent arrives as this
        # member's PARTIAL (dp-local data shard; tp/sp/ep-local compute
        # slice; heads zero off the last pp stage). Summing each leaf
        # over its spec's unmentioned axes assembles the full gradient
        # (vjp is linear in the cotangent, so partial dy hops through
        # the ring sum correctly too), and the psum also registers the
        # replication the out_specs check needs. g_embed's dp/pp/sp
        # reductions happen explicitly below — only its remaining
        # unmentioned axes (tp, and ep for MoE) are summed here.
        # The raw-JAX psum transpose re-psums cotangents ("psum +
        # pbroadcast"), so each explicit in-body collective axis the
        # backward crosses (tp in the Megatron psums, sp in the head
        # pmean, ep in the MoE psums) inflates every assembled leaf by
        # that axis size, uniformly — divide it back out (ad_overcount
        # = tp·sp·ep, computed by the factory from the mesh).
        inv_over = 1.0 / ad_overcount

        def _assemble(g, axes):
            return (jax.lax.psum(g, axes) if axes else g) * inv_over

        g_stacked = {k: _assemble(v, unmentioned["stacked"][k])
                     for k, v in g_stacked.items()}
        g_lnf = _assemble(g_lnf, unmentioned["ln_f"])
        g_lmh = _assemble(g_lmh, unmentioned["lm_head"])
        g_embed = _assemble(g_embed, tuple(
            a for a in unmentioned["embed"] if a not in ("dp", "pp", "sp")))
    g_embed = jax.lax.pmean(
        jax.lax.psum(jax.lax.psum(g_embed * inv_m, "pp"), "sp"), "dp")
    scale = inv_m / dp_size
    g_stacked = jax.tree.map(lambda g: g * scale, g_stacked)
    g_lnf = g_lnf * scale
    g_lmh = g_lmh * scale

    grads = {"embed": g_embed, "stacked": g_stacked,
             "ln_f": g_lnf, "lm_head": g_lmh}
    return loss, grads


def make_pp_1f1b_value_and_grad(cfg: ModelConfig, mesh: Mesh):
    """Jittable ``fn(pp_params, tokens_mb, targets_mb) → (loss, grads)``
    — the 1F1B analog of ``jax.value_and_grad(make_pp_loss(...))``, with
    activation memory bounded by the schedule depth instead of the tick
    count."""
    n_stages = _validate_pp_mesh(cfg, mesh)
    param_specs, data_spec = _pp_specs(cfg, mesh)

    # 1F1B keeps the replication check ON on every JAX version. On old
    # (check_rep) JAX the in-body vjp produces per-member PARTIAL
    # cotangents, so the body must assemble each grad leaf with a psum
    # over the axes its spec leaves unmentioned (which also proves the
    # out_specs replication to the static tracker) — precompute those
    # axis tuples here, where the mesh is in hand.
    unmentioned = None
    ad_overcount = 1.0
    if not SHARD_MAP_HAS_VMA:
        def _un(spec):
            named = {n for part in spec if part is not None
                     for n in (part if isinstance(part, tuple) else (part,))}
            return tuple(a for a in mesh.axis_names if a not in named)

        unmentioned = {
            "embed": _un(param_specs["embed"]),
            "ln_f": _un(param_specs["ln_f"]),
            "lm_head": _un(param_specs["lm_head"]),
            "stacked": {k: _un(s)
                        for k, s in param_specs["stacked"].items()},
        }
        # Axes the in-body backward crosses through EXPLICIT collectives
        # (see _pipeline_1f1b_local._assemble): tp (Megatron psums), sp
        # (head pmean + gathered-KV attention), ep (MoE psums).
        ad_overcount = float(mesh.shape.get("tp", 1)
                             * mesh.shape.get("sp", 1)
                             * mesh.shape.get("ep", 1))

    local = partial(_pipeline_1f1b_local, cfg=cfg, n_stages=n_stages,
                    dp_size=mesh.shape["dp"], unmentioned=unmentioned,
                    ad_overcount=ad_overcount)
    return shard_map_compat(local, mesh=mesh,
                            in_specs=(param_specs, data_spec, data_spec),
                            out_specs=(P(), param_specs))


def microbatch(tokens: jax.Array, n_microbatches: int) -> jax.Array:
    """(B, S) → (M, B/M, S)."""
    b, s = tokens.shape
    if b % n_microbatches:
        raise ValueError(
            f"batch {b} not divisible by n_microbatches={n_microbatches}")
    return tokens.reshape(n_microbatches, b // n_microbatches, s)


def make_pp_train_step(cfg: ModelConfig, mesh: Mesh, optimizer=None,
                       n_microbatches: int = 4,
                       schedule_name: str = "gpipe"):
    """Returns jitted ``step(pp_params, opt_state, tokens, targets) →
    (pp_params, opt_state, loss)``; tokens/targets are (B, S) and are
    microbatched internally. ``schedule_name``:

    - ``"gpipe"``: the scan-based forward with ``jax.value_and_grad``
      deriving the mirrored backward (activation memory O(M + S)
      per-tick outputs, remat inside stages).
    - ``"1f1b"``: the hand-scheduled interleaved forward/backward
      (activation memory O(S) ring of saved stage inputs).
    """
    from faabric_tpu.models.train import make_optimizer

    import optax

    optimizer = optimizer or make_optimizer()
    if schedule_name == "1f1b":
        value_and_grad = make_pp_1f1b_value_and_grad(cfg, mesh)
    elif schedule_name == "gpipe":
        loss_fn = make_pp_loss(cfg, mesh)

        def value_and_grad(pp_params, tok_mb, tgt_mb):
            return jax.value_and_grad(
                lambda p: loss_fn(p, tok_mb, tgt_mb))(pp_params)
    else:
        raise ValueError(f"Unknown pipeline schedule {schedule_name!r}")

    def step(pp_params, opt_state, tokens, targets):
        tok_mb = microbatch(tokens, n_microbatches)
        tgt_mb = microbatch(targets, n_microbatches)
        loss, grads = value_and_grad(pp_params, tok_mb, tgt_mb)
        updates, opt_state = optimizer.update(grads, opt_state, pp_params)
        pp_params = optax.apply_updates(pp_params, updates)
        return pp_params, opt_state, loss

    return jax.jit(step, donate_argnums=(0, 1))


def init_pp_train_state(key: jax.Array, cfg: ModelConfig, mesh: Mesh,
                        optimizer=None):
    """Stacked params + optimizer state laid out over the pp mesh."""
    from faabric_tpu.models.train import make_optimizer
    from faabric_tpu.models.transformer import init_params

    optimizer = optimizer or make_optimizer()
    if _is_moe(cfg):
        from faabric_tpu.models.moe import init_moe_params

        raw = init_moe_params(key, cfg)
    else:
        raw = init_params(key, cfg)
    pp_params = stack_block_params(raw)
    pp_params = jax.device_put(pp_params, pp_param_shardings(mesh, cfg))
    opt_state = optimizer.init(pp_params)
    return pp_params, opt_state
