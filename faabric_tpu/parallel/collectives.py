"""Compiled device collectives over a JAX mesh.

This is the TPU-native data plane that replaces the reference's
leader-tree collectives over raw TCP (src/mpi/MpiWorld.cpp:786-1775): the
per-rank buffers live as shards of a global array laid out over a
``jax.sharding.Mesh``, and each collective is a jitted ``shard_map`` whose
``jax.lax`` collective XLA lowers onto ICI (psum/all_gather/psum_scatter/
all_to_all/ppermute). No host round-trips, no per-pair sockets — the
compiler owns the schedule.

Array convention (maps 1:1 onto MPI semantics):
- ``allreduce``: global shape (n_ranks, *buf) sharded on axis 0; every
  rank's output shard is the full reduction.
- ``allgather``: shard (k, *buf) per rank → replicated (n_ranks*k, *buf).
- ``reduce_scatter``: shard (n_ranks*k,) per rank → (k,) reduced segment.
- ``alltoall``: shard rows (n_ranks, *buf) per rank → row i of rank j
  lands as row j of rank i.
- ``broadcast``: root rank's shard replicated to every rank.

Compiled callables are cached per (kind, op, global shape, dtype) — the
first call pays XLA compilation, steady state is a cached executable.
"""

from __future__ import annotations

import threading
from typing import Any, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # JAX >= 0.4.35 exports shard_map at the top level
    from jax import shard_map  # type: ignore[attr-defined]

    _SHARD_MAP_NO_CHECK_KW = "check_vma"
except ImportError:  # pragma: no cover — older JAX
    from jax.experimental.shard_map import shard_map  # type: ignore

    _SHARD_MAP_NO_CHECK_KW = "check_rep"

# Whether the installed JAX has the varying-mesh-axes (vma) machinery:
# shard_map(check_vma=), lax.pcast/pvary. Without it (<= 0.4.x) the
# older check_rep static-replication inference runs instead — it cannot
# be helped along by _mark_varying (a no-op there) and is known not to
# see through scan/vjp-heavy bodies like the pipeline schedules.
SHARD_MAP_HAS_VMA = _SHARD_MAP_NO_CHECK_KW == "check_vma"


def shard_map_compat(fn, mesh=None, in_specs=None, out_specs=None,
                     check_vma=None):
    """Version-portable ``shard_map``: ``check_vma`` maps onto whichever
    check kwarg the installed JAX understands (``check_vma`` on current
    releases, ``check_rep`` on 0.4.x). ``None`` keeps the library
    default. Every shard_map in this codebase that passes a check kwarg
    must go through here — JAX 0.4.37 raises TypeError on a literal
    ``check_vma=`` (the seed test_ops failure)."""
    kwargs = {}
    if check_vma is not None:
        kwargs[_SHARD_MAP_NO_CHECK_KW] = check_vma
    return shard_map(fn, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, **kwargs)

from faabric_tpu.mpi.types import MpiOp

_PRIMITIVE_REDUCERS = {
    MpiOp.SUM: jax.lax.psum,
    MpiOp.MAX: jax.lax.pmax,
    MpiOp.MIN: jax.lax.pmin,
}

_GATHER_REDUCERS = {
    MpiOp.PROD: jnp.prod,
    MpiOp.LAND: jnp.all,
    MpiOp.LOR: jnp.any,
}


class DeviceCollectives:
    """Collectives bound to an ordered set of devices (rank i ↔ device i)."""

    def __init__(self, devices: Sequence[Any], axis_name: str = "ranks") -> None:
        self.devices = list(devices)
        self.n = len(self.devices)
        self.axis = axis_name
        self.mesh = Mesh(np.array(self.devices), (axis_name,))
        self._cache: dict[tuple, Any] = {}
        self._cache_lock = threading.Lock()

    # ------------------------------------------------------------------
    def sharding(self, partitioned: bool = True) -> NamedSharding:
        return NamedSharding(self.mesh,
                             P(self.axis) if partitioned else P())

    def shard_stacked(self, per_rank: Sequence[np.ndarray]) -> jax.Array:
        """Place one buffer per rank onto its device as a stacked global
        array of shape (n_ranks, *buf). Single-controller form: this
        process must hold every rank's buffer (all devices addressable
        or the data replicated); on a multi-process plane use
        :meth:`shard_stacked_addressable`."""
        stacked = jnp.stack([jnp.asarray(b) for b in per_rank])
        return jax.device_put(stacked, self.sharding())

    def shard_stacked_addressable(self, local_per_rank,
                                  buf_shape: tuple,
                                  dtype) -> jax.Array:
        """Multi-process form of :meth:`shard_stacked`: each process
        supplies buffers ONLY for the ranks whose devices it owns
        (``local_per_rank``: rank → buffer mapping), and the global
        (n_ranks, *buf) array is assembled from the per-device shards —
        no process ever materialises another process's data. This is
        the construction every cross-process collective starts from
        (jax multi-controller SPMD: same jitted call in every process,
        one global array)."""
        my_proc = jax.process_index()
        shards = []
        for rank, dev in enumerate(self.devices):
            if dev.process_index != my_proc:
                continue
            if rank not in local_per_rank:
                raise KeyError(
                    f"process {my_proc} owns rank {rank} (device {dev}) "
                    "but no buffer was supplied for it")
            buf = np.asarray(local_per_rank[rank], dtype).reshape(buf_shape)
            shards.append(jax.device_put(buf[None], dev))
        return jax.make_array_from_single_device_arrays(
            (self.n, *buf_shape), self.sharding(), shards)

    def addressable_shard(self, x: jax.Array, rank: int) -> np.ndarray:
        """This process's view of ``rank``'s shard (raises if the rank's
        device belongs to another process)."""
        dev = self.devices[rank]
        for s in x.addressable_shards:
            if s.device == dev:
                return np.asarray(s.data)
        raise KeyError(f"rank {rank} shard lives on {dev}, not in "
                       f"process {jax.process_index()}")

    # ------------------------------------------------------------------
    def _compiled(self, key: tuple, build) -> Any:
        with self._cache_lock:
            fn = self._cache.get(key)
            if fn is None:
                fn = build()
                self._cache[key] = fn
            return fn

    def _shard_mapped(self, fn, in_spec, out_spec, replicated_out: bool = False):
        kwargs = {}
        if replicated_out:
            # all_gather/broadcast outputs ARE replicated, but the static
            # replication check cannot infer it (kwarg name differs by
            # JAX version: check_vma on current, check_rep on older)
            kwargs[_SHARD_MAP_NO_CHECK_KW] = False
        return jax.jit(shard_map(fn, mesh=self.mesh, in_specs=in_spec,
                                 out_specs=out_spec, **kwargs))

    # ------------------------------------------------------------------
    # Collectives
    # ------------------------------------------------------------------
    def allreduce(self, x: jax.Array, op: MpiOp = MpiOp.SUM) -> jax.Array:
        key = ("allreduce", int(op), x.shape, str(x.dtype))

        def build():
            prim = _PRIMITIVE_REDUCERS.get(op)
            if prim is not None:
                def f(shard):
                    return prim(shard, self.axis)
            else:
                reducer = _GATHER_REDUCERS.get(op)
                if reducer is None:
                    raise NotImplementedError(f"Device allreduce op {op}")

                def f(shard):
                    gathered = jax.lax.all_gather(shard, self.axis)
                    return reducer(gathered, axis=0).astype(shard.dtype)
            return self._shard_mapped(f, P(self.axis), P(self.axis))

        return self._compiled(key, build)(x)

    def allreduce_loop(self, x: jax.Array, n: int,
                       op: MpiOp = MpiOp.SUM) -> jax.Array:
        """``n`` chained allreduces inside ONE compiled program
        (``fori_loop`` around the collective), returning exactly what a
        single :meth:`allreduce` would. One dispatch per n collectives —
        the benchmarking form for high-latency PJRT clients, where
        per-call dispatch would otherwise swamp the on-ICI time being
        measured.

        The loop body is the bare reduce (no per-hop work rides inside
        the timed region); for SUM the value grows ×ranks per extra hop
        and ONE post-loop rescale by ranks^(n−1) restores the plain sum.
        The rescale (a full elementwise HBM pass) exists only for n ≥ 2
        (growth is 1 at n = 1), so a two-point timing slope cancels it
        ONLY if both trip counts are ≥ 2 — time with n_lo=2, not 1, or
        the slope charges that pass to per-hop time (ADVICE r3). Interim
        SUM values must stay within the dtype's range for the chosen n
        (the caller bounds magnitudes; MAX/MIN are idempotent).
        """
        prim = _PRIMITIVE_REDUCERS.get(op)
        if prim is None:
            raise NotImplementedError(f"allreduce_loop op {op}")
        key = ("allreduce_loop", int(op), n, x.shape, str(x.dtype))
        growth = self.n ** (n - 1)

        def build():
            def f(shard):
                def body(_, y):
                    return prim(y, self.axis)
                r = jax.lax.fori_loop(0, n, body, shard)
                if op == MpiOp.SUM and growth > 1:
                    if jnp.issubdtype(r.dtype, jnp.inexact):
                        r = r * jnp.asarray(1.0 / growth, r.dtype)
                    else:
                        # Exact: the interim value is growth·sum
                        r = r // growth
                return r
            # The carry flips rank-varying → invariant after the first
            # reduce; the static replication check can't type that loop
            return self._shard_mapped(f, P(self.axis), P(self.axis),
                                      replicated_out=True)

        return self._compiled(key, build)(x)

    def allgather(self, x: jax.Array) -> jax.Array:
        """(n*k, *buf) global, shard (k,*buf) per rank → replicated
        (n*k, *buf)."""
        key = ("allgather", x.shape, str(x.dtype))

        def build():
            def f(shard):
                return jax.lax.all_gather(shard, self.axis, tiled=True)
            return self._shard_mapped(f, P(self.axis), P(),
                                      replicated_out=True)

        return self._compiled(key, build)(x)

    def reduce_scatter(self, x: jax.Array, op: MpiOp = MpiOp.SUM) -> jax.Array:
        """Each rank holds (n*k,) (global (n, n*k) stacked); output shard
        (k,) is the reduced segment — global (n, k)."""
        if op != MpiOp.SUM:
            raise NotImplementedError("Device reduce_scatter supports SUM")
        key = ("reduce_scatter", x.shape, str(x.dtype))

        def build():
            def f(shard):
                # shard: (1, n*k) → (1, k)
                return jax.lax.psum_scatter(shard, self.axis,
                                            scatter_dimension=1, tiled=True)
            return self._shard_mapped(f, P(self.axis), P(self.axis))

        return self._compiled(key, build)(x)

    def alltoall(self, x: jax.Array) -> jax.Array:
        """Global (n, n, *buf), shard (1, n, *buf) rows per rank; row i of
        rank j becomes row j of rank i."""
        key = ("alltoall", x.shape, str(x.dtype))

        def build():
            def f(shard):
                # shard (1, n, *buf): chunk j of rank i lands as chunk i of
                # rank j (MPI alltoall)
                rows = jax.lax.all_to_all(shard[0], self.axis, split_axis=0,
                                          concat_axis=0, tiled=True)
                return rows[None]
            return self._shard_mapped(f, P(self.axis), P(self.axis))

        return self._compiled(key, build)(x)

    def broadcast(self, x: jax.Array, root: int = 0) -> jax.Array:
        """Root rank's shard replicated to all ranks: (n, *buf) → (*buf)."""
        key = ("broadcast", int(root), x.shape, str(x.dtype))

        def build():
            def f(shard):
                gathered = jax.lax.all_gather(shard, self.axis, tiled=True)
                return gathered[root]
            return self._shard_mapped(f, P(self.axis), P(),
                                      replicated_out=True)

        return self._compiled(key, build)(x)

    def scan(self, x: jax.Array, op: MpiOp = MpiOp.SUM) -> jax.Array:
        """Inclusive prefix reduction across ranks (MPI_Scan)."""
        key = ("scan", int(op), x.shape, str(x.dtype))
        reducers = {MpiOp.SUM: jnp.cumsum,
                    MpiOp.PROD: jnp.cumprod,
                    MpiOp.MAX: lambda g, axis: jax.lax.cummax(g, axis=axis),
                    MpiOp.MIN: lambda g, axis: jax.lax.cummin(g, axis=axis)}
        reducer = reducers.get(op)
        if reducer is None:
            raise NotImplementedError(f"Device scan op {op}")

        def build():
            def f(shard):
                gathered = jax.lax.all_gather(shard, self.axis, tiled=True)
                idx = jax.lax.axis_index(self.axis)
                prefix = reducer(gathered, axis=0).astype(shard.dtype)
                return jax.lax.dynamic_slice_in_dim(prefix, idx, 1, axis=0)
            return self._shard_mapped(f, P(self.axis), P(self.axis))

        return self._compiled(key, build)(x)

    # ------------------------------------------------------------------
    # Point-to-point over ICI (the device analog of the PTP broker's
    # host dispatch — SURVEY §5.8: "PTP dispatch becomes device-to-device
    # transfers over ICI")
    # ------------------------------------------------------------------
    def permute(self, x: jax.Array,
                pairs: Sequence[tuple[int, int]]) -> jax.Array:
        """Move rank shards along (src, dst) pairs in ONE compiled
        ``ppermute`` (each a direct ICI transfer). Ranks that are not a
        destination receive zeros — MPI-style sendrecv chains compose
        from these primitives without host round-trips."""
        key = ("permute", tuple(pairs), x.shape, str(x.dtype))

        def build():
            perm = list(pairs)

            def f(shard):
                return jax.lax.ppermute(shard, self.axis, perm)
            return self._shard_mapped(f, P(self.axis), P(self.axis))

        return self._compiled(key, build)(x)

    def send_recv(self, x: jax.Array, src: int, dst: int) -> jax.Array:
        """Single device-to-device transfer: rank ``src``'s shard lands
        on rank ``dst`` (others zero)."""
        return self.permute(x, [(src, dst)])

    def shift(self, x: jax.Array, disp: int = 1) -> jax.Array:
        """Ring rotation by ``disp`` (every rank sends, every rank
        receives — the neighbour-exchange building block)."""
        return self.permute(
            x, [(i, (i + disp) % self.n) for i in range(self.n)])

    # ------------------------------------------------------------------
    def to_per_rank(self, x: jax.Array) -> list[np.ndarray]:
        """Read a stacked (n, *buf) array back as per-rank host buffers."""
        host = np.asarray(x)
        return [host[i] for i in range(self.n)]


def local_devices_for_ids(device_ids: Sequence[int]) -> list:
    """Resolve planner-assigned chip ids to jax devices on this host.

    Ids that don't exist locally (e.g. a CPU test mesh whose jax ids
    differ from the planner's numbering) wrap modulo the local device
    count — but a mesh needs unique devices, so a wrap that collides
    raises instead of silently aliasing two ranks onto one chip."""
    all_devs = jax.local_devices()
    by_id = {d.id: d for d in all_devs}
    out = [by_id.get(i, all_devs[i % len(all_devs)]) for i in device_ids]
    if len({id(d) for d in out}) != len(out):
        raise ValueError(
            f"Device ids {list(device_ids)} do not map onto distinct local "
            f"devices ({len(all_devs)} available)")
    return out
