"""TPU mesh substrate: device collectives, shardings (reference analog:
the ICI/XLA data plane replacing src/mpi's TCP collectives)."""

from faabric_tpu.parallel.collectives import (
    DeviceCollectives,
    local_devices_for_ids,
)

__all__ = ["DeviceCollectives", "local_devices_for_ids"]

from faabric_tpu.parallel.mesh import (  # noqa: E402
    MeshConfig,
    build_mesh,
    constraint,
    mesh_from_group,
    named,
    replicated,
)

__all__ += ["MeshConfig", "build_mesh", "constraint", "mesh_from_group",
            "named", "replicated"]

from faabric_tpu.parallel.ring_attention import (  # noqa: E402
    ring_attention,
    shard_sequence,
)

__all__ += ["ring_attention", "shard_sequence"]

from faabric_tpu.parallel.pipeline import (  # noqa: E402
    init_pp_train_state,
    make_pp_loss,
    make_pp_train_step,
    microbatch,
    pp_data_sharding,
    pp_param_shardings,
    stack_block_params,
    unstack_block_params,
)

__all__ += ["init_pp_train_state", "make_pp_loss", "make_pp_train_step",
            "microbatch", "pp_data_sharding", "pp_param_shardings",
            "stack_block_params", "unstack_block_params"]

from faabric_tpu.parallel.distributed import (  # noqa: E402
    DevicePlaneSpec,
    current_plane,
    join_device_plane,
    leave_device_plane,
    plane_summary,
    request_device_plane,
)

__all__ += ["DevicePlaneSpec", "current_plane", "join_device_plane",
            "leave_device_plane", "plane_summary", "request_device_plane"]
