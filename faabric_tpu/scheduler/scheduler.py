"""Host-local scheduler.

Reference analog: src/scheduler/Scheduler.cpp:250-386 (executeBatch /
claimExecutor), :160-237 (reaper), include/faabric/scheduler/Scheduler.h.

Each worker host runs one Scheduler. It receives dispatched batches from
the planner, claims a warm executor per message (one executor runs all
messages of a THREADS batch), and reports results back to the planner.
Executors idle longer than ``bound_timeout`` are reaped periodically.

Unlike the reference (process-wide singleton), a Scheduler is instantiable
with an explicit host identity so in-process multi-host tests can run two
full worker runtimes side by side (SURVEY §4.2's aliasing trick).
"""

from __future__ import annotations

import threading
from typing import TYPE_CHECKING, Optional

from faabric_tpu.executor.executor import Executor
from faabric_tpu.executor.factory import get_executor_factory
from faabric_tpu.proto import (
    BatchExecuteRequest,
    BatchExecuteType,
    Message,
    ReturnValue,
    func_to_string,
)
from faabric_tpu.util.config import get_system_config
from faabric_tpu.util.logging import get_logger
from faabric_tpu.util.periodic import PeriodicBackgroundThread

if TYPE_CHECKING:  # pragma: no cover
    from faabric_tpu.planner.client import PlannerClient

logger = get_logger(__name__)


class ReaperThread(PeriodicBackgroundThread):
    """Reaps executors idle beyond bound_timeout
    (reference SchedulerReaperThread, Scheduler.cpp:160-237)."""

    thread_name = "scheduler/reaper"

    def __init__(self, scheduler: "Scheduler") -> None:
        super().__init__()
        self.scheduler = scheduler

    def do_work(self) -> None:
        self.scheduler.reap_idle_executors()


class Scheduler:
    def __init__(self, host: str, planner_client: "PlannerClient") -> None:
        self.host = host
        self.planner_client = planner_client

        self._lock = threading.RLock()
        # func string → executors (warm pool)
        self._executors: dict[str, list[Executor]] = {}
        # func string → executors that announced idle (ISSUE 8): an
        # O(1) claim free-list — at high invocation QPS the linear
        # try_claim scan over a deep warm pool was a measurable share
        # of per-message cost. Entries may be stale (claimed via the
        # scan fallback, or reaped); a failed try_claim on pop simply
        # discards them, and the reaper prunes its casualties.
        self._idle: dict[str, list[Executor]] = {}
        # id()s of currently-registered executors: the O(1) park-
        # eligibility check for notify_executor_idle (a list membership
        # scan over a deep warm pool would re-introduce the linear cost
        # the free-list removed). Maintained strictly alongside
        # _executors under _lock; ids of removed executors are dropped
        # while _executors still references them, so id reuse cannot
        # alias a live entry.
        self._parkable: set[int] = set()

        self._reaper = ReaperThread(self)
        self._started = False

        # Set by the WorkerRuntime: this host's PTP broker / MPI registry /
        # snapshot registry, reachable from guest code via
        # ExecutorContext → executor → scheduler
        self.ptp_broker = None
        self.mpi_registry = None
        self.snapshot_registry = None

        from faabric_tpu.snapshot.remote import SnapshotClient
        from faabric_tpu.transport.client_pool import ClientPool

        self._snapshot_clients = ClientPool(SnapshotClient)

        # Thread results cache for THREADS batches (msg id → (ret, msg))
        self._thread_results: dict[int, tuple[int, Message]] = {}
        self._thread_result_cv = threading.Condition()

    # ------------------------------------------------------------------
    # Lifecycle
    # ------------------------------------------------------------------
    def start(self) -> None:
        if self._started:
            return
        self._started = True
        conf = get_system_config()
        self._reaper.start(conf.reaper_interval_secs)

    def shutdown(self) -> None:
        self._reaper.stop()
        with self._lock:
            executors = [e for lst in self._executors.values() for e in lst]
            self._executors.clear()
            self._idle.clear()
            self._parkable.clear()
        for e in executors:
            e.shutdown()
        self._snapshot_clients.close_all()
        # Outbound state connections (remote KVs, replicate forwards)
        # are pooled per host and would outlive the runtime otherwise
        self.state.close_clients()
        self._started = False

    def reset(self) -> None:
        """Test reset: drop executors, keep identity."""
        self.shutdown()
        with self._thread_result_cv:
            self._thread_results.clear()

    def flush(self) -> None:
        """Host flush (reference FunctionCallServer::recvFlush): clear
        executors and give the factory its flush hook."""
        logger.debug("Flushing host %s", self.host)
        with self._lock:
            executors = [e for lst in self._executors.values() for e in lst]
            self._executors.clear()
            self._idle.clear()
            self._parkable.clear()
        for e in executors:
            e.shutdown()
        try:
            get_executor_factory().flush_host()
        except RuntimeError:
            pass

    # ------------------------------------------------------------------
    # Batch execution (reference Scheduler.cpp:250-325)
    # ------------------------------------------------------------------
    def execute_batch(self, req: BatchExecuteRequest) -> None:
        if req.n_messages() == 0:
            return
        is_threads = req.type == int(BatchExecuteType.THREADS)
        first = req.messages[0]

        if is_threads:
            # One executor runs every thread of the batch (shared memory)
            executor = self.claim_executor(first)
            if executor is None:
                self._fail_batch(req)
                return
            executor.execute_tasks(list(range(req.n_messages())), req)
            return

        # FUNCTIONS/PROCESSES/MIGRATION: one executor per message
        for idx, msg in enumerate(req.messages):
            executor = self.claim_executor(msg)
            if executor is None:
                # Could not claim: report failure so callers don't hang
                # (reference Scheduler.cpp:307-322)
                msg.return_value = int(ReturnValue.FAILED)
                msg.output_data = b"No executor available"
                self.report_message_result(msg)
                continue
            executor.execute_tasks([idx], req)

    def _fail_batch(self, req: BatchExecuteRequest) -> None:
        for msg in req.messages:
            msg.return_value = int(ReturnValue.FAILED)
            msg.output_data = b"No executor available"
            self.report_message_result(msg)

    def claim_executor(self, msg: Message) -> Optional[Executor]:
        """Reuse a warm executor or create one via the factory
        (reference Scheduler.cpp:339-386)."""
        func = func_to_string(msg)
        with self._lock:
            idle = self._idle.get(func)
            while idle:
                e = idle.pop()
                if e.try_claim():
                    return e
            for e in self._executors.get(func, []):
                if e.try_claim():
                    return e
            try:
                factory = get_executor_factory()
            except RuntimeError:
                logger.error("No executor factory while claiming for %s", func)
                return None
            executor = factory.create_executor(msg)
            executor.scheduler = self
            if not executor.try_claim():  # pragma: no cover — fresh executor
                return None
            self._executors.setdefault(func, []).append(executor)
            self._parkable.add(id(executor))
            logger.debug("%s created executor %s (%d warm)", self.host,
                         executor.id, len(self._executors[func]))
            return executor

    def notify_executor_idle(self, executor: Executor) -> None:
        """Hook from the executor when its batch drains: park it on the
        O(1) claim free-list. Reaping still happens on the periodic
        thread."""
        if executor.bound_msg is None:
            return
        func = func_to_string(executor.bound_msg)
        with self._lock:
            # Only executors still registered may park: an executor whose
            # last batch drains concurrently with flush()/shutdown() (which
            # clear _executors and then shut it down) must not re-enter the
            # free-list, or a later claim would hand out a dead executor
            # whose pool thread already exited.
            if id(executor) in self._parkable:
                self._idle.setdefault(func, []).append(executor)

    def reap_idle_executors(self) -> None:
        conf = get_system_config()
        to_shutdown: list[Executor] = []
        with self._lock:
            for func, lst in list(self._executors.items()):
                keep: list[Executor] = []
                for e in lst:
                    if not e.is_claimed() and e.uptime_idle() > conf.bound_timeout:
                        to_shutdown.append(e)
                        self._parkable.discard(id(e))
                    else:
                        keep.append(e)
                if keep:
                    self._executors[func] = keep
                else:
                    self._executors.pop(func, None)
                # Free-list entries for reaped executors must not be
                # claimable: rebuild against the surviving set
                if func in self._idle:
                    keep_set = set(map(id, keep))
                    self._idle[func] = [e for e in self._idle[func]
                                        if id(e) in keep_set]
                    if not self._idle[func]:
                        self._idle.pop(func, None)
        for e in to_shutdown:
            logger.debug("Reaping executor %s (idle %.1fs)", e.id, e.uptime_idle())
            e.shutdown()

    def get_executor_count(self, msg: Message | None = None) -> int:
        with self._lock:
            if msg is not None:
                return len(self._executors.get(func_to_string(msg), []))
            return sum(len(v) for v in self._executors.values())

    # ------------------------------------------------------------------
    # Results
    # ------------------------------------------------------------------
    def report_message_result(self, msg: Message) -> None:
        self.planner_client.set_message_result(msg)

    def set_thread_result_locally(self, msg: Message,
                                  return_value: int) -> None:
        """Cache a thread result on this host and wake waiters (reference
        setThreadResultLocally; also invoked by the SnapshotServer when a
        remote thread's result arrives)."""
        with self._thread_result_cv:
            self._thread_results[msg.id] = (return_value, msg)
            self._thread_result_cv.notify_all()

    def report_thread_result(self, msg: Message, return_value: int,
                             snapshot_key: str = "",
                             diffs=None) -> None:
        """THREADS result: diffs queue on the main host's snapshot — local
        queue when we are the main host, SnapshotClient push otherwise
        (reference Executor::setThreadResult :271-305). The planner still
        learns the message result so slots release and waiters unblock."""
        main_host = msg.main_host or self.host
        if main_host == self.host:
            self.set_thread_result_locally(msg, return_value)
            if diffs and snapshot_key and self.snapshot_registry is not None:
                snap = self.snapshot_registry.try_get_snapshot(snapshot_key)
                if snap is not None:
                    snap.queue_diffs(diffs)
        else:
            try:
                client = self._snapshot_clients.get(main_host)
                client.push_thread_result(msg.app_id, msg.id, return_value,
                                          snapshot_key, diffs or [])
            except Exception:  # noqa: BLE001 — the planner must still learn
                # the result even if the main host is unreachable
                logger.exception(
                    "Failed pushing thread result %d to %s", msg.id, main_host)
        self.planner_client.set_message_result(msg)

    def await_thread_result(self, msg_id: int, timeout: float | None = None) -> int:
        conf = get_system_config()
        timeout = timeout if timeout is not None else conf.global_message_timeout
        with self._thread_result_cv:
            ok = self._thread_result_cv.wait_for(
                lambda: msg_id in self._thread_results, timeout=timeout)
            if not ok:
                raise TimeoutError(f"Timed out waiting for thread result {msg_id}")
            return self._thread_results[msg_id][0]
