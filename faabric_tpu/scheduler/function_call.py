"""Worker-to-worker / planner-to-worker function RPC.

Reference analog: src/scheduler/FunctionCallServer.cpp (ports 8005/8006) and
src/scheduler/FunctionCallClient.cpp. Async plane: EXECUTE_FUNCTIONS
(planner dispatch → host scheduler) and SET_MESSAGE_RESULT (planner pushing
a result to a waiting host). Sync plane: FLUSH.

In mock mode the client records calls instead of sending — the backbone of
the reference's unit-test strategy (FunctionCallClient.cpp:22-60).
"""

from __future__ import annotations

import enum
import threading
from typing import TYPE_CHECKING

from faabric_tpu.proto import (
    BatchExecuteRequest,
    Message,
    ber_from_wire,
    ber_to_wire,
)
from faabric_tpu.transport.client import MessageEndpointClient
from faabric_tpu.transport.common import (
    FUNCTION_CALL_ASYNC_PORT,
    FUNCTION_CALL_SYNC_PORT,
    get_host_alias_offset,
)
from faabric_tpu.transport.message import TransportMessage
from faabric_tpu.transport.server import MessageEndpointServer, handler_response
from faabric_tpu.util.config import get_system_config
from faabric_tpu.util.logging import get_logger
from faabric_tpu.util.testing import is_mock_mode

if TYPE_CHECKING:  # pragma: no cover
    from faabric_tpu.scheduler.scheduler import Scheduler

logger = get_logger(__name__)


class FunctionCalls(enum.IntEnum):
    NO_FUNCTION_CALL = 0
    EXECUTE_FUNCTIONS = 1
    FLUSH = 2
    SET_MESSAGE_RESULT = 3
    GET_TELEMETRY = 4
    # Pipelined dispatch (ISSUE 8): one RPC per (host, scheduling tick)
    # carrying EVERY sub-batch bound for that host — at high invocation
    # QPS the per-app EXECUTE_FUNCTIONS round-trips were the planner's
    # dominant dispatch cost
    EXECUTE_BATCHES = 5


# ---------------------------------------------------------------------------
# Mock recording (reference getBatchRequests/getMessageResults)
# ---------------------------------------------------------------------------
_mock_lock = threading.Lock()
_batch_messages: list[tuple[str, BatchExecuteRequest]] = []
_message_results: list[tuple[str, Message]] = []
_flush_calls: list[str] = []


def get_batch_requests() -> list[tuple[str, BatchExecuteRequest]]:
    with _mock_lock:
        return list(_batch_messages)


def get_message_results() -> list[tuple[str, Message]]:
    with _mock_lock:
        return list(_message_results)


def get_flush_calls() -> list[str]:
    with _mock_lock:
        return list(_flush_calls)


def clear_mock_requests() -> None:
    with _mock_lock:
        _batch_messages.clear()
        _message_results.clear()
        _flush_calls.clear()


# ---------------------------------------------------------------------------

class FunctionCallClient(MessageEndpointClient):
    def __init__(self, host: str) -> None:
        super().__init__(host, FUNCTION_CALL_ASYNC_PORT, FUNCTION_CALL_SYNC_PORT)

    def execute_functions(self, req: BatchExecuteRequest) -> None:
        if is_mock_mode():
            with _mock_lock:
                _batch_messages.append((self.host, req))
            return
        header, tail = ber_to_wire(req)
        self.async_send(int(FunctionCalls.EXECUTE_FUNCTIONS), header, tail)

    def execute_functions_many(self,
                               reqs: list[BatchExecuteRequest]) -> None:
        """Pipelined dispatch: every sub-batch bound for this host in
        ONE async RPC (one frame, one kernel round-trip) instead of one
        EXECUTE_FUNCTIONS per app. Wire shape: per-request headers ride
        a ``bers`` list with per-request tail lengths; the binary tails
        are concatenated in order."""
        if not reqs:
            return
        if len(reqs) == 1:
            self.execute_functions(reqs[0])
            return
        if is_mock_mode():
            with _mock_lock:
                for req in reqs:
                    _batch_messages.append((self.host, req))
            return
        from faabric_tpu.proto import bers_to_wire

        header, tail = bers_to_wire(reqs)
        self.async_send(int(FunctionCalls.EXECUTE_BATCHES), header, tail)

    def set_message_result(self, msg: Message) -> None:
        if is_mock_mode():
            with _mock_lock:
                _message_results.append((self.host, msg))
            return
        header, tail = _message_to_wire(msg)
        self.async_send(int(FunctionCalls.SET_MESSAGE_RESULT), header, tail)

    def send_flush(self) -> None:
        if is_mock_mode():
            with _mock_lock:
                _flush_calls.append(self.host)
            return
        self.sync_send(int(FunctionCalls.FLUSH))

    def get_telemetry(self, include_trace: bool = False,
                      blocks: tuple[str, ...] | None = None) -> dict:
        """This host's local metrics snapshot (and optionally its trace
        buffer) — the wire the planner aggregates ``GET /metrics`` and
        ``GET /trace`` from. ``blocks`` narrows the response to the
        named blocks (e.g. ``("timeseries",)`` for the continuously
        polled trend surface — a trend poll must not pay for the full
        metrics + comm-matrix + perf payload per host per tick)."""
        if is_mock_mode():
            return {"metrics": {}, "trace": []}
        header: dict = {"trace": bool(include_trace)}
        if blocks is not None:
            header["blocks"] = list(blocks)
        resp = self.sync_send(int(FunctionCalls.GET_TELEMETRY), header,
                              idempotent=True)
        import json as _json

        return _json.loads(resp.payload.decode()) if resp.payload else {}


def _device_planes_block() -> list:
    from faabric_tpu.device_plane.plane import device_planes_summary

    return device_planes_summary()


def _message_to_wire(msg: Message) -> tuple[dict, bytes]:
    from faabric_tpu.proto import messages_to_wire

    dicts, tail = messages_to_wire([msg])
    return {"msg": dicts[0]}, tail


def _message_from_wire(header: dict, tail: bytes) -> Message:
    from faabric_tpu.proto import messages_from_wire

    return messages_from_wire([header["msg"]], tail)[0]


class FunctionCallServer(MessageEndpointServer):
    def __init__(self, scheduler: "Scheduler") -> None:
        conf = get_system_config()
        offset = get_host_alias_offset(scheduler.host)
        super().__init__(
            FUNCTION_CALL_ASYNC_PORT + offset,
            FUNCTION_CALL_SYNC_PORT + offset,
            label=f"function-server-{scheduler.host}",
            n_threads=conf.function_server_threads,
        )
        self.scheduler = scheduler

    def do_async_recv(self, msg: TransportMessage) -> None:
        code = msg.code
        if code == int(FunctionCalls.EXECUTE_FUNCTIONS):
            req = ber_from_wire(msg.header, msg.payload)
            self.scheduler.execute_batch(req)
        elif code == int(FunctionCalls.EXECUTE_BATCHES):
            # Pipelined dispatch: unpack each sub-batch and hand it to
            # the scheduler in arrival order (execute_batch only
            # enqueues onto executor pools, so one big frame does not
            # hold the server worker hostage). Per-sub-batch isolation:
            # one raising execute_batch (e.g. an executor factory
            # blowing up) must not silently drop the frame's REMAINING
            # apps — the planner already recorded them as dispatched
            # and nothing else would ever run them.
            from faabric_tpu.proto import bers_from_wire

            for req in bers_from_wire(msg.header, msg.payload):
                try:
                    self.scheduler.execute_batch(req)
                except Exception:  # noqa: BLE001
                    logger.exception(
                        "Pipelined sub-batch (app %d) failed; "
                        "continuing with the rest of the frame",
                        req.app_id)
        elif code == int(FunctionCalls.SET_MESSAGE_RESULT):
            result = _message_from_wire(msg.header, msg.payload)
            self.scheduler.planner_client.set_message_result_locally(result)
        else:
            logger.warning("Unknown async function call %d", code)

    def do_sync_recv(self, msg: TransportMessage) -> TransportMessage:
        if msg.code == int(FunctionCalls.FLUSH):
            self.scheduler.flush()
            return handler_response()
        if msg.code == int(FunctionCalls.GET_TELEMETRY):
            import json as _json

            from faabric_tpu.telemetry import (
                get_comm_matrix,
                get_lifecycle_stats,
                get_metrics,
                get_proc_stats,
                get_timeseries,
                perf_telemetry_block,
                profile_telemetry_block,
                statestats_telemetry_block,
                trace_events,
            )

            # Fresh process gauges on every scrape (ISSUE 14 satellite)
            get_proc_stats().refresh()
            # Lazy per-block builders: a blocks-narrowed request (the
            # continuously polled /timeseries trend surface) must not
            # pay for the full metrics/comm-matrix/perf serialization
            builders = {
                "metrics": lambda: get_metrics().snapshot(),
                "commmatrix": lambda: get_comm_matrix().snapshot(),
                # ISSUE 12: this host's rolling link profiles +
                # collective phase series (GET /perf)
                "perf": perf_telemetry_block,
                # ISSUE 14: lifecycle digest (mostly planner-side, but
                # workers fold nothing and ship an empty block) + this
                # host's time-series ring
                "lifecycle": lambda: get_lifecycle_stats().snapshot(),
                "timeseries": lambda: get_timeseries().snapshot(),
                # ISSUE 15: this host's live device-plane summaries
                # (executable-cache stats + copy accounting) for the
                # planner's GET /topology device block
                "device_planes": _device_planes_block,
                # ISSUE 16: this host's per-key state access ledger +
                # snapshot lifecycle stats (planner GET /statemap)
                "statestats": statestats_telemetry_block,
                # ISSUE 18: this host's stack-sampler trie + GIL gauge
                # (planner GET /profile)
                "profile": profile_telemetry_block,
            }
            wanted = msg.header.get("blocks")
            body: dict = {name: build() for name, build in
                          builders.items()
                          if wanted is None or name in wanted}
            if msg.header.get("trace"):
                body["trace"] = trace_events()
            # Payload, not header: a full trace buffer is bulk data
            return handler_response(payload=_json.dumps(body).encode())
        raise ValueError(f"Unknown sync function call {msg.code}")
