"""Function chaining: guests invoking further functions.

Reference analog: the chained-call capability (README capability list;
Faasm's chainedCall via PlannerClient::callFunctions with the parent's app
id, plus util/ExecGraph logChainedFunction). A chained call is a
SCALE_CHANGE on the running app; the child's message id is recorded on the
parent so exec graphs reconstruct the call tree
(reference include/faabric/util/ExecGraph.h:19-48).
"""

from __future__ import annotations

from faabric_tpu.batch_scheduler.decision import is_sentinel_decision
from faabric_tpu.proto import BatchExecuteRequest, Message, message_factory
from faabric_tpu.util.exec_graph import log_chained_function
from faabric_tpu.util.logging import get_logger

logger = get_logger(__name__)


def chain_function(function: str, input_data: bytes = b"",
                   user: str = "") -> int:
    """Invoke ``function`` as a chained call of the currently executing
    task. Returns the chained message id (await it with
    ``await_chained``)."""
    from faabric_tpu.executor.context import ExecutorContext

    ctx = ExecutorContext.get()
    parent = ctx.msg
    executor = ctx.executor
    planner_client = executor.scheduler.planner_client

    req = BatchExecuteRequest(
        app_id=parent.app_id, user=user or parent.user, function=function)
    child = message_factory(user or parent.user, function)
    child.app_id = parent.app_id
    child.input_data = input_data
    child.record_exec_graph = parent.record_exec_graph
    req.messages = [child]

    decision = planner_client.call_functions(req)
    if is_sentinel_decision(decision):
        # The child was never dispatched (no slots / frozen): fail fast
        # instead of letting await_chained time out on a ghost id
        raise RuntimeError(
            f"Chained call {function} could not be scheduled "
            f"(decision {decision.app_id})")

    # Record the chain on the parent for exec-graph reconstruction
    log_chained_function(parent, child.id)
    executor.add_chained_message(child)
    logger.debug("Chained %s/%s (%d) from parent %d", child.user,
                 child.function, child.id, parent.id)
    return child.id


def await_chained(msg_id: int, timeout: float | None = None) -> Message:
    """Block on a chained call's result (the guest-side analog of
    awaitChainedCall)."""
    from faabric_tpu.executor.context import ExecutorContext

    ctx = ExecutorContext.get()
    planner_client = ctx.executor.scheduler.planner_client
    return planner_client.get_message_result(ctx.msg.app_id, msg_id,
                                             timeout=timeout)
