"""Per-host scheduler + function-call RPC (reference src/scheduler)."""

from faabric_tpu.scheduler.function_call import (
    FunctionCallClient,
    FunctionCalls,
    FunctionCallServer,
    clear_mock_requests,
    get_batch_requests,
    get_flush_calls,
    get_message_results,
)
from faabric_tpu.scheduler.scheduler import Scheduler

__all__ = [
    "FunctionCallClient",
    "FunctionCallServer",
    "FunctionCalls",
    "Scheduler",
    "clear_mock_requests",
    "get_batch_requests",
    "get_flush_calls",
    "get_message_results",
]

from faabric_tpu.scheduler.chain import await_chained, chain_function  # noqa: E402

__all__ += ["await_chained", "chain_function"]
