"""World registry + per-message MPI context.

Reference analog: src/mpi/MpiWorldRegistry.cpp:13-75 (createWorld for
rank 0 vs getOrInitialiseWorld for other ranks) and src/mpi/MpiContext.cpp
:14-50. Instantiable per worker runtime (like the broker/scheduler) so
in-process multi-host tests can run one registry per logical host.
"""

from __future__ import annotations

import threading
from typing import Optional

from faabric_tpu.mpi.world import MpiWorld
from faabric_tpu.proto import BatchExecuteRequest, Message, batch_exec_factory
from faabric_tpu.util.logging import get_logger

logger = get_logger(__name__)


class MpiWorldRegistry:
    # Concurrency contract (tools/concheck.py): world creation/join/
    # destroy race across executor threads; the id map is the shared
    # state (reservation under the lock is what makes duplicate create
    # fail instead of double-chaining ranks).
    GUARDS = {"_worlds": "_lock"}

    def __init__(self, broker, planner_client=None) -> None:
        self.broker = broker
        self.planner_client = planner_client
        self._lock = threading.Lock()
        self._worlds: dict[int, MpiWorld] = {}

    # ------------------------------------------------------------------
    def create_world(self, msg: Message, world_size: int | None = None) -> MpiWorld:
        """Rank 0 creates the world: chain (size-1) functions through the
        planner so every rank gets scheduled, a group, a chip, and an MPI
        port (reference MpiWorld::create :157-226)."""
        size = world_size or msg.mpi_world_size
        if size <= 0:
            raise ValueError(f"Invalid MPI world size {size}")
        world_id = msg.mpi_world_id
        with self._lock:
            # Reserve the id under the lock: a concurrent duplicate create
            # must fail here, not double-chain ranks through the planner
            if world_id in self._worlds:
                raise ValueError(f"World {world_id} already exists")
            self._worlds[world_id] = None  # type: ignore[assignment]

        try:
            if size > 1:
                if self.planner_client is None:
                    raise RuntimeError("No planner client to chain MPI ranks")
                req = BatchExecuteRequest(
                    app_id=msg.app_id, user=msg.user, function=msg.function)
                for rank in range(1, size):
                    chained = batch_exec_factory(msg.user, msg.function,
                                                 1).messages[0]
                    chained.app_id = msg.app_id
                    chained.app_idx = rank
                    chained.group_idx = rank
                    chained.is_mpi = True
                    chained.mpi_world_id = world_id
                    chained.mpi_world_size = size
                    chained.mpi_rank = rank
                    req.messages.append(chained)
                decision = self.planner_client.call_functions(req)
                group_id = decision.group_id or msg.group_id
            else:
                group_id = msg.group_id

            world = MpiWorld(self.broker, world_id, size, group_id,
                             user=msg.user, function=msg.function)
            world.record_exec_graph = msg.record_exec_graph
        except BaseException:
            with self._lock:
                if self._worlds.get(world_id) is None:
                    self._worlds.pop(world_id, None)
            raise
        with self._lock:
            if world_id not in self._worlds:
                # clear() swept the registry (worker teardown) while we
                # were chaining ranks: don't resurrect a world into a
                # dead registry
                world.close()
                raise RuntimeError(
                    f"Registry cleared while creating world {world_id}")
            self._worlds[world_id] = world
        logger.debug("Created MPI world %d (size=%d group=%d)", world_id,
                     size, group_id)
        return world

    def get_or_initialise_world(self, msg: Message) -> MpiWorld:
        """Non-zero ranks join from their dispatched message (reference
        getOrInitialiseWorld :54-75 — idempotent per host)."""
        with self._lock:
            world = self._worlds.get(msg.mpi_world_id)
            # A None entry is a reservation by an in-progress create_world
            # on this host; joining ranks build their own view
            if world is None:
                world = MpiWorld(self.broker, msg.mpi_world_id,
                                 msg.mpi_world_size, msg.group_id,
                                 user=msg.user, function=msg.function)
                world.record_exec_graph = msg.record_exec_graph
                if self._worlds.get(msg.mpi_world_id) is None \
                        and msg.mpi_world_id in self._worlds:
                    # keep the creator's reservation authoritative
                    return world
                self._worlds[msg.mpi_world_id] = world
            return world

    def get_world(self, world_id: int) -> MpiWorld:
        with self._lock:
            return self._worlds[world_id]

    def has_world(self, world_id: int) -> bool:
        with self._lock:
            return world_id in self._worlds

    def destroy_world(self, world_id: int) -> None:
        with self._lock:
            world = self._worlds.pop(world_id, None)
        if world is not None:
            world.close()
            self.broker.clear_group(world.group_id)

    def clear(self) -> None:
        with self._lock:
            worlds, self._worlds = dict(self._worlds), {}
        for w in worlds.values():
            if w is not None:  # None = create_world's in-flight reservation
                w.close()


class MpiContext:
    """Per-executing-message MPI binding (reference MpiContext.cpp:14-50)."""

    def __init__(self, registry: MpiWorldRegistry) -> None:
        self.registry = registry
        self.world_id = 0
        self.rank = -1
        self._world: Optional[MpiWorld] = None

    def create_world(self, msg: Message, world_size: int | None = None) -> MpiWorld:
        if msg.mpi_rank != 0:
            raise ValueError("Only rank 0 creates the world")
        self._world = self.registry.create_world(msg, world_size)
        self.world_id = self._world.id
        self.rank = 0
        return self._world

    def join_world(self, msg: Message) -> MpiWorld:
        self._world = self.registry.get_or_initialise_world(msg)
        self.world_id = self._world.id
        self.rank = msg.mpi_rank
        return self._world

    @property
    def world(self) -> MpiWorld:
        if self._world is None:
            raise RuntimeError("MPI context not initialised")
        return self._world

    def is_mpi(self) -> bool:
        return self._world is not None


def get_mpi_context() -> MpiContext:
    """Build an MPI context for the currently executing task, using the
    host's broker/registry (guest-code entry point)."""
    from faabric_tpu.executor.context import ExecutorContext

    ctx = ExecutorContext.get()
    scheduler = ctx.executor.scheduler
    registry = getattr(scheduler, "mpi_registry", None)
    if registry is None:
        raise RuntimeError("This host has no MPI registry")
    return MpiContext(registry)
