"""MPI datatypes, reduce ops and message framing.

Reference analog: include/faabric/mpi/mpi.h (datatype/op singletons,
597 lines) and include/faabric/mpi/MpiMessage.h:8-68 (the 40-byte POD
header {id, worldId, sendRank, recvRank, typeSize, count, requestId,
messageType}).

Buffers are numpy arrays end-to-end: typed reduce loops become numpy
ufuncs on the host path and jax.lax collectives on the device path.
"""

from __future__ import annotations

import dataclasses
import enum
import struct

import numpy as np


class MpiDataType(enum.IntEnum):
    # mirror of faabric_datatype_t (mpi.h)
    INT8 = 1
    INT16 = 2
    INT32 = 3
    INT = 4
    INT64 = 5
    UINT8 = 6
    UINT16 = 7
    UINT32 = 8
    UINT = 9
    UINT64 = 10
    LONG = 11
    LONG_LONG = 12
    LONG_LONG_INT = 13
    FLOAT = 14
    DOUBLE = 15
    DOUBLE_INT = 16
    CHAR = 17
    C_BOOL = 18
    BYTE = 19


# MPI_DOUBLE_INT: (value, index) pairs for MINLOC/MAXLOC (mpi.h's
# struct { double val; int rank; })
DOUBLE_INT_DTYPE = np.dtype([("val", "<f8"), ("loc", "<i4")])

_NP_DTYPES: dict[int, np.dtype] = {
    MpiDataType.INT8: np.dtype(np.int8),
    MpiDataType.INT16: np.dtype(np.int16),
    MpiDataType.INT32: np.dtype(np.int32),
    MpiDataType.INT: np.dtype(np.int32),
    MpiDataType.INT64: np.dtype(np.int64),
    MpiDataType.UINT8: np.dtype(np.uint8),
    MpiDataType.UINT16: np.dtype(np.uint16),
    MpiDataType.UINT32: np.dtype(np.uint32),
    MpiDataType.UINT: np.dtype(np.uint32),
    MpiDataType.UINT64: np.dtype(np.uint64),
    MpiDataType.LONG: np.dtype(np.int64),
    MpiDataType.LONG_LONG: np.dtype(np.int64),
    MpiDataType.LONG_LONG_INT: np.dtype(np.int64),
    MpiDataType.FLOAT: np.dtype(np.float32),
    MpiDataType.DOUBLE: np.dtype(np.float64),
    MpiDataType.DOUBLE_INT: DOUBLE_INT_DTYPE,
    MpiDataType.CHAR: np.dtype(np.uint8),
    MpiDataType.C_BOOL: np.dtype(np.uint8),
    MpiDataType.BYTE: np.dtype(np.uint8),
}


def np_dtype_for(dtype: MpiDataType) -> np.dtype:
    return _NP_DTYPES[dtype]


# Reverse lookup: first writer wins, so aliased entries (INT32/INT, …)
# resolve to the canonical MPI code — the same answer the original
# linear scan produced, minus the per-message scan cost
_MPI_FOR_NP: dict[np.dtype, MpiDataType] = {}
for _mpi_t, _np_t in _NP_DTYPES.items():
    _MPI_FOR_NP.setdefault(_np_t, MpiDataType(_mpi_t))


def mpi_dtype_for(np_dtype: np.dtype) -> MpiDataType:
    try:
        return _MPI_FOR_NP[np_dtype]
    except (KeyError, TypeError):
        pass
    mpi_t = _MPI_FOR_NP.get(np.dtype(np_dtype))
    if mpi_t is None:
        raise ValueError(f"No MPI datatype for numpy {np_dtype}")
    return mpi_t


class MpiOp(enum.IntEnum):
    # mirror of faabric_op_t
    MAX = 1
    MIN = 2
    SUM = 3
    PROD = 4
    LAND = 5
    LOR = 6
    BAND = 7
    BOR = 8
    MAXLOC = 9
    MINLOC = 10


_NP_OPS = {
    MpiOp.MAX: np.maximum,
    MpiOp.MIN: np.minimum,
    MpiOp.SUM: np.add,
    MpiOp.PROD: np.multiply,
    MpiOp.LAND: np.logical_and,
    MpiOp.LOR: np.logical_or,
    MpiOp.BAND: np.bitwise_and,
    MpiOp.BOR: np.bitwise_or,
}


def _minmaxloc(op: MpiOp, a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """MINLOC/MAXLOC over (val, loc) structured pairs: pick the extreme
    value; ties resolve to the lower index (MPI semantics)."""
    if a.dtype.names != ("val", "loc"):
        raise TypeError(
            f"{op.name} needs DOUBLE_INT (val, loc) pairs, got {a.dtype}")
    if op == MpiOp.MINLOC:
        pick_b = (b["val"] < a["val"]) | \
            ((b["val"] == a["val"]) & (b["loc"] < a["loc"]))
    else:
        pick_b = (b["val"] > a["val"]) | \
            ((b["val"] == a["val"]) & (b["loc"] < a["loc"]))
    out = a.copy()
    out[pick_b] = b[pick_b]
    return out


class UserOp:
    """User-defined reduction (MPI_Op_create analog — the reference's
    native shim throws notImplemented for it; here ``fn(a, b) -> array``
    plugs into every host-path collective: reduce/allreduce/scan/
    reduce_scatter). ``commute=False`` is accepted and recorded; the
    leader-tree reduction applies contributions in rank order within
    each level, which is what non-commutative ops get from the
    reference's linear loops too."""

    __slots__ = ("fn", "commute", "name")

    def __init__(self, fn, commute: bool = True,
                 name: str = "user_op") -> None:
        self.fn = fn
        self.commute = commute
        self.name = name

    def __repr__(self) -> str:  # pragma: no cover — debugging nicety
        return f"UserOp({self.name})"


def apply_op(op: MpiOp, a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Typed reduce (reference MpiWorld::op_reduce:1266-1388 — there hand
    rolled loops per dtype; numpy ufuncs vectorise the same semantics)."""
    if isinstance(op, UserOp):
        return np.asarray(op.fn(a, b)).astype(a.dtype, copy=False)
    if op in (MpiOp.MINLOC, MpiOp.MAXLOC):
        return _minmaxloc(op, a, b)
    fn = _NP_OPS.get(op)
    if fn is None:
        raise NotImplementedError(f"MPI op {op} not supported")
    out = fn(a, b)
    return out.astype(a.dtype, copy=False)


def apply_op_inplace(op: MpiOp, acc: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Accumulate ``b`` into ``acc`` without allocating when the ufunc's
    result dtype matches (the reduce-tree hot path: one fewer buffer per
    received contribution)."""
    fn = _NP_OPS.get(op)
    if (fn is not None and acc.flags.writeable and acc.dtype == b.dtype
            and op in (MpiOp.SUM, MpiOp.PROD, MpiOp.MAX,
                       MpiOp.MIN, MpiOp.BAND, MpiOp.BOR)):
        fn(acc, b, out=acc)
        return acc
    # Non-ufunc ops (MINLOC/MAXLOC) and dtype mismatches allocate
    return apply_op(op, acc, b)


class MpiMessageType(enum.IntEnum):
    # mirror of MpiMessage.h MpiMessageType
    NORMAL = 0
    BARRIER_JOIN = 1
    BARRIER_DONE = 2
    SCATTER = 3
    GATHER = 4
    ALLGATHER = 5
    REDUCE = 6
    SCAN = 7
    ALLREDUCE = 8
    ALLTOALL = 9
    ALLTOALL_PACKED = 10
    SENDRECV = 11
    BROADCAST = 12
    UNACKED = 13
    HANDSHAKE = 14
    # Extension beyond the reference enum: announces a chunk-pipelined
    # broadcast stream ([n_chunks, total_elems, dtype_code] int64) so
    # receivers follow the sender's chunking decision instead of having
    # to replicate it from local (possibly size-less) templates
    CHUNK_HEADER = 100


# Wire header for MPI payloads riding PTP: type u8, dtype u8, pad u16,
# count u64, request_id i64 (a compact analog of the reference's POD header)
MPI_HEADER_FMT = "<BBHQq"
MPI_HEADER_LEN = struct.calcsize(MPI_HEADER_FMT)


@dataclasses.dataclass
class MpiStatus:
    source: int = 0
    error: int = 0
    count: int = 0
    dtype: int = int(MpiDataType.BYTE)


def pack_mpi_payload(msg_type: MpiMessageType, data: np.ndarray,
                     request_id: int = 0) -> bytes:
    data = np.ascontiguousarray(data)
    head = struct.pack(MPI_HEADER_FMT, int(msg_type),
                       int(mpi_dtype_for(data.dtype)), 0, data.size,
                       request_id)
    return head + data.tobytes()


class MpiWirePayload:
    """Lazily-serialized MPI payload: header and array buffer stay
    separate so the bulk data plane can hand them to the kernel without
    a 100 MiB concatenation (reference analog: writev in
    tcp::SendSocket::sendOne). ``to_bytes()`` materializes for the RPC
    plane / mock recording."""

    __slots__ = ("head", "arr")

    def __init__(self, msg_type: MpiMessageType, data: np.ndarray,
                 request_id: int = 0) -> None:
        self.arr = np.ascontiguousarray(data)
        self.head = struct.pack(MPI_HEADER_FMT, int(msg_type),
                                int(mpi_dtype_for(self.arr.dtype)), 0,
                                self.arr.size, request_id)

    def __len__(self) -> int:
        return len(self.head) + self.arr.nbytes

    def buffers(self) -> list:
        return [self.head,
                memoryview(self.arr.reshape(-1).view(np.uint8))]

    def to_bytes(self) -> bytes:
        return self.head + self.arr.tobytes()


def unpack_mpi_payload(raw) -> tuple[MpiMessageType, np.ndarray, int]:
    msg_type, dtype, _, count, request_id = struct.unpack(
        MPI_HEADER_FMT, bytes(raw[:MPI_HEADER_LEN]))
    arr = np.frombuffer(raw, dtype=np_dtype_for(MpiDataType(dtype)),
                        count=count, offset=MPI_HEADER_LEN)
    # A bytearray / uint8 ndarray is exclusively owned by this frame
    # (bulk plane recv buffer): wrap it writable with no copy. Immutable
    # bytes (shared RPC plane) still copy so callers get a caller-owned
    # writable array.
    if not isinstance(raw, (bytearray, np.ndarray)):
        arr = arr.copy()
    return MpiMessageType(msg_type), arr, request_id
