"""Schedule lowerings + perf-store-driven selection (ISSUE 13).

Compiles (collective, Topology) into the verified step programs of
``mpi/schedule.py``. Families:

- ``alltoall.flat``   — direct pairwise exchange (the seed-era naive
  pattern, expressed as a schedule so the generic runner replaces the
  hand-written loop).
- ``alltoall.hier``   — locality-aware leader composition (the
  disabled-in-faabric packed variant, SURVEY §MPI): local blocks swap
  in process, remote blocks gather to the local leader per destination
  host, leaders exchange ONE packed host-block per host pair, then
  redistribute in process. Cross-host **bytes are invariant** (alltoall
  is a permutation — every remote block must cross exactly once on any
  algorithm); what the composition cuts is cross-host **messages**:
  H·(H−1) instead of Σ m_A·m_B ≈ 1/ranks-per-host² of naive, the
  per-message latency + per-link framing the perf store's slow links
  pay for.
- ``scatter.flat`` / ``scatter.tree`` — root-direct vs root→leaders→
  local fan-out (one wire message per remote host instead of one per
  remote rank; scatterv binds split sizes through an int64 count-vector
  header block so leaders can split without a planner round-trip).
- ``scan.chain``      — the reference linear chain (byte-optimal; the
  runner path adds the telemetry the hand-written one never had).
- ``scan.hier``       — contiguous (gang) placements only: intra-host
  chains + a carrier chain between hosts + local carry fix-up; serial
  path ≈ ranks/host + hosts instead of N.
- ``allreduce.hier`` / ``reduce_scatter.hier`` / ``allgather.hier`` —
  schedule twins of the hand-written hierarchical paths (intra-host
  fold/gather to the leader, leader ring / pairwise host-block
  exchange, in-process redistribute), bitwise-pinned against them in
  tests. The tuned zero-copy hand-written paths stay the default
  executors; the lowerings prove the IR covers them and are selectable
  under ``FAABRIC_SCHED_COLLECTIVES=force`` + ``world.sched_reductions``.

Selection (``choose_family``) is the perf-introspection consumer the
ROADMAP promised: measured per-link GiB/s from
``get_perf_store().link_gibs`` (big-frame evidence, like the wire-codec
governor), comm-matrix window as the unmeasured-link fallback, and an
assume-slow default — slow or unmeasured cross-machine links pick the
composed families (fewer, bigger messages), links measured faster than
``FAABRIC_SCHED_FAST_GIBS`` keep the flat schedules (the extra
gather/redistribute copies outweigh message savings on loopback-class
links). The verdict is computed on rank 0 only and broadcast by the
selection-sync round in ``MpiWorld._sched_family`` — per-process perf
stores measure different links, so a locally-derived verdict could
desync the world's algorithm choice and hang the collective.
"""

from __future__ import annotations

import os

from faabric_tpu.mpi.schedule import (
    COPY,
    FOLD,
    RECV,
    SEND,
    Schedule,
    ScheduleError,
    Step,
    verify_schedule,
)

ALL = ("all",)
CNT = ("cnt",)


def BLK(j) -> tuple:
    return ("blk", j)


def SEG(i) -> tuple:
    return ("seg", i)


# Families in a stable order: the selection-sync broadcast ships the
# INDEX, so this tuple is wire protocol — append only.
FAMILIES = (
    "alltoall.flat",
    "alltoall.hier",
    "scatter.flat",
    "scatter.tree",
    "scan.chain",
    "scan.hier",
    "allreduce.hier",
    "reduce_scatter.hier",
    "allgather.hier",
    "allgather.ring",
)
FAMILY_IDS = {f: i for i, f in enumerate(FAMILIES)}

# Links measured at or above this are "fast": flat schedules win there
# (loopback/shm-class links make per-message overhead negligible next
# to the composed families' extra local copies). Below it — or
# unmeasured, the governor's assume-slow convention — the composed
# families' 1/m² message count wins.
FAST_LINK_GIBS = float(os.environ.get("FAABRIC_SCHED_FAST_GIBS", "2.0"))

# Bandwidth evidence floor, mirroring the wire-codec governor: small
# frames measure dispatch overhead, not the link.
EVIDENCE_BYTES = 1 << 20


class _Prog:
    """Per-rank step-list builder."""

    def __init__(self, size: int) -> None:
        self.size = size
        self._steps: dict[int, list[Step]] = {r: [] for r in range(size)}

    def send(self, r, peer, keys, syms, phase):
        self._steps[r].append(Step(SEND, peer=peer, keys=tuple(keys),
                                   syms=tuple(syms), phase=phase))

    def recv(self, r, peer, keys, syms, phase):
        self._steps[r].append(Step(RECV, peer=peer, keys=tuple(keys),
                                   syms=tuple(syms), phase=phase))

    def fold(self, r, dst, a, b, phase):
        self._steps[r].append(Step(FOLD, dst=dst, a=a, b=b, phase=phase))

    def copy(self, r, dst, src, phase):
        self._steps[r].append(Step(COPY, dst=dst, src=src, phase=phase))

    def build(self, name, collective, spec=None) -> Schedule:
        return Schedule(name=name, collective=collective, size=self.size,
                        steps={r: tuple(s) for r, s in self._steps.items()},
                        spec=spec or {})


# ---------------------------------------------------------------------------
# alltoall
# ---------------------------------------------------------------------------
def _alltoall_flat(topo) -> Schedule:
    n = topo.size
    p = _Prog(n)
    for r in range(n):
        for s in range(n):
            if s != r:
                p.send(r, s, [("in", s)], [BLK(s)], "exchange")
        p.copy(r, ("out", r), ("in", r), "exchange")
        for s in range(n):
            if s != r:
                p.recv(r, s, [("out", s)], [BLK(r)], "exchange")
    return p.build("alltoall.flat", "alltoall")


def _alltoall_hier(topo) -> Schedule:
    n = topo.size
    p = _Prog(n)
    hosts = list(topo.hosts)
    for host in hosts:
        locals_ = list(topo.ranks_on_host(host))
        leader = locals_[0]
        remote_hosts = [h for h in hosts if h != host]
        for r in locals_:
            # -- local blocks swap directly in process ------------------
            for s in locals_:
                if s != r:
                    p.send(r, s, [("in", s)], [BLK(s)], "local")
            p.copy(r, ("out", r), ("in", r), "local")
            for s in locals_:
                if s != r:
                    p.recv(r, s, [("out", s)], [BLK(r)], "local")
            # -- remote blocks gather to the leader, per dest host ------
            if r != leader:
                for h in remote_hosts:
                    dsts = topo.ranks_on_host(h)
                    p.send(r, leader, [("in", s) for s in dsts],
                           [BLK(s) for s in dsts], "intra")
        for r2 in locals_[1:]:
            for h in remote_hosts:
                dsts = topo.ranks_on_host(h)
                p.recv(leader, r2,
                       [("tmp", ("g", r2, s)) for s in dsts],
                       [BLK(s) for s in dsts], "intra")

        # -- leaders exchange ONE packed block per host pair ------------
        def _gkey(src_rank, dst_rank):
            return (("in", dst_rank) if src_rank == leader
                    else ("tmp", ("g", src_rank, dst_rank)))

        for h in remote_hosts:
            dsts = topo.ranks_on_host(h)
            keys = [_gkey(r2, s) for r2 in locals_ for s in dsts]
            syms = [BLK(s) for _ in locals_ for s in dsts]
            p.send(leader, topo.ranks_on_host(h)[0], keys, syms, "leader")
        for h in remote_hosts:
            srcs = topo.ranks_on_host(h)
            keys = [("tmp", ("x", r2, s)) for r2 in srcs for s in locals_]
            syms = [BLK(s) for _ in srcs for s in locals_]
            p.recv(leader, srcs[0], keys, syms, "leader")

        # -- leaders redistribute in process ----------------------------
        remote_ranks = [r2 for h in remote_hosts
                        for r2 in topo.ranks_on_host(h)]
        for s in locals_[1:]:
            p.send(leader, s, [("tmp", ("x", r2, s)) for r2 in remote_ranks],
                   [BLK(s) for _ in remote_ranks], "redistribute")
        for r2 in remote_ranks:
            p.copy(leader, ("out", r2), ("tmp", ("x", r2, leader)),
                   "redistribute")
        for s in locals_[1:]:
            p.recv(s, leader, [("out", r2) for r2 in remote_ranks],
                   [BLK(s) for _ in remote_ranks], "redistribute")
    return p.build("alltoall.hier", "alltoall")


# ---------------------------------------------------------------------------
# scatter / scatterv
# ---------------------------------------------------------------------------
def _scatter_flat(topo, collective: str, root: int) -> Schedule:
    n = topo.size
    p = _Prog(n)
    for s in range(n):
        if s == root:
            continue
        p.send(root, s, [("in", s)], [BLK(s)], "scatter")
    p.copy(root, ("out", 0), ("in", root), "scatter")
    for s in range(n):
        if s != root:
            p.recv(s, root, [("out", 0)], [BLK(s)], "scatter")
    return p.build("scatter.flat", collective, {"root": root})


def _scatter_tree(topo, collective: str, root: int) -> Schedule:
    n = topo.size
    p = _Prog(n)
    root_host = topo.host_of(root)
    counts_header = collective == "scatterv"
    spec = {"root": root}
    if counts_header:
        spec["counts_header"] = True
    for host in topo.hosts:
        locals_ = list(topo.ranks_on_host(host))
        leader = locals_[0]
        if host == root_host:
            # Root is its own host's fan-out point, leader or not
            for s in locals_:
                if s != root:
                    p.send(root, s, [("in", s)], [BLK(s)], "local")
            p.copy(root, ("out", 0), ("in", root), "local")
            for s in locals_:
                if s != root:
                    p.recv(s, root, [("out", 0)], [BLK(s)], "local")
            continue
        # The count-vector header precedes the packed bundle so the
        # leader can split it (scatterv leaders have no count vector)
        if counts_header and len(locals_) > 1:
            p.send(root, leader, [("in", "cnt")], [CNT], "header")
            p.recv(leader, root, [("tmp", "cnt")], [CNT], "header")
        p.send(root, leader, [("in", s) for s in locals_],
               [BLK(s) for s in locals_], "tree")
        p.recv(leader, root, [("tmp", ("s", s)) for s in locals_],
               [BLK(s) for s in locals_], "tree")
        p.copy(leader, ("out", 0), ("tmp", ("s", leader)), "fanout")
        for s in locals_[1:]:
            p.send(leader, s, [("tmp", ("s", s))], [BLK(s)], "fanout")
            p.recv(s, leader, [("out", 0)], [BLK(s)], "fanout")
    return p.build("scatter.tree", collective, spec)


# ---------------------------------------------------------------------------
# scan
# ---------------------------------------------------------------------------
def _scan_chain(topo) -> Schedule:
    n = topo.size
    p = _Prog(n)
    for r in range(n):
        if r == 0:
            p.copy(r, ("out", 0), ("in", 0), "chain")
        else:
            p.recv(r, r - 1, [("tmp", "p")], [ALL], "chain")
            # Operand order (prefix, mine) — the reference chain's
            # apply_op(op, prev, data), so non-commutative user ops and
            # float folds stay bit-identical to the legacy path
            p.fold(r, ("out", 0), ("tmp", "p"), ("in", 0), "chain")
        if r < n - 1:
            p.send(r, r + 1, [("out", 0)], [ALL], "chain")
    return p.build("scan.chain", "scan")


def _scan_hier(topo) -> Schedule:
    if not topo.hosts_contiguous():
        raise ScheduleError("scan.hier needs gang-contiguous placement")
    n = topo.size
    p = _Prog(n)
    host_runs = [list(topo.ranks_on_host(h)) for h in topo.hosts]
    # Contiguity gives each host one rank run; prefix order needs the
    # runs sorted by their first rank (host first-appearance order
    # already is, but make it explicit)
    host_runs.sort(key=lambda run: run[0])
    carriers = [run[-1] for run in host_runs]
    for hi, run in enumerate(host_runs):
        for i, r in enumerate(run):
            # -- intra-host prefix chain --------------------------------
            if i == 0:
                p.copy(r, ("tmp", "acc"), ("in", 0), "intra")
            else:
                p.recv(r, run[i - 1], [("tmp", "lp")], [ALL], "intra")
                p.fold(r, ("tmp", "acc"), ("tmp", "lp"), ("in", 0),
                       "intra")
            if i < len(run) - 1:
                p.send(r, run[i + 1], [("tmp", "acc")], [ALL], "intra")
        carrier = carriers[hi]
        # -- carrier chain between hosts --------------------------------
        if hi == 0:
            p.copy(carrier, ("out", 0), ("tmp", "acc"), "leader")
        else:
            p.recv(carrier, carriers[hi - 1], [("tmp", "carry")], [ALL],
                   "leader")
            p.fold(carrier, ("out", 0), ("tmp", "carry"), ("tmp", "acc"),
                   "leader")
        if hi < len(host_runs) - 1:
            p.send(carrier, carriers[hi + 1], [("out", 0)], [ALL],
                   "leader")
        # -- carry fix-up for the host's other ranks --------------------
        for r in run[:-1]:
            if hi == 0:
                p.copy(r, ("out", 0), ("tmp", "acc"), "redistribute")
            else:
                p.send(carrier, r, [("tmp", "carry")], [ALL],
                       "redistribute")
                p.recv(r, carrier, [("tmp", "carry")], [ALL],
                       "redistribute")
                p.fold(r, ("out", 0), ("tmp", "carry"), ("tmp", "acc"),
                       "redistribute")
    return p.build("scan.hier", "scan")


# ---------------------------------------------------------------------------
# Hierarchical reductions — schedule twins of the hand-written paths
# ---------------------------------------------------------------------------
def _allreduce_hier(topo) -> Schedule:
    n = topo.size
    leaders = list(topo.leaders)
    nh = len(leaders)
    if nh < 2:
        raise ScheduleError("allreduce.hier needs multiple hosts")
    segs = nh
    p = _Prog(n)
    seg_keys = [("tmp", ("acc", s)) for s in range(segs)]
    for host in topo.hosts:
        locals_ = list(topo.ranks_on_host(host))
        leader = locals_[0]
        for r in locals_[1:]:
            p.send(r, leader, [("in", s) for s in range(segs)],
                   [SEG(s) for s in range(segs)], "intra")
        for s in range(segs):
            p.copy(leader, seg_keys[s], ("in", s), "intra")
        for r in locals_[1:]:
            p.recv(leader, r, [("tmp", ("c", r, s)) for s in range(segs)],
                   [SEG(s) for s in range(segs)], "intra")
            for s in range(segs):
                p.fold(leader, seg_keys[s], ("tmp", ("c", r, s)),
                       seg_keys[s], "intra")
    # Leader ring: reduce-scatter then allgather over the segments,
    # mirroring _allreduce_ring's (received, mine) fold convention
    for pos, leader in enumerate(leaders):
        nxt = leaders[(pos + 1) % nh]
        prv = leaders[(pos - 1) % nh]
        p.send(leader, nxt, [seg_keys[pos]], [SEG(pos)], "leader")
        for t in range(nh - 1):
            q = (pos - 1 - t) % nh
            p.recv(leader, prv, [("tmp", ("r", t))], [SEG(q)], "leader")
            p.fold(leader, seg_keys[q], ("tmp", ("r", t)), seg_keys[q],
                   "leader")
            if t < nh - 2:
                p.send(leader, nxt, [seg_keys[q]], [SEG(q)], "leader")
        full = (pos + 1) % nh
        p.copy(leader, ("out", full), seg_keys[full], "leader")
        for t in range(nh - 1):
            g = (pos + 1 - t) % nh
            p.send(leader, nxt, [("out", g)], [SEG(g)], "leader")
            g2 = (pos - t) % nh
            p.recv(leader, prv, [("out", g2)], [SEG(g2)], "leader")
    for host in topo.hosts:
        locals_ = list(topo.ranks_on_host(host))
        leader = locals_[0]
        for r in locals_[1:]:
            p.send(leader, r, [("out", s) for s in range(segs)],
                   [SEG(s) for s in range(segs)], "redistribute")
            p.recv(r, leader, [("out", s) for s in range(segs)],
                   [SEG(s) for s in range(segs)], "redistribute")
    return p.build("allreduce.hier", "allreduce", {"segments": segs})


def _reduce_scatter_hier(topo) -> Schedule:
    n = topo.size
    if len(topo.hosts) < 2:
        raise ScheduleError("reduce_scatter.hier needs multiple hosts")
    p = _Prog(n)
    for host in topo.hosts:
        locals_ = list(topo.ranks_on_host(host))
        leader = locals_[0]
        remote_hosts = [h for h in topo.hosts if h != host]
        acc = {j: ("tmp", ("acc", j)) for j in range(n)}
        for r in locals_[1:]:
            p.send(r, leader, [("in", j) for j in range(n)],
                   [BLK(j) for j in range(n)], "intra")
        for j in range(n):
            p.copy(leader, acc[j], ("in", j), "intra")
        for r in locals_[1:]:
            p.recv(leader, r, [("tmp", ("c", r, j)) for j in range(n)],
                   [BLK(j) for j in range(n)], "intra")
            for j in range(n):
                p.fold(leader, acc[j], ("tmp", ("c", r, j)), acc[j],
                       "intra")
        # One packed partial per remote host: exactly that host's output
        # blocks, host-folded
        for h in remote_hosts:
            dsts = topo.ranks_on_host(h)
            p.send(leader, dsts[0], [acc[j] for j in dsts],
                   [BLK(j) for j in dsts], "leader")
        for h in remote_hosts:
            src = topo.ranks_on_host(h)[0]
            p.recv(leader, src,
                   [("tmp", ("x", src, j)) for j in locals_],
                   [BLK(j) for j in locals_], "leader")
            for j in locals_:
                p.fold(leader, acc[j], ("tmp", ("x", src, j)), acc[j],
                       "leader")
        p.copy(leader, ("out", 0), acc[leader], "redistribute")
        for s in locals_[1:]:
            p.send(leader, s, [acc[s]], [BLK(s)], "redistribute")
            p.recv(s, leader, [("out", 0)], [BLK(s)], "redistribute")
    return p.build("reduce_scatter.hier", "reduce_scatter")


def _allgather_hier(topo) -> Schedule:
    n = topo.size
    if len(topo.hosts) < 2:
        raise ScheduleError("allgather.hier needs multiple hosts")
    p = _Prog(n)
    for host in topo.hosts:
        locals_ = list(topo.ranks_on_host(host))
        leader = locals_[0]
        remote_hosts = [h for h in topo.hosts if h != host]
        for r in locals_[1:]:
            p.send(r, leader, [("in", 0)], [BLK(r)], "intra")
        p.copy(leader, ("out", leader), ("in", 0), "intra")
        for r in locals_[1:]:
            p.recv(leader, r, [("out", r)], [BLK(r)], "intra")
        # Pairwise host-block exchange between leaders
        for h in remote_hosts:
            p.send(leader, topo.ranks_on_host(h)[0],
                   [("out", r) for r in locals_],
                   [BLK(r) for r in locals_], "leader")
        for h in remote_hosts:
            srcs = topo.ranks_on_host(h)
            p.recv(leader, srcs[0], [("out", q) for q in srcs],
                   [BLK(q) for q in srcs], "leader")
        for s in locals_[1:]:
            p.send(leader, s, [("out", q) for q in range(n)],
                   [BLK(q) for q in range(n)], "redistribute")
            p.recv(s, leader, [("out", q) for q in range(n)],
                   [BLK(q) for q in range(n)], "redistribute")
    return p.build("allgather.hier", "allgather")


def _allgather_ring(topo) -> Schedule:
    """Flat shift-1 ring allgather (ISSUE 15): n−1 rounds of "send the
    block I most recently hold to my right neighbour, receive the left
    neighbour's" — the bandwidth-optimal pattern on a ring, and every
    wire leg a pure uniform-shift permute. The ``ring`` phase is
    annotated with the ``device-ring`` execution target: on an
    activated device world the runner executes each round as ONE
    compiled mesh permute (Pallas ``make_async_remote_copy`` over ICI
    on TPU, ``lax.ppermute`` elsewhere) instead of 2(n−1) host
    messages; without a device plane the same verified steps run on the
    host path unchanged. ``ring_uniform`` records the compile-time
    guarantee the target relies on: every block resolves to the same
    element count (allgather contributions are uniform by contract)."""
    n = topo.size
    if n < 2:
        raise ScheduleError("allgather.ring needs at least 2 ranks")
    p = _Prog(n)
    for r in range(n):
        p.copy(r, ("out", r), ("in", 0), "assemble")
    for step in range(n - 1):
        for r in range(n):
            seg = (r - step) % n
            p.send(r, (r + 1) % n, [("out", seg)], [BLK(seg)], "ring")
            p.recv(r, (r - 1) % n, [("out", (r - step - 1) % n)],
                   [BLK((r - step - 1) % n)], "ring")
    return p.build("allgather.ring", "allgather",
                   spec={"targets": {"ring": "device-ring"},
                         "ring_uniform": True})


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------
_LOWERINGS = {
    "alltoall.flat": lambda topo, root: _alltoall_flat(topo),
    "alltoall.hier": lambda topo, root: _alltoall_hier(topo),
    "scatter.flat": None,  # needs the collective name; see compile_schedule
    "scatter.tree": None,
    "scan.chain": lambda topo, root: _scan_chain(topo),
    "scan.hier": lambda topo, root: _scan_hier(topo),
    "allreduce.hier": lambda topo, root: _allreduce_hier(topo),
    "reduce_scatter.hier": lambda topo, root: _reduce_scatter_hier(topo),
    "allgather.hier": lambda topo, root: _allgather_hier(topo),
    "allgather.ring": lambda topo, root: _allgather_ring(topo),
}


def compile_schedule(family: str, collective: str, topo,
                     root: int = 0) -> Schedule:
    """Compile AND verify one family over one Topology. The verifier
    runs on every compile — a schedule object with ``verified`` unset
    cannot exist outside this module's negative tests."""
    if family.startswith("scatter."):
        fn = _scatter_flat if family == "scatter.flat" else _scatter_tree
        sched = fn(topo, collective, root)
    else:
        lower = _LOWERINGS.get(family)
        if lower is None:
            raise ScheduleError(f"Unknown schedule family {family!r}")
        sched = lower(topo, root)
        if sched.collective != collective:
            raise ScheduleError(
                f"{family} lowers {sched.collective}, not {collective}")
    return verify_schedule(sched)


def measured_cross_gibs(topo, my_host: str, store=None,
                        matrix=None) -> float | None:
    """Worst measured outbound bandwidth toward the topology's OTHER
    hosts: perf-profile store first (big-frame evidence), comm-matrix
    window as fallback, None when every remote link is unmeasured."""
    if store is None:
        from faabric_tpu.telemetry.perfprofile import get_perf_store

        store = get_perf_store()
    worst = None
    for host in topo.hosts:
        if host == my_host:
            continue
        gibs = store.link_gibs(host, plane="bulk-tcp",
                               min_bytes=EVIDENCE_BYTES)
        if gibs is None:
            gibs = _matrix_gibs(topo, host, matrix)
        if gibs is None:
            continue
        if worst is None or gibs < worst:
            worst = gibs
    return worst


def _matrix_gibs(topo, dst_host: str, matrix=None) -> float | None:
    """Comm-matrix window fallback for one destination host: best
    observed wire rate of any cell whose dst rank lives there."""
    if matrix is None:
        from faabric_tpu.telemetry import get_comm_matrix

        matrix = get_comm_matrix()
    snap = matrix.snapshot() or {}
    dst_ranks = {str(r) for r in topo.ranks_on_host(dst_host)}
    best = None
    for c in snap.get("cells", []):
        if c.get("plane") not in ("bulk-tcp", "shm"):
            continue
        if c.get("dst") not in dst_ranks:
            continue
        lat = c.get("lat_sum") or 0.0
        if lat <= 0:
            continue
        gibs = (c.get("bytes_raw", c.get("bytes", 0)) / lat) / (1 << 30)
        if best is None or gibs > best:
            best = gibs
    return best


def _links_slow(topo, mode, store, matrix) -> bool:
    """Assume-slow convention: unmeasured links are slow (a fresh WAN
    link must not run the copy-heavy flat schedule until a measurement
    earns it)."""
    if mode == "force":
        return True
    gibs = measured_cross_gibs(topo, topo.host_of(0), store=store,
                               matrix=matrix)
    return gibs is None or gibs < FAST_LINK_GIBS


def choose_family(collective: str, topo, nbytes: int, mode,
                  store=None, matrix=None) -> str:
    """Pick the schedule family for one (collective, Topology, payload).
    Deterministic given its inputs; the WORLD-agreed verdict is rank
    0's, distributed by the selection-sync round (per-process perf
    stores disagree, and a desynced family choice hangs the world).
    ``mode`` is the world's sched knob value (True / "force")."""
    multi_host = topo.n_hosts > 1
    if collective == "alltoall":
        if not multi_host:
            return "alltoall.flat"
        return ("alltoall.hier"
                if _links_slow(topo, mode, store, matrix)
                else "alltoall.flat")
    if collective in ("scatter", "scatterv"):
        if not multi_host:
            return "scatter.flat"
        return ("scatter.tree"
                if _links_slow(topo, mode, store, matrix)
                else "scatter.flat")
    if collective == "scan":
        if (multi_host and topo.max_ranks_per_host > 1
                and topo.hosts_contiguous()
                and _links_slow(topo, mode, store, matrix)):
            return "scan.hier"
        return "scan.chain"
    if collective in ("allreduce", "reduce_scatter", "allgather"):
        # Only reachable under force + world.sched_reductions; the flat
        # shapes keep the tuned hand-written executors. Allgather over
        # a one-rank-per-host placement (the TPU gang shape: every rank
        # its own process/chip) lowers to the flat ring whose permute
        # legs the device-ring target can execute on the mesh.
        if collective == "allgather" and topo.n_hosts == topo.size:
            return "allgather.ring"
        return f"{collective}.hier"
    raise ScheduleError(f"No schedule families for {collective!r}")


# ---------------------------------------------------------------------------
# Selftest: compile + verify every family over a topology matrix
# ---------------------------------------------------------------------------
def selftest(verbose: bool = False) -> int:
    """Compile and verify every applicable (family, topology, root)
    combination, plus a negative check that the verifier still rejects
    a corrupted schedule. Returns the number of schedules verified;
    raises on any failure. Wired into tools/check.sh."""
    from faabric_tpu.mpi.topology import Topology, interleave_hosts

    shapes = {
        "1x4": {r: "h0" for r in range(4)},
        "2x1": {0: "h0", 1: "h1"},
        "2x3-gang": {r: f"h{r // 3}" for r in range(6)},
        "4x3-scattered": interleave_hosts([f"h{i}" for i in range(4)], 12),
        "uneven-3-2-1": {0: "h0", 1: "h0", 2: "h0", 3: "h1", 4: "h1",
                         5: "h2"},
        "2x2-scattered": interleave_hosts(["h0", "h1"], 4),
    }
    verified = 0
    for label, rank_hosts in shapes.items():
        topo = Topology(rank_hosts)
        for family in FAMILIES:
            collectives = ([family.split(".")[0]]
                           if not family.startswith("scatter.")
                           else ["scatter", "scatterv"])
            for coll in collectives:
                roots = [0] if not family.startswith("scatter.") \
                    else sorted({0, topo.size - 1})
                for root in roots:
                    try:
                        compile_schedule(family, coll, topo, root=root)
                    except ScheduleError as e:
                        structural = (".hier" in family
                                      and ("multiple hosts" in str(e)
                                           or "contiguous" in str(e)))
                        if structural:
                            continue  # family not applicable to shape
                        raise
                    verified += 1
                    if verbose:
                        print(f"  ok {label:>15} {family} "
                              f"{coll} root={root}")
    # Negative check: a corrupted schedule must still be rejected
    from faabric_tpu.mpi.schedule import ScheduleVerificationError

    topo = Topology(shapes["2x3-gang"])
    sched = _alltoall_hier(topo)
    sched.steps[1] = sched.steps[1][:-1]  # drop rank 1's last step
    try:
        verify_schedule(sched)
    except ScheduleVerificationError:
        pass
    else:
        raise ScheduleError(
            "verifier accepted a corrupted schedule — selftest FAILED")
    return verified


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(prog="schedule_compile")
    parser.add_argument("--selftest", action="store_true")
    parser.add_argument("--verbose", action="store_true")
    args = parser.parse_args(argv)
    if args.selftest:
        count = selftest(verbose=args.verbose)
        print(f"schedule selftest: {count} schedule(s) compiled and "
              f"verified, corrupted schedule rejected")
        return 0
    parser.print_help()
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
