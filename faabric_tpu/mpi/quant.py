"""EQuARX-style int8 wire quantization for the leader-ring fold.

Opt-in via ``FAABRIC_ALLREDUCE_QUANT=int8`` (or per world through
``MpiWorld.allreduce_quant`` — like ``hier_enabled`` it must agree
across every process of a world, or the ring peers disagree on the
wire format and the collective hangs). When enabled, the hierarchical
collectives' LEADER ring — the only leg that crosses real machines —
sends each pipeline chunk as an int8 payload with one per-chunk fp32
scale instead of raw fp32: 4× fewer bytes on the bandwidth-bound
cross-host links (EQuARX, arXiv:2506.17615, gets near-2× allreduce
from exactly this shape of block-wise in-collective quantization).

Scope (deliberately narrow, ROADMAP item 4 groundwork):
- ALLREDUCE only, as the knob names: the hierarchical reduce_scatter's
  leader ring stays exact even with the knob on (lossy scatter slices
  under an allreduce-named knob would surprise; quantize it under its
  own knob if a later round wants it).
- fold (reduce-scatter) leg of allreduce's leader ring only. The
  trailing allgather circulation forwards the SAME folded buffers to
  every leader verbatim, so all ranks still agree bitwise on the
  (lossy) result — re-quantizing per allgather hop would compound
  error for no agreement benefit.
- ``MpiOp.SUM`` over float32 only: per-chunk scales distribute over a
  linear fold; other ops / dtypes silently keep the fp32 wire.
- intra-host phases never quantize — shm/in-process bytes are free.

Error model: one quantization event bounds per-element error by
scale/2 = max|chunk|/254; a chunk is re-quantized once per leader-ring
fold hop, so worst case grows with (H−1) and the interim magnitudes.
The bench block reports the measured ``max_abs_err`` against the exact
fp32 path (bench_host_allreduce_hier quant mode).

Wire format: one uint8 buffer per chunk — 4-byte little-endian fp32
scale, then the int8 payload bytes; a NaN scale marks the raw-fp32
passthrough form for non-finite chunks (divergence must propagate, not
quantize to garbage). Self-contained per chunk, so the chunk-pipelined
ring needs no side channel and every participant derives identical
framing from the shared chunk bounds.
"""

from __future__ import annotations

import os
import struct

import numpy as np

# Module default (process-wide); per-world override via
# MpiWorld.allreduce_quant. Values: "" (off) or "int8".
ALLREDUCE_QUANT = os.environ.get("FAABRIC_ALLREDUCE_QUANT", "").strip().lower()

_SCALE_FMT = "<f"
_SCALE_BYTES = struct.calcsize(_SCALE_FMT)


class Int8ChunkCodec:
    """Per-chunk max-abs int8 quantizer. Stateless; shared freely."""

    name = "int8"
    wire_dtype = np.uint8

    def encode(self, chunk: np.ndarray,
               quantize: bool = True) -> np.ndarray:
        """float32 chunk → private uint8 buffer [scale | int8 payload].
        The output is freshly allocated — callers may hand it to the
        transport zero-copy without freezing the source view.

        ``quantize=False`` ships the chunk in the raw-fp32 passthrough
        form (NaN-scale sentinel): the per-LINK escape the wire-codec
        governor uses for hops whose bytes are nearly free (same-
        machine leaders) — lossy compression there is pure error for
        no bandwidth. Self-describing per chunk, so a ring may mix
        quantized and raw hops with no side channel.

        Non-finite chunks (a diverging training step's NaN/Inf
        gradients) must NOT quantize: a NaN element would decode to 0
        (erasing the divergence signal the exact path propagates) and
        one Inf makes the scale Inf, flooding the whole chunk with
        0·Inf = NaN. They use the same raw passthrough form."""
        chunk = np.ascontiguousarray(chunk, dtype=np.float32)
        peak = float(np.max(np.abs(chunk))) if chunk.size else 0.0
        if not quantize or not np.isfinite(peak):
            out = np.empty(_SCALE_BYTES + chunk.nbytes, dtype=np.uint8)
            out[:_SCALE_BYTES] = np.frombuffer(
                struct.pack(_SCALE_FMT, float("nan")), dtype=np.uint8)
            out[_SCALE_BYTES:] = chunk.view(np.uint8)
            return out
        scale = peak / 127.0 if peak > 0.0 else 1.0
        q = np.rint(chunk * (1.0 / scale))
        np.clip(q, -127, 127, out=q)
        out = np.empty(_SCALE_BYTES + chunk.size, dtype=np.uint8)
        out[:_SCALE_BYTES] = np.frombuffer(
            struct.pack(_SCALE_FMT, scale), dtype=np.uint8)
        out[_SCALE_BYTES:] = q.astype(np.int8).view(np.uint8)
        return out

    def decode(self, buf: np.ndarray) -> np.ndarray:
        """uint8 wire buffer → private writable float32 chunk (the
        receiver folds into it in place). A NaN scale marks the raw
        fp32 passthrough form (non-finite source chunk)."""
        buf = buf.view(np.uint8).reshape(-1)
        (scale,) = struct.unpack(_SCALE_FMT,
                                 buf[:_SCALE_BYTES].tobytes())
        if np.isnan(scale):
            return buf[_SCALE_BYTES:].view(np.float32).copy()
        out = buf[_SCALE_BYTES:].view(np.int8).astype(np.float32)
        out *= scale
        return out


_INT8 = Int8ChunkCodec()


def resolve_quant_mode(world_knob: str) -> str:
    """The effective quant mode for a world: the explicit knob
    (``FAABRIC_ALLREDUCE_QUANT`` / ``MpiWorld.allreduce_quant``) wins;
    otherwise the wire-codec governor's ``quant`` policy token enables
    it (ISSUE 11: the quant knob becomes one governor policy instead of
    a global env switch). Deterministic across a world's processes —
    both inputs are env/world-level configuration."""
    from faabric_tpu.transport.codec import get_wire_governor

    return get_wire_governor().quant_mode(world_knob)


def leader_ring_codec(mode, dtype, op) -> Int8ChunkCodec | None:
    """The codec the leader ring should apply for this (mode, dtype,
    op), or None for the raw fp32 wire. Deterministic in its inputs —
    every leader derives the same verdict from the world-wide knob and
    the collective's own payload, no exchange needed."""
    from faabric_tpu.mpi.types import MpiOp

    if mode != "int8":
        return None
    if np.dtype(dtype) != np.float32:
        return None
    if op != MpiOp.SUM:
        return None
    return _INT8
