"""Cluster topology of one rank group: who shares a machine with whom.

The rank→host→leader/local-rank structure that the locality-aware
collectives in ``mpi/world.py`` and the batch scheduler's gang-placement
hook both read (ISSUE 9). Before this object existed the same facts
lived as ad-hoc caches inside ``MpiWorld`` (``_rank_hosts``,
``_local_leader_cache``) and as throwaway ``host_freq_count()`` dicts in
the scheduler — two views of one structure that could not be shared.

Reference analog: ``MpiWorld::initLocalRemoteLeaders``
(src/mpi/MpiWorld.cpp:318-366) computes the same leader sets per world;
HiCCL (arXiv:2408.05962) is the argument for making the hierarchy an
explicit, composable input to collective construction rather than an
implementation detail.

A ``Topology`` is **immutable after construction** — every derived
field is computed once in ``__init__`` — so readers on N rank threads
(and the scheduler reading a decision's topology) need no lock.
``MpiWorld`` caches one per topology generation and rebuilds it on
migration remaps.
"""

from __future__ import annotations

from typing import Iterable, Mapping

# Immutable after construction: all fields are written once in
# __init__ before the object is published (no concurrent mutation to
# guard — see class docstring).
GUARDS: dict = {}


class Topology:
    """rank → host → (leader, local rank) for one rank group.

    Host order is first-appearance-by-rank (rank 0's host first), so
    every participant derives the identical leader ring without any
    exchange. Leaders are the lowest rank on each host, matching the
    reference's local-leader election.
    """

    __slots__ = ("size", "rank_hosts", "hosts", "host_ranks", "leaders",
                 "_local_idx", "ranks_per_host", "max_ranks_per_host",
                 "rank_devices")

    def __init__(self, rank_hosts: Mapping[int, str],
                 rank_devices: Mapping[int, int] | None = None) -> None:
        size = len(rank_hosts)
        if sorted(rank_hosts) != list(range(size)):
            raise ValueError(
                f"rank set must be exactly 0..{size - 1}, got "
                f"{sorted(rank_hosts)[:8]}...")
        self.size = size
        self.rank_hosts: tuple[str, ...] = tuple(
            rank_hosts[r] for r in range(size))

        host_ranks: dict[str, list[int]] = {}
        for r, h in enumerate(self.rank_hosts):
            host_ranks.setdefault(h, []).append(r)
        # dict preserves first-appearance order; rank iteration above is
        # 0..size-1, so hosts[0] is rank 0's host on every participant
        self.hosts: tuple[str, ...] = tuple(host_ranks)
        self.host_ranks: dict[str, tuple[int, ...]] = {
            h: tuple(ranks) for h, ranks in host_ranks.items()}
        self.leaders: tuple[int, ...] = tuple(
            ranks[0] for ranks in self.host_ranks.values())
        self._local_idx: dict[int, int] = {
            r: i for ranks in self.host_ranks.values()
            for i, r in enumerate(ranks)}
        self.ranks_per_host: dict[str, int] = {
            h: len(ranks) for h, ranks in self.host_ranks.items()}
        self.max_ranks_per_host = max(self.ranks_per_host.values(),
                                      default=0)
        # Device placement (ISSUE 10): the planner-assigned per-host
        # chip index of each rank, -1 unknown. None when the placement
        # carries no device information at all. Identity (__eq__/
        # __hash__) stays rank→host only — devices are a placement
        # DETAIL of the same topology, and the MpiWorld cache must not
        # rebuild over a device re-claim that moved no rank.
        if rank_devices is None:
            self.rank_devices: tuple[int, ...] | None = None
        else:
            self.rank_devices = tuple(
                int(rank_devices.get(r, -1)) for r in range(size))

    # -- constructors ---------------------------------------------------
    @classmethod
    def from_rank_hosts(cls, rank_hosts: Mapping[int, str]) -> "Topology":
        return cls(rank_hosts)

    @classmethod
    def from_decision(cls, decision) -> "Topology":
        """Topology of a SchedulingDecision's placement: group idx (the
        MPI rank of gang-scheduled worlds) → host. This is the object
        the planner/batch-scheduler side reads. Decisions whose group
        idxs are not a clean 0..N-1 rank set (non-gang batches) fall
        back to positional order — host structure is what matters to
        the scheduler's locality metrics, not rank labels."""
        idxs = list(decision.group_idxs)
        if sorted(idxs) != list(range(len(idxs))):
            idxs = list(range(len(decision.hosts)))
        devices = None
        if any(d >= 0 for d in decision.device_ids):
            devices = dict(zip(idxs, decision.device_ids))
        return cls(dict(zip(idxs, decision.hosts)), rank_devices=devices)

    # -- structure queries ----------------------------------------------
    def host_of(self, rank: int) -> str:
        return self.rank_hosts[rank]

    def ranks_on_host(self, host: str) -> tuple[int, ...]:
        """Ranks co-located on ``host``, ascending (empty for unknown)."""
        return self.host_ranks.get(host, ())

    def leader_of(self, rank: int) -> int:
        """Lowest co-located rank (reference initLocalRemoteLeaders)."""
        return self.host_ranks[self.rank_hosts[rank]][0]

    def is_leader(self, rank: int) -> bool:
        return self.leader_of(rank) == rank

    def local_rank(self, rank: int) -> int:
        """Index of ``rank`` among its host's ranks (0 = leader)."""
        return self._local_idx[rank]

    @property
    def n_hosts(self) -> int:
        return len(self.hosts)

    @property
    def single_host(self) -> bool:
        return self.n_hosts <= 1

    @property
    def one_rank_per_host(self) -> bool:
        return self.max_ranks_per_host <= 1

    @property
    def hierarchical(self) -> bool:
        """True when composing collectives over the hierarchy can win:
        more than one host AND at least one host with co-located ranks.
        The degenerate shapes (1 host, or 1 rank/host) are exactly the
        flat rings' sweet spot and must stay on them."""
        return self.n_hosts > 1 and self.max_ranks_per_host > 1

    def hosts_contiguous(self) -> bool:
        """True when every host's rank set is a contiguous run of rank
        ids (the gang-scheduled layout). Collectives whose output
        assignment is positional (reduce_scatter) need this to map
        per-host wire segments onto per-rank result segments."""
        return all(ranks[-1] - ranks[0] + 1 == len(ranks)
                   for ranks in self.host_ranks.values())

    def device_of(self, rank: int) -> int:
        """Planner-assigned per-host chip index of ``rank`` (-1 when the
        placement carries no device information)."""
        if self.rank_devices is None:
            return -1
        return self.rank_devices[rank]

    def devices_on_host(self, host: str) -> tuple[int, ...]:
        """Chip indexes claimed by ``host``'s ranks, in rank order."""
        if self.rank_devices is None:
            return ()
        return tuple(self.rank_devices[r] for r in self.ranks_on_host(host))

    def mesh_contiguous(self) -> bool:
        """True when the placement can light up a device mesh cleanly:
        gang-contiguous rank runs per host AND every co-located rank on
        its own chip (distinct, known device ids). This is the layout
        the gang scheduler prefers for device-eligible worlds — a host
        double-booking a chip (or a scattered rank run) forces the
        device plane's eligibility check to fall back to the host
        ladder."""
        if self.rank_devices is None or not self.hosts_contiguous():
            return False
        for ranks in self.host_ranks.values():
            devs = [self.rank_devices[r] for r in ranks]
            if any(d < 0 for d in devs) or len(set(devs)) != len(devs):
                return False
        return True

    def cross_host_pairs(self) -> int:
        """Rank pairs that would hit the wire in a fully-connected
        traffic pattern (reference BinPackScheduler.cpp:97-148) — the
        scheduler's locality tie-break metric."""
        if self.n_hosts <= 1:
            return 0
        total = self.size
        return sum(n * (total - n)
                   for n in self.ranks_per_host.values()) // 2

    # -- export ----------------------------------------------------------
    def to_dict(self) -> dict:
        """JSON-safe summary (planner telemetry / debugging)."""
        out = {
            "size": self.size,
            "n_hosts": self.n_hosts,
            "hosts": {h: list(r) for h, r in self.host_ranks.items()},
            "leaders": list(self.leaders),
            "max_ranks_per_host": self.max_ranks_per_host,
            "hierarchical": self.hierarchical,
        }
        if self.rank_devices is not None:
            out["devices"] = list(self.rank_devices)
            out["mesh_contiguous"] = self.mesh_contiguous()
        return out

    def __repr__(self) -> str:
        per_host = ",".join(str(n) for n in self.ranks_per_host.values())
        return (f"Topology(size={self.size}, hosts={self.n_hosts}, "
                f"ranks/host=[{per_host}])")

    def __eq__(self, other) -> bool:
        return (isinstance(other, Topology)
                and self.rank_hosts == other.rank_hosts)

    def __hash__(self) -> int:
        return hash(self.rank_hosts)


def leader_ring(topology: Topology) -> list[int]:
    """The cross-host wire ring: one leader per host, host order —
    identical on every rank by construction."""
    return list(topology.leaders)


def interleave_hosts(hosts: Iterable[str], n_ranks: int) -> dict[int, str]:
    """Round-robin rank→host mapping (the topology-BLIND placement a
    scheduler without the gang hook produces). Test/bench helper: the
    worst case for flat rings — every ring hop crosses hosts."""
    hosts = list(hosts)
    return {r: hosts[r % len(hosts)] for r in range(n_ranks)}
