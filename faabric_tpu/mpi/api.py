"""Guest-facing MPI API.

Reference analog: the extern-C MPI subset in include/faabric/mpi/mpi.h
(597 lines) and the mpi_native shim that implements it over MpiWorld for
native runs (tests/dist/mpi/native/mpi_native.cpp) — the same shim pattern
Faasm uses from WASM. Guest code written against this module runs unchanged
whether its world spans threads, hosts, or (via device_collectives) chips.

Thread-local binding: ``mpi_init()`` inside an executor task creates or
joins the task's world from its message; every call after that uses the
calling thread's (world, rank).
"""

from __future__ import annotations

import threading
import time
from typing import Optional

import numpy as np

from faabric_tpu.mpi.types import MpiOp, MpiStatus, UserOp
from faabric_tpu.mpi.world import MpiWorld

MPI_COMM_WORLD = "MPI_COMM_WORLD"
MPI_COMM_NULL = None
MPI_UNDEFINED = -1
MPI_SUCCESS = 0


class MpiComm:
    """A communicator handle: a (sub)world plus this thread's rank in it.
    ``MPI_COMM_WORLD`` (the string sentinel) resolves to the thread's
    bound world; handles from mpi_comm_split/dup/create pass as the
    ``comm`` argument of every call here."""

    __slots__ = ("world", "rank")

    def __init__(self, world: MpiWorld, rank: int) -> None:
        self.world = world
        self.rank = rank

    @property
    def size(self) -> int:
        return self.world.size

# Re-exported op constants (reference faabric_op_t singletons)
MPI_MAX = MpiOp.MAX
MPI_MIN = MpiOp.MIN
MPI_SUM = MpiOp.SUM
MPI_PROD = MpiOp.PROD
MPI_LAND = MpiOp.LAND
MPI_LOR = MpiOp.LOR
MPI_BAND = MpiOp.BAND
MPI_BOR = MpiOp.BOR
MPI_MAXLOC = MpiOp.MAXLOC
MPI_MINLOC = MpiOp.MINLOC

_tls = threading.local()


class MpiError(Exception):
    pass


class MpiRequest:
    """Async request handle tagged with its communicator's world — so
    MPI_Wait/Test (which take no comm in real MPI) always resolve
    against the world the isend/irecv ran on, never the thread's bound
    parent. Bare int ids (the world-level API) still work for
    MPI_COMM_WORLD callers."""

    __slots__ = ("world", "rank", "id")

    def __init__(self, world: MpiWorld, rank: int, rid: int) -> None:
        self.world = world
        self.rank = rank
        self.id = rid


def _bind(world: MpiWorld, rank: int) -> None:
    _tls.world = world
    _tls.rank = rank
    _tls.start_time = time.monotonic()
    _tls.finalized = False


def _current(comm=MPI_COMM_WORLD) -> tuple[MpiWorld, int]:
    if isinstance(comm, MpiComm):
        return comm.world, comm.rank
    if comm is MPI_COMM_NULL:
        raise MpiError("Communication on MPI_COMM_NULL")
    if comm != MPI_COMM_WORLD:
        raise MpiError(f"Not a communicator: {comm!r}")
    world = getattr(_tls, "world", None)
    if world is None:
        raise MpiError("MPI not initialised on this thread (call mpi_init)")
    return world, _tls.rank


def mpi_init(world_size: int | None = None, world_id: int | None = None) -> int:
    """MPI_Init: bind this thread to its task's world — rank 0 creates it
    (chaining the other ranks through the planner), others join."""
    from faabric_tpu.mpi.registry import get_mpi_context

    ctx = get_mpi_context()
    from faabric_tpu.executor.context import ExecutorContext

    msg = ExecutorContext.get().msg
    if msg.mpi_rank == 0 and not msg.is_mpi:
        msg.is_mpi = True
        if world_id is not None:
            msg.mpi_world_id = world_id
        if world_size is not None:
            msg.mpi_world_size = world_size
        world = ctx.create_world(msg)
    else:
        world = ctx.join_world(msg)
    world.refresh_rank_hosts()
    _bind(world, msg.mpi_rank)
    return MPI_SUCCESS


def mpi_initialized() -> bool:
    return getattr(_tls, "world", None) is not None


def mpi_finalize() -> int:
    _tls.world = None
    _tls.finalized = True
    return MPI_SUCCESS


def mpi_finalized() -> bool:
    return bool(getattr(_tls, "finalized", False))


# Thread-support levels (reference mpi.h MPI_THREAD_*)
MPI_THREAD_SINGLE = 0
MPI_THREAD_FUNNELED = 1
MPI_THREAD_SERIALIZED = 2
MPI_THREAD_MULTIPLE = 3


def mpi_init_thread(required: int = MPI_THREAD_SERIALIZED,
                    world_size: int | None = None,
                    world_id: int | None = None) -> int:
    """MPI_Init_thread: ranks here are one-thread-per-rank with TLS world
    binding, so the provided level is SERIALIZED."""
    mpi_init(world_size, world_id)
    return min(required, MPI_THREAD_SERIALIZED)


def mpi_query_thread() -> int:
    return MPI_THREAD_SERIALIZED


def mpi_get_version() -> tuple[int, int]:
    """The MPI standard version this subset tracks (as the reference's
    header does): 3.1."""
    return (3, 1)


def mpi_abort(comm=MPI_COMM_WORLD, errorcode: int = 1) -> None:
    raise MpiError(f"MPI_Abort with code {errorcode}")


# ---------------------------------------------------------------------------
# Introspection
# ---------------------------------------------------------------------------

def mpi_comm_rank(comm=MPI_COMM_WORLD) -> int:
    return _current(comm)[1]


def mpi_comm_size(comm=MPI_COMM_WORLD) -> int:
    return _current(comm)[0].size


def mpi_wtime() -> float:
    return time.monotonic()


def mpi_get_processor_name() -> str:
    world, rank = _current()
    return world.host_for_rank(rank)


def mpi_topology(comm=MPI_COMM_WORLD):
    """The communicator's Topology (mpi/topology.py): rank→host→
    leader/local-rank — the same structure the scheduler's gang-
    placement hook reads and the hierarchical collectives compose over.
    Guest code uses it to shard work by locality (e.g. one I/O rank per
    host via ``topo.is_leader(rank)``)."""
    world, _ = _current(comm)
    return world.topology()


# ---------------------------------------------------------------------------
# Point-to-point
# ---------------------------------------------------------------------------

def mpi_send(buf, dest: int, comm=MPI_COMM_WORLD) -> int:
    world, rank = _current(comm)
    world.send(rank, dest, np.asarray(buf))
    return MPI_SUCCESS


def mpi_rsend(buf, dest: int, comm=MPI_COMM_WORLD) -> int:
    """MPI_Rsend: ready-mode send — the 'receiver is already posted'
    contract adds nothing over the buffered channel, so it is a plain
    send (the reference shim throws; OpenMPI treats rsend == send on
    most transports too)."""
    return mpi_send(buf, dest, comm)


def mpi_recv(source: int, comm=MPI_COMM_WORLD
             ) -> tuple[np.ndarray, MpiStatus]:
    world, rank = _current(comm)
    return world.recv(source, rank)


def mpi_sendrecv(sendbuf, dest: int, source: int, comm=MPI_COMM_WORLD
                 ) -> tuple[np.ndarray, MpiStatus]:
    world, rank = _current(comm)
    return world.sendrecv(np.asarray(sendbuf), rank, dest, source, rank)


def mpi_isend(buf, dest: int, comm=MPI_COMM_WORLD) -> MpiRequest:
    world, rank = _current(comm)
    return MpiRequest(world, rank, world.isend(rank, dest, np.asarray(buf)))


def mpi_irecv(source: int, comm=MPI_COMM_WORLD) -> MpiRequest:
    world, rank = _current(comm)
    return MpiRequest(world, rank, world.irecv(source, rank))


def _resolve_request(request, comm) -> tuple[MpiWorld, int, int]:
    if isinstance(request, MpiRequest):
        return request.world, request.rank, request.id
    world, rank = _current(comm)
    return world, rank, int(request)


def mpi_wait(request, comm=MPI_COMM_WORLD
             ) -> Optional[tuple[np.ndarray, MpiStatus]]:
    world, rank, rid = _resolve_request(request, comm)
    return world.await_async(rank, rid)


def mpi_waitall(requests: list, comm=MPI_COMM_WORLD
                ) -> list[Optional[tuple[np.ndarray, MpiStatus]]]:
    return [mpi_wait(r, comm) for r in requests]


def mpi_waitany(requests: list, comm=MPI_COMM_WORLD
                ) -> tuple[int, Optional[tuple[np.ndarray, MpiStatus]]]:
    """First completable request across possibly-mixed communicators."""
    resolved = [_resolve_request(r, comm) for r in requests]
    deadline = time.monotonic() + 60.0
    while True:
        live = 0
        for i, (world, rank, rid) in enumerate(resolved):
            try:
                ready = world.request_ready(rank, rid)
            except KeyError:
                continue  # completed by an earlier wait
            live += 1
            if ready:
                return i, world.await_async(rank, rid)
        if live == 0:
            return -1, None
        if time.monotonic() >= deadline:
            raise TimeoutError("MPI_Waitany timed out")
        time.sleep(0.0005)


def mpi_test(request, comm=MPI_COMM_WORLD
             ) -> tuple[bool, Optional[tuple]]:
    """MPI_Test: (flag, result). flag False → request still pending (the
    request stays live); True → completed, result as mpi_wait. Testing a
    handle that already completed is legal (MPI_REQUEST_NULL semantics)
    and reports (True, None)."""
    world, rank, rid = _resolve_request(request, comm)
    try:
        if not world.request_ready(rank, rid):
            return False, None
    except KeyError:
        return True, None  # completed by an earlier wait/test
    return True, world.await_async(rank, rid)


def mpi_request_free(request, comm=MPI_COMM_WORLD) -> int:
    """MPI_Request_free: drop the handle without waiting. Sends complete
    in their worker; a freed irecv's already-arrived message is consumed
    and discarded so it can't leak into a later unrelated recv."""
    world, rank, rid = _resolve_request(request, comm)
    world.request_free(rank, rid)
    return MPI_SUCCESS


class MpiContiguousType:
    """Derived datatype from MPI_Type_contiguous: ``count`` elements of a
    base type. mpi_type_size resolves it; commit/free are lifecycle
    no-ops (the reference shim logs and returns for these)."""

    __slots__ = ("base", "count", "committed")

    def __init__(self, base, count: int) -> None:
        self.base = base
        self.count = count
        self.committed = False


def mpi_type_contiguous(count: int, oldtype) -> MpiContiguousType:
    return MpiContiguousType(oldtype, count)


def mpi_type_commit(newtype: MpiContiguousType) -> int:
    newtype.committed = True
    return MPI_SUCCESS


def mpi_type_free(newtype: MpiContiguousType) -> int:
    newtype.committed = False
    return MPI_SUCCESS


def mpi_type_size(dtype) -> int:
    """MPI_Type_size over the framework's datatype enum, a numpy dtype,
    or a derived contiguous type."""
    from faabric_tpu.mpi.types import MpiDataType, np_dtype_for

    if isinstance(dtype, MpiContiguousType):
        return dtype.count * mpi_type_size(dtype.base)
    if isinstance(dtype, (int, MpiDataType)):
        return int(np_dtype_for(MpiDataType(int(dtype))).itemsize)
    return int(np.dtype(dtype).itemsize)


def mpi_op_create(fn, commute: bool = True, name: str = "user_op") -> UserOp:
    """MPI_Op_create: a user reduction ``fn(a, b) -> array`` usable in
    reduce/allreduce/scan/reduce_scatter (the reference shim throws
    notImplemented for user ops; here they ride the same leader-tree
    collectives as the built-ins)."""
    return UserOp(fn, commute, name)


def mpi_op_free(op: UserOp) -> int:
    return MPI_SUCCESS


def mpi_alloc_mem(nbytes: int) -> np.ndarray:
    """MPI_Alloc_mem: page-aligned byte buffer (util.memory allocator)."""
    from faabric_tpu.util.memory import allocate_buffer

    return allocate_buffer(nbytes)


def mpi_free_mem(buf) -> int:
    return MPI_SUCCESS  # numpy buffers are GC-owned


def mpi_reduce_scatter(sendbuf, op: MpiOp, comm=MPI_COMM_WORLD
                       ) -> np.ndarray:
    world, rank = _current(comm)
    return world.reduce_scatter(rank, np.asarray(sendbuf), op)


def mpi_probe(source: int, comm=MPI_COMM_WORLD) -> MpiStatus:
    world, rank = _current(comm)
    return world.probe(source, rank)


def mpi_iprobe(source: int, comm=MPI_COMM_WORLD) -> Optional[MpiStatus]:
    """Non-blocking: pending-message status or None (flag=false)."""
    world, rank = _current(comm)
    return world.iprobe(source, rank)


def mpi_get_count(status: MpiStatus) -> int:
    """MPI_Get_count: elements in the message the status describes."""
    return status.count


# ---------------------------------------------------------------------------
# Collectives
# ---------------------------------------------------------------------------

def mpi_barrier(comm=MPI_COMM_WORLD) -> int:
    world, rank = _current(comm)
    world.barrier(rank)
    return MPI_SUCCESS


def mpi_bcast(buf, root: int, comm=MPI_COMM_WORLD) -> np.ndarray:
    world, rank = _current(comm)
    return world.broadcast(root, rank,
                           np.asarray(buf) if buf is not None else np.empty(0))


def mpi_scatter(sendbuf, recv_count: int, root: int,
                comm=MPI_COMM_WORLD) -> np.ndarray:
    world, rank = _current(comm)
    return world.scatter(root, rank,
                         np.asarray(sendbuf) if sendbuf is not None
                         else np.empty(0), recv_count)


def mpi_gather(sendbuf, root: int, comm=MPI_COMM_WORLD
               ) -> Optional[np.ndarray]:
    world, rank = _current(comm)
    return world.gather(rank, root, np.asarray(sendbuf))


def mpi_gatherv(sendbuf, root: int, comm=MPI_COMM_WORLD
                ) -> Optional[tuple[np.ndarray, list[int]]]:
    """Root returns (concatenated values in rank order, per-rank counts)."""
    world, rank = _current(comm)
    return world.gatherv(rank, root, np.asarray(sendbuf))


def mpi_scatterv(sendbuf, counts, root: int, comm=MPI_COMM_WORLD
                 ) -> np.ndarray:
    world, rank = _current(comm)
    return world.scatterv(root, rank,
                          np.asarray(sendbuf) if sendbuf is not None
                          else None, counts)


def mpi_alltoallv(sendbuf, send_counts, comm=MPI_COMM_WORLD
                  ) -> tuple[np.ndarray, list[int]]:
    world, rank = _current(comm)
    return world.alltoallv(rank, np.asarray(sendbuf), list(send_counts))


def mpi_allgather(sendbuf, comm=MPI_COMM_WORLD) -> np.ndarray:
    world, rank = _current(comm)
    return world.allgather(rank, np.asarray(sendbuf))


def mpi_allgatherv(sendbuf, comm=MPI_COMM_WORLD
                   ) -> tuple[np.ndarray, list[int]]:
    """MPI_Allgatherv (the reference shim throws notImplemented):
    variable-count gather to root + two broadcasts. Every rank returns
    (concatenated values in rank order, per-rank counts)."""
    world, rank = _current(comm)
    res = world.gatherv(rank, 0, np.asarray(sendbuf))
    if rank == 0:
        data, counts = res
        counts_arr = np.asarray(counts, np.int64)
        world.broadcast(0, rank, counts_arr)
        world.broadcast(0, rank, data)
        return data, list(counts)
    counts_arr = np.asarray(world.broadcast(0, rank, np.empty(0, np.int64)))
    data = np.asarray(world.broadcast(0, rank, np.empty(0)))
    return data, [int(c) for c in counts_arr]


def mpi_reduce(sendbuf, op: MpiOp, root: int, comm=MPI_COMM_WORLD
               ) -> Optional[np.ndarray]:
    world, rank = _current(comm)
    return world.reduce(rank, root, np.asarray(sendbuf), op)


def mpi_allreduce(sendbuf, op: MpiOp, comm=MPI_COMM_WORLD) -> np.ndarray:
    world, rank = _current(comm)
    return world.allreduce(rank, np.asarray(sendbuf), op)


def mpi_scan(sendbuf, op: MpiOp, comm=MPI_COMM_WORLD) -> np.ndarray:
    world, rank = _current(comm)
    return world.scan(rank, np.asarray(sendbuf), op)


def mpi_alltoall(sendbuf, comm=MPI_COMM_WORLD) -> np.ndarray:
    world, rank = _current(comm)
    return world.alltoall(rank, np.asarray(sendbuf))


# ---------------------------------------------------------------------------
# Cartesian topology (reference MPI_Cart_*)
# ---------------------------------------------------------------------------

def mpi_cart_create(dims=None, comm=MPI_COMM_WORLD) -> tuple[int, ...]:
    """MPI_Cart_create with user dims (all-periodic); None keeps the
    default near-square 2-D factorisation."""
    world, _ = _current(comm)
    return world.cart_create(dims)


def mpi_cart_get(comm=MPI_COMM_WORLD) -> tuple[tuple[int, ...],
                                               tuple[int, ...]]:
    world, rank = _current(comm)
    return world.cart_dims(), world.cart_coords(rank)


def mpi_cart_rank(coords: tuple[int, int], comm=MPI_COMM_WORLD) -> int:
    world, _ = _current(comm)
    return world.cart_rank(coords)


def mpi_cart_shift(direction: int, disp: int, comm=MPI_COMM_WORLD
                   ) -> tuple[int, int]:
    world, rank = _current(comm)
    return world.cart_shift(rank, direction, disp)


# ---------------------------------------------------------------------------
# Communicator / group management (reference mpi.h MPI_Comm_split_type,
# MPI_Comm_dup, MPI_Comm_group/Group_incl/Comm_create_group, MPI_Comm_free)
# ---------------------------------------------------------------------------

def mpi_comm_split(color: int, key: int = 0,
                   comm=MPI_COMM_WORLD) -> Optional[MpiComm]:
    """Collective: ranks sharing ``color`` form a new communicator,
    ordered by (key, rank). ``MPI_UNDEFINED`` color → MPI_COMM_NULL."""
    world, rank = _current(comm)
    sub, new_rank = world.split(rank, color, key)
    if sub is None:
        return MPI_COMM_NULL
    return MpiComm(sub, new_rank)


def mpi_comm_dup(comm=MPI_COMM_WORLD) -> MpiComm:
    """Collective: same membership, isolated communication context."""
    world, rank = _current(comm)
    sub, new_rank = world.dup(rank)
    return MpiComm(sub, new_rank)


def mpi_comm_group(comm=MPI_COMM_WORLD) -> list[int]:
    """MPI_Comm_group: the group is simply the rank list (local op)."""
    world, _ = _current(comm)
    return list(range(world.size))


def mpi_group_incl(group: list[int], ranks: list[int]) -> list[int]:
    """MPI_Group_incl (local op)."""
    return [group[r] for r in ranks]


def mpi_comm_create_group(group: list[int], tag: int = 0,
                          comm=MPI_COMM_WORLD) -> Optional[MpiComm]:
    """Collective over ``group``'s members only (MPI_Comm_create_group)."""
    world, rank = _current(comm)
    sub, new_rank = world.create_group_comm(rank, list(group), tag)
    if sub is None:
        return MPI_COMM_NULL
    return MpiComm(sub, new_rank)


def mpi_comm_free(comm: MpiComm) -> int:
    """MPI_Comm_free — collective: barriers the sub-communicator so all
    in-flight traffic lands, then stops its send workers. The (tiny)
    per-host queue/mapping stubs stay until the app's groups clear at
    batch teardown: clearing them here would race co-located ranks still
    draining their last messages."""
    if isinstance(comm, MpiComm):
        comm.world.barrier(comm.rank)
        comm.world.close()
    return MPI_SUCCESS


MPI_COMM_TYPE_SHARED = 1


def mpi_comm_split_type(split_type: int = MPI_COMM_TYPE_SHARED,
                        key: int = 0, comm=MPI_COMM_WORLD) -> MpiComm:
    """MPI_Comm_split_type: MPI_COMM_TYPE_SHARED groups co-located
    (shared-memory) ranks — one subworld per host."""
    if split_type != MPI_COMM_TYPE_SHARED:
        raise MpiError(f"Unsupported split type {split_type}")
    world, rank = _current(comm)
    sub, new_rank = world.split_type_shared(rank, key)
    return MpiComm(sub, new_rank)


def mpi_comm_create(group: list[int], comm=MPI_COMM_WORLD
                    ) -> Optional[MpiComm]:
    """MPI_Comm_create — collective over ALL of ``comm`` (unlike
    mpi_comm_create_group): members form the new communicator in group
    order, everyone else gets MPI_COMM_NULL."""
    world, rank = _current(comm)
    in_group = rank in group
    color = 0 if in_group else MPI_UNDEFINED
    key = list(group).index(rank) if in_group else 0
    sub, new_rank = world.split(rank, color, key)
    if sub is None:
        return MPI_COMM_NULL
    return MpiComm(sub, new_rank)


# ---------------------------------------------------------------------------
# One-sided (shared windows — mpi/window.py; the reference shim stubs all
# of MPI_Win_*/Put/Get with notImplemented)
# ---------------------------------------------------------------------------

def mpi_win_allocate_shared(size: int, comm=MPI_COMM_WORLD):
    """MPI_Win_allocate_shared: collective over a host-local communicator
    (use mpi_comm_split_type(MPI_COMM_TYPE_SHARED) first on multi-host
    worlds). Returns (window, own byte segment view)."""
    from faabric_tpu.mpi.window import allocate_shared

    world, rank = _current(comm)
    win = allocate_shared(world, rank, size)
    return win, win.segment()


def mpi_win_shared_query(win, rank: int) -> tuple[np.ndarray, int]:
    """(segment view, size) of another rank's share."""
    return win.segment(rank), win.sizes[rank]


def mpi_win_fence(win) -> int:
    win.fence()
    return MPI_SUCCESS


def mpi_put(data, target_rank: int, target_disp: int, win) -> int:
    win.put(data, target_rank, target_disp)
    return MPI_SUCCESS


def mpi_get(target_rank: int, nbytes: int, target_disp: int,
            win) -> np.ndarray:
    return win.get(target_rank, nbytes, target_disp)


def mpi_win_get_attr(win, keyval: int):
    return win.get_attr(keyval)


def mpi_win_free(win) -> int:
    win.free()
    return MPI_SUCCESS


def mpi_win_create(*_a, **_k):
    raise MpiError(
        "MPI_Win_create over caller-provided buffers cannot span "
        "processes; use mpi_win_allocate_shared (the reference stubs "
        "both with notImplemented)")


# ---------------------------------------------------------------------------
# Group management extras
# ---------------------------------------------------------------------------

def mpi_group_free(group) -> int:
    """MPI_Group_free: groups are plain rank lists (local objects)."""
    return MPI_SUCCESS


def mpi_dims_create(nnodes: int, ndims: int) -> list[int]:
    """MPI_Dims_create: balanced factorization of ``nnodes`` over
    ``ndims`` dimensions (descending, as the standard requires)."""
    if nnodes <= 0 or ndims <= 0:
        raise MpiError("dims_create needs positive nnodes/ndims")
    dims = [1] * ndims
    remaining = nnodes
    # Peel prime factors largest-first onto the smallest dimension
    factors = []
    f = 2
    while f * f <= remaining:
        while remaining % f == 0:
            factors.append(f)
            remaining //= f
        f += 1
    if remaining > 1:
        factors.append(remaining)
    for factor in sorted(factors, reverse=True):
        dims[dims.index(min(dims))] *= factor
    return sorted(dims, reverse=True)
