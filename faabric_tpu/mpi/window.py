"""One-sided MPI: shared-memory windows.

Reference analog: the MPI_Win_* / MPI_Put / MPI_Get surface of
include/faabric/mpi/mpi.h. The reference's own native shim stubs ALL of
it except attribute reads (tests/dist/mpi/mpi_native.cpp: notImplemented
for Win_create/fence/free/Put/Get) — here the shared-window flavor
(MPI_Win_allocate_shared / MPI_Win_shared_query, the OpenMP-over-MPI
pattern) is actually implemented: one named shared-memory segment per
window that every co-located rank maps, with per-rank base offsets.

Put/Get against any rank of the window are direct memory ops on the
mapped segment — true one-sided access with no receiver involvement;
MPI_Win_fence is the communicator barrier (the standard's active-target
synchronization). Windows spanning hosts raise: cross-host one-sided
needs the DSM/snapshot machinery, and the reference has no remote RMA
either.
"""

from __future__ import annotations

import numpy as np

from faabric_tpu.util.memory import SharedBuffer

# Window attribute keys (reference mpi.h MPI_WIN_BASE/SIZE/DISP_UNIT)
MPI_WIN_BASE = 1
MPI_WIN_SIZE = 2
MPI_WIN_DISP_UNIT = 3

_NAME_BYTES = 200


class MpiWindow:
    """One rank's handle onto a shared window: the mapped segment plus
    every rank's (offset, size). Created collectively by
    :func:`allocate_shared`."""

    def __init__(self, world, rank: int, shm: SharedBuffer,
                 sizes: list[int], created: bool) -> None:
        self.world = world
        self.rank = rank
        self._shm = shm
        self.sizes = sizes
        self.offsets = np.concatenate([[0], np.cumsum(sizes)])[:-1].tolist()
        self._created = created  # creator unlinks on free
        self.freed = False

    # -- access ---------------------------------------------------------
    def segment(self, rank: int | None = None) -> np.ndarray:
        """The (mutable) byte view of ``rank``'s share (own by default) —
        MPI_Win_shared_query."""
        self._check_live()
        r = self.rank if rank is None else rank
        off = self.offsets[r]
        return self._shm.array[off:off + self.sizes[r]]

    def put(self, data, target_rank: int, target_disp: int = 0) -> None:
        """One-sided write into ``target_rank``'s share (MPI_Put)."""
        self._check_live()
        raw = np.asarray(data).reshape(-1).view(np.uint8)
        seg = self.segment(target_rank)
        if target_disp < 0 or target_disp + raw.size > seg.size:
            raise ValueError(
                f"MPI_Put of {raw.size} B at disp {target_disp} overruns "
                f"rank {target_rank}'s {seg.size} B window")
        seg[target_disp:target_disp + raw.size] = raw

    def get(self, target_rank: int, nbytes: int,
            target_disp: int = 0) -> np.ndarray:
        """One-sided read from ``target_rank``'s share (MPI_Get)."""
        self._check_live()
        seg = self.segment(target_rank)
        if target_disp < 0 or nbytes < 0 or target_disp + nbytes > seg.size:
            raise ValueError(
                f"MPI_Get of {nbytes} B at disp {target_disp} overruns "
                f"rank {target_rank}'s {seg.size} B window")
        return seg[target_disp:target_disp + nbytes].copy()

    def fence(self) -> None:
        """Active-target epoch boundary: all ranks' prior Put/Get are
        globally visible after the fence (MPI_Win_fence = barrier over
        shared memory)."""
        self._check_live()
        self.world.barrier(self.rank)

    def get_attr(self, keyval: int):
        self._check_live()
        if keyval == MPI_WIN_BASE:
            return self.segment()
        if keyval == MPI_WIN_SIZE:
            return self.sizes[self.rank]
        if keyval == MPI_WIN_DISP_UNIT:
            return 1  # byte-addressed
        raise ValueError(f"Unknown window attribute {keyval}")

    def free(self) -> None:
        """Collective: barrier, then unmap (creator unlinks)."""
        if self.freed:
            return
        self.world.barrier(self.rank)
        self.freed = True
        # Never raises: segments pinned by caller-held views unmap once
        # those views die (SharedBuffer graveyard)
        self._shm.close(unlink=self._created)

    def _check_live(self) -> None:
        if self.freed:
            raise RuntimeError("Window already freed")


def allocate_shared(world, rank: int, size: int) -> MpiWindow:
    """Collective window creation over ``world`` (which must be
    host-local — e.g. from MPI_Comm_split_type(SHARED)). Rank 0 creates
    the named segment sized to the sum of contributions and broadcasts
    (name, sizes); everyone maps it."""
    hosts = {world.host_for_rank(r) for r in range(world.size)}
    if len(hosts) > 1:
        raise RuntimeError(
            "Shared windows need co-located ranks (split the world with "
            "MPI_Comm_split_type(MPI_COMM_TYPE_SHARED) first); ranks span "
            f"{sorted(hosts)}")

    gathered = world.gather(rank, 0, np.array([size], np.int64))
    if rank == 0:
        sizes = [int(x) for x in np.asarray(gathered).reshape(-1)]
        total = max(1, sum(sizes))
        shm = SharedBuffer(total, create=True)
        name_b = shm.name.encode()
        if len(name_b) > _NAME_BYTES:
            raise RuntimeError(f"shm name too long: {shm.name}")
        meta = np.zeros(_NAME_BYTES + 8 * world.size, np.uint8)
        meta[0] = len(name_b)
        meta[1:1 + len(name_b)] = np.frombuffer(name_b, np.uint8)
        meta[_NAME_BYTES:] = np.array(sizes, np.int64).view(np.uint8)
        world.broadcast(0, rank, meta)
        return MpiWindow(world, rank, shm, sizes, created=True)

    meta = np.asarray(world.broadcast(0, rank, np.empty(0, np.uint8)))
    name = bytes(meta[1:1 + int(meta[0])]).decode()
    sizes = [int(x) for x in meta[_NAME_BYTES:].view(np.int64)]
    shm = SharedBuffer(max(1, sum(sizes)), name=name, create=False)
    return MpiWindow(world, rank, shm, sizes, created=False)
