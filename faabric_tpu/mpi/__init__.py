"""MPI-semantics layer on the group substrate (reference src/mpi)."""

from faabric_tpu.mpi.types import (
    MpiDataType,
    MpiMessageType,
    MpiOp,
    MpiStatus,
    UserOp,
    apply_op,
    mpi_dtype_for,
    np_dtype_for,
)
from faabric_tpu.mpi.schedule import (
    Schedule,
    ScheduleCache,
    ScheduleError,
    ScheduleVerificationError,
    verify_schedule,
)
from faabric_tpu.mpi.topology import Topology
from faabric_tpu.mpi.window import MpiWindow
from faabric_tpu.mpi.world import MAIN_RANK, MpiWorld, MpiWorldAborted
from faabric_tpu.mpi.registry import MpiContext, MpiWorldRegistry, get_mpi_context

__all__ = [
    "MAIN_RANK",
    "MpiContext",
    "MpiDataType",
    "MpiMessageType",
    "MpiOp",
    "MpiStatus",
    "MpiWindow",
    "MpiWorld",
    "MpiWorldAborted",
    "MpiWorldRegistry",
    "Schedule",
    "ScheduleCache",
    "ScheduleError",
    "ScheduleVerificationError",
    "Topology",
    "verify_schedule",
    "UserOp",
    "apply_op",
    "get_mpi_context",
    "mpi_dtype_for",
    "np_dtype_for",
]
