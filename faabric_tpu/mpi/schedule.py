"""Collective schedule IR + verifier + cache (ISSUE 13, ROADMAP 5).

PRs 5/9/10 accreted three hand-written collective algorithm families
(flat ring, hierarchical leader-ring, compiled device rung) times
per-collective special cases. GC3 (arXiv:2201.11840) and HiCCL
(arXiv:2408.05962) show the scalable shape instead: express a
collective as a small **schedule** — an ordered per-rank program of
``send`` / ``recv`` / ``fold`` / ``copy`` steps over abstract payload
blocks — compiled per (collective, Topology) by
``mpi/schedule_compile.py``, statically **verified** for exactly-once
delivery, cached per (topology-generation, collective, op/dtype-class,
size-class), and executed by one generic runner in ``MpiWorld``. Every
future topology then becomes a data change (a new lowering), not a new
hand-written collective.

The IR deliberately stays above chunking: a block is an abstract
contiguous span whose element count is a **size symbol** resolved by
the runner at execution time (uniform chunk, scatterv count vector,
ring segment arithmetic). The verifier never needs real sizes — it
checks that the sender's concatenation symbol sequence equals the
receiver's split sequence, so framing can never desync.

Verifier guarantee (``verify_schedule``): abstract interpretation of
the whole world's programs against per-(src, dst) FIFO channels —
exactly the ordering contract the PTP broker provides — proving:

- **progress**: no rank blocks forever on a recv no send will match
  (deadlock and send/recv framing mismatches are structural errors);
- **exactly-once**: every output block is written exactly once, and
  holds exactly its expected atom set — for data-movement collectives
  the (source rank, block) atoms, for reductions the contribution set
  folded without overlap (a double-fold = double-counted contribution
  is rejected even though the shapes would agree);
- **drained channels**: no message is left undelivered at exit.

A schedule that fails verification never reaches the cache, and the
runner refuses any schedule whose ``verified`` flag is unset — "no
schedule executes uncached or unverified".
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable

# Buffer keys are ("in"|"out"|"tmp", block-id); size symbols are small
# tuples resolved by the runner: ("blk", j) → block j's element count,
# ("seg", i) → ring-segment i of the flat payload, ("all",) → the whole
# payload, ("cnt",) → the scatterv count-vector header (size-N int64).
BufKey = tuple
SizeSym = tuple

SEND = "send"
RECV = "recv"
FOLD = "fold"
COPY = "copy"


class ScheduleError(Exception):
    pass


# ---------------------------------------------------------------------------
# Execution targets (ISSUE 15)
# ---------------------------------------------------------------------------
# A compiled schedule may annotate a phase with an execution TARGET
# (``spec["targets"] = {phase: name}``): an alternative executor the
# runner offers the phase's step group to before falling back to the
# per-step host path. The canonical target is ``device-ring``
# (device_plane/pallas_ring.py) — annotated permute phases run as
# compiled device mesh steps (Pallas ``make_async_remote_copy`` over
# ICI on TPU) instead of 2(n−1) host messages. Targets must DECLINE
# (return None from ``try_run``) on any mismatch, and their verdict
# must be world-symmetric: a rank-dependent accept/decline would desync
# the message pattern exactly like a desynced family choice.

_STEP_TARGETS: dict[str, object] = {}
_STEP_TARGETS_LOCK = threading.Lock()


def register_step_target(target) -> None:
    """Register (or replace) an execution target under ``target.name``.
    Targets expose ``try_run(world, rank, sched, phase, steps, env,
    resolver) -> int | None`` — the number of leading steps executed,
    or None to decline."""
    with _STEP_TARGETS_LOCK:
        _STEP_TARGETS[target.name] = target


def get_registered_target(name: str):
    with _STEP_TARGETS_LOCK:
        return _STEP_TARGETS.get(name)


def get_step_target(name: str):
    """Runner-side lookup; lazily arms the built-in device-ring target
    so schedules annotated with it work without any import order
    ceremony (the device plane may not have been touched yet when the
    first annotated schedule executes)."""
    t = get_registered_target(name)
    if t is None and name == "device-ring":
        try:
            from faabric_tpu.device_plane.pallas_ring import (
                ensure_registered,
            )

            ensure_registered()
        except Exception:  # noqa: BLE001 — targets are an optimization
            return None
        t = get_registered_target(name)
    return t


class ScheduleVerificationError(ScheduleError):
    """The schedule does not prove exactly-once delivery."""


@dataclass(frozen=True)
class Step:
    """One instruction of one rank's program.

    send: concatenate ``keys`` (in order) into one message to ``peer``.
    recv: receive one message from ``peer``, split into ``keys`` by the
          resolved ``syms`` sizes (single-key recvs discover the size
          from the wire and need no resolution).
    fold: ``dst = op(a, b)`` — operand ORDER is part of the schedule
          (prefix scans are order-sensitive; reductions conventionally
          fold (received, mine) like the hand-written rings).
    copy: ``dst = src`` (output assembly / accumulator seeding).
    ``phase`` tags the telemetry span the runner groups this step under.
    """

    op: str
    peer: int = -1
    keys: tuple = ()
    syms: tuple = ()
    dst: BufKey | None = None
    a: BufKey | None = None
    b: BufKey | None = None
    src: BufKey | None = None
    phase: str = ""


@dataclass
class Schedule:
    """A compiled collective: per-rank step programs + the semantic spec
    the verifier checks them against. ``spec`` is (collective-specific)
    extra structure: scatter/scatterv carry ``root``, allreduce carries
    the segment count. ``verified`` is set only by ``verify_schedule``;
    the runner refuses schedules without it."""

    name: str                     # family, e.g. "alltoall.hier"
    collective: str
    size: int
    steps: dict[int, tuple[Step, ...]]
    spec: dict = field(default_factory=dict)
    verified: bool = False

    def n_steps(self) -> int:
        return sum(len(s) for s in self.steps.values())


# ---------------------------------------------------------------------------
# Collective semantics: expected inputs/outputs as atom sets
# ---------------------------------------------------------------------------
# An atom is (owner rank, block id): the indivisible unit of payload the
# verifier tracks. Reductions treat an atom as "rank owner's
# contribution to block id"; fold unions atom sets and rejects overlap.

def _expected_io(collective: str, size: int, spec: dict):
    """(ins, outs): per-rank dicts of BufKey → frozenset(atoms)."""
    n = size
    ins: dict[int, dict] = {r: {} for r in range(n)}
    outs: dict[int, dict] = {r: {} for r in range(n)}
    if collective == "alltoall":
        for r in range(n):
            for j in range(n):
                ins[r][("in", j)] = frozenset({(r, j)})
                outs[r][("out", j)] = frozenset({(j, r)})
    elif collective in ("scatter", "scatterv"):
        root = spec["root"]
        for j in range(n):
            ins[root][("in", j)] = frozenset({(root, j)})
            outs[j][("out", 0)] = frozenset({(root, j)})
        if spec.get("counts_header"):
            # The count-vector header carries no payload atoms; it only
            # binds split sizes at the leaders
            ins[root][("in", "cnt")] = frozenset()
    elif collective == "scan":
        for r in range(n):
            ins[r][("in", 0)] = frozenset({(r, 0)})
            outs[r][("out", 0)] = frozenset({(q, 0) for q in range(r + 1)})
    elif collective == "allreduce":
        segs = spec["segments"]
        for r in range(n):
            for s in range(segs):
                ins[r][("in", s)] = frozenset({(r, s)})
                outs[r][("out", s)] = frozenset({(q, s) for q in range(n)})
    elif collective == "reduce_scatter":
        for r in range(n):
            for j in range(n):
                ins[r][("in", j)] = frozenset({(r, j)})
            outs[r][("out", 0)] = frozenset({(q, r) for q in range(n)})
    elif collective == "allgather":
        for r in range(n):
            ins[r][("in", 0)] = frozenset({(r, 0)})
            for q in range(n):
                outs[r][("out", q)] = frozenset({(q, 0)})
    else:
        raise ScheduleError(f"Unknown collective {collective!r}")
    return ins, outs


# ---------------------------------------------------------------------------
# Verifier
# ---------------------------------------------------------------------------
def verify_schedule(sched: Schedule) -> Schedule:
    """Prove exactly-once delivery by abstract interpretation (see
    module docstring). Returns ``sched`` with ``verified`` set; raises
    ScheduleVerificationError naming the first violation."""
    n = sched.size
    ins, outs_expected = _expected_io(sched.collective, n, sched.spec)
    env: dict[int, dict] = {r: dict(ins[r]) for r in range(n)}
    out_writes: dict[int, dict] = {r: {} for r in range(n)}
    chans: dict[tuple[int, int], list] = {}
    ptr = [0] * n
    steps = {r: sched.steps.get(r, ()) for r in range(n)}

    def fail(msg: str):
        raise ScheduleVerificationError(
            f"{sched.name} ({sched.collective}, n={n}): {msg}")

    def read(r: int, key: BufKey):
        try:
            return env[r][key]
        except KeyError:
            fail(f"rank {r} reads undefined buffer {key}")

    def write(r: int, key: BufKey, atoms):
        if key[0] == "out":
            count = out_writes[r].get(key, 0)
            if count:
                fail(f"rank {r} writes output {key} twice "
                     f"(double delivery)")
            out_writes[r][key] = count + 1
        env[r][key] = atoms

    progressed = True
    while progressed:
        progressed = False
        for r in range(n):
            while ptr[r] < len(steps[r]):
                st = steps[r][ptr[r]]
                if st.op == SEND:
                    if st.peer == r or not (0 <= st.peer < n):
                        fail(f"rank {r} sends to invalid peer {st.peer}")
                    vals = [read(r, k) for k in st.keys]
                    chans.setdefault((r, st.peer), []).append(
                        (vals, st.syms))
                elif st.op == RECV:
                    q = chans.get((st.peer, r))
                    if not q:
                        break  # blocked on the channel; try other ranks
                    vals, syms = q.pop(0)
                    if len(vals) != len(st.keys) or syms != st.syms:
                        fail(f"rank {r} recv from {st.peer} framing "
                             f"mismatch: sent {syms}, expected {st.syms}")
                    for k, v in zip(st.keys, vals):
                        write(r, k, v)
                elif st.op == FOLD:
                    a, b = read(r, st.a), read(r, st.b)
                    if a & b:
                        fail(f"rank {r} fold {st.dst} double-counts "
                             f"contributions {sorted(a & b)[:4]}")
                    write(r, st.dst, a | b)
                elif st.op == COPY:
                    write(r, st.dst, read(r, st.src))
                else:
                    fail(f"rank {r}: unknown step op {st.op!r}")
                ptr[r] += 1
                progressed = True

    stuck = [r for r in range(n) if ptr[r] < len(steps[r])]
    if stuck:
        details = ", ".join(
            f"r{r}@{ptr[r]}:{steps[r][ptr[r]].op}<-{steps[r][ptr[r]].peer}"
            for r in stuck[:4])
        fail(f"deadlock: ranks {stuck} blocked ({details})")
    leftover = {c: len(q) for c, q in chans.items() if q}
    if leftover:
        fail(f"undelivered messages on channels {leftover} "
             f"(missing recvs)")
    for r in range(n):
        for key, expected in outs_expected[r].items():
            if key not in out_writes[r]:
                fail(f"rank {r} output {key} never written "
                     f"(missing element)")
            got = env[r][key]
            if got != expected:
                missing = sorted(expected - got)[:4]
                extra = sorted(got - expected)[:4]
                fail(f"rank {r} output {key} wrong contents: "
                     f"missing {missing}, extra {extra}")
        unexpected = set(out_writes[r]) - set(outs_expected[r])
        if unexpected:
            fail(f"rank {r} writes undeclared outputs "
                 f"{sorted(unexpected)[:4]}")
    sched.verified = True
    return sched


# ---------------------------------------------------------------------------
# Cache
# ---------------------------------------------------------------------------
class ScheduleCache:
    """Verified-schedule cache of one MpiWorld, keyed
    (topology-generation, collective, root, op-class, dtype-class,
    size-class) — the device plane's executable-cache discipline. The
    generation in the key makes migration/topology remaps invalidate
    naturally: a remap bumps the world's generation, old entries stop
    matching and age out at the cardinality backstop.

    Cache state across PROCESSES stays in lockstep because every rank
    executes the same collective call sequence with the same payload
    classes — the property the selection-sync round in MpiWorld relies
    on (see ``_sched_family``)."""

    # Concurrency contract (tools/concheck.py): entries and counters
    # mutate under the cache lock; rank threads of one process share it
    GUARDS = {
        "_entries": "_lock",
        "_families": "_lock",
        "compiles": "_lock",
        "hits": "_lock",
    }

    MAX_ENTRIES = 256

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._entries: dict[tuple, tuple[str, Schedule]] = {}
        # key → world-agreed family, SEPARATE from the evictable
        # schedule entries: MpiWorld's per-rank seen-ledger promises
        # that a key which already ran its selection round never runs
        # another (the round is a world-wide broadcast — skipping it
        # unilaterally would desync channels), so the agreed family
        # must survive the cardinality backstop below. Bytes-tiny (a
        # string per distinct key); pruned of dead generations with
        # the entries.
        self._families: dict[tuple, str] = {}
        self.compiles = 0
        self.hits = 0

    def family_of(self, key: tuple) -> str | None:
        with self._lock:
            return self._families.get(key)

    def note_family(self, key: tuple, family: str) -> None:
        """Record the world-agreed family the moment the selection
        round concludes — before any compile can fail — so a rank that
        marked the round done can always recover the verdict."""
        with self._lock:
            self._families[key] = family

    def get(self, key: tuple) -> Schedule | None:
        with self._lock:
            hit = self._entries.get(key)
            if hit is None:
                return None
            self.hits += 1
            return hit[1]

    def get_or_compile(self, key: tuple, family: str,
                       compile_fn: Callable[[], Schedule]) -> Schedule:
        """Single-compilation get: the first rank thread through compiles
        and VERIFIES (verify_schedule is the only path to verified=True);
        siblings wait on the lock and hit. An unverified compile result
        never lands in the cache — the raise propagates to every caller
        of this collective, never a silent fallback."""
        with self._lock:
            hit = self._entries.get(key)
            if hit is not None:
                self.hits += 1
                return hit[1]
            sched = compile_fn()
            if not sched.verified:
                verify_schedule(sched)
            if len(self._entries) >= self.MAX_ENTRIES:
                # Cardinality backstop: drop entries from dead
                # generations first, then wholesale (recompiles are
                # cheap and deterministic). The family ledger only
                # sheds dead generations — a live key's agreed family
                # must outlive its schedule (see __init__).
                gen = key[0]
                for k in [k for k in self._entries if k[0] != gen]:
                    del self._entries[k]
                for k in [k for k in self._families if k[0] != gen]:
                    del self._families[k]
                if len(self._entries) >= self.MAX_ENTRIES:
                    self._entries.clear()
            self._entries[key] = (family, sched)
            self._families[key] = family
            self.compiles += 1
            return sched

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries),
                    "compiles": self.compiles, "hits": self.hits}
