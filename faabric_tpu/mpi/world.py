"""MpiWorld: MPI semantics on the framework's group substrate.

Reference analog: src/mpi/MpiWorld.cpp (2132 lines) and
include/faabric/mpi/MpiWorld.h. One world per app; rank 0 creates the
world by chaining (size-1) functions through the planner
(MpiWorld.cpp:157-226); other ranks join from their dispatched message.

Transport split, re-designed TPU-first:
- **Host path** (this file): rank↔host routing comes from the PTP group
  mappings; send/recv/sendrecv/isend/irecv and the collectives ride the
  PTP broker — same-host ranks through in-process queues, cross-host over
  the PTP RPC plane. Collectives keep the reference's locality-aware
  local-leader trees (broadcast :786-853, reduce :1127-1249, gather
  two-step :917-1080) so cross-host traffic is one leg per host, not per
  rank.
- **Device path** (``device_collectives()``): when buffers are
  device-resident, collectives compile to ``jax.lax`` ops over a
  ``jax.sharding.Mesh`` built from the chips the planner pinned each rank
  to (decision device ids → mesh positions) — see
  parallel/collectives.py. This replaces the reference's per-rank-pair
  TCP mesh (initSendRecvSockets :1789-1934): on TPU the rank mesh IS the
  ICI topology and XLA owns the schedule.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional, Sequence

import numpy as np

from faabric_tpu.mpi.types import (
    MpiMessageType,
    MpiOp,
    MpiStatus,
    MpiWirePayload,
    UserOp,
    apply_op,
    apply_op_inplace,
    mpi_dtype_for,
    pack_mpi_payload,
    unpack_mpi_payload,
)
from faabric_tpu.faults import fault_point, faults_enabled
from faabric_tpu.mpi.quant import (
    ALLREDUCE_QUANT,
    leader_ring_codec,
    resolve_quant_mode,
)
from faabric_tpu.telemetry import (
    NULL_SPAN,
    get_collective_profiler,
    get_metrics,
    span,
    tracing_enabled,
)
from faabric_tpu.transport.point_to_point import GroupAbortedError
from faabric_tpu.util.logging import get_logger

logger = get_logger(__name__)

MAIN_RANK = 0

# The MPI-facing name for a group abort: recv/barrier/collectives raise
# this within ~one liveness-check interval (mpi_abort_check_seconds)
# when a peer's host is dead or a send to it failed terminally — the
# transport layer detects and broadcasts the abort (point_to_point.py),
# this is simply its MPI-domain name.
MpiWorldAborted = GroupAbortedError

_FAULTS = faults_enabled()
_FP_COLLECTIVE = fault_point("mpi.collective")

# Ring collectives stream each per-rank segment as a pipeline of
# chunk-sized messages (one bulk frame each): while a rank folds chunk k
# its predecessor already has chunk k+1 on the wire and its successor is
# folding chunk k-1 — serialize/wire/deserialize overlap across hops the
# way HiCCL's pipelined collectives overlap channel stages. This
# replaced the PR 1 RING_MSG_CAP skip-to-fallback: oversized segments
# now chunk instead of bailing to the root-serialized tree. 2 MiB rides
# comfortably inside the shm rings / kernel socket buffers that carry
# the cross-process legs; measured against 4/8 MiB it holds the same
# throughput while cutting blocked-recv (enqueue_wait) time by ~35%.
RING_CHUNK_BYTES = int(os.environ.get("FAABRIC_RING_CHUNK_BYTES",
                                      2 * 1024 * 1024))

# Hierarchical topology-composed collectives (ISSUE 9): compose
# allreduce/reduce_scatter/allgather over the Topology — shm
# reduce-scatter within each host, a cross-host ring over the per-host
# LEADERS only on the striped bulk plane, then redistribution back down
# — so an N-rank world on H hosts puts ~1/(ranks-per-host) of the flat
# ring's bytes on the wire. Values: on (default; composes only when the
# hosts span real machines — see _hier_wins), "force" (compose even
# when every host resolves to this machine: the simulated-host dist
# tests/benches that measure the composition itself), off (flat paths
# always; the A/B baseline). It must agree across every process of a
# world or algorithm choice desyncs and the collective hangs, hence
# env-level with a per-world override that tests set identically on
# all sides.
_hier_env = os.environ.get("FAABRIC_HIER_COLLECTIVES", "1").lower()
HIER_COLLECTIVES = ("force" if _hier_env == "force"
                    else _hier_env not in ("0", "false", "off"))

# Collective schedule compiler (ISSUE 13, mpi/schedule.py): lower
# alltoall/scatter/scatterv/scan into verified step programs executed
# by the generic runner, selected per topology + measured link
# bandwidth. Values: on (default), off (the seed-era hand-written
# paths; the A/B baseline), "force" (compose hierarchically even when
# every host resolves to this machine — the simulated-host dist
# tests/benches). Like FAABRIC_HIER_COLLECTIVES it must agree across
# every process of a world: a desynced schedule choice mismatches the
# message pattern and hangs the collective. The world-level attribute
# ``sched_enabled`` overrides per world (tests set it identically on
# all sides).
_sched_env = os.environ.get("FAABRIC_SCHED_COLLECTIVES", "1").lower()
SCHED_COLLECTIVES = ("force" if _sched_env == "force"
                     else _sched_env not in ("0", "false", "off"))

# Device collective plane (ISSUE 10, faabric_tpu/device_plane/): the
# rung ABOVE the whole host ladder. Routing is opt-in per world — a
# world only has the rung after every rank ran the
# activate_device_plane handshake — so this knob exists for A/B runs
# and emergency disable: "0"/"off" makes activation refuse everywhere
# (must agree across the world's processes like the knobs above).
DEVICE_PLANE_ENABLED = os.environ.get(
    "FAABRIC_DEVICE_PLANE", "1").lower() not in ("0", "false", "off")

_metrics = get_metrics()
_coll_total: dict = {}
_coll_bytes: dict = {}

# Collective phase fold-in (ISSUE 12): every rank records its round
# entry stamp, per-phase durations and total into the collective
# profiler — the store behind /perf's critical-path decomposition and
# the straggler detector. Shared no-op when metrics/profiling are off.
_PROFILER = get_collective_profiler()


def _count_collective(op: str, nbytes: int) -> None:
    if _FAULTS:
        # One chaos choke point covering every host-path collective:
        # delay rules add straggler latency, raise rules fail the rank
        _FP_COLLECTIVE.fire(op=op, bytes=nbytes)
    c = _coll_total.get(op)
    b = _coll_bytes.get(op)
    if c is None or b is None:
        # Both setdefaults run unconditionally: ranks are concurrent
        # threads, and observing one dict populated must not imply the
        # other is (the registry dedupes handles, so racers agree)
        c = _coll_total.setdefault(op, _metrics.counter(
            "faabric_mpi_collectives_total",
            "Host-path collective invocations (per participating rank)",
            op=op))
        b = _coll_bytes.setdefault(op, _metrics.counter(
            "faabric_mpi_collective_bytes_total",
            "Per-rank payload bytes entering host-path collectives",
            op=op))
    c.inc()
    b.inc(nbytes)


class _SendWorker:
    """Daemon FIFO worker for one rank's remote async sends. Daemon so a
    transfer wedged on a dead peer can never hang interpreter exit; FIFO
    so a rank's sends to any one destination stay in order."""

    # _closed orders submits against shutdown's sentinel (see submit);
    # the SimpleQueue itself is internally synchronized
    GUARDS = {"_closed": "_state_lock"}

    def __init__(self, name: str) -> None:
        import queue as _queue

        self._q: "_queue.SimpleQueue" = _queue.SimpleQueue()
        self._closed = False
        self._state_lock = threading.Lock()
        self._t = threading.Thread(target=self._loop, name=name, daemon=True)
        self._t.start()

    def submit(self, fn):
        from concurrent.futures import Future

        fut: Future = Future()
        # Lock orders submits against shutdown's sentinel: either this
        # lands in the FIFO before the None (worker runs it) or _closed
        # is already visible and the future FAILS — never runs inline
        # (inline would reorder past still-queued sends and could block
        # the caller on a wedged peer) and never silently drops (which
        # would hang await_async forever)
        with self._state_lock:
            if self._closed:
                fut.set_exception(RuntimeError(
                    "MPI world closed while async send pending"))
            else:
                self._q.put((fn, fut))
        return fut

    def _loop(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            fn, fut = item
            try:
                fut.set_result(fn())
            except BaseException as e:  # noqa: BLE001 — delivered at wait()
                fut.set_exception(e)

    def shutdown(self) -> None:
        with self._state_lock:
            self._closed = True
            self._q.put(None)


class _LocalMpiPayload:
    """Same-host MPI message: the array object itself rides the queue.
    ``shared`` marks fan-out buffers delivered to several receivers (a
    consumer must copy before exposing them writable)."""

    __slots__ = ("msg_type", "data", "shared", "owned")

    def __init__(self, msg_type: MpiMessageType, data: np.ndarray,
                 shared: bool = False, owned: bool = False) -> None:
        self.msg_type = msg_type
        self.data = data
        self.shared = shared
        # owned=True: the sender TRANSFERRED the buffer — the receiver
        # may fold into it in place. This must ride the payload, not the
        # numpy writeable flag: flags live on the shared array object
        # and a sender restoring its view's writability would race the
        # receiver's flags-based ownership check
        self.owned = owned

    def to_bytes(self) -> bytes:
        """Late wire conversion if routing sends this remote after all
        (e.g. a live-migration remap between send and delivery)."""
        return pack_mpi_payload(self.msg_type, self.data)

    def __len__(self) -> int:
        return len(MpiWirePayload(self.msg_type, self.data))

    def buffers(self) -> list:
        return MpiWirePayload(self.msg_type, self.data).buffers()


class MpiWorld:
    # Concurrency contract (tools/concheck.py): rank bookkeeping and
    # topology caches mutate under the world RLock — collectives on N
    # rank threads share them. Deliberately unlisted: record_exec_graph
    # (configured before traffic starts), _in_send_pool (thread-local),
    # _send_workers (per-rank entries created under _lock in
    # _send_worker(); reads are GIL-atomic dict hits on an add-only
    # dict), _split_seq (only mutated under _lock in _split_draw).
    GUARDS = {
        "_requests": "_lock",
        "_next_request_id": "_lock",
        "_rank_hosts": "_lock",
        "_rank_devices": "_lock",
        "_topology_cache": "_lock",
        "_same_machine_cache": "_lock",
        "_topology_gen": "_lock",
        "_msg_count_to_rank": "_lock",
        "_msg_type_count": "_lock",
        "_device_collectives": "_lock",
        "_device_plane": "_lock",
        "_sched_seen": "_lock",
    }

    def __init__(self, broker, world_id: int, size: int, group_id: int,
                 user: str = "", function: str = "") -> None:
        self.broker = broker
        self.id = world_id
        self.size = size
        self.group_id = group_id
        self.user = user
        self.function = function

        self._lock = threading.RLock()
        # Per-rank async request bookkeeping (reference MpiRankState)
        self._requests: dict[int, dict[int, tuple]] = {}
        self._next_request_id = 1

        # rank → host cache (initLocalRemoteLeaders, MpiWorld.cpp:318-366)
        # and the immutable Topology derived from it (mpi/topology.py);
        # the cache object itself is lock-free to read once handed out
        self._rank_hosts: dict[int, str] = {}
        self._rank_devices: dict[int, int] = {}
        self._topology_cache = None
        self._same_machine_cache: bool | None = None
        self._topology_gen = 0  # bumped by refresh_rank_hosts

        # Hierarchical collective composition (module knob; tests/bench
        # override per world — identically on every process of the world)
        self.hier_enabled = HIER_COLLECTIVES
        # Leader-ring wire quantization (mpi/quant.py): "" or "int8".
        # World-level override of FAABRIC_ALLREDUCE_QUANT — like
        # hier_enabled it must agree across every process of the world
        self.allreduce_quant = ALLREDUCE_QUANT

        # Collective schedule compiler (ISSUE 13): the per-world
        # verified-schedule cache (keys carry the topology generation,
        # so migration remaps invalidate naturally) and the per-RANK
        # selection-round ledger — a rank joins the world-wide
        # selection broadcast exactly when ITS call sequence first
        # meets a key, which is identical on every rank because every
        # rank executes the same collective sequence (see
        # _sched_family). sched_reductions opts the hierarchical
        # reduction LOWERINGS in (with sched_enabled == "force"): the
        # hand-written zero-copy paths stay the tuned default
        # executors; the lowerings exist to prove IR coverage and are
        # bitwise-pinned against them in tests.
        from faabric_tpu.mpi.schedule import ScheduleCache

        self.sched_enabled = SCHED_COLLECTIVES
        self.sched_reductions = False
        self._sched_cache = ScheduleCache()
        self._sched_seen: dict[int, set] = {}

        # Exec-graph accounting (MpiWorld.h:13-18)
        self._msg_count_to_rank: dict[int, int] = {}
        self._msg_type_count: dict[tuple[int, int], int] = {}
        self.record_exec_graph = False

        self._device_collectives = None
        # The device collective plane (faabric_tpu/device_plane/):
        # None until activate_device_plane's handshake resolves the
        # world onto one mesh; cleared on migration remaps
        self._device_plane = None
        self._send_workers: dict[int, _SendWorker] = {}
        self._in_send_pool = threading.local()
        self._split_seq = 0  # split-generation draws (see _split_draw)

        # Bounded-time failure propagation: register with the broker so
        # recvs blocked on this world probe peer liveness and raise
        # MpiWorldAborted instead of hanging to the socket timeout
        # (guarded: some unit tests drive worlds with stub brokers)
        watch = getattr(broker, "watch_group", None)
        if watch is not None:
            watch(group_id)

    def abort(self, reason: str = "MPI_Abort") -> None:
        """Abort the world: every rank's blocked/future recv, barrier or
        collective on it raises MpiWorldAborted. Idempotent; callable
        from any rank or from the runtime when it learns a peer died."""
        abort = getattr(self.broker, "abort_group", None)
        if abort is not None:
            abort(self.group_id, reason)

    # ------------------------------------------------------------------
    # Topology
    # ------------------------------------------------------------------
    def refresh_rank_hosts(self) -> None:
        self.broker.wait_for_mappings(self.group_id)
        # Stub brokers in unit tests may not expose device mappings
        get_dev = getattr(self.broker, "get_device_for_idx", None)
        with self._lock:
            self._rank_hosts = {
                idx: self.broker.get_host_for_receiver(self.group_id, idx)
                for idx in range(self.size)
            }
            self._rank_devices = (
                {idx: get_dev(self.group_id, idx)
                 for idx in range(self.size)}
                if get_dev is not None else {})
            self._topology_cache = None
            self._same_machine_cache = None
            self._topology_gen += 1

    def topology(self):
        """The world's Topology (mpi/topology.py): immutable once built,
        rebuilt lazily after refresh_rank_hosts / migration remaps. The
        collectives' hierarchy decisions and the exported scheduler view
        both read this one object.

        Check-completeness and build-cache happen under ONE lock
        acquisition: a migration remap between them would cache a
        Topology built from a cleared/partial rank map (the same race
        class _all_hosts_same_machine guards with its gen check). A
        remap racing the out-of-lock refresh just sends us around the
        loop again."""
        from faabric_tpu.mpi.topology import Topology

        while True:
            with self._lock:
                if self._topology_cache is not None:
                    return self._topology_cache
                if len(self._rank_hosts) == self.size:
                    devices = (dict(self._rank_devices)
                               if any(d >= 0 for d in
                                      self._rank_devices.values())
                               else None)
                    self._topology_cache = Topology(dict(self._rank_hosts),
                                                    rank_devices=devices)
                    return self._topology_cache
            # Broker RPCs — must not run under _lock
            self.refresh_rank_hosts()

    def host_for_rank(self, rank: int) -> str:
        with self._lock:
            if rank not in self._rank_hosts:
                self.refresh_rank_hosts()
            return self._rank_hosts[rank]

    def ranks_on_host(self, host: str) -> list[int]:
        return list(self.topology().ranks_on_host(host))

    def local_leader(self, host: str) -> int:
        """Lowest rank on a host (reference initLocalRemoteLeaders)."""
        ranks = self.topology().ranks_on_host(host)
        if not ranks:
            raise ValueError(f"No ranks on host {host}")
        return ranks[0]

    def hosts(self) -> list[str]:
        return list(self.topology().hosts)

    def device_for_rank(self, rank: int) -> int:
        self.broker.wait_for_mappings(self.group_id)
        return self.broker.get_device_for_idx(self.group_id, rank)

    # ------------------------------------------------------------------
    # Device path
    # ------------------------------------------------------------------
    def device_collectives(self):
        """Compiled XLA collectives over the mesh of this world's chips
        (rank i ↔ planner-assigned device of rank i)."""
        with self._lock:
            if self._device_collectives is None:
                from faabric_tpu.parallel.collectives import (
                    DeviceCollectives,
                    local_devices_for_ids,
                )

                device_ids = [self.device_for_rank(r) for r in range(self.size)]
                devices = local_devices_for_ids(device_ids)
                self._device_collectives = DeviceCollectives(devices)
            return self._device_collectives

    def device_send_recv(self, x, src_rank: int, dst_rank: int):
        """Device-plane p2p: rank ``src``'s shard lands on rank ``dst``'s
        chip in one compiled ICI transfer (others zero) — the device twin
        of the host send/recv below."""
        return self.device_collectives().send_recv(x, src_rank, dst_rank)

    # ------------------------------------------------------------------
    # Device collective plane (ISSUE 10, faabric_tpu/device_plane/)
    # ------------------------------------------------------------------
    def activate_device_plane(self, rank: int, device=None) -> bool:
        """Collective registration handshake: every rank calls this once
        (after the world forms, or again after a migration remap) with
        its device — default: the planner-assigned chip riding the PTP
        mappings. One host-path allgather exchanges the registrations;
        every rank then derives the SAME activate/fall-back verdict from
        the full row set (device_plane/registry.py), so the dispatch
        ladder can never desync. Returns True when the plane activated:
        from then on eligible allreduce/allgather/reduce_scatter run as
        compiled donated-buffer programs over the resolved mesh and put
        ZERO collective-payload bytes on the host shm/tcp planes."""
        import jax

        from faabric_tpu.device_plane import (
            DevicePlane,
            MeshMismatch,
            registration_row,
            resolve_local_device,
            resolve_mesh,
        )

        if not DEVICE_PLANE_ENABLED:
            return False
        if device is None:
            device = resolve_local_device(self, rank)
        # The handshake is the ONLY wire exchange; it must ride the
        # host ladder even if a previous activation is still live
        # (re-activation after migration), so clear the rung first
        with self._lock:
            gen = self._topology_gen
            plane = self._device_plane
            if plane is not None and plane.topology_gen != gen:
                self._device_plane = None
        rows = self.allgather(rank, registration_row(rank, device))
        with self._lock:
            plane = self._device_plane
            if (plane is not None and plane.topology_gen == gen
                    and plane.disabled_reason is None):
                return True  # a sibling local rank already resolved it
        try:
            devices = resolve_mesh(
                rows, self.size,
                local_ranks=self.ranks_on_host(self.broker.host),
                process_index=jax.process_index())
        except MeshMismatch as e:
            logger.info("Device plane for world %s not activated: %s",
                        self.id, e)
            return False
        plane = DevicePlane(
            self.id, devices,
            local_ranks=self.ranks_on_host(self.broker.host),
            topology_gen=gen)
        with self._lock:
            # First resolver publishes (a re-handshake REPLACES a
            # disabled plane — the collective activation call is the
            # recovery path after a backend error); a topology remap
            # racing the handshake leaves the rung down and reports so
            if self._topology_gen != gen:
                return False  # remap raced the handshake; re-activate
            cur = self._device_plane
            if (cur is None or cur.topology_gen != gen
                    or cur.disabled_reason is not None):
                self._device_plane = plane
        return True

    def device_plane(self):
        """The active DevicePlane rung, or None (host ladder only).
        Stale planes (migration remap bumped the topology generation)
        read as None — mesh mismatch falls back, never desyncs."""
        with self._lock:
            plane = self._device_plane
            if plane is not None and plane.topology_gen != self._topology_gen:
                return None
            return plane

    # ------------------------------------------------------------------
    # Point-to-point
    # ------------------------------------------------------------------
    def send(self, send_rank: int, recv_rank: int, data: np.ndarray,
             msg_type: MpiMessageType = MpiMessageType.NORMAL,
             request_id: int = 0, _copy: bool = True,
             _transfer: bool = False) -> None:
        """``_copy=False`` is for fan-out callers that already hold an
        immutable private buffer (broadcast trees) — skips the per-receiver
        defensive copy. ``_transfer=True`` additionally hands the buffer's
        OWNERSHIP to the receiver (the sender must drop every reference):
        the array stays writable so the receiver can fold into it in
        place (ring allreduce)."""
        if self.record_exec_graph:
            with self._lock:
                self._msg_count_to_rank[recv_rank] = \
                    self._msg_count_to_rank.get(recv_rank, 0) + 1
                key = (int(msg_type), recv_rank)
                self._msg_type_count[key] = self._msg_type_count.get(key, 0) + 1

        # Program order: a blocking send must not overtake this rank's
        # queued async sends to the same destination
        self._fence_sends(send_rank, recv_rank)

        # Same-host ranks skip serialization entirely: one defensive copy
        # (MPI semantics: the sender may reuse its buffer immediately) rides
        # the in-process queue as an array object — the analog of the
        # reference's malloc+memcpy onto the InMemoryMpiQueue
        # (MpiWorld.cpp:620-634), minus the wire pack/unpack copies.
        self.broker.wait_for_mappings(self.group_id)
        if self.broker.get_host_for_receiver(self.group_id, recv_rank) \
                == self.broker.host:
            arr = np.asarray(data)
            if _copy and not _transfer:
                arr = arr.copy()
            if not _transfer:
                arr.flags.writeable = False
            payload = _LocalMpiPayload(msg_type, arr,
                                       shared=not _copy and not _transfer,
                                       owned=_transfer)
        else:
            # Lazy wire form: the bulk plane sends header + array buffer
            # straight from this rank's memory, no concatenation copy.
            # The serialize span exists for the bandwidth-attribution
            # report: with zero-copy framing it SHOULD be ~0, and a fat
            # one (non-contiguous input forcing a copy) is a suspect.
            with span("mpi.wire", "serialize", rank=send_rank) \
                    if tracing_enabled() else NULL_SPAN:
                payload = MpiWirePayload(msg_type, np.asarray(data),
                                         request_id)
        self.broker.send_message(self.group_id, send_rank, recv_rank,
                                 payload, must_order=True)

    def _recv_raw(self, send_rank: int, recv_rank: int,
                  timeout: float | None = None
                  ) -> tuple[np.ndarray, MpiStatus]:
        """Internal receive: the array may be read-only / shared (zero-copy
        local path). Collectives use this — they never mutate received
        buffers in place unless the sender transferred ownership (see
        _recv_raw_owned)."""
        arr, status, _ = self._recv_raw_owned(send_rank, recv_rank,
                                              timeout=timeout)
        return arr, status

    def _recv_raw_owned(self, send_rank: int, recv_rank: int,
                        timeout: float | None = None
                        ) -> tuple[np.ndarray, MpiStatus, bool]:
        """Internal receive + ownership bit: True iff the sender
        TRANSFERRED the buffer (ring fold path), so the receiver may
        mutate it in place."""
        raw = self.broker.recv_message(self.group_id, send_rank, recv_rank,
                                       must_order=True, timeout=timeout)
        if isinstance(raw, _LocalMpiPayload):
            arr = raw.data
            owned = raw.owned
        else:
            _, arr, _req = self._unpack_wire(raw)
            # Wire arrays are exclusively ours but frombuffer-read-only;
            # writable ones (bytearray-backed) may be folded in place
            owned = arr.flags.writeable
        status = MpiStatus(source=send_rank, count=arr.size,
                           dtype=int(mpi_dtype_for(arr.dtype)))
        return arr, status, owned

    @staticmethod
    def _unpack_wire(raw):
        """Wire unpack with a deserialize span for the attribution
        report (zero-copy wrap for bulk-plane buffers; the span being
        fat means the RPC plane's bytes→array copy is the suspect)."""
        with span("mpi.wire", "deserialize", bytes=len(raw)) \
                if tracing_enabled() else NULL_SPAN:
            return unpack_mpi_payload(raw)

    def recv(self, send_rank: int, recv_rank: int,
             timeout: float | None = None) -> tuple[np.ndarray, MpiStatus]:
        """Public receive: the returned buffer is caller-owned and
        writable (MPI semantics)."""
        raw = self.broker.recv_message(self.group_id, send_rank, recv_rank,
                                       must_order=True, timeout=timeout)
        if isinstance(raw, _LocalMpiPayload):
            arr = raw.data
            if raw.shared:
                arr = arr.copy()  # several receivers hold this buffer
            elif not arr.flags.writeable:
                try:
                    # Exclusively ours (the sender's private copy): flip the
                    # owning array back to writable, no copy
                    arr.flags.writeable = True
                except ValueError:
                    arr = arr.copy()
        else:
            _, arr, _req = self._unpack_wire(raw)
            if not arr.flags.writeable:
                # Zero-copy coded-stream delivery (transport/codec.py)
                # shares the receiver's immutable base cache; the
                # public recv contract is a caller-owned writable array
                arr = arr.copy()
        status = MpiStatus(source=send_rank, count=arr.size,
                           dtype=int(mpi_dtype_for(arr.dtype)))
        return arr, status

    def recv_shared(self, send_rank: int, recv_rank: int,
                    timeout: float | None = None
                    ) -> tuple[np.ndarray, MpiStatus]:
        """Zero-copy receive: like ``recv`` but the returned array may
        be READ-ONLY and shared — with other local receivers of a
        fan-out, or with the transport's receive-side delta cache
        (repeated payloads on a coded stream deliver as the SAME
        immutable buffer, ISSUE 11). The faabric analog of serving
        state from the mapped shared-memory region instead of copying
        it out. Safe indefinitely: shared buffers are immutable by
        construction, and a consumer's reference keeps one alive past
        cache eviction. Use for read-only consumers (serving weights,
        assembling into your own destination); call ``recv`` when you
        need a private writable array."""
        return self._recv_raw(send_rank, recv_rank, timeout=timeout)

    def probe(self, send_rank: int, recv_rank: int,
              timeout: float | None = None) -> MpiStatus:
        """Blocking MPI_Probe: status of the next pending message from
        ``send_rank`` without consuming it (reference mpi.h MPI_Probe)."""
        raw = self.broker.probe_message(self.group_id, send_rank, recv_rank,
                                        timeout=timeout)
        return self._status_of(send_rank, raw)

    def iprobe(self, send_rank: int, recv_rank: int) -> Optional[MpiStatus]:
        """Non-blocking MPI_Iprobe: status or None."""
        raw = self.broker.try_probe_message(self.group_id, send_rank,
                                            recv_rank)
        if raw is None:
            return None
        return self._status_of(send_rank, raw)

    @staticmethod
    def _status_of(send_rank: int, raw) -> MpiStatus:
        if isinstance(raw, _LocalMpiPayload):
            return MpiStatus(source=send_rank, count=raw.data.size,
                             dtype=int(mpi_dtype_for(raw.data.dtype)))
        # Wire payload: count/dtype come from the fixed header — probing
        # a pending 100 MiB message must not deserialize it
        import struct as _struct

        from faabric_tpu.mpi.types import MPI_HEADER_FMT, MPI_HEADER_LEN

        _mt, dtype, _, count, _rid = _struct.unpack(
            MPI_HEADER_FMT, bytes(raw[:MPI_HEADER_LEN]))
        return MpiStatus(source=send_rank, count=count, dtype=dtype)

    def sendrecv(self, send_data: np.ndarray, send_rank: int, dst: int,
                 src: int, recv_rank: int) -> tuple[np.ndarray, MpiStatus]:
        """Concurrent send+recv for one rank (reference :752-785 uses an
        async send; sends here never block on the receiver). ``send_rank``
        is the sending index of the outbound message; ``recv_rank`` the
        receiving index of the inbound one (normally the same rank)."""
        self.send(send_rank, dst, send_data, MpiMessageType.SENDRECV)
        return self.recv(src, recv_rank)

    # -- async (reference :496-540 encodes requests + UNACKED buffers;
    # here a registry + per-rank send workers) ---------------------------
    def _send_worker(self, rank: int) -> "_SendWorker":
        """One daemon worker per sending rank: submission order per rank
        keeps (source, dest) streams non-overtaking, and one rank's slow
        transfer never stalls another rank's async sends."""
        with self._lock:
            w = self._send_workers.get(rank)
            if w is None:
                w = _SendWorker(f"mpi/send@{self.id}-r{rank}")
                self._send_workers[rank] = w
            return w

    def _fence_sends(self, rank: int, recv_rank: int) -> None:
        """Order a blocking send after the rank's queued isends TO THE
        SAME DESTINATION (MPI non-overtaking is per (source, dest) pair).
        Skipped on the send worker itself — it IS the queue."""
        if not self._send_workers:
            return  # no remote isend ever issued: nothing to fence
        if getattr(self._in_send_pool, "flag", False):
            return
        with self._lock:
            futs = [entry[1] for entry in
                    self._requests.get(rank, {}).values()
                    if entry[0] == "send" and entry[1] is not None
                    and entry[2] == recv_rank]
        for f in futs:
            f.exception()  # wait; errors surface at wait()

    def isend(self, send_rank: int, recv_rank: int, data: np.ndarray) -> int:
        with self._lock:
            rid = self._next_request_id
            self._next_request_id += 1

        self.broker.wait_for_mappings(self.group_id)
        remote = self.broker.get_host_for_receiver(
            self.group_id, recv_rank) != self.broker.host
        if remote:
            # Remote sends can block on TCP: run on the rank's send
            # worker so isend returns immediately (the reference's
            # UNACKED-buffer progress analog). Copy now — MPI lets the
            # caller reuse the buffer as soon as isend returns.
            payload = np.asarray(data).copy()

            def _do_send():
                self._in_send_pool.flag = True
                self.send(send_rank, recv_rank, payload, request_id=rid)

            fut = self._send_worker(send_rank).submit(_do_send)
            with self._lock:
                self._requests.setdefault(send_rank, {})[rid] = (
                    "send", fut, recv_rank)
        else:
            # Local enqueue never blocks; fire inline
            self.send(send_rank, recv_rank, data, request_id=rid)
            with self._lock:
                self._requests.setdefault(send_rank, {})[rid] = (
                    "send", None, recv_rank)
        return rid

    def irecv(self, send_rank: int, recv_rank: int) -> int:
        with self._lock:
            rid = self._next_request_id
            self._next_request_id += 1
            self._requests.setdefault(recv_rank, {})[rid] = (
                "recv", send_rank, recv_rank)
        return rid

    def await_async(self, rank: int, request_id: int
                    ) -> Optional[tuple[np.ndarray, MpiStatus]]:
        """MPI_Wait. Recvs complete here (lazy, like the reference's
        recvBatchReturnLast :1963-2030); local sends completed at isend,
        remote isends join their send worker here (errors surface now)."""
        with self._lock:
            entry = self._requests.get(rank, {}).pop(request_id, None)
        if entry is None:
            raise KeyError(f"Unknown MPI request {request_id} for rank {rank}")
        if entry[0] == "send":
            fut = entry[1]
            if fut is not None:
                fut.result()  # join the send worker; surfaces send errors
            return None
        _, send_rank, recv_rank = entry
        return self.recv(send_rank, recv_rank)

    def pending_requests(self, rank: int) -> int:
        with self._lock:
            return len(self._requests.get(rank, {}))

    def request_free(self, rank: int, request_id: int) -> None:
        """MPI_Request_free: drop the handle without waiting. Sends
        complete in their worker regardless. A freed irecv whose message
        already arrived consumes and discards it (so it can't be handed
        to a later unrelated recv); freeing a still-unmatched irecv just
        drops the handle — the standard itself calls that erroneous on
        the user's part (a message sent for it would go to the next
        matching recv)."""
        with self._lock:
            entry = self._requests.get(rank, {}).pop(request_id, None)
        if entry is None:
            return  # already completed/freed — MPI_REQUEST_NULL no-op
        if entry[0] == "recv":
            _, send_rank, recv_rank = entry
            if self.broker.try_probe_message(self.group_id, send_rank,
                                             recv_rank) is not None:
                self.recv(send_rank, recv_rank)  # consume + discard

    def request_ready(self, rank: int, request_id: int) -> bool:
        """True when await_async would complete without blocking (local
        sends at isend, remote isends when their send worker finishes,
        recvs when their message has arrived)."""
        with self._lock:
            entry = self._requests.get(rank, {}).get(request_id)
        if entry is None:
            raise KeyError(f"Unknown MPI request {request_id} for rank {rank}")
        if entry[0] == "send":
            fut = entry[1]
            return fut is None or fut.done()
        _, send_rank, recv_rank = entry
        return self.broker.try_probe_message(self.group_id, send_rank,
                                             recv_rank) is not None

    def waitall(self, rank: int, request_ids: list[int]
                ) -> list[Optional[tuple[np.ndarray, MpiStatus]]]:
        """MPI_Waitall: complete every request, results in input order."""
        return [self.await_async(rank, rid) for rid in request_ids]

    def waitany(self, rank: int, request_ids: list[int],
                timeout: float | None = None
                ) -> tuple[int, Optional[tuple[np.ndarray, MpiStatus]]]:
        """MPI_Waitany: (index, result) of the first completable request.
        Local sends are instantly ready, remote isends once their send
        worker finishes them, recvs when their message arrives. Ids
        already completed by an earlier wait are skipped (the standard
        repeated-waitany loop); an empty/fully-completed list returns
        (-1, None) — MPI_UNDEFINED."""
        import time as _time

        deadline = None if timeout is None else _time.monotonic() + timeout
        while True:
            live = 0
            for i, rid in enumerate(request_ids):
                try:
                    ready = self.request_ready(rank, rid)
                except KeyError:
                    continue  # completed by an earlier wait
                live += 1
                if ready:
                    return i, self.await_async(rank, rid)
            if live == 0:
                return -1, None
            if deadline is not None and _time.monotonic() >= deadline:
                raise TimeoutError("MPI_Waitany timed out")
            _time.sleep(0.0005)

    # ------------------------------------------------------------------
    # Collective schedule compiler (ISSUE 13, mpi/schedule.py)
    # ------------------------------------------------------------------
    def _sched_key(self, collective: str, op=None, dtype=None,
                   nbytes=None, root: int = 0) -> tuple:
        """Cache key: (topology-generation, collective, root, op-class,
        dtype-class, size-class) — the device-plane executable-cache
        discipline. Every component is identical on every rank of a
        call (MPI requires matching payload shapes; scatterv receivers,
        which know nothing of the payload, key class-less), so
        per-process caches stay in lockstep and migration remaps
        (generation bumps) invalidate world-wide."""
        from faabric_tpu.telemetry.perfprofile import size_class

        self.topology()  # ensure the generation matches a built topology
        with self._lock:
            gen = self._topology_gen
        opc = ("-" if op is None
               else "u" if isinstance(op, UserOp) else f"b{int(op)}")
        dtc = "-" if dtype is None else np.dtype(dtype).str
        szc = "-" if nbytes is None else size_class(int(nbytes))
        return (gen, collective, root, opc, dtc, szc)

    def _sched_family(self, rank: int, key: tuple, collective: str,
                      nbytes: int | None) -> str:
        """World-agreed schedule family for ``key``. Selection reads
        THIS process's perf-profile store — which measures different
        links on every process — so the verdict is computed on rank 0
        only and distributed by a one-shot broadcast (the selection
        sync round); a locally-derived choice could desync the world's
        message pattern and hang the collective. A rank joins the round
        exactly when its OWN call sequence first meets ``key`` — that
        predicate is identical on every rank (same call sequence, same
        keys), unlike the process-shared cache a sibling rank thread
        may already have filled."""
        from faabric_tpu.mpi.schedule_compile import (
            FAMILIES,
            FAMILY_IDS,
            choose_family,
        )

        with self._lock:
            seen = self._sched_seen.setdefault(rank, set())
            need_round = key not in seen
        if not need_round:
            fam = self._sched_cache.family_of(key)
            assert fam is not None, f"selection ran but {key} uncached"
            return fam
        if rank == MAIN_RANK:
            fam = self._sched_cache.family_of(key)
            if fam is None:
                fam = choose_family(collective, self.topology(),
                                    nbytes or 0, self.sched_enabled)
            self._broadcast_impl(
                MAIN_RANK, rank,
                np.array([FAMILY_IDS[fam]], dtype=np.int64))
        else:
            arr = self._broadcast_impl(MAIN_RANK, rank,
                                       np.empty(1, dtype=np.int64))
            fam = FAMILIES[int(arr.reshape(-1)[0])]
        # Ledger write BEFORE the seen-mark: a rank that will skip all
        # future rounds for this key must always be able to recover
        # the agreed verdict, even across schedule-entry eviction or a
        # compile failure after this point
        self._sched_cache.note_family(key, fam)
        with self._lock:
            seen = self._sched_seen[rank]
            # Generations are monotonic, so keys of other generations
            # can never be looked up again — shed them here or a
            # migration-churned long-lived world leaks one seen-set
            # entry per (rank, key, generation) forever
            stale = {k for k in seen if k[0] != key[0]}
            if stale:
                seen -= stale
            seen.add(key)
        return fam

    def _sched_get(self, rank: int, collective: str, op=None, dtype=None,
                   nbytes=None, root: int = 0):
        """(schedule, family) for one collective call: selection sync on
        first encounter, then compile-verify-cache once per process.
        Every schedule handed out is verified — get_or_compile runs the
        verifier before caching and the runner refuses unverified
        schedules, so nothing executes uncached or unverified."""
        from faabric_tpu.mpi.schedule_compile import compile_schedule

        key = self._sched_key(collective, op=op, dtype=dtype,
                              nbytes=nbytes, root=root)
        family = self._sched_family(rank, key, collective, nbytes)
        topo = self.topology()
        sched = self._sched_cache.get_or_compile(
            key, family,
            lambda: compile_schedule(family, collective, topo, root=root))
        return sched, family

    @staticmethod
    def _sched_phase_groups(steps):
        groups: list[tuple[str, list]] = []
        for st in steps:
            if not groups or groups[-1][0] != st.phase:
                groups.append((st.phase, []))
            groups[-1][1].append(st)
        return groups

    def _run_schedule(self, rank: int, sched, env: dict, op,
                      resolver, msg_type: MpiMessageType) -> dict:
        """The generic schedule runner: execute ``rank``'s step program
        over ``env`` (block key → flat ndarray). Sends concatenate
        blocks into one message; recvs split by ``resolver``-bound
        sizes (single-block recvs discover their size from the wire);
        folds apply ``op`` in the schedule's operand order; copies are
        reference moves (assembly copies where ownership demands).
        Per-phase spans ride ``mpi.phase`` like the hand-written
        hierarchical paths, so /perf's critical path decomposes
        schedule rounds the same way.

        Phases annotated with an execution TARGET (``spec["targets"]``,
        ISSUE 15 — the device-ring permute executor) are offered to the
        registered target first; a decline (None) or a partial run (the
        target returns how many leading steps it executed) falls
        through to the per-step host path for the remainder, so a
        target can never change the message pattern it does not fully
        own."""
        from faabric_tpu.mpi.schedule import (
            COPY,
            FOLD,
            RECV,
            SEND,
            ScheduleError,
            get_step_target,
        )

        if not sched.verified:
            raise ScheduleError(
                f"refusing to execute unverified schedule {sched.name}")
        steps = sched.steps.get(rank, ())
        traced = tracing_enabled()
        phase_targets = sched.spec.get("targets") or {}
        for phase, group in self._sched_phase_groups(steps):
            done = 0
            tname = phase_targets.get(phase)
            if tname:
                target = get_step_target(tname)
                if target is not None:
                    handled = target.try_run(self, rank, sched, phase,
                                             group, env, resolver)
                    if handled:
                        done = handled
            if done >= len(group):
                continue
            with span("mpi.phase", phase or "run", rank=rank) \
                    if traced else NULL_SPAN:
                for st in group[done:]:
                    if st.op == SEND:
                        bufs = [np.asarray(env[k]).reshape(-1)
                                for k in st.keys]
                        payload = (bufs[0] if len(bufs) == 1
                                   else np.concatenate(bufs))
                        self.send(rank, st.peer, payload, msg_type)
                    elif st.op == RECV:
                        arr, _ = self._recv_raw(st.peer, rank)
                        arr = arr.reshape(-1)
                        if len(st.keys) == 1:
                            env[st.keys[0]] = arr
                            continue
                        pos = 0
                        for k, sym in zip(st.keys, st.syms):
                            count = int(resolver(sym, env))
                            env[k] = arr[pos:pos + count]
                            pos += count
                        if pos != arr.size:
                            raise ScheduleError(
                                f"{sched.name}: rank {rank} recv from "
                                f"{st.peer} split {pos} of {arr.size} "
                                f"elements (framing desync)")
                    elif st.op == FOLD:
                        env[st.dst] = np.asarray(
                            apply_op(op, env[st.a], env[st.b])
                        ).reshape(-1)
                    elif st.op == COPY:
                        env[st.dst] = np.asarray(env[st.src]).reshape(-1)
        return env

    # ------------------------------------------------------------------
    # Collectives — locality-aware leader trees on the host path
    # ------------------------------------------------------------------
    def barrier(self, rank: int) -> None:
        # Gather-to-0 + broadcast (reference :1753-1775) — delegated to the
        # group barrier, which already has a single-host fast path
        _count_collective("barrier", 0)
        with span("mpi", "barrier", rank=rank, size=self.size):
            self.broker.wait_for_mappings(self.group_id)
            group = self.broker.get_group(self.group_id)
            group.barrier(rank)

    def _try_device(self, kind: str, dplane, rank: int, arr: np.ndarray,
                    op=None):
        """The ``plane=device`` rung (ISSUE 10): run the collective as a
        compiled program over the activated mesh. Returns the result, or
        None after a clean fallback — a backend error disabled the plane
        (symmetrically: the compiled collective is synchronous across
        processes) and the caller re-runs on the host ladder. The
        fallback re-run counts the collective a second time; that is the
        truthful reading (two attempts were made) and only occurs on the
        plane's terminal failure."""
        from faabric_tpu.device_plane import DevicePlaneFallback

        _count_collective(kind, int(arr.nbytes))
        with span("mpi", kind, rank=rank, size=self.size,
                  bytes=int(arr.nbytes), algo="device"):
            try:
                if kind == "allreduce":
                    return dplane.allreduce(rank, arr, op)
                if kind == "allgather":
                    return dplane.allgather(rank, arr)
                return dplane.reduce_scatter(rank, arr, op)
            except DevicePlaneFallback as e:
                logger.warning("Device %s (world %s) fell back to the "
                               "host ladder: %s", kind, self.id, e)
                return None

    # Above this, collectives stream in chunks so tree stages overlap:
    # while a leader reduces chunk k, chunk k+1 is on the wire and chunk
    # k-1 is being folded at the root — the host-path analog of a
    # pipelined ring. 4 MiB rides the kernel socket buffer cap on the
    # cross-host wire; a single-host world has no wire leg to overlap,
    # so bigger chunks win (fewer queue wakeups per GiB — measured +10%
    # effective on the 4-rank 97 MiB allreduce bench).
    CHUNK_BYTES = 4 * 1024 * 1024
    CHUNK_BYTES_LOCAL = 16 * 1024 * 1024

    def _chunk_bounds(self, arr: np.ndarray) -> list[tuple[int, int]]:
        chunk_bytes = (self.CHUNK_BYTES_LOCAL if len(self.hosts()) == 1
                       else self.CHUNK_BYTES)
        elems = max(1, chunk_bytes // max(1, arr.itemsize))
        flat_n = arr.size
        return [(lo, min(lo + elems, flat_n))
                for lo in range(0, flat_n, elems)]

    def broadcast(self, send_rank: int, recv_rank: int, data: np.ndarray
                  ) -> np.ndarray:
        data = np.asarray(data)
        _count_collective("broadcast", int(data.nbytes))
        with span("mpi", "broadcast", rank=recv_rank, root=send_rank,
                  bytes=int(data.nbytes)):
            return self._broadcast_impl(send_rank, recv_rank, data)

    def _broadcast_impl(self, send_rank: int, recv_rank: int,
                        data: np.ndarray) -> np.ndarray:
        """Reference :786-853: root sends once per remote host (to its
        local leader) + to its own host's ranks; leaders re-broadcast
        locally.

        Large payloads stream chunk-pipelined. The stream is
        SELF-DESCRIBING: the root prefixes a CHUNK_HEADER message, so
        receivers follow the root's chunking decision and never need a
        correctly-sized local template (mpi_bcast(buf=None) callers)."""
        data = np.asarray(data)
        my_host = self.host_for_rank(recv_rank)
        root_host = self.host_for_rank(send_rank)

        # -- root: decide chunking from the REAL payload ----------------
        if recv_rank == send_rank:
            local = [r for r in self.ranks_on_host(root_host)
                     if r != send_rank]
            remote_leaders = [self.local_leader(h) for h in self.hosts()
                              if h != root_host]
            dests_remote_first = remote_leaders + local

            if data.nbytes >= self.CHUNK_BYTES * 2:
                flat = data.reshape(-1)
                bounds = self._chunk_bounds(flat)
                shared = np.array(flat, copy=True)
                shared.flags.writeable = False
                header = self._chunk_header(len(bounds), flat)
                for d in dests_remote_first:
                    self.send(send_rank, d, header,
                              MpiMessageType.CHUNK_HEADER)
                for lo, hi in bounds:
                    chunk = shared[lo:hi]
                    # Remote first: get the wire moving before local fan-out
                    for d in dests_remote_first:
                        self.send(send_rank, d, chunk,
                                  MpiMessageType.BROADCAST, _copy=False)
            else:
                shared = np.array(data, copy=True)
                for d in dests_remote_first:
                    self.send(send_rank, d, shared,
                              MpiMessageType.BROADCAST, _copy=False)
            return data

        # -- leaders: follow the incoming stream, forwarding locally ----
        leader = self.local_leader(my_host)
        if my_host != root_host and recv_rank == leader:
            local = [r for r in self.ranks_on_host(my_host)
                     if r != recv_rank]

            def forward(arr, msg_type=MpiMessageType.BROADCAST):
                for r in local:
                    self.send(recv_rank, r, arr, msg_type, _copy=False)

            msg_type, first = self._recv_typed(send_rank, recv_rank)
            if msg_type != MpiMessageType.CHUNK_HEADER:
                forward(first)
                return self._private_result(first, data)
            n_chunks, out = self._parse_chunk_header(first)
            # Local ranks follow the same self-describing stream shape
            forward(first, MpiMessageType.CHUNK_HEADER)
            pos = 0
            for _ in range(n_chunks):
                arr, _ = self._recv_raw(send_rank, recv_rank)
                out[pos:pos + arr.size] = arr
                ro = out[pos:pos + arr.size]
                ro.flags.writeable = False
                forward(ro)
                pos += arr.size
            # out's chunk views were shared read-only with local
            # receivers; hand the caller a private copy
            return self._private_result(out.copy(), data, private=True)

        # -- plain receivers --------------------------------------------
        src = send_rank if my_host == root_host else leader
        msg_type, first = self._recv_typed(src, recv_rank)
        if msg_type != MpiMessageType.CHUNK_HEADER:
            return self._private_result(first, data)
        n_chunks, out = self._parse_chunk_header(first)
        pos = 0
        for _ in range(n_chunks):
            arr, _ = self._recv_raw(src, recv_rank)
            out[pos:pos + arr.size] = arr
            pos += arr.size
        return self._private_result(out, data, private=True)

    @staticmethod
    def _chunk_header(n_chunks: int, flat: np.ndarray) -> np.ndarray:
        return np.array([n_chunks, flat.size,
                         int(mpi_dtype_for(flat.dtype))], dtype=np.int64)

    @staticmethod
    def _parse_chunk_header(header: np.ndarray) -> tuple[int, np.ndarray]:
        from faabric_tpu.mpi.types import MpiDataType, np_dtype_for

        n_chunks, total, dtype_code = (int(x) for x in header[:3])
        return n_chunks, np.empty(total,
                                  dtype=np_dtype_for(MpiDataType(dtype_code)))

    def _recv_typed(self, send_rank: int, recv_rank: int
                    ) -> tuple[MpiMessageType, np.ndarray]:
        """Receive preserving the message type; the array may be shared/
        read-only (zero-copy paths) — see _private_result."""
        raw = self.broker.recv_message(self.group_id, send_rank, recv_rank,
                                       must_order=True)
        if isinstance(raw, _LocalMpiPayload):
            return raw.msg_type, raw.data
        msg_type, arr, _req = self._unpack_wire(raw)
        return msg_type, arr

    @staticmethod
    def _private_result(arr: np.ndarray, template: np.ndarray,
                        private: bool = False) -> np.ndarray:
        """Caller-owned writable result, reshaped to the template when the
        sizes agree (lenient size-less templates stay flat). ``private``
        marks buffers this rank already exclusively owns."""
        if not private and not arr.flags.writeable:
            arr = arr.copy()  # shared zero-copy fan-out buffer
        if template.size == arr.size and template.shape != arr.shape:
            arr = arr.reshape(template.shape)
        return arr

    def reduce(self, rank: int, root: int, data: np.ndarray,
               op: MpiOp = MpiOp.SUM,
               _shared_ok: bool = False) -> Optional[np.ndarray]:
        data = np.asarray(data)
        _count_collective("reduce", int(data.nbytes))
        with span("mpi", "reduce", rank=rank, root=root,
                  bytes=int(data.nbytes)):
            return self._reduce_impl(rank, root, data, op, _shared_ok)

    def _reduce_impl(self, rank: int, root: int, data: np.ndarray,
                     op: MpiOp = MpiOp.SUM,
                     _shared_ok: bool = False) -> Optional[np.ndarray]:
        """Reference :1127-1249: non-leaders send to their local leader;
        leaders partially reduce and forward one message to root.
        Large payloads stream chunk-pipelined."""
        data = np.asarray(data)
        if data.nbytes >= self.CHUNK_BYTES * 2:
            return self._reduce_chunked(rank, root, data, op, _shared_ok)
        my_host = self.host_for_rank(rank)
        root_host = self.host_for_rank(root)
        leader = self.local_leader(my_host)

        if rank == root:
            acc = data.copy()
            # Local ranks send directly (root acts as its host's sink)
            for r in self.ranks_on_host(root_host):
                if r != root:
                    arr, _ = self._recv_raw(r, root)
                    acc = apply_op_inplace(op, acc, arr)
            # One partial result per remote host
            for host in self.hosts():
                if host != root_host:
                    arr, _ = self._recv_raw(self.local_leader(host), root)
                    acc = apply_op_inplace(op, acc, arr)
            return acc

        if my_host == root_host:
            # Same host as root: send directly
            self.send(rank, root, data, MpiMessageType.REDUCE)
            return None

        if rank == leader:
            acc = data.copy()
            for r in self.ranks_on_host(my_host):
                if r != rank:
                    arr, _ = self._recv_raw(r, rank)
                    acc = apply_op_inplace(op, acc, arr)
            self.send(rank, root, acc, MpiMessageType.REDUCE)
            return None

        self.send(rank, leader, data, MpiMessageType.REDUCE)
        return None

    def _reduce_chunked(self, rank: int, root: int, data: np.ndarray,
                        op: MpiOp, _shared_ok: bool = False
                        ) -> Optional[np.ndarray]:
        """Chunk-pipelined leader-tree reduce: leaders fold and forward
        chunk k while chunk k+1 is still arriving; the root folds chunks
        as its senders' streams land.

        ``_shared_ok`` (allreduce-only): senders' local chunks ride the
        queues as read-only views with NO defensive copy — safe because
        allreduce's trailing broadcast guarantees every contribution is
        consumed before any caller regains control of its buffer. A bare
        reduce() must copy (MPI says the send buffer is reusable on
        return, but a lagging receiver may still be reading it)."""
        my_host = self.host_for_rank(rank)
        root_host = self.host_for_rank(root)
        leader = self.local_leader(my_host)
        flat = data.reshape(-1)
        bounds = self._chunk_bounds(flat)

        def send_chunk(dst: int, chunk: np.ndarray) -> None:
            if _shared_ok:
                view = chunk[:]
                view.flags.writeable = False
                self.send(rank, dst, view, MpiMessageType.REDUCE,
                          _copy=False)
            else:
                self.send(rank, dst, chunk, MpiMessageType.REDUCE)

        if rank == root:
            senders = [r for r in self.ranks_on_host(root_host)
                       if r != root]
            senders += [self.local_leader(h) for h in self.hosts()
                        if h != root_host]
            acc = flat.copy()
            for lo, hi in bounds:
                acc_chunk = acc[lo:hi]
                for s in senders:
                    arr, _ = self._recv_raw(s, root)
                    res = apply_op_inplace(op, acc_chunk, arr)
                    if res is not acc_chunk:  # non-inplace op fallback
                        acc[lo:hi] = res
                        acc_chunk = acc[lo:hi]
            return acc.reshape(data.shape)

        if my_host == root_host:
            for lo, hi in bounds:
                send_chunk(root, flat[lo:hi])
            return None

        if rank == leader:
            locals_ = [r for r in self.ranks_on_host(my_host) if r != rank]
            acc = flat.copy()
            for lo, hi in bounds:
                acc_chunk = acc[lo:hi]
                for s in locals_:
                    arr, _ = self._recv_raw(s, rank)
                    res = apply_op_inplace(op, acc_chunk, arr)
                    if res is not acc_chunk:  # non-inplace op fallback
                        acc[lo:hi] = res
                        acc_chunk = acc[lo:hi]
                # acc is leader-private: forward upstream without a copy
                self.send(rank, root, acc_chunk, MpiMessageType.REDUCE)
            return None

        for lo, hi in bounds:
            send_chunk(leader, flat[lo:hi])
        return None

    def _stage_host(self, arr):
        """Device-resident payloads that cannot (or did not) ride the
        device rung take ONE explicit device→host staging copy —
        counted on the ``faabric_device_copy_*`` surface (reason
        ``staging``) so the fallback cost is observable, never silent.
        Host arrays pass through untouched."""
        from faabric_tpu.device_plane.plane import is_device_payload

        if not is_device_payload(arr):
            return arr
        from faabric_tpu.device_plane.copies import D2H, count_copy

        out = np.asarray(arr)
        count_copy(D2H, int(out.nbytes), "staging")
        return out

    def allreduce(self, rank: int, data, op: MpiOp = MpiOp.SUM):
        from faabric_tpu.device_plane.plane import is_device_payload

        # jax.Array payloads stay device-resident through dispatch: the
        # eligibility question is answered from shape/dtype alone and
        # the device rung consumes the array in place (ISSUE 15). Only
        # a host-ladder fallback materializes it (one counted copy).
        arr = data if is_device_payload(data) else np.asarray(data)
        if not _PROFILER.enabled:
            return self._allreduce_entry(rank, arr, op)
        # Collective fold-in (ISSUE 12): the wall-anchored ENTRY stamp
        # is what straggler analysis compares across ranks — in a
        # synchronous collective the late arriver inflates everyone's
        # total equally, so only arrival skew can identify it
        _PROFILER.record_phase(self.id, "allreduce", rank, "enter_ts",
                               time.time())
        t0 = time.monotonic()
        try:
            return self._allreduce_entry(rank, arr, op)
        finally:
            _PROFILER.record_phase(self.id, "allreduce", rank, "total",
                                   time.monotonic() - t0,
                                   int(arr.nbytes))

    def _allreduce_entry(self, rank: int, arr: np.ndarray,
                         op: MpiOp) -> np.ndarray:
        # Large single-host payloads: ring reduce-scatter + allgather.
        # The root-serialized leader tree bottlenecks on ONE thread doing
        # every add and every fan-out send; the ring splits the fold
        # np ways across the already-running rank threads (the same
        # reason the device plane reduces via psum_scatter+all_gather).
        # Multi-host worlds keep the leader tree: it sends exactly one
        # message per remote host over the wire, which the ring does not.
        # Rung 0 — the device plane (shm → tcp → DEVICE): an activated
        # world's eligible payloads run as one compiled program over the
        # mesh; everything below is the host ladder it falls back to
        dplane = self.device_plane()
        if dplane is not None and dplane.eligible("allreduce", arr, op):
            out = self._try_device("allreduce", dplane, rank, arr, op)
            if out is not None:
                return out
        arr = self._stage_host(arr)
        if self._sched_reduction_eligible(op):
            return self._reduction_sched(rank, "allreduce", arr, op)
        use_hier = self._hier_eligible(arr, op)
        use_ring = (not use_hier and arr.size >= self.size
                    and self._ring_eligible(arr, op))
        _count_collective("allreduce", int(arr.nbytes))
        with span("mpi", "allreduce", rank=rank, size=self.size,
                  bytes=int(arr.nbytes),
                  algo=("hier" if use_hier
                        else "ring" if use_ring else "tree")):
            if use_hier:
                return self._allreduce_hier(rank, arr, op)
            if use_ring:
                return self._allreduce_ring(rank, arr, op)
            # reduce to 0 + broadcast (reference :1251-1264). The trailing
            # broadcast is the completion barrier that makes zero-copy local
            # contribution sends safe (_shared_ok).
            with span("mpi.phase", "reduce", rank=rank):
                reduced = self._reduce_impl(rank, MAIN_RANK, arr, op,
                                            _shared_ok=True)
            with span("mpi.phase", "broadcast", rank=rank):
                return self._broadcast_impl(
                    MAIN_RANK, rank,
                    reduced if rank == MAIN_RANK else arr)

    def _sched_reduction_eligible(self, op=None) -> bool:
        """Whether the hierarchical reduction LOWERINGS execute instead
        of the hand-written paths: explicit double opt-in (knob "force"
        + world.sched_reductions, set identically on every process) —
        they exist to prove the IR covers the tuned paths and are
        bitwise-pinned against them; the zero-copy hand-written rings
        remain the throughput defaults."""
        if self.sched_enabled != "force" or not self.sched_reductions:
            return False
        if op is not None and isinstance(op, UserOp) and not op.commute:
            return False
        return self.size > 1 and self.topology().n_hosts > 1

    def _reduction_sched(self, rank: int, collective: str,
                         data: np.ndarray, op: MpiOp) -> np.ndarray:
        """Run allreduce / reduce_scatter / allgather as its verified
        schedule lowering (mpi/schedule_compile.py): intra-host fold or
        gather to the leader, leader ring / pairwise host-block
        exchange, in-process redistribute — the schedule twin of the
        hand-written hierarchical paths."""
        flat = np.asarray(data).reshape(-1)
        op_arg = None if collective == "allgather" else op
        sched, family = self._sched_get(
            rank, collective, op=op_arg, dtype=flat.dtype,
            nbytes=int(flat.nbytes))
        _count_collective(collective, int(flat.nbytes))
        with span("mpi", collective, rank=rank, size=self.size,
                  bytes=int(flat.nbytes),
                  algo="sched:" + family.split(".", 1)[1]):
            env: dict = {}
            if collective == "allreduce":
                segs = self._ring_segments(flat.size,
                                           sched.spec["segments"])
                for s, (lo, hi) in enumerate(segs):
                    env[("in", s)] = flat[lo:hi]

                def resolver(sym, e, _segs=segs):
                    return _segs[sym[1]][1] - _segs[sym[1]][0]

                self._run_schedule(rank, sched, env, op, resolver,
                                   MpiMessageType.ALLREDUCE)
                out = np.empty(flat.size, dtype=flat.dtype)
                for s, (lo, hi) in enumerate(segs):
                    out[lo:hi] = env[("out", s)]
                return out.reshape(np.asarray(data).shape)
            if collective == "reduce_scatter":
                k = flat.size // self.size
                for j in range(self.size):
                    env[("in", j)] = flat[j * k:(j + 1) * k]
                self._run_schedule(rank, sched, env, op,
                                   lambda sym, e: k,
                                   MpiMessageType.REDUCE)
                return np.array(env[("out", 0)])
            # allgather: contribution is the whole payload, k per rank
            k = flat.size
            env[("in", 0)] = flat
            self._run_schedule(rank, sched, env, None,
                               lambda sym, e: k,
                               MpiMessageType.ALLGATHER)
            out = np.empty(self.size * k, dtype=flat.dtype)
            for q in range(self.size):
                out[q * k:(q + 1) * k] = env[("out", q)]
            return out

    def _ring_eligible(self, arr: np.ndarray, op) -> bool:
        """Shared ring-path predicate for allreduce/reduce_scatter: big
        enough to beat the tree, all ranks on this machine, and a
        commuting op. No size ceiling: segments above one bulk frame
        stream as pipeline chunks (see RING_CHUNK_BYTES)."""
        return (self.size > 1 and arr.nbytes >= self.CHUNK_BYTES * 2
                and (not isinstance(op, UserOp) or op.commute)
                and self._all_hosts_same_machine())

    def _all_hosts_same_machine(self) -> bool:
        """True when every rank's host resolves to THIS machine (rank
        threads in one process, or worker processes sharing the box whose
        cross-process legs ride the shm ring). The ring's extra hop count
        is free on local bandwidth; over a real network the hierarchical
        leader tree's one-message-per-host wins instead."""
        from faabric_tpu.transport.common import resolve_host
        from faabric_tpu.util.network import is_local_ip

        with self._lock:
            if self._same_machine_cache is not None:
                return self._same_machine_cache
            gen = self._topology_gen
        hosts = self.hosts()
        # A single-host world is same-machine by definition — delivery is
        # in-process no matter what the host label resolves to
        result = len(hosts) == 1 or all(
            is_local_ip(resolve_host(h, 0)[0]) for h in hosts)
        with self._lock:
            # Only cache if no refresh_rank_hosts (migration remap) raced
            # this computation — a stale verdict would desync ring/tree
            # algorithm choice across processes and hang the collective
            if self._topology_gen == gen:
                self._same_machine_cache = result
        return result

    # ------------------------------------------------------------------
    # Hierarchical topology-composed collectives (ISSUE 9 / ROADMAP 1)
    # ------------------------------------------------------------------
    def _hier_eligible(self, arr: np.ndarray, op=None) -> bool:
        """Hierarchical-composition predicate: payload big enough to
        chunk-pipeline, a commuting op, and a Topology with BOTH
        multiple hosts and co-located ranks. The degenerate shapes —
        one host, or one rank per host — fall through to the flat
        ring / leader-tree paths, which are already optimal there (the
        1-host bench shape must keep the flat fast path)."""
        if not self.hier_enabled:
            return False
        if op is not None and isinstance(op, UserOp) and not op.commute:
            return False
        if arr.nbytes < self.CHUNK_BYTES * 2 or arr.size < self.size:
            return False
        return self.topology().hierarchical and self._hier_wins()

    def _hier_wins(self) -> bool:
        """Composing only pays when the leader ring's saved bytes cross
        a REAL machine boundary. When every host of the world resolves
        to this machine (simulated hosts, co-located worker procs) the
        "wire" is loopback/shm where bytes are nearly free, and the
        flat ring — which pipelines the fold across EVERY rank thread
        instead of serializing the wire leg through one leader per host
        — is measurably faster (host_allreduce_procs: 2.8–3.3 GiB/s
        ring vs ~1.6 composed). ``hier_enabled = "force"`` overrides
        for the simulated-host dist tests and benches, which exist to
        measure the composition itself."""
        return (self.hier_enabled == "force"
                or not self._all_hosts_same_machine())

    def _host_reduce(self, rank: int, data: np.ndarray, op: MpiOp,
                     locals_: list[int]):
        """Phase ``intra`` of the hierarchical collectives: a chunked
        ring reduce-scatter over THIS host's ranks (the fold spread
        across the co-located rank threads through the in-process
        queues), then non-leaders hand their folded segments to the
        local leader as ownership transfers while the leader assembles
        the full host-reduced vector.

        Returns ``(host_acc, restore_fn)``: ``host_acc`` is the
        host-reduced vector on the leader (the caller's own flat buffer
        when the host has a single rank) and None on non-leaders. Every
        caller must run ``restore_fn`` only once its own later phase
        proves the local successor consumed this rank's step-0 views —
        see the causality note in _allreduce_hier."""
        flat = data.reshape(-1)
        m = len(locals_)
        leader = locals_[0]
        if m == 1:
            return (flat if rank == leader else None), (lambda: None)
        with span("mpi.phase", "reduce_scatter", rank=rank,
                  phase="intra"):
            held, restore = self._ring_reduce_scatter(rank, data, op,
                                                      ring=locals_)
        with span("mpi.phase", "gather", rank=rank, phase="intra"):
            seg = self._ring_segments(flat.size, m)
            pos = locals_.index(rank)
            if rank != leader:
                # Folded chunks are receiver-private (allocated or
                # ownership-received during the fold): transfer outright
                for part in held:
                    self.send(rank, leader, part, MpiMessageType.REDUCE,
                              _transfer=True)
                return None, restore
            host_acc = np.empty(
                flat.size, dtype=held[0].dtype if held else flat.dtype)
            # Own held chunks cover segment (pos+1) % m ...
            write = seg[(pos + 1) % m][0]
            for part in held:
                host_acc[write:write + part.size] = part
                write += part.size
            # ... and local rank at position p holds segment (p+1) % m
            for p in range(m):
                if locals_[p] == rank:
                    continue
                slo, shi = seg[(p + 1) % m]
                # The INPUT itemsize is the protocol's agreed bound
                # unit (senders chunked by it; == host_acc.itemsize
                # since apply_op casts folds back to the input dtype)
                for clo, chi in self._ring_chunks(slo, shi,
                                                  flat.itemsize):
                    arr, _ = self._recv_raw(locals_[p], rank)
                    host_acc[clo:chi] = arr
            return host_acc, restore

    def _allreduce_hier(self, rank: int, data: np.ndarray,
                        op: MpiOp) -> np.ndarray:
        """Topology-composed allreduce (HiCCL-style composition): shm
        reduce-scatter within each host → chunk-pipelined ring over the
        per-host LEADERS only on the wire (striped bulk plane) →
        redistribution back down through the in-process queues. Only
        the leader ring leaves the host, so each host puts
        2·(H−1)/H·payload on the wire instead of one ring link per
        RANK — ~1/(ranks-per-host) of the flat ring's cross-host bytes
        under topology-blind placement.

        Phases (spans tagged ``phase=intra|leader|redistribute``):
        intra (_host_reduce), leader (leaders-only _allreduce_ring),
        redistribute (leader freezes the result and fans the reference
        out locally; every rank finishes with a private copy).

        Ownership causality: a rank's step-0 views are consumed by its
        local-ring successor before that successor's segment handover
        reaches the leader; the leader's fan-out (or, on the leader
        itself, completing the host assembly) therefore transitively
        proves consumption — restore runs last on every path. A
        single-rank host feeds its caller's buffer straight into the
        leader ring, whose trailing allgather provides the same
        guarantee the flat ring relies on."""
        topo = self.topology()
        locals_ = list(topo.ranks_on_host(topo.host_of(rank)))
        leader = locals_[0]
        # Per-phase fold-in (ISSUE 12): intra/leader/redistribute wall
        # durations land in the collective profiler so /perf's critical
        # path names the slow HIERARCHY LEVEL, not just the slow rank
        prof = _PROFILER.enabled
        t_ph = time.monotonic() if prof else 0.0
        host_acc, restore = self._host_reduce(rank, data, op, locals_)
        if prof:
            now = time.monotonic()
            _PROFILER.record_phase(self.id, "allreduce", rank, "intra",
                                   now - t_ph)
            t_ph = now

        if rank != leader:
            with span("mpi.phase", "broadcast", rank=rank,
                      phase="redistribute"):
                arr, _ = self._recv_raw(leader, rank)
                out = self._private_result(arr, data)
            if prof:
                _PROFILER.record_phase(self.id, "allreduce", rank,
                                       "redistribute",
                                       time.monotonic() - t_ph)
            restore()
            return out

        # Opt-in int8 wire quantization on the leader ring's fold leg
        # only (mpi/quant.py) — the cross-machine links are the
        # bandwidth-bound segment EQuARX targets; intra-host phases
        # stay exact fp32. The mode resolves through the wire-codec
        # governor (ISSUE 11): the legacy knob forces every hop, the
        # governor's `quant` token enables it per-LINK (each sender
        # decides for its own next-hop, carried in-band via the
        # NaN-scale raw passthrough form, never inferred).
        result = self._allreduce_ring(
            rank, host_acc, op, ring=list(topo.leaders), phase="leader",
            codec=leader_ring_codec(
                resolve_quant_mode(self.allreduce_quant),
                host_acc.dtype, op))
        if prof:
            now = time.monotonic()
            _PROFILER.record_phase(self.id, "allreduce", rank, "leader",
                                   now - t_ph)
            t_ph = now
        with span("mpi.phase", "broadcast", rank=rank,
                  phase="redistribute"):
            if len(locals_) > 1:
                shared = result.reshape(-1)
                shared.flags.writeable = False
                for r in locals_[1:]:
                    self.send(rank, r, shared, MpiMessageType.BROADCAST,
                              _copy=False)
                # Receivers keep the frozen buffer; the caller gets a
                # private copy it may mutate immediately
                result = shared.copy()
        if prof:
            _PROFILER.record_phase(self.id, "allreduce", rank,
                                   "redistribute",
                                   time.monotonic() - t_ph)
        restore()
        return self._private_result(result, data, private=True)

    def _allreduce_ring(self, rank: int, data: np.ndarray,
                        op: MpiOp, ring: list[int] | None = None,
                        phase: str | None = None,
                        codec=None) -> np.ndarray:
        """Zero-copy CHUNK-PIPELINED ring allreduce over the rank
        threads: np-1 reduce-scatter steps (each rank folds 1/np of the
        data per step) then np-1 allgather steps that pass chunk
        REFERENCES through the in-process queues — the only bulk copies
        are the fold itself and one assembly write per chunk, and the
        folds run on ALL rank threads concurrently instead of serially
        on the root. Segments above RING_CHUNK_BYTES stream as multiple
        chunk messages, so while this rank folds chunk k its
        predecessor's chunk k+1 is already crossing the wire and its
        successor is folding chunk k-1 (hop-level pipelining; no
        RING_MSG_CAP bail-out for big segments anymore).

        Ownership protocol (what makes zero-copy safe):
        - step 0 sends READ-ONLY chunk views of the caller's buffer; the
          ring's causal chain (every rank's return transitively requires
          its successor to have consumed those messages) guarantees
          consumption before any caller regains control.
        - a received partial chunk is exclusively owned by the receiver,
          which folds its own contribution INTO it in place — unless it
          is a read-only step-0 view, where the fold allocates.
        - after the fold a chunk is sent on and never written again;
          allgather forwards the same objects, every holder read-only.
        Requires an associative+commutative op, which MPI mandates.

        ``ring`` restricts the ring to an ordered rank subset (the
        hierarchical path's leader ring); callers outside it must not
        call. ``phase`` tags the spans with the hierarchy level."""
        flat = data.reshape(-1)
        if ring is None:
            ring = list(range(self.size))
        n = len(ring)
        pos = ring.index(rank)
        seg = self._ring_segments(flat.size, n)
        nxt, prv = ring[(pos + 1) % n], ring[(pos - 1) % n]
        lvl = {"phase": phase} if phase else {}
        with span("mpi.phase", "reduce_scatter", rank=rank, **lvl):
            held, restore = self._ring_reduce_scatter(rank, data, op,
                                                      ring=ring,
                                                      codec=codec)
        out = np.empty(flat.size,
                       dtype=held[0].dtype if held else flat.dtype)
        with span("mpi.phase", "allgather", rank=rank, **lvl):
            # Assemble our fully-reduced segment while its chunks are
            # still in hand (they leave at allgather step 0)
            start = seg[(pos + 1) % n][0]
            for part in held:
                out[start:start + part.size] = part
                start += part.size
            # Circulate the complete segments chunk by chunk, writing
            # each received chunk straight into the result (the assembly
            # copy IS the receive) and forwarding the same object on
            parts: dict[int, list[np.ndarray]] = {(pos + 1) % n: held}
            for step in range(n - 1):
                send_seg = (pos + 1 - step) % n
                for part in parts.pop(send_seg):
                    if part.flags.writeable:
                        part.flags.writeable = False
                    self.send(rank, nxt, part, MpiMessageType.REDUCE,
                              _copy=False)
                recv_seg = (pos - step) % n
                rlo, rhi = seg[recv_seg]
                recv_parts = []
                for clo, chi in self._ring_chunks(rlo, rhi,
                                                  flat.itemsize):
                    arr, _ = self._recv_raw(prv, rank)
                    out[clo:chi] = arr
                    recv_parts.append(arr)
                parts[recv_seg] = recv_parts
        # Our last allgather recv causally implies nxt completed its
        # whole fold phase (chain length n-1), i.e. consumed our step-0
        # views — only now may the caller's buffer go writable again
        restore()
        return out.reshape(data.shape)

    def _ring_segments(self, n_elems: int,
                       n: int | None = None) -> list[tuple[int, int]]:
        if n is None:
            n = self.size
        return [((i * n_elems) // n, ((i + 1) * n_elems) // n)
                for i in range(n)]

    @staticmethod
    def _ring_chunks(lo: int, hi: int, itemsize: int
                     ) -> list[tuple[int, int]]:
        """Pipeline-chunk bounds of one segment [lo, hi): a pure function
        of the bounds, so every rank derives the identical stream shape
        for every link without a header exchange."""
        elems = max(1, RING_CHUNK_BYTES // max(1, itemsize))
        return [(c, min(c + elems, hi)) for c in range(lo, hi, elems)]

    def _quant_link_ok(self, peer: int) -> bool:
        """Whether the leader-ring hop to ``peer`` should actually
        quantize (wire-codec governor, ISSUE 11). The legacy knob
        forces every hop; governor-token quant skips same-machine hops
        in auto mode. The verdict is carried in-band per chunk (the
        NaN-scale raw passthrough form), so peers never need to agree
        on it — only on the codec FRAMING, which resolves from
        world-level configuration."""
        from faabric_tpu.transport.codec import get_wire_governor

        gov = get_wire_governor()
        host = self.host_for_rank(peer)
        if host == self.broker.host:
            local = True
        else:
            from faabric_tpu.transport.common import host_is_local

            local = host_is_local(host)
        return gov.quant_for_link(self.allreduce_quant, host, local)

    def _ring_reduce_scatter(self, rank: int, data: np.ndarray,
                             op: MpiOp, ring: list[int] | None = None,
                             seg: list[tuple[int, int]] | None = None,
                             codec=None):
        """The ring's fold phase: n-1 steps, each participant folding
        1/n of the data into the partials it receives, one pipeline
        chunk at a time (ownership rides the payload — folding based on
        the numpy writeable FLAG would race the sender restoring its
        step-0 views' writability). Returns (chunks of the fully reduced
        segment (pos+1) % n in offset order, restore_fn): the CALLER
        must run restore_fn only after its trailing ring phase — one
        more full circulation — guarantees every neighbour consumed the
        step-0 views of this rank's buffer.

        ``ring`` restricts the ring to an ordered rank subset (the
        hierarchical leader ring); position in ``ring`` replaces the
        rank in all segment arithmetic. ``seg`` overrides the segment
        partition (len(ring) (lo, hi) spans covering the flat array) —
        any partition works as long as every participant passes the
        same one; the hierarchical reduce_scatter uses per-HOST spans
        so each leader ends up holding exactly its own host's output.

        ``codec`` (mpi/quant.py) switches the ring's wire format: every
        chunk travels encoded (int8 + per-chunk scale), decoded into a
        receiver-private buffer before the fold and re-encoded for the
        next hop. Encoding copies, so the caller's buffer is never
        shared with a peer and restore() is a no-op; every participant
        must agree on the codec (world-level knob) or framing desyncs."""
        flat = data.reshape(-1)
        if ring is None:
            ring = list(range(self.size))
        n = len(ring)
        pos = ring.index(rank)
        if seg is None:
            seg = self._ring_segments(flat.size, n)
        nxt, prv = ring[(pos + 1) % n], ring[(pos - 1) % n]
        traced = tracing_enabled()

        lo, hi = seg[pos]
        first = flat[lo:hi]
        was_writeable = first.flags.writeable
        if codec is None:
            first.flags.writeable = False
        else:
            # Per-LINK codec selection (ISSUE 11): whether THIS rank's
            # next-hop actually quantizes is the governor's call — a
            # same-machine hop's bytes are nearly free, so it ships the
            # raw-fp32 passthrough form. Self-describing per chunk (NaN
            # scale), so mixed hops coexist on one ring.
            quant_link = self._quant_link_ok(nxt)
        for clo, chi in self._ring_chunks(lo, hi, flat.itemsize):
            if codec is not None:
                # Encoded chunks are private copies — zero-copy safe
                # without freezing the caller's views
                self.send(rank, nxt,
                          codec.encode(first[clo - lo:chi - lo],
                                       quantize=quant_link),
                          MpiMessageType.REDUCE, _copy=False)
            else:
                self.send(rank, nxt, first[clo - lo:chi - lo],
                          MpiMessageType.REDUCE, _copy=False)
        held: list[np.ndarray] = []
        for step in range(n - 1):
            slo, shi = seg[(pos - step - 1) % n]
            for clo, chi in self._ring_chunks(slo, shi, flat.itemsize):
                arr, _, owned = self._recv_raw_owned(prv, rank)
                mine = flat[clo:chi]
                with span("mpi.detail", "fold", rank=rank, step=step) \
                        if traced else NULL_SPAN:
                    if codec is not None:
                        # Decode allocates a private fp32 buffer; the
                        # fold lands in it in place
                        folded = apply_op_inplace(op, codec.decode(arr),
                                                  mine)
                    elif owned and arr.flags.writeable \
                            and arr.dtype == mine.dtype:
                        folded = apply_op_inplace(op, arr, mine)
                    else:  # step-0 shared view (or dtype-promoting op):
                        # non-inplace apply allocates + folds in ONE pass
                        folded = np.asarray(apply_op(op, arr, mine))
                if step < n - 2:
                    if codec is not None:
                        self.send(rank, nxt,
                                  codec.encode(folded,
                                               quantize=quant_link),
                                  MpiMessageType.REDUCE, _copy=False)
                    else:
                        # Ownership transfer: the receiver folds into
                        # this buffer in place; we drop our reference
                        # here — and the wire leg of chunk k overlaps
                        # our fold of chunk k+1 (the pipeline the
                        # chunking exists for)
                        self.send(rank, nxt, folded, MpiMessageType.REDUCE,
                                  _transfer=True)
                    del folded
                else:
                    held.append(folded)  # segment (rank+1) % n

        def restore():
            if codec is None and was_writeable:
                first.flags.writeable = True

        return held, restore

    def scatter(self, send_rank: int, recv_rank: int, data: np.ndarray,
                recv_count: int) -> np.ndarray:
        _count_collective("scatter", int(np.asarray(data).nbytes))
        if self.sched_enabled and self.size > 1:
            sched, family = self._sched_get(rank=recv_rank,
                                            collective="scatter",
                                            root=send_rank)
            with span("mpi", "scatter", rank=recv_rank, root=send_rank,
                      algo="sched:" + family.split(".", 1)[1]):
                return self._scatter_sched(send_rank, recv_rank, sched,
                                           data, recv_count=recv_count)
        with span("mpi", "scatter", rank=recv_rank, root=send_rank,
                  algo="direct"):
            return self._scatter_impl(send_rank, recv_rank, data,
                                      recv_count)

    def _scatter_sched(self, root: int, rank: int, sched,
                       data, recv_count: int | None = None,
                       counts=None) -> np.ndarray:
        """Schedule-path scatter/scatterv: the root binds its per-rank
        input blocks (and, for scatterv trees, the int64 count-vector
        header the leaders split by); every other rank's blocks arrive
        sized by the wire or the header."""
        env: dict = {}
        if rank == root:
            flat = np.asarray(data).reshape(-1)
            if counts is None:
                chunks = flat.reshape(self.size, recv_count)
                for j in range(self.size):
                    env[("in", j)] = chunks[j]
            else:
                offsets = np.cumsum([0] + list(counts[:-1]))
                for j in range(self.size):
                    env[("in", j)] = flat[offsets[j]:offsets[j]
                                          + counts[j]]
                if sched.spec.get("counts_header"):
                    env[("in", "cnt")] = np.asarray(counts,
                                                    dtype=np.int64)

        def resolver(sym, e):
            if sym == ("cnt",):
                return self.size
            j = sym[1]
            if counts is not None and rank == root:
                return int(counts[j])
            if recv_count is not None:
                return int(recv_count)
            return int(np.asarray(e[("tmp", "cnt")]).reshape(-1)[j])

        self._run_schedule(rank, sched, env, None, resolver,
                           MpiMessageType.SCATTER)
        # Out blocks may alias the root's input or a shared receive
        # buffer; the public contract is a caller-owned writable array
        return np.array(env[("out", 0)])

    def _scatter_impl(self, send_rank: int, recv_rank: int,
                      data: np.ndarray, recv_count: int) -> np.ndarray:
        """Root splits (size*recv_count) into per-rank chunks."""
        if recv_rank == send_rank:
            data = np.asarray(data)
            chunks = data.reshape(self.size, recv_count)
            for r in range(self.size):
                if r != send_rank:
                    self.send(send_rank, r, chunks[r], MpiMessageType.SCATTER)
            return chunks[send_rank].copy()
        arr, _ = self.recv(send_rank, recv_rank)
        return arr

    def gather(self, send_rank: int, root: int, data: np.ndarray
               ) -> Optional[np.ndarray]:
        data = np.asarray(data)
        _count_collective("gather", int(data.nbytes))
        with span("mpi", "gather", rank=send_rank, root=root,
                  bytes=int(data.nbytes)):
            return self._gather_impl(send_rank, root, data)

    def _gather_impl(self, send_rank: int, root: int, data: np.ndarray
                     ) -> Optional[np.ndarray]:
        """Two-step local-leader aggregation (reference :917-1080)."""
        my_host = self.host_for_rank(send_rank)
        root_host = self.host_for_rank(root)
        leader = self.local_leader(my_host)
        data = np.asarray(data)
        chunk = data.size

        if send_rank == root:
            out = np.empty((self.size, chunk), dtype=data.dtype)
            out[root] = data
            for r in self.ranks_on_host(root_host):
                if r != root:
                    arr, _ = self.recv(r, root)
                    out[r] = arr
            for host in self.hosts():
                if host != root_host:
                    remote_ranks = sorted(self.ranks_on_host(host))
                    arr, _ = self.recv(self.local_leader(host), root)
                    packed = arr.reshape(len(remote_ranks), chunk)
                    for i, r in enumerate(remote_ranks):
                        out[r] = packed[i]
            return out.reshape(-1)

        if my_host == root_host:
            self.send(send_rank, root, data, MpiMessageType.GATHER)
            return None

        if send_rank == leader:
            local_ranks = sorted(self.ranks_on_host(my_host))
            packed = np.empty((len(local_ranks), chunk), dtype=data.dtype)
            packed[local_ranks.index(send_rank)] = data
            for r in local_ranks:
                if r != send_rank:
                    arr, _ = self.recv(r, send_rank)
                    packed[local_ranks.index(r)] = arr
            self.send(send_rank, root, packed.reshape(-1),
                      MpiMessageType.GATHER)
            return None

        self.send(send_rank, leader, data, MpiMessageType.GATHER)
        return None

    # ------------------------------------------------------------------
    # v-variants (variable counts; reference mpi.h gatherv/scatterv/
    # alltoallv). Counts ride the wire with each message, so only the
    # root needs the count vector; transfers are direct sends (the
    # leader-tree optimisation applies to the uniform-count fast paths).
    # ------------------------------------------------------------------
    def gatherv(self, rank: int, root: int, data: np.ndarray
                ) -> Optional[tuple[np.ndarray, list[int]]]:
        """Root returns (concatenated values in rank order, counts)."""
        data = np.asarray(data).reshape(-1)
        if rank != root:
            self.send(rank, root, data, MpiMessageType.GATHER)
            return None
        parts: list[np.ndarray] = []
        for r in range(self.size):
            if r == root:
                parts.append(data)
            else:
                # _recv_raw: concatenate copies anyway, skip recv()'s
                # defensive copy
                arr, _ = self._recv_raw(r, root)
                parts.append(arr)
        return np.concatenate(parts), [int(p.size) for p in parts]

    def scatterv(self, send_rank: int, recv_rank: int,
                 data: Optional[np.ndarray],
                 counts: Optional[list[int]]) -> np.ndarray:
        """Root splits ``data`` into per-rank pieces of ``counts`` sizes.
        Schedule-compiled (ISSUE 13): the tree family packs one bundle
        per remote host behind an int64 count-vector header, so leaders
        split without a planner round-trip; receivers stay count-blind
        (sizes bind from the wire/header, exactly once, verified)."""
        if recv_rank == send_rank:
            flat = np.asarray(data).reshape(-1)
            if counts is None or len(counts) != self.size:
                raise ValueError("scatterv root needs one count per rank")
            if sum(counts) != flat.size:
                raise ValueError(
                    f"scatterv counts sum {sum(counts)} != data {flat.size}")
        # Payload bytes enter at the root only; receivers count the
        # invocation (the per-participating-rank convention)
        _count_collective(
            "scatterv",
            int(np.asarray(data).nbytes) if recv_rank == send_rank else 0)
        if self.sched_enabled and self.size > 1:
            sched, family = self._sched_get(rank=recv_rank,
                                            collective="scatterv",
                                            root=send_rank)
            with span("mpi", "scatterv", rank=recv_rank, root=send_rank,
                      algo="sched:" + family.split(".", 1)[1]):
                return self._scatter_sched(send_rank, recv_rank, sched,
                                           data, counts=counts)
        with span("mpi", "scatterv", rank=recv_rank, root=send_rank,
                  algo="direct"):
            return self._scatterv_direct(send_rank, recv_rank, data,
                                         counts)

    def _scatterv_direct(self, send_rank: int, recv_rank: int,
                         data: Optional[np.ndarray],
                         counts: Optional[list[int]]) -> np.ndarray:
        """Seed-era direct sends, kept as the knob-off fallback."""
        if recv_rank == send_rank:
            flat = np.asarray(data).reshape(-1)
            offsets = np.cumsum([0] + list(counts[:-1]))
            for r in range(self.size):
                if r != send_rank:
                    self.send(send_rank, r,
                              flat[offsets[r]:offsets[r] + counts[r]],
                              MpiMessageType.SCATTER)
            lo = offsets[send_rank]
            return flat[lo:lo + counts[send_rank]].copy()
        arr, _ = self.recv(send_rank, recv_rank)
        return arr

    def alltoallv(self, rank: int, data: np.ndarray,
                  send_counts: list[int]
                  ) -> tuple[np.ndarray, list[int]]:
        """Rank-``j`` slice of ``data`` (``send_counts[j]`` elements) goes
        to rank j; returns (concatenation of received blocks in rank
        order, received counts)."""
        flat = np.asarray(data).reshape(-1)
        if len(send_counts) != self.size:
            raise ValueError("alltoallv needs one send count per rank")
        if sum(send_counts) != flat.size:
            raise ValueError(
                f"alltoallv counts sum {sum(send_counts)} != {flat.size}")
        offsets = np.cumsum([0] + list(send_counts[:-1]))
        my_block = None
        for r in range(self.size):
            block = flat[offsets[r]:offsets[r] + send_counts[r]]
            if r == rank:
                my_block = block.copy()
            else:
                self.send(rank, r, block, MpiMessageType.ALLTOALL)
        parts: list[np.ndarray] = []
        for r in range(self.size):
            if r == rank:
                parts.append(my_block)
            else:
                arr, _ = self._recv_raw(r, rank)
                parts.append(arr)
        return np.concatenate(parts), [int(p.size) for p in parts]

    def reduce_scatter(self, rank: int, data,
                       op: MpiOp = MpiOp.SUM):
        """MPI_Reduce_scatter_block: reduce (size·k,) contributions, rank
        r keeps segment r (reference composes it the same way: reduce to
        root + scatter). Large same-machine payloads take the ring's
        reduce-scatter phase directly — every rank folds 1/np per step
        and the root never materialises the full reduction."""
        from faabric_tpu.device_plane.plane import is_device_payload

        data = (data.reshape(-1) if is_device_payload(data)
                else np.asarray(data).reshape(-1))
        if not _PROFILER.enabled:
            return self._reduce_scatter_entry(rank, data, op)
        _PROFILER.record_phase(self.id, "reduce_scatter", rank,
                               "enter_ts", time.time())
        t0 = time.monotonic()
        try:
            return self._reduce_scatter_entry(rank, data, op)
        finally:
            _PROFILER.record_phase(self.id, "reduce_scatter", rank,
                                   "total", time.monotonic() - t0,
                                   int(data.nbytes))

    def _reduce_scatter_entry(self, rank: int, data: np.ndarray,
                              op: MpiOp) -> np.ndarray:
        if data.size % self.size:
            raise ValueError(
                f"reduce_scatter needs size divisible by {self.size}")
        k = data.size // self.size
        dplane = self.device_plane()
        if dplane is not None and dplane.eligible("reduce_scatter",
                                                  data, op):
            out = self._try_device("reduce_scatter", dplane, rank, data,
                                   op)
            if out is not None:
                return out
        data = self._stage_host(data)
        if self._sched_reduction_eligible(op):
            return self._reduction_sched(rank, "reduce_scatter", data, op)
        # Scattered (non-gang-contiguous) placements compose too: the
        # leader ring folds over a PERMUTED span partition derived from
        # the Topology (see _reduce_scatter_hier), so the
        # hosts_contiguous() gate PR 9 shipped with is gone
        use_hier = self._hier_eligible(data, op)
        use_ring = not use_hier and self._ring_eligible(data, op)
        _count_collective("reduce_scatter", int(data.nbytes))
        with span("mpi", "reduce_scatter", rank=rank, size=self.size,
                  bytes=int(data.nbytes),
                  algo=("hier" if use_hier
                        else "ring" if use_ring else "tree")):
            if use_hier:
                return self._reduce_scatter_hier(rank, data, op)
            if use_ring:
                with span("mpi.phase", "reduce_scatter", rank=rank):
                    held, restore = self._ring_reduce_scatter(rank, data,
                                                              op)
                # The ring leaves rank holding segment (rank+1) — which
                # belongs to rank+1; rotate one hop forward (chunk by
                # chunk) so every rank ends with ITS OWN segment (rank-1
                # holds ours). Ownership transfers with the rotation:
                # the receiver returns the buffers to its caller outright
                with span("mpi.phase", "rotate", rank=rank):
                    for part in held:
                        self.send(rank, (rank + 1) % self.size,
                                  np.asarray(part), MpiMessageType.REDUCE,
                                  _transfer=True)
                    del held
                    slo, shi = self._ring_segments(data.size)[rank]
                    chunks = self._ring_chunks(slo, shi, data.itemsize)
                    out = pos = None
                    for clo, chi in chunks:
                        arr, _, owned = self._recv_raw_owned(
                            (rank - 1) % self.size, rank)
                        if len(chunks) == 1:
                            # Single-chunk segment: hand the received
                            # buffer over outright when we own it
                            out = (arr if owned and arr.flags.writeable
                                   else arr.copy())
                            break
                        if out is None:
                            out = np.empty(shi - slo, dtype=arr.dtype)
                            pos = 0
                        out[pos:pos + arr.size] = arr
                        pos += arr.size
                    # The rotation recv extends the causal chain to
                    # length n, so nxt has consumed our step-0 views:
                    # safe to restore
                    restore()
                    return out
            with span("mpi.phase", "reduce", rank=rank):
                reduced = self._reduce_impl(rank, MAIN_RANK, data, op)
            with span("mpi.phase", "scatter", rank=rank):
                return self._scatter_impl(
                    MAIN_RANK, rank,
                    reduced if rank == MAIN_RANK else np.empty(0), k)

    def _reduce_scatter_hier(self, rank: int, data: np.ndarray,
                             op: MpiOp) -> np.ndarray:
        """Hierarchical reduce_scatter: intra-host reduce-scatter +
        handover (_host_reduce), then the leader ring runs ONLY the
        fold phase over per-HOST segment spans — permuted so each
        leader finishes holding exactly its own host's output span
        ((H−1)/H·payload per wire link, no trailing allgather) — and
        scatters the per-rank slices back down in process. Covers BOTH
        gang-contiguous and scattered placements: the spans live in a
        permuted coordinate space derived from the Topology (identity
        when contiguous; see the order/spans construction below)."""
        topo = self.topology()
        k = data.size // self.size
        locals_ = list(topo.ranks_on_host(topo.host_of(rank)))
        leader = locals_[0]
        leaders = list(topo.leaders)
        n_hosts = len(leaders)
        host_acc, restore = self._host_reduce(rank, data, op, locals_)

        if rank != leader:
            with span("mpi.phase", "scatter", rank=rank,
                      phase="redistribute"):
                out, _ = self.recv(leader, rank)
            restore()
            return out

        # The leader ring folds over per-HOST spans of a PERMUTED
        # coordinate space: rank order grouped by host (topology host
        # order, ranks ascending within each host). For gang-contiguous
        # placements the permutation is the identity; for scattered
        # placements (the PR 9 headroom this closes) the leader gathers
        # its host-reduced vector's k-blocks into that order first, so
        # each host's output is one contiguous span again and the
        # fold-only ring works unchanged. Every leader derives the same
        # order from the shared Topology — no exchange.
        order = [r for h in topo.hosts for r in topo.ranks_on_host(h)]
        if order != list(range(self.size)):
            perm = np.empty(host_acc.size, dtype=host_acc.dtype)
            for j, r in enumerate(order):
                perm[j * k:(j + 1) * k] = host_acc[r * k:(r + 1) * k]
            host_acc = perm  # private by construction
        elif len(locals_) == 1:
            # The fold-only leader ring has no trailing circulation to
            # extend the causal chain, so the caller's buffer must not
            # feed it directly: a peer could still be reading its
            # step-0 views after this rank returns (the flat path
            # restores only after its rotation for the same reason)
            host_acc = host_acc.copy()

        # spans[p] = permuted-space span of ring position p's host; the
        # fold phase leaves position p holding seg[(p+1) % n], so pass
        # the partition rotated one position back
        spans = []
        off = 0
        for lead in leaders:
            m_host = len(topo.ranks_on_host(topo.host_of(lead)))
            spans.append((off, off + m_host * k))
            off += m_host * k
        seg = [spans[(q - 1) % n_hosts] for q in range(n_hosts)]
        # No codec here: FAABRIC_ALLREDUCE_QUANT scopes to ALLREDUCE —
        # reduce_scatter hands each rank a slice nothing re-replicates,
        # and silently lossy slices under an allreduce-named knob would
        # surprise (quantize it deliberately under its own knob if
        # ROADMAP 4 wants it)
        with span("mpi.phase", "reduce_scatter", rank=rank,
                  phase="leader"):
            held, _noop_restore = self._ring_reduce_scatter(
                rank, host_acc, op, ring=leaders, seg=seg)

        with span("mpi.phase", "scatter", rank=rank,
                  phase="redistribute"):
            slo, shi = spans[leaders.index(rank)]
            hostseg = np.empty(
                shi - slo, dtype=held[0].dtype if held else data.dtype)
            write = 0
            for part in held:
                hostseg[write:write + part.size] = part
                write += part.size
            del held
            # hostseg holds this host's per-rank outputs in LOCAL rank
            # order (ascending), whatever the global layout
            for i, r in enumerate(locals_[1:], start=1):
                self.send(rank, r, hostseg[i * k:(i + 1) * k],
                          MpiMessageType.SCATTER)
            out = hostseg[:k].copy()  # leader is local position 0
        restore()
        return out

    def allgather(self, rank: int, data):
        from faabric_tpu.device_plane.plane import is_device_payload

        data = data if is_device_payload(data) else np.asarray(data)
        if not _PROFILER.enabled:
            return self._allgather_entry(rank, data)
        _PROFILER.record_phase(self.id, "allgather", rank, "enter_ts",
                               time.time())
        t0 = time.monotonic()
        try:
            return self._allgather_entry(rank, data)
        finally:
            _PROFILER.record_phase(self.id, "allgather", rank, "total",
                                   time.monotonic() - t0,
                                   int(data.nbytes))

    def _allgather_entry(self, rank: int, data: np.ndarray) -> np.ndarray:
        # Large same-machine payloads: ring allgather — contributions
        # circulate as read-only chunk references through the in-process
        # queues (n-1 steps, one assembly write per chunk) instead of
        # funnelling through rank 0 twice. Contributions above one bulk
        # frame stream as pipeline chunks (no size cap).
        dplane = self.device_plane()
        if dplane is not None and dplane.eligible("allgather", data):
            out = self._try_device("allgather", dplane, rank, data)
            if out is not None:
                return out
        data = self._stage_host(data)
        if self._sched_reduction_eligible() and data.size > 0:
            return self._reduction_sched(rank, "allgather", data, None)
        # Hierarchy pays off once the OUTPUT (size × contribution) is
        # pipeline-sized; the per-rank contribution itself can be small
        use_hier = (self.hier_enabled and data.size > 0
                    and data.nbytes * self.size >= self.CHUNK_BYTES * 2
                    and self.topology().hierarchical
                    and self._hier_wins())
        use_ring = (not use_hier and self.size > 1
                    and data.nbytes >= self.CHUNK_BYTES
                    and self._all_hosts_same_machine())
        _count_collective("allgather", int(data.nbytes))
        with span("mpi", "allgather", rank=rank, size=self.size,
                  bytes=int(data.nbytes),
                  algo=("hier" if use_hier
                        else "ring" if use_ring else "tree")):
            if use_hier:
                return self._allgather_hier(rank, data)
            if use_ring:
                return self._allgather_ring(rank, data)
            # gather(0) + broadcast (reference :1082-1111). The broadcast
            # stream is self-describing (CHUNK_HEADER), so non-roots need
            # no sized template — they follow the root's framing.
            with span("mpi.phase", "gather", rank=rank):
                gathered = self._gather_impl(rank, MAIN_RANK, data)
            template = (gathered if rank == MAIN_RANK
                        else np.empty(0, dtype=data.dtype))
            with span("mpi.phase", "broadcast", rank=rank):
                return self._broadcast_impl(MAIN_RANK, rank, template)

    def _allgather_ring(self, rank: int, data: np.ndarray) -> np.ndarray:
        """Chunk-pipelined ring allgather: rank r's contribution is
        segment r; n-1 steps pass chunk references around the ring, each
        received chunk written straight into the result and forwarded.
        The contribution rides as private read-only copies (other ranks
        keep the references through their assembly even after this rank
        returns, so views of the caller's buffer — which MPI lets the
        caller reuse immediately — would be a torn-read hazard)."""
        flat = data.reshape(-1)
        n = self.size
        k = flat.size
        nxt, prv = (rank + 1) % n, (rank - 1) % n
        shared = flat.copy()
        shared.flags.writeable = False
        chunks = self._ring_chunks(0, k, flat.itemsize)
        out = np.empty(n * k, dtype=flat.dtype)
        out[rank * k:(rank + 1) * k] = flat
        parts: dict[int, list[np.ndarray]] = {
            rank: [shared[clo:chi] for clo, chi in chunks]}
        for step in range(n - 1):
            send_seg = (rank - step) % n
            for part in parts.pop(send_seg):
                if part.flags.writeable:
                    part.flags.writeable = False
                self.send(rank, nxt, part, MpiMessageType.ALLGATHER,
                          _copy=False)
            recv_seg = (rank - step - 1) % n
            base = recv_seg * k
            recv_parts = []
            for clo, chi in chunks:
                arr, _ = self._recv_raw(prv, rank)
                out[base + clo:base + chi] = arr
                recv_parts.append(arr)
            parts[recv_seg] = recv_parts
        return out

    def _allgather_hier(self, rank: int, data: np.ndarray) -> np.ndarray:
        """Hierarchical allgather: contributions gather to the local
        leader in process (phase ``intra``), the leaders circulate
        per-HOST blocks around the wire ring chunk-pipelined (phase
        ``leader`` — each link carries (N−m)/N of the output instead of
        every rank being a wire peer), and the assembled result fans
        back out as a frozen in-process reference (``redistribute``).
        Host blocks are keyed by the Topology's rank lists, so
        scattered (non-contiguous) placements reassemble correctly."""
        topo = self.topology()
        flat = data.reshape(-1)
        k = flat.size
        locals_ = list(topo.ranks_on_host(topo.host_of(rank)))
        leader = locals_[0]
        leaders = list(topo.leaders)
        n_hosts = len(leaders)

        if rank != leader:
            with span("mpi.phase", "gather", rank=rank, phase="intra"):
                self.send(rank, leader, flat, MpiMessageType.GATHER)
            with span("mpi.phase", "broadcast", rank=rank,
                      phase="redistribute"):
                arr, _ = self._recv_raw(leader, rank)
                return self._private_result(
                    arr, np.empty(0, dtype=flat.dtype))

        m = len(locals_)
        out = np.empty(self.size * k, dtype=flat.dtype)

        def place(host_ranks, block) -> None:
            for i, r in enumerate(host_ranks):
                out[r * k:(r + 1) * k] = block[i * k:(i + 1) * k]

        with span("mpi.phase", "gather", rank=rank, phase="intra"):
            block = np.empty(m * k, dtype=flat.dtype)
            block[:k] = flat  # leader is local position 0
            for i, r in enumerate(locals_[1:], start=1):
                arr, _ = self._recv_raw(r, rank)
                block[i * k:(i + 1) * k] = arr

        with span("mpi.phase", "allgather", rank=rank, phase="leader"):
            place(locals_, block)
            block.flags.writeable = False
            pos = leaders.index(rank)
            nxt = leaders[(pos + 1) % n_hosts]
            prv = leaders[(pos - 1) % n_hosts]
            blocks: dict[int, list[np.ndarray]] = {
                pos: [block[clo:chi] for clo, chi in
                      self._ring_chunks(0, block.size, block.itemsize)]}
            for step in range(n_hosts - 1):
                send_pos = (pos - step) % n_hosts
                for part in blocks.pop(send_pos):
                    if part.flags.writeable:
                        part.flags.writeable = False
                    self.send(rank, nxt, part, MpiMessageType.ALLGATHER,
                              _copy=False)
                recv_pos = (pos - step - 1) % n_hosts
                rranks = topo.ranks_on_host(
                    topo.host_of(leaders[recv_pos]))
                rblock = np.empty(len(rranks) * k, dtype=flat.dtype)
                parts = []
                write = 0
                for clo, chi in self._ring_chunks(0, rblock.size,
                                                  flat.itemsize):
                    arr, _ = self._recv_raw(prv, rank)
                    rblock[write:write + arr.size] = arr
                    parts.append(arr)
                    write += arr.size
                place(rranks, rblock)
                blocks[recv_pos] = parts

        with span("mpi.phase", "broadcast", rank=rank,
                  phase="redistribute"):
            if m > 1:
                out.flags.writeable = False
                for r in locals_[1:]:
                    self.send(rank, r, out, MpiMessageType.BROADCAST,
                              _copy=False)
                out = out.copy()  # receivers keep the frozen buffer
        return out

    def scan(self, rank: int, data: np.ndarray,
             op: MpiOp = MpiOp.SUM) -> np.ndarray:
        """MPI_Scan. Schedule-compiled (ISSUE 13): ``scan.chain`` is the
        reference linear chain (:1390-1431) as a verified step program
        — bit-identical fold order (prefix, mine) — and ``scan.hier``
        (gang-contiguous placements) runs intra-host chains + a carrier
        chain between hosts, ≈ ranks/host + hosts serial hops instead
        of N. Previously the one collective with neither a span nor a
        _count_collective — the comm-matrix/profiler blind spot ISSUE
        13's satellite closes."""
        data = np.asarray(data)
        _count_collective("scan", int(data.nbytes))
        if not (self.sched_enabled and self.size > 1):
            with span("mpi", "scan", rank=rank, size=self.size,
                      bytes=int(data.nbytes), algo="chain"):
                return self._scan_chain(rank, data, op)
        sched, family = self._sched_get(
            rank, "scan", op=op, dtype=data.dtype,
            nbytes=int(data.nbytes))
        with span("mpi", "scan", rank=rank, size=self.size,
                  bytes=int(data.nbytes),
                  algo="sched:" + family.split(".", 1)[1]):
            flat = data.reshape(-1)
            env: dict = {("in", 0): flat}
            self._run_schedule(rank, sched, env, op,
                               lambda sym, e: flat.size,
                               MpiMessageType.SCAN)
            out = np.array(env[("out", 0)]).reshape(data.shape)
            return out

    def _scan_chain(self, rank: int, data: np.ndarray,
                    op: MpiOp) -> np.ndarray:
        """Seed-era linear chain, kept as the knob-off fallback: rank r
        receives the prefix from r-1, merges, forwards to r+1."""
        if rank > 0:
            prev, _ = self.recv(rank - 1, rank)
            acc = apply_op(op, prev, data)
        else:
            acc = data.copy()
        if rank < self.size - 1:
            self.send(rank, rank + 1, acc, MpiMessageType.SCAN)
        return acc

    def alltoall(self, rank: int, data: np.ndarray) -> np.ndarray:
        """All-pairs exchange of equal chunks: data is (size*chunk,),
        row r goes to rank r. Schedule-compiled (ISSUE 13): the runner
        executes a verified step program — ``alltoall.hier`` packs host
        blocks through the local leaders (the reference's
        disabled-since-2024 locality-aware ALLTOALL_PACKED variant,
        cutting cross-host messages to ≈1/ranks-per-host² — bytes are
        invariant, alltoall is a permutation), ``alltoall.flat`` is the
        naive pairwise pattern as a schedule. FAABRIC_SCHED_COLLECTIVES
        =off keeps the seed-era hand-written loop."""
        data = np.asarray(data)
        _count_collective("alltoall", int(data.nbytes))
        if not (self.sched_enabled and self.size > 1):
            with span("mpi", "alltoall", rank=rank, size=self.size,
                      bytes=int(data.nbytes), algo="direct"):
                return self._alltoall_direct(rank, data)
        sched, family = self._sched_get(
            rank, "alltoall", dtype=data.dtype, nbytes=int(data.nbytes))
        with span("mpi", "alltoall", rank=rank, size=self.size,
                  bytes=int(data.nbytes),
                  algo="sched:" + family.split(".", 1)[1]):
            return self._alltoall_sched(rank, data, sched, family)

    def _alltoall_direct(self, rank: int, data: np.ndarray) -> np.ndarray:
        """Seed-era naive all-pairs loop (reference :1433-1736), kept as
        the knob-off fallback and the A/B baseline."""
        chunk = data.size // self.size
        rows = data.reshape(self.size, chunk)
        for r in range(self.size):
            if r != rank:
                self.send(rank, r, rows[r], MpiMessageType.ALLTOALL)
        out = np.empty_like(rows)
        out[rank] = rows[rank]
        for r in range(self.size):
            if r != rank:
                arr, _ = self.recv(r, rank)
                out[r] = arr
        return out.reshape(-1)

    def _alltoall_sched(self, rank: int, data: np.ndarray, sched,
                        family: str) -> np.ndarray:
        flat = data.reshape(-1)
        k = flat.size // self.size
        rows = flat.reshape(self.size, k)
        env: dict = {("in", j): rows[j] for j in range(self.size)}
        msg_type = (MpiMessageType.ALLTOALL_PACKED
                    if family == "alltoall.hier"
                    else MpiMessageType.ALLTOALL)
        self._run_schedule(rank, sched, env, None,
                           lambda sym, e: k, msg_type)
        out = np.empty(self.size * k, dtype=flat.dtype)
        for j in range(self.size):
            out[j * k:(j + 1) * k] = env[("out", j)]
        return out

    # ------------------------------------------------------------------
    # Cartesian topology (reference :369-493 — there fixed 2-D periodic,
    # LAMMPS-style; here user dims via cart_create, defaulting to the
    # reference's near-square 2-D factorisation)
    # ------------------------------------------------------------------
    _cart_user_dims: Optional[tuple[int, ...]] = None

    def cart_create(self, dims: Optional[Sequence[int]] = None
                    ) -> tuple[int, ...]:
        """MPI_Cart_create with user dims (all-periodic); ``None`` keeps
        the default 2-D factorisation."""
        if dims is None:
            self._cart_user_dims = None
            return self.cart_dims()
        dims = tuple(int(d) for d in dims)
        if any(d <= 0 for d in dims):
            raise ValueError(f"Cartesian dims must be positive: {dims}")
        if int(np.prod(dims)) != self.size:
            raise ValueError(
                f"Cartesian dims {dims} do not tile {self.size} ranks")
        self._cart_user_dims = dims
        return dims

    def cart_dims(self) -> tuple[int, ...]:
        if self._cart_user_dims is not None:
            return self._cart_user_dims
        side = int(np.floor(np.sqrt(self.size)))
        while side > 1 and self.size % side != 0:
            side -= 1
        return side, self.size // side

    def cart_coords(self, rank: int) -> tuple[int, ...]:
        return tuple(int(c) for c in
                     np.unravel_index(rank, self.cart_dims()))

    def cart_rank(self, coords: Sequence[int]) -> int:
        dims = self.cart_dims()
        wrapped = [c % d for c, d in zip(coords, dims)]
        return int(np.ravel_multi_index(wrapped, dims))

    def cart_shift(self, rank: int, dim: int, disp: int) -> tuple[int, int]:
        """(source, dest) for a periodic shift along dim."""
        coords = list(self.cart_coords(rank))
        src_coords = list(coords)
        dst_coords = list(coords)
        src_coords[dim] -= disp
        dst_coords[dim] += disp
        return self.cart_rank(src_coords), self.cart_rank(dst_coords)

    # ------------------------------------------------------------------
    # Sub-communicators (reference mpi.h MPI_Comm_split_type /
    # MPI_Comm_create / MPI_Comm_dup / MPI_Group_incl)
    # ------------------------------------------------------------------
    # Split-generation draws: ranks co-located on a host SHARE this world
    # object, so a plain per-world counter would hand concurrent callers
    # different values. Each rank draws a locally-unique number and the
    # split's allgather agrees on max(draws) — monotonic per collective
    # call and identical on every rank
    def _split_draw(self) -> int:
        with self._lock:
            self._split_seq += 1
            return self._split_seq

    @staticmethod
    def _derive_group_id(parent: int, seq: int, color: int) -> int:
        # Cryptographic mix (NOT Python hash(): randomized per process;
        # NOT linear arithmetic: colors are arbitrary ints and a linear
        # mix collides whenever color deltas cancel seq deltas), folded
        # into a distinct high range so derived ids can't collide with
        # planner-generated GIDs
        import hashlib

        digest = hashlib.sha256(
            f"{parent}:{seq}:{color}".encode()).digest()
        mixed = int.from_bytes(digest[:8], "little") & ((1 << 62) - 1)
        return (1 << 126) | mixed

    def make_subworld(self, member_ranks: list[int], sub_group_id: int
                      ) -> "MpiWorld":
        """A real MpiWorld whose rank i is parent rank member_ranks[i]:
        every member host derives the SAME mappings from the parent's, so
        no planner round-trip is needed. All existing point-to-point and
        collective machinery works unchanged on the result."""
        from faabric_tpu.batch_scheduler.decision import SchedulingDecision

        self.broker.wait_for_mappings(self.group_id)
        d = SchedulingDecision(app_id=sub_group_id, group_id=sub_group_id)
        for new_idx, parent_rank in enumerate(member_ranks):
            host = self.broker.get_host_for_receiver(self.group_id,
                                                     parent_rank)
            port = self.broker.get_mpi_port_for_receiver(self.group_id,
                                                         parent_rank)
            dev = self.broker.get_device_for_idx(self.group_id, parent_rank)
            d.add_message(host, sub_group_id + new_idx + 1, new_idx,
                          new_idx, mpi_port=port, device_id=dev)
        # Installed by every local member; idempotent per host
        self.broker.set_up_local_mappings_from_decision(d)
        sub = MpiWorld(self.broker, sub_group_id, len(member_ranks),
                       sub_group_id, user=self.user, function=self.function)
        sub.record_exec_graph = self.record_exec_graph
        return sub

    def split(self, rank: int, color: int, key: int = 0
              ) -> tuple[Optional["MpiWorld"], int]:
        """MPI_Comm_split: ranks with the same ``color`` form a subworld,
        ordered by (key, parent rank). color < 0 (MPI_UNDEFINED) opts
        out → (None, -1). Collective over the PARENT world."""
        triple = np.array([color, key, rank, self._split_draw()],
                          dtype=np.int64)
        gathered = self.allgather(rank, triple).reshape(self.size, 4)
        seq = int(gathered[:, 3].max())
        if color < 0:
            return None, -1
        members = sorted((int(k), int(r)) for c, k, r, _ in gathered
                         if int(c) == color)
        member_ranks = [r for _, r in members]
        sub_group_id = self._derive_group_id(self.group_id, seq, color)
        sub = self.make_subworld(member_ranks, sub_group_id)
        return sub, member_ranks.index(rank)

    def split_type_shared(self, rank: int, key: int = 0
                          ) -> tuple["MpiWorld", int]:
        """MPI_Comm_split_type(MPI_COMM_TYPE_SHARED): one subworld per
        HOST — co-located ranks that can share memory (the reference's
        split_type semantics, mpi.h:565)."""
        host = self.host_for_rank(rank)
        color = sorted(self.hosts()).index(host)
        sub, new_rank = self.split(rank, color, key)
        assert sub is not None
        return sub, new_rank

    def dup(self, rank: int) -> tuple["MpiWorld", int]:
        """MPI_Comm_dup: same membership, fresh communication context
        (a new group id → isolated queues/sequence state)."""
        return self.split(rank, color=0, key=rank)

    def create_group_comm(self, rank: int, member_ranks: list[int],
                          tag: int = 0) -> tuple[Optional["MpiWorld"], int]:
        """MPI_Comm_create_group: collective only over ``member_ranks``
        (every member passes the same list); non-members just get None.
        No parent-wide communication — the membership is given, so the
        derived id comes from (parent, members, tag) rather than the
        split counter (non-members never call this, and a shared counter
        would desync). Reuse with identical arguments needs a distinct
        ``tag``, as in MPI."""
        if rank not in member_ranks:
            return None, -1
        mix = 0
        for r in member_ranks:
            mix = (mix * 131 + int(r) + 1) & ((1 << 62) - 1)
        sub_group_id = self._derive_group_id(self.group_id, mix,
                                             tag + (1 << 20))
        sub = self.make_subworld(list(member_ranks), sub_group_id)
        return sub, list(member_ranks).index(rank)

    def close(self) -> None:
        """Stop this world's send workers (registry teardown)."""
        with self._lock:
            workers, self._send_workers = dict(self._send_workers), {}
        for w in workers.values():
            w.shutdown()

    # ------------------------------------------------------------------
    # Migration (reference prepareMigration :2095-2131)
    # ------------------------------------------------------------------
    def prepare_migration(self, rank: int, new_group_id: int | None = None) -> None:
        with self._lock:
            if any(self._requests.values()):
                raise RuntimeError(
                    "Cannot migrate an MPI world with pending async requests")
            if new_group_id is not None:
                self.group_id = new_group_id
            self._rank_hosts.clear()
            self._rank_devices.clear()
            self._topology_cache = None
            self._same_machine_cache = None
            self._topology_gen += 1
            self._device_collectives = None
            # Post-migration the rank→device map is stale: the rung
            # drops until every rank re-runs the activation handshake
            self._device_plane = None
        # Outstanding device-resident state handles (ISSUE 15) point at
        # HBM on the PRE-migration chip assignment: drop them all (the
        # re-handshake path re-pushes, minting fresh-generation
        # handles) so a migrated rank can never pull a stale reference.
        # Flight-recorded inside invalidate_world.
        from faabric_tpu.state.device_handle import invalidate_world

        invalidate_world(self.id)
        watch = getattr(self.broker, "watch_group", None)
        if watch is not None:
            watch(self.group_id)  # liveness checking follows the new gid

    # ------------------------------------------------------------------
    def exec_graph_details(self) -> dict[str, int]:
        with self._lock:
            out = {f"mpi-msgcount-torank-{r}": n
                   for r, n in self._msg_count_to_rank.items()}
            for (t, r), n in self._msg_type_count.items():
                out[f"mpi-msgtype-{t}-torank-{r}"] = n
            return out
