"""Device-platform environment handling.

This image's sitecustomize registers the remote-TPU ("axon") PJRT plugin
and explicitly sets ``jax_platforms="axon,cpu"`` via jax.config — which
overrides the JAX_PLATFORMS env var. Initialising that backend dials the
TPU tunnel (minutes-slow, single claimant), so CPU-targeted processes
(tests, dryruns, benches) must re-assert the env var's choice explicitly
before touching a device.
"""

from __future__ import annotations

import os


def force_cpu_if_requested() -> None:
    """Honor JAX_PLATFORMS=cpu even when an explicit jax.config override
    (e.g. from sitecustomize) would win over the env var."""
    if "cpu" in os.environ.get("JAX_PLATFORMS", "").split(","):
        import jax

        jax.config.update("jax_platforms", "cpu")
