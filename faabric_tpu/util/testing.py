"""Global test/mock switches (reference: include/faabric/util/testing.h:1-11).

When mock mode is on, every RPC client records calls instead of sending over
the network; tests assert on the recorded queues. This is the backbone of the
reference's unit-test strategy (SURVEY.md §4.1) and is preserved here.
"""

from __future__ import annotations

_test_mode = False
_mock_mode = False


def set_test_mode(value: bool) -> None:
    global _test_mode
    _test_mode = value


def is_test_mode() -> bool:
    return _test_mode


def set_mock_mode(value: bool) -> None:
    global _mock_mode
    _mock_mode = value


def is_mock_mode() -> bool:
    return _mock_mode
