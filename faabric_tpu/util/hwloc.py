"""CPU pinning (reference include/faabric/util/hwloc.h:11-31 — there an
hwloc-based global free-CPU allocator used to pin MPI rank threads; here
``os.sched_setaffinity`` with the same claim/release slot discipline and
the OVERRIDE_FREE_CPU_START escape hatch for colocated test processes)."""

from __future__ import annotations

import os
import threading
from typing import Optional

from faabric_tpu.util.config import get_system_config
from faabric_tpu.util.logging import get_logger

logger = get_logger(__name__)

_lock = threading.Lock()
_claimed: set[int] = set()


def _cpu_pool() -> list[int]:
    conf = get_system_config()
    start = conf.override_free_cpu_start
    try:
        cpus = sorted(os.sched_getaffinity(0))
    except AttributeError:  # pragma: no cover — non-Linux
        cpus = list(range(os.cpu_count() or 1))
    return cpus[start:] or cpus


def pin_thread_to_free_cpu() -> Optional[int]:
    """Claim the lowest unclaimed CPU and pin the calling thread to it.
    Returns the CPU id, or None when the pool is exhausted or pinning is
    unsupported."""
    with _lock:
        for cpu in _cpu_pool():
            if cpu not in _claimed:
                _claimed.add(cpu)
                chosen = cpu
                break
        else:
            return None
    try:
        os.sched_setaffinity(0, {chosen})
        return chosen
    except (AttributeError, OSError):  # pragma: no cover
        with _lock:
            _claimed.discard(chosen)
        return None


def unpin_cpu(cpu: int) -> None:
    """Release a claimed CPU slot. Does NOT touch any thread's affinity —
    the releasing thread is often not the pinned one (pool cleanup), and
    widening its mask would clobber its own pin. A pinned thread that
    wants its affinity back calls unpin_current_thread()."""
    with _lock:
        _claimed.discard(cpu)


def unpin_current_thread(cpu: int) -> None:
    """Release the slot AND restore this thread's affinity to the pool."""
    unpin_cpu(cpu)
    try:
        os.sched_setaffinity(0, set(_cpu_pool()))
    except (AttributeError, OSError):  # pragma: no cover
        pass


def reset_pins_for_tests() -> None:
    with _lock:
        _claimed.clear()
