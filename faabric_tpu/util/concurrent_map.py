"""Sharded concurrent map (reference: include/faabric/util/concurrent_map.h).

Provides atomic get-or-create (``try_emplace_then_mutate``) used throughout
the runtime for registries (worlds, groups, endpoints).
"""

from __future__ import annotations

import threading
from typing import Callable, Generic, Iterator, TypeVar

K = TypeVar("K")
V = TypeVar("V")


class ConcurrentMap(Generic[K, V]):
    def __init__(self) -> None:
        self._map: dict[K, V] = {}
        self._lock = threading.RLock()

    def get(self, key: K) -> V | None:
        with self._lock:
            return self._map.get(key)

    def __contains__(self, key: K) -> bool:
        with self._lock:
            return key in self._map

    def insert(self, key: K, value: V) -> None:
        with self._lock:
            self._map[key] = value

    def try_emplace(self, key: K, factory: Callable[[], V]) -> tuple[V, bool]:
        """Returns (value, inserted). Factory only runs if the key is absent."""
        with self._lock:
            if key in self._map:
                return self._map[key], False
            value = factory()
            self._map[key] = value
            return value, True

    def try_emplace_then_mutate(
        self, key: K, factory: Callable[[], V], mutate: Callable[[V], None]
    ) -> V:
        with self._lock:
            if key not in self._map:
                self._map[key] = factory()
            value = self._map[key]
            mutate(value)
            return value

    def erase(self, key: K) -> None:
        with self._lock:
            self._map.pop(key, None)

    def clear(self) -> None:
        with self._lock:
            self._map.clear()

    def keys(self) -> list[K]:
        with self._lock:
            return list(self._map.keys())

    def values(self) -> list[V]:
        with self._lock:
            return list(self._map.values())

    def items(self) -> list[tuple[K, V]]:
        with self._lock:
            return list(self._map.items())

    def size(self) -> int:
        with self._lock:
            return len(self._map)

    def __iter__(self) -> Iterator[K]:
        return iter(self.keys())
