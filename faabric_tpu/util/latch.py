"""Latches, cyclic barriers and flag waiters.

Reference: include/faabric/util/latch.h:11, barrier.h:11, locks.h:18.
"""

from __future__ import annotations

import threading
from typing import Callable

DEFAULT_LATCH_TIMEOUT = 10.0


class LatchTimeoutException(Exception):
    pass


class Latch:
    """Count-down latch: ``count`` parties call wait(); all are released when
    the last arrives. Single-use."""

    def __init__(self, count: int, timeout: float = DEFAULT_LATCH_TIMEOUT) -> None:
        self.count = count
        self.timeout = timeout
        self._waiters = 0
        self._cond = threading.Condition()

    @classmethod
    def create(cls, count: int, timeout: float = DEFAULT_LATCH_TIMEOUT) -> "Latch":
        return cls(count, timeout)

    def wait(self) -> None:
        with self._cond:
            self._waiters += 1
            if self._waiters > self.count:
                raise RuntimeError("Latch already used")
            if self._waiters == self.count:
                self._cond.notify_all()
                return
            if not self._cond.wait_for(lambda: self._waiters >= self.count, self.timeout):
                raise LatchTimeoutException("Latch timed out")


class Barrier:
    """Cyclic barrier with optional completion function
    (reference barrier.h: completion fn runs once per cycle)."""

    def __init__(self, count: int, completion: Callable[[], None] | None = None,
                 timeout: float = DEFAULT_LATCH_TIMEOUT) -> None:
        self._barrier = threading.Barrier(count, action=completion, timeout=timeout)

    def wait(self) -> None:
        try:
            self._barrier.wait()
        except threading.BrokenBarrierError as e:
            raise LatchTimeoutException("Barrier broken or timed out") from e


class FlagWaiter:
    """waitOnFlag/setFlag — used for PTP mapping readiness
    (reference locks.h:18, PointToPointBroker.cpp:528-534)."""

    def __init__(self, timeout: float = DEFAULT_LATCH_TIMEOUT) -> None:
        self._event = threading.Event()
        self.timeout = timeout

    def wait_on_flag(self, timeout: float | None = None) -> None:
        if not self._event.wait(timeout if timeout is not None else self.timeout):
            raise LatchTimeoutException("Timeout waiting on flag")

    def is_set(self) -> bool:
        """Lock-free fast-path check (Event.is_set is a plain attribute
        read) — lets per-message hot paths skip the condvar dance once
        the flag has been raised."""
        return self._event.is_set()

    def set_flag(self, value: bool = True) -> None:
        if value:
            self._event.set()
        else:
            self._event.clear()

    def is_set(self) -> bool:
        return self._event.is_set()
