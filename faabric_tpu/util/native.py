"""Loader for the native C++ helpers (native/pagediff.cpp).

Compiles the shared library on first use (g++ is baked into the image;
pybind11 is not, so the binding is ctypes over an extern-C surface) and
caches it next to the source. Falls back cleanly: callers check
``get_pagediff_lib() is not None`` and use the numpy path otherwise.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

from faabric_tpu.util.logging import get_logger

logger = get_logger(__name__)

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "pagediff.cpp")
_SO = os.path.join(_REPO_ROOT, "native", "build", "libpagediff.so")

_lib: Optional[ctypes.CDLL] = None
_tried = False
_lock = threading.Lock()


def _build() -> bool:
    os.makedirs(os.path.dirname(_SO), exist_ok=True)
    cmd = ["g++", "-O3", "-march=native", "-shared", "-fPIC", _SRC, "-o", _SO]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (subprocess.SubprocessError, OSError) as e:
        logger.warning("Native pagediff build failed (%s); using numpy path", e)
        return False


def get_pagediff_lib() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SRC):
            return None
        if not os.path.exists(_SO) or (os.path.getmtime(_SO)
                                       < os.path.getmtime(_SRC)):
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError as e:
            logger.warning("Could not load %s: %s", _SO, e)
            return None
        # void* arguments: callers pass numpy buffer addresses
        lib.diff_pages.restype = ctypes.c_size_t
        lib.diff_pages.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                   ctypes.c_size_t, ctypes.c_size_t,
                                   ctypes.c_void_p]
        lib.diff_ranges.restype = ctypes.c_size_t
        lib.diff_ranges.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                    ctypes.c_size_t, ctypes.c_size_t,
                                    ctypes.c_void_p, ctypes.c_void_p,
                                    ctypes.c_size_t]
        lib.xor_buffers.restype = None
        lib.xor_buffers.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                    ctypes.c_void_p, ctypes.c_size_t]
        _lib = lib
        return _lib


_SHM_SRC = os.path.join(_REPO_ROOT, "native", "shm_ring.cpp")
_SHM_SO = os.path.join(_REPO_ROOT, "native", "build", "libshmring.so")

_shm_lib: Optional[ctypes.CDLL] = None
_shm_tried = False


def get_shmring_lib() -> Optional[ctypes.CDLL]:
    """The SPSC shared-memory ring (native/shm_ring.cpp) — the
    same-machine bulk data plane's hot path. None when g++ or the source
    is unavailable; callers fall back to the TCP plane."""
    global _shm_lib, _shm_tried
    with _lock:
        if _shm_tried:
            return _shm_lib
        _shm_tried = True
        if not os.path.exists(_SHM_SRC):
            return None
        if not os.path.exists(_SHM_SO) or (os.path.getmtime(_SHM_SO)
                                           < os.path.getmtime(_SHM_SRC)):
            os.makedirs(os.path.dirname(_SHM_SO), exist_ok=True)
            cmd = ["g++", "-O3", "-march=native", "-shared", "-fPIC",
                   _SHM_SRC, "-o", _SHM_SO]
            try:
                subprocess.run(cmd, check=True, capture_output=True,
                               timeout=120)
            except (subprocess.SubprocessError, OSError) as e:
                logger.warning("Native shm_ring build failed (%s); "
                               "same-machine bulk stays on TCP", e)
                return None
        try:
            lib = ctypes.CDLL(_SHM_SO)
        except OSError as e:
            logger.warning("Could not load %s: %s", _SHM_SO, e)
            return None
        lib.ring_init.restype = ctypes.c_int
        lib.ring_init.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.ring_check.restype = ctypes.c_int64
        lib.ring_check.argtypes = [ctypes.c_void_p]
        lib.ring_free_space.restype = ctypes.c_int64
        lib.ring_free_space.argtypes = [ctypes.c_void_p]
        lib.ring_try_pushv.restype = ctypes.c_int
        lib.ring_try_pushv.argtypes = [ctypes.c_void_p,
                                       ctypes.POINTER(ctypes.c_void_p),
                                       ctypes.POINTER(ctypes.c_uint64),
                                       ctypes.c_uint64]
        lib.ring_peek.restype = ctypes.c_int64
        lib.ring_peek.argtypes = [ctypes.c_void_p]
        lib.ring_pop.restype = ctypes.c_int64
        lib.ring_pop.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                 ctypes.c_uint64]
        lib.ring_wait_data.restype = ctypes.c_int
        lib.ring_wait_data.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
        lib.ring_wait_space.restype = ctypes.c_int
        lib.ring_wait_space.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                        ctypes.c_uint32]
        _shm_lib = lib
        return _shm_lib


_SEGV_SRC = os.path.join(_REPO_ROOT, "native", "segv_tracker.cpp")
_SEGV_SO = os.path.join(_REPO_ROOT, "native", "build", "libsegvtracker.so")

_segv_lib: Optional[ctypes.CDLL] = None
_segv_tried = False


def get_segv_lib() -> Optional[ctypes.CDLL]:
    """The SIGSEGV write-fault dirty tracker (native/segv_tracker.cpp) —
    O(dirty) page tracking with no baseline copy. None when g++ or the
    source is unavailable; callers fall back to comparison tracking."""
    global _segv_lib, _segv_tried
    with _lock:
        if _segv_tried:
            return _segv_lib
        _segv_tried = True
        if not os.path.exists(_SEGV_SRC):
            return None
        if not os.path.exists(_SEGV_SO) or (os.path.getmtime(_SEGV_SO)
                                            < os.path.getmtime(_SEGV_SRC)):
            os.makedirs(os.path.dirname(_SEGV_SO), exist_ok=True)
            cmd = ["g++", "-O3", "-march=native", "-shared", "-fPIC",
                   _SEGV_SRC, "-o", _SEGV_SO]
            try:
                subprocess.run(cmd, check=True, capture_output=True,
                               timeout=120)
            except (subprocess.SubprocessError, OSError) as e:
                logger.warning("Native segv_tracker build failed (%s); "
                               "segv dirty mode unavailable", e)
                return None
        try:
            lib = ctypes.CDLL(_SEGV_SO)
        except OSError as e:
            logger.warning("Could not load %s: %s", _SEGV_SO, e)
            return None
        lib.segv_install.restype = ctypes.c_int
        lib.segv_install.argtypes = []
        lib.segv_start.restype = ctypes.c_int
        lib.segv_start.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                   ctypes.c_void_p]
        lib.segv_stop.restype = ctypes.c_int
        lib.segv_stop.argtypes = [ctypes.c_int]
        if lib.segv_install() != 0:
            logger.warning("segv_tracker handler install failed")
            return None
        _segv_lib = lib
        return _segv_lib


_UFFD_SRC = os.path.join(_REPO_ROOT, "native", "uffd_tracker.cpp")
_UFFD_SO = os.path.join(_REPO_ROOT, "native", "build", "libuffdtracker.so")

_uffd_lib: Optional[ctypes.CDLL] = None
_uffd_tried = False


def get_uffd_lib() -> Optional[ctypes.CDLL]:
    """The userfaultfd write-protect dirty tracker
    (native/uffd_tracker.cpp) — O(dirty) like the segv mode but faults
    are resolved by a dedicated event thread instead of a process-wide
    signal handler (the reference's uffd-thread-wp mode). None when the
    kernel lacks uffd-wp or the native build fails."""
    global _uffd_lib, _uffd_tried
    with _lock:
        if _uffd_tried:
            return _uffd_lib
        _uffd_tried = True
        if not os.path.exists(_UFFD_SRC):
            return None
        if not os.path.exists(_UFFD_SO) or (os.path.getmtime(_UFFD_SO)
                                            < os.path.getmtime(_UFFD_SRC)):
            os.makedirs(os.path.dirname(_UFFD_SO), exist_ok=True)
            cmd = ["g++", "-O3", "-march=native", "-shared", "-fPIC",
                   _UFFD_SRC, "-o", _UFFD_SO, "-lpthread"]
            try:
                subprocess.run(cmd, check=True, capture_output=True,
                               timeout=120)
            except (subprocess.SubprocessError, OSError) as e:
                logger.warning("Native uffd_tracker build failed (%s); "
                               "uffd dirty mode unavailable", e)
                return None
        try:
            lib = ctypes.CDLL(_UFFD_SO)
        except OSError as e:
            logger.warning("Could not load %s: %s", _UFFD_SO, e)
            return None
        lib.uffd_install.restype = ctypes.c_int
        lib.uffd_install.argtypes = []
        lib.uffd_start.restype = ctypes.c_int
        lib.uffd_start.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                   ctypes.c_void_p]
        lib.uffd_stop.restype = ctypes.c_int
        lib.uffd_stop.argtypes = [ctypes.c_int]
        rc = lib.uffd_install()
        if rc != 0:
            logger.info("userfaultfd write-protect unavailable (rc=%d); "
                        "DIRTY_TRACKING_MODE=uffd falls back", rc)
            return None
        _uffd_lib = lib
        return _uffd_lib


def reset_for_tests() -> None:
    global _lib, _tried, _shm_lib, _shm_tried
    with _lock:
        _lib = None
        _tried = False
        _shm_lib = None
        _shm_tried = False
        # segv lib deliberately NOT reset: its SIGSEGV handler is
        # process-wide state that must not be re-installed per test
