"""Loader for the native C++ helpers under native/.

Compiles each shared library on first use (g++ is baked into the image;
pybind11 is not, so the bindings are ctypes over extern-C surfaces) and
caches it next to the source. Falls back cleanly: callers check
``get_*_lib() is not None`` and use the pure-Python/numpy path otherwise.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Callable, Optional

from faabric_tpu.util.logging import get_logger

logger = get_logger(__name__)

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_lock = threading.Lock()
# name → loaded lib, or None after a failed attempt (one try per process)
_cache: dict[str, Optional[ctypes.CDLL]] = {}
# name → Event while a build/load is in flight: the compile (up to
# 120 s of subprocess.run) must not run under the module lock — that
# would serialize every other native lib's first use behind it and is
# exactly the blocking-call-under-lock pattern tools/concheck.py flags.
# Losers of the build race park on the event, then re-read the cache.
_in_progress: dict[str, threading.Event] = {}

# Sanitizer build mode (ISSUE 7 satellite): FAABRIC_NATIVE_SAN=tsan|asan
# compiles every native helper with the matching -fsanitize flag into a
# suffixed .so. Loading one into an unsanitized interpreter requires the
# runtime preloaded (LD_PRELOAD=$(g++ -print-file-name=libtsan.so)) —
# tests/unit/test_native_san.py drives that in a subprocess; an
# in-process load attempt without the preload fails cleanly into the
# usual pure-Python fallback.
_SAN_FLAGS = {
    "tsan": ("-fsanitize=thread", "-O1", "-g", "-fno-omit-frame-pointer"),
    "asan": ("-fsanitize=address", "-O1", "-g",
             "-fno-omit-frame-pointer"),
}


def _san_mode() -> str:
    mode = os.environ.get("FAABRIC_NATIVE_SAN", "").strip().lower()
    return mode if mode in _SAN_FLAGS else ""


def _build_and_load(name: str, src_file: str, so_file: str,
                    declare: Callable[[ctypes.CDLL], None],
                    install: Optional[Callable[[ctypes.CDLL], bool]],
                    extra_args: tuple,
                    fail_note: str) -> Optional[ctypes.CDLL]:
    """Compile-if-stale / load / declare / install — no locks held."""
    src = os.path.join(_REPO_ROOT, "native", src_file)
    san = _san_mode()
    if san:
        so_file = f"{so_file.removesuffix('.so')}.{san}.so"
    so = os.path.join(_REPO_ROOT, "native", "build", so_file)
    if not os.path.exists(src):
        return None
    if not os.path.exists(so) or (os.path.getmtime(so)
                                  < os.path.getmtime(src)):
        os.makedirs(os.path.dirname(so), exist_ok=True)
        if san:
            opt_args: tuple = _SAN_FLAGS[san]
        else:
            opt_args = ("-O3", "-march=native")
        cmd = ["g++", *opt_args, "-shared", "-fPIC",
               src, "-o", so, *extra_args]
        # Never compile under an inherited sanitizer preload: cc1plus/
        # as/ld running through libtsan's interceptors turns a 5 s build
        # into minutes (observed hang when a TSAN-preloaded test process
        # triggered the first sanitized build)
        env = {k: v for k, v in os.environ.items() if k != "LD_PRELOAD"}
        try:
            subprocess.run(cmd, check=True, capture_output=True,
                           timeout=120, env=env)
        except (subprocess.SubprocessError, OSError) as e:
            logger.warning("Native %s build failed (%s); %s",
                           name, e, fail_note)
            return None
    try:
        lib = ctypes.CDLL(so)
    except OSError as e:
        logger.warning("Could not load %s: %s", so, e)
        return None
    declare(lib)
    if install is not None and not install(lib):
        return None
    return lib


def _load_native(name: str, src_file: str, so_file: str,
                 declare: Callable[[ctypes.CDLL], None],
                 install: Optional[Callable[[ctypes.CDLL], bool]] = None,
                 extra_args: tuple = (),
                 fail_note: str = "") -> Optional[ctypes.CDLL]:
    """Shared load path for every native helper; one attempt per process
    per lib, with the build itself running outside the module lock."""
    while True:
        with _lock:
            if name in _cache:
                return _cache[name]
            ev = _in_progress.get(name)
            if ev is None:
                _in_progress[name] = threading.Event()
                break
        # Another thread owns this lib's build: park until it publishes
        # its verdict, then re-read the cache
        ev.wait()
    lib: Optional[ctypes.CDLL] = None
    try:
        lib = _build_and_load(name, src_file, so_file, declare, install,
                              extra_args, fail_note)
    finally:
        with _lock:
            _cache[name] = lib
            _in_progress.pop(name).set()
    return lib


def _declare_pagediff(lib: ctypes.CDLL) -> None:
    # void* arguments: callers pass numpy buffer addresses
    lib.diff_pages.restype = ctypes.c_size_t
    lib.diff_pages.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                               ctypes.c_size_t, ctypes.c_size_t,
                               ctypes.c_void_p]
    lib.diff_ranges.restype = ctypes.c_size_t
    lib.diff_ranges.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                ctypes.c_size_t, ctypes.c_size_t,
                                ctypes.c_void_p, ctypes.c_void_p,
                                ctypes.c_size_t]
    lib.xor_buffers.restype = None
    lib.xor_buffers.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                ctypes.c_void_p, ctypes.c_size_t]


def get_pagediff_lib() -> Optional[ctypes.CDLL]:
    return _load_native("pagediff", "pagediff.cpp", "libpagediff.so",
                        _declare_pagediff, fail_note="using numpy path")


def _declare_shmring(lib: ctypes.CDLL) -> None:
    lib.ring_init.restype = ctypes.c_int
    lib.ring_init.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.ring_check.restype = ctypes.c_int64
    lib.ring_check.argtypes = [ctypes.c_void_p]
    lib.ring_free_space.restype = ctypes.c_int64
    lib.ring_free_space.argtypes = [ctypes.c_void_p]
    lib.ring_try_pushv.restype = ctypes.c_int
    lib.ring_try_pushv.argtypes = [ctypes.c_void_p,
                                   ctypes.POINTER(ctypes.c_void_p),
                                   ctypes.POINTER(ctypes.c_uint64),
                                   ctypes.c_uint64]
    lib.ring_peek.restype = ctypes.c_int64
    lib.ring_peek.argtypes = [ctypes.c_void_p]
    lib.ring_pop.restype = ctypes.c_int64
    lib.ring_pop.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                             ctypes.c_uint64]
    lib.ring_pop_batch.restype = ctypes.c_int64
    lib.ring_pop_batch.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                   ctypes.c_uint64,
                                   ctypes.POINTER(ctypes.c_uint64),
                                   ctypes.c_uint64]
    lib.ring_wait_data.restype = ctypes.c_int
    lib.ring_wait_data.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
    lib.ring_wait_space.restype = ctypes.c_int
    lib.ring_wait_space.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                    ctypes.c_uint32]


def get_shmring_lib() -> Optional[ctypes.CDLL]:
    """The SPSC shared-memory ring (native/shm_ring.cpp) — the
    same-machine bulk data plane's hot path. None when g++ or the source
    is unavailable; callers fall back to the TCP plane."""
    return _load_native("shm_ring", "shm_ring.cpp", "libshmring.so",
                        _declare_shmring,
                        fail_note="same-machine bulk stays on TCP")


def _declare_tracker(prefix: str) -> Callable[[ctypes.CDLL], None]:
    def declare(lib: ctypes.CDLL) -> None:
        install = getattr(lib, f"{prefix}_install")
        install.restype = ctypes.c_int
        install.argtypes = []
        start = getattr(lib, f"{prefix}_start")
        start.restype = ctypes.c_int
        start.argtypes = [ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p]
        stop = getattr(lib, f"{prefix}_stop")
        stop.restype = ctypes.c_int
        stop.argtypes = [ctypes.c_int]
    return declare


def get_segv_lib() -> Optional[ctypes.CDLL]:
    """The SIGSEGV write-fault dirty tracker (native/segv_tracker.cpp) —
    O(dirty) page tracking with no baseline copy. None when g++ or the
    source is unavailable; callers fall back to comparison tracking."""
    def install(lib: ctypes.CDLL) -> bool:
        if lib.segv_install() != 0:
            logger.warning("segv_tracker handler install failed")
            return False
        return True

    return _load_native("segv_tracker", "segv_tracker.cpp",
                        "libsegvtracker.so", _declare_tracker("segv"),
                        install=install,
                        fail_note="segv dirty mode unavailable")


def get_uffd_lib() -> Optional[ctypes.CDLL]:
    """The userfaultfd write-protect dirty tracker
    (native/uffd_tracker.cpp) — O(dirty) like the segv mode but faults
    are resolved by a dedicated event thread instead of a process-wide
    signal handler (the reference's uffd-thread-wp mode). None when the
    kernel lacks uffd-wp or the native build fails."""
    def install(lib: ctypes.CDLL) -> bool:
        rc = lib.uffd_install()
        if rc != 0:
            logger.info("userfaultfd write-protect unavailable (rc=%d); "
                        "DIRTY_TRACKING_MODE=uffd falls back", rc)
            return False
        return True

    return _load_native("uffd_tracker", "uffd_tracker.cpp",
                        "libuffdtracker.so", _declare_tracker("uffd"),
                        install=install, extra_args=("-lpthread",),
                        fail_note="uffd dirty mode unavailable")


def reset_for_tests() -> None:
    with _lock:
        # segv/uffd deliberately NOT reset: the SIGSEGV handler and the
        # uffd event thread are process-wide state that must not be
        # re-installed per test
        _cache.pop("pagediff", None)
        _cache.pop("shm_ring", None)
