"""Loader for the native C++ helpers (native/pagediff.cpp).

Compiles the shared library on first use (g++ is baked into the image;
pybind11 is not, so the binding is ctypes over an extern-C surface) and
caches it next to the source. Falls back cleanly: callers check
``get_pagediff_lib() is not None`` and use the numpy path otherwise.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

from faabric_tpu.util.logging import get_logger

logger = get_logger(__name__)

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "pagediff.cpp")
_SO = os.path.join(_REPO_ROOT, "native", "build", "libpagediff.so")

_lib: Optional[ctypes.CDLL] = None
_tried = False
_lock = threading.Lock()


def _build() -> bool:
    os.makedirs(os.path.dirname(_SO), exist_ok=True)
    cmd = ["g++", "-O3", "-march=native", "-shared", "-fPIC", _SRC, "-o", _SO]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (subprocess.SubprocessError, OSError) as e:
        logger.warning("Native pagediff build failed (%s); using numpy path", e)
        return False


def get_pagediff_lib() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SRC):
            return None
        if not os.path.exists(_SO) or (os.path.getmtime(_SO)
                                       < os.path.getmtime(_SRC)):
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError as e:
            logger.warning("Could not load %s: %s", _SO, e)
            return None
        # void* arguments: callers pass numpy buffer addresses
        lib.diff_pages.restype = ctypes.c_size_t
        lib.diff_pages.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                   ctypes.c_size_t, ctypes.c_size_t,
                                   ctypes.c_void_p]
        lib.diff_ranges.restype = ctypes.c_size_t
        lib.diff_ranges.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                    ctypes.c_size_t, ctypes.c_size_t,
                                    ctypes.c_void_p, ctypes.c_void_p,
                                    ctypes.c_size_t]
        lib.xor_buffers.restype = None
        lib.xor_buffers.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                    ctypes.c_void_p, ctypes.c_size_t]
        _lib = lib
        return _lib


def reset_for_tests() -> None:
    global _lib, _tried
    with _lock:
        _lib = None
        _tried = False
