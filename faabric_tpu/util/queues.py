"""Queue primitives (reference: include/faabric/util/queue.h:25-245).

- Queue: mutex+condvar queue with timeout dequeue and drain.
- FixedCapacityQueue: bounded SPSC-style circular buffer (the moodycamel
  analog) — used for per-rank-pair MPI delivery.
- SpinLockQueue: busy-wait dequeue for latency-critical paths (the
  atomic_queue analog). In CPython a condvar wait has ~µs wakeup latency;
  the spin variant polls a deque guarded by the GIL for lower latency at
  the cost of a core.
- TokenPool: bounded token claim/release.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Generic, TypeVar

T = TypeVar("T")


class QueueTimeoutException(Exception):
    pass


class Queue(Generic[T]):
    def __init__(self) -> None:
        self._items: collections.deque[T] = collections.deque()
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)

    def enqueue(self, item: T) -> None:
        with self._cond:
            self._items.append(item)
            self._cond.notify()

    def enqueue_many(self, items) -> None:
        """Append a pre-ordered batch under ONE lock acquisition with one
        wakeup round — the bulk drain's burst-delivery path."""
        with self._cond:
            self._items.extend(items)
            self._cond.notify(len(items))

    def dequeue(self, timeout: float | None = None) -> T:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while not self._items:
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise QueueTimeoutException("Timeout waiting for dequeue")
                if not self._cond.wait(remaining):
                    raise QueueTimeoutException("Timeout waiting for dequeue")
            return self._items.popleft()

    def try_dequeue(self) -> T | None:
        with self._cond:
            if self._items:
                return self._items.popleft()
            return None

    def peek(self) -> T | None:
        with self._cond:
            return self._items[0] if self._items else None

    def size(self) -> int:
        with self._cond:
            return len(self._items)

    def drain(self) -> list[T]:
        with self._cond:
            out = list(self._items)
            self._items.clear()
            return out


class FixedCapacityQueue(Generic[T]):
    """Bounded queue; enqueue blocks when full (backpressure)."""

    def __init__(self, capacity: int) -> None:
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.capacity = capacity
        self._items: collections.deque[T] = collections.deque()
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._not_full = threading.Condition(self._lock)

    def enqueue(self, item: T, timeout: float | None = None) -> None:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while len(self._items) >= self.capacity:
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise QueueTimeoutException("Timeout waiting to enqueue")
                if not self._not_full.wait(remaining):
                    raise QueueTimeoutException("Timeout waiting to enqueue")
            self._items.append(item)
            self._not_empty.notify()

    def dequeue(self, timeout: float | None = None) -> T:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while not self._items:
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise QueueTimeoutException("Timeout waiting for dequeue")
                if not self._not_empty.wait(remaining):
                    raise QueueTimeoutException("Timeout waiting for dequeue")
            item = self._items.popleft()
            self._not_full.notify()
            return item

    def size(self) -> int:
        with self._lock:
            return len(self._items)


class SpinLockQueue(Generic[T]):
    """Low-latency queue: dequeue spins briefly before falling back to a
    condvar wait (hybrid spin, so idle receivers don't burn a core forever).
    """

    SPIN_NS = 50_000  # 50us of spinning before sleeping

    def __init__(self) -> None:
        self._items: collections.deque[T] = collections.deque()
        self._cond = threading.Condition()

    def enqueue(self, item: T) -> None:
        self._items.append(item)  # deque.append is atomic under the GIL
        with self._cond:
            self._cond.notify()

    def dequeue(self, timeout: float | None = None) -> T:
        end_spin = time.monotonic_ns() + self.SPIN_NS
        while time.monotonic_ns() < end_spin:
            try:
                return self._items.popleft()
            except IndexError:
                pass
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while True:
                try:
                    return self._items.popleft()
                except IndexError:
                    pass
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise QueueTimeoutException("Timeout waiting for dequeue")
                self._cond.wait(remaining if remaining is None else min(remaining, 0.001))

    def size(self) -> int:
        return len(self._items)


class TokenPool:
    """Fixed pool of integer tokens (reference queue.h:245)."""

    def __init__(self, n_tokens: int) -> None:
        self._queue: Queue[int] = Queue()
        self.size = n_tokens
        for i in range(n_tokens):
            self._queue.enqueue(i)

    def get_token(self, timeout: float | None = None) -> int:
        return self._queue.dequeue(timeout)

    def release_token(self, token: int) -> None:
        self._queue.enqueue(token)

    def free_tokens(self) -> int:
        return self._queue.size()
