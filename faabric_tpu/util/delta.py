"""Standalone snapshot delta encoding.

Reference analog: include/faabric/util/delta.h:10-52 and
src/util/delta.cpp (272 lines): page-granular compare, optional
XOR-with-old, optional compression, command-stream format. The reference
uses zstd; zlib is what this image bakes in, and the config string keeps
the same shape (``pages=4096;xor;zlib=1``).

Commands: TOTAL_SIZE, ZLIB_COMPRESSED_COMMANDS, DELTA_OVERWRITE,
DELTA_XOR, END — one byte each, lengths/offsets u64 little-endian.
"""

from __future__ import annotations

import dataclasses
import struct
import zlib

import numpy as np

CMD_TOTAL_SIZE = 1
CMD_ZLIB_COMMANDS = 2
CMD_DELTA_OVERWRITE = 3
CMD_DELTA_XOR = 4
CMD_END = 5


@dataclasses.dataclass
class DeltaSettings:
    """Parsed from e.g. "pages=4096;xor;zlib=1"."""

    page_size: int = 4096
    use_xor: bool = False
    zlib_level: int = 0

    @classmethod
    def parse(cls, spec: str) -> "DeltaSettings":
        out = cls()
        for part in filter(None, (p.strip() for p in spec.split(";"))):
            if part.startswith("pages="):
                out.page_size = int(part.split("=", 1)[1])
            elif part == "xor":
                out.use_xor = True
            elif part.startswith("zlib="):
                out.zlib_level = int(part.split("=", 1)[1])
        return out


def _dirty_runs(flags: np.ndarray) -> list[tuple[int, int]]:
    """Consecutive dirty pages coalesced into (first_page, n_pages)."""
    idx = np.where(flags)[0]
    if idx.size == 0:
        return []
    breaks = np.where(np.diff(idx) > 1)[0]
    starts = np.concatenate([[idx[0]], idx[breaks + 1]])
    ends = np.concatenate([idx[breaks], [idx[-1]]])
    return [(int(s), int(e - s + 1)) for s, e in zip(starts, ends)]


def sampled_overlap(old: "bytes | np.ndarray", new: "bytes | np.ndarray",
                    page_size: int = 4096, samples: int = 8) -> float:
    """Sampled XOR-density probe: the fraction of ``samples``
    evenly-spaced pages that are byte-identical between ``old`` and
    ``new``. O(samples · page_size) — the cheap pre-check the wire
    delta codec runs before committing to a full page scan, so a
    same-shape-but-unrelated payload costs a few memcmps instead of a
    doomed encode. Size-mismatched buffers report 0.0 (a resized
    payload is a different stream generation, not a mutated round)."""
    old_arr = (old.reshape(-1).view(np.uint8) if isinstance(old, np.ndarray)
               else np.frombuffer(old, dtype=np.uint8))
    new_arr = (new.reshape(-1).view(np.uint8) if isinstance(new, np.ndarray)
               else np.frombuffer(new, dtype=np.uint8))
    if old_arr.size != new_arr.size or new_arr.size == 0:
        return 0.0
    n_pages = (new_arr.size + page_size - 1) // page_size
    idx = np.unique(np.linspace(0, n_pages - 1,
                                min(samples, n_pages)).astype(np.int64))
    hits = 0
    for p in idx:
        lo = int(p) * page_size
        hi = min(lo + page_size, new_arr.size)
        if np.array_equal(old_arr[lo:hi], new_arr[lo:hi]):
            hits += 1
    return hits / idx.size


def sampled_overlap_parts(old: "bytes | np.ndarray", parts: list,
                          page_size: int = 4096,
                          samples: int = 8) -> float:
    """``sampled_overlap`` over a SEGMENTED candidate payload (ordered
    buffers whose concatenation is the logical frame) — no flatten
    copy. Sampled pages that straddle a segment boundary are skipped;
    a size mismatch reports 0.0."""
    old_arr = (old.reshape(-1).view(np.uint8) if isinstance(old, np.ndarray)
               else np.frombuffer(old, dtype=np.uint8))
    arrs = [(p.reshape(-1).view(np.uint8) if isinstance(p, np.ndarray)
             else np.frombuffer(p, dtype=np.uint8)) for p in parts]
    total = sum(a.size for a in arrs)
    if total != old_arr.size or total == 0:
        return 0.0
    bounds = []
    off = 0
    for a in arrs:
        bounds.append((off, off + a.size, a))
        off += a.size
    n_pages = (total + page_size - 1) // page_size
    idx = np.unique(np.linspace(0, n_pages - 1,
                                min(samples, n_pages)).astype(np.int64))
    hits = tried = 0
    for pg in idx:
        lo = int(pg) * page_size
        hi = min(lo + page_size, total)
        for s_lo, s_hi, a in bounds:
            if s_lo <= lo and hi <= s_hi:
                tried += 1
                if np.array_equal(a[lo - s_lo:hi - s_lo],
                                  old_arr[lo:hi]):
                    hits += 1
                break
    return hits / tried if tried else 0.0


def _append_delta_body(settings: DeltaSettings, old_arr: np.ndarray,
                       new_arr: np.ndarray, frame_off: int,
                       body: bytearray) -> None:
    """Append DELTA_XOR/OVERWRITE commands for ``new_arr`` vs
    ``old_arr``, with every command offset shifted by ``frame_off``
    (segmented encoding: the segment lives at that offset of the
    logical frame)."""
    ps = settings.page_size
    n = new_arr.size
    from faabric_tpu.util.dirty import page_flags

    for first_page, n_pages in _dirty_runs(page_flags(old_arr, new_arr,
                                                      ps)):
        off = first_page * ps
        end = min((first_page + n_pages) * ps, n)
        # XOR needs old coverage; split a run at the old-size boundary
        xor_end = min(end, old_arr.size) if settings.use_xor else off
        if settings.use_xor and xor_end > off:
            payload = np.bitwise_xor(new_arr[off:xor_end],
                                     old_arr[off:xor_end]).tobytes()
            body += struct.pack("<BQQ", CMD_DELTA_XOR, frame_off + off,
                                len(payload))
            body += payload
            off = xor_end
        if off < end:
            payload = new_arr[off:end].tobytes()
            body += struct.pack("<BQQ", CMD_DELTA_OVERWRITE,
                                frame_off + off, len(payload))
            body += payload


def _finish_delta(settings: DeltaSettings, total: int,
                  body: bytearray) -> bytes:
    body += struct.pack("<B", CMD_END)
    out = bytearray()
    out += struct.pack("<BQ", CMD_TOTAL_SIZE, total)
    use_zlib = settings.zlib_level > 0
    if use_zlib and len(body) > (1 << 16):
        # Compressibility probe: a large command body of structured
        # XOR noise (float mantissa churn) costs zlib ~2.5 ms/MiB to
        # shrink maybe 30% — a loss against any link the delta itself
        # already beat. Sample 4 KiB and compress only when the body
        # is GENUINELY sparse (<~58% of raw), i.e. when zlib pays for
        # itself even on a fast link. The stream stays self-describing
        # (no ZLIB_COMMANDS marker → raw body).
        probe = zlib.compress(bytes(body[:4096]), settings.zlib_level)
        if len(probe) > 2400:
            use_zlib = False
    if use_zlib:
        compressed = zlib.compress(bytes(body), settings.zlib_level)
        out += struct.pack("<BQ", CMD_ZLIB_COMMANDS, len(compressed))
        out += compressed
    else:
        out += body
    return bytes(out)


def _as_u8(buf) -> np.ndarray:
    return (buf.reshape(-1).view(np.uint8) if isinstance(buf, np.ndarray)
            else np.frombuffer(buf, dtype=np.uint8))


def serialize_delta(settings: DeltaSettings, old: "bytes | np.ndarray",
                    new: "bytes | np.ndarray") -> bytes:
    """Encode new relative to old (arrays skip the bytes-conversion
    copy). The dirty scan is one native/vectorized pass and consecutive
    dirty pages emit as single runs, so sparse deltas over big images
    cost ~a memcmp, not a Python loop."""
    old_arr, new_arr = _as_u8(old), _as_u8(new)
    body = bytearray()
    _append_delta_body(settings, old_arr, new_arr, 0, body)
    return _finish_delta(settings, new_arr.size, body)


def serialize_delta_parts(settings: DeltaSettings,
                          old: "bytes | np.ndarray",
                          parts: list) -> bytes:
    """Encode a SEGMENTED new payload (``parts``: ordered buffers whose
    concatenation is the logical frame) against a flat base WITHOUT
    materializing the concatenation — the wire delta codec's hot path,
    where a frame arrives as [small header | big body view] and the
    steady state must cost a memcmp, not a 100 MiB flatten copy. Each
    part compares against its base slice (page-granular within the
    part); command offsets are frame offsets, so ``apply_delta`` needs
    no segment awareness. Parts past the base's end emit as overwrites
    (frame growth)."""
    old_arr = _as_u8(old)
    body = bytearray()
    off = 0
    for part in parts:
        p = _as_u8(part)
        if p.size == 0:
            continue
        if off + p.size <= old_arr.size:
            _append_delta_body(settings, old_arr[off:off + p.size], p,
                               off, body)
        else:
            covered = max(0, old_arr.size - off)
            if covered:
                _append_delta_body(settings, old_arr[off:], p[:covered],
                                   off, body)
            payload = p[covered:].tobytes()
            body += struct.pack("<BQQ", CMD_DELTA_OVERWRITE,
                                off + covered, len(payload))
            body += payload
        off += p.size
    return _finish_delta(settings, off, body)


def delta_is_xor_only(delta: bytes) -> bool:
    """True iff every payload command in the stream is DELTA_XOR — the
    self-inverting form (``apply_delta`` of the same stream onto the
    NEW image yields the OLD one back), which the wire codec's
    NACK-heal reconstruction relies on. An OVERWRITE destroys the old
    bytes and is not invertible. Lives next to the encoder so a format
    change cannot drift past it unnoticed."""
    try:
        cmd, _total = struct.unpack_from("<BQ", delta, 0)
        if cmd != CMD_TOTAL_SIZE:
            return False
        pos = struct.calcsize("<BQ")
        if delta[pos] == CMD_ZLIB_COMMANDS:
            _, comp_len = struct.unpack_from("<BQ", delta, pos)
            off = pos + struct.calcsize("<BQ")
            body = zlib.decompress(delta[off:off + comp_len])
        else:
            body = delta[pos:]
        pos = 0
        while True:
            cmd = body[pos]
            if cmd == CMD_END:
                return True
            if cmd != CMD_DELTA_XOR:
                return False
            _, _off, length = struct.unpack_from("<BQQ", body, pos)
            pos += struct.calcsize("<BQQ") + length
    except (IndexError, struct.error, zlib.error):
        return False


def apply_delta(delta: bytes, old: "bytes | np.ndarray",
                out: "np.ndarray | None" = None) -> np.ndarray:
    """Reconstruct new from old + delta, returning a uint8 array.

    Cost model (reference src/util/delta.cpp applyDelta writes straight
    into the destination buffer; this matches it):
      - default: ONE pass to build the base image (empty + copy of old,
        zero-fill only for growth), then O(delta) patching — no trailing
        ``tobytes`` copy.
      - ``out=`` a preallocated uint8 array of the right size: the base
        copy lands there (steady-state memcpy, no allocation/page-fault
        cost on the hot freeze/thaw path).
      - ``out`` aliasing ``old`` (patch the resident image in place):
        the base copy is skipped entirely — apply is O(delta).
    """
    pos = 0
    cmd, total = struct.unpack_from("<BQ", delta, pos)
    if cmd != CMD_TOTAL_SIZE:
        raise ValueError("Delta stream must start with TOTAL_SIZE")
    pos += struct.calcsize("<BQ")

    cmd = delta[pos]
    if cmd == CMD_ZLIB_COMMANDS:
        (_, comp_len) = struct.unpack_from("<BQ", delta, pos)
        pos += struct.calcsize("<BQ")
        body = zlib.decompress(delta[pos:pos + comp_len])
    else:
        body = delta[pos:]

    old_arr = (old.reshape(-1).view(np.uint8) if isinstance(old, np.ndarray)
               else np.frombuffer(old, dtype=np.uint8))
    common = min(total, old_arr.size)
    if out is None:
        out = np.empty(total, dtype=np.uint8)
        out[:common] = old_arr[:common]
        if total > common:
            out[common:] = 0
    else:
        out = out.reshape(-1).view(np.uint8)
        if out.size != total:
            raise ValueError(
                f"out buffer is {out.size} bytes, delta target is {total}")
        if np.shares_memory(out, old_arr):
            # In-place patch: out already IS the old image (XOR payloads
            # are new^old at their offsets, so patching over old content
            # is exactly right; overwrites don't read it at all)
            if total > common:
                out[common:] = 0
        else:
            out[:common] = old_arr[:common]
            if total > common:
                out[common:] = 0

    pos = 0
    while True:
        cmd = body[pos]
        if cmd == CMD_END:
            break
        _, off, length = struct.unpack_from("<BQQ", body, pos)
        pos += struct.calcsize("<BQQ")
        payload = np.frombuffer(body[pos:pos + length], dtype=np.uint8)
        pos += length
        if cmd == CMD_DELTA_OVERWRITE:
            out[off:off + length] = payload
        elif cmd == CMD_DELTA_XOR:
            np.bitwise_xor(out[off:off + length], payload,
                           out=out[off:off + length])
        else:
            raise ValueError(f"Unknown delta command {cmd}")
    return out
