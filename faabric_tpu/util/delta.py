"""Standalone snapshot delta encoding.

Reference analog: include/faabric/util/delta.h:10-52 and
src/util/delta.cpp (272 lines): page-granular compare, optional
XOR-with-old, optional compression, command-stream format. The reference
uses zstd; zlib is what this image bakes in, and the config string keeps
the same shape (``pages=4096;xor;zlib=1``).

Commands: TOTAL_SIZE, ZLIB_COMPRESSED_COMMANDS, DELTA_OVERWRITE,
DELTA_XOR, END — one byte each, lengths/offsets u64 little-endian.
"""

from __future__ import annotations

import dataclasses
import struct
import zlib

import numpy as np

CMD_TOTAL_SIZE = 1
CMD_ZLIB_COMMANDS = 2
CMD_DELTA_OVERWRITE = 3
CMD_DELTA_XOR = 4
CMD_END = 5


@dataclasses.dataclass
class DeltaSettings:
    """Parsed from e.g. "pages=4096;xor;zlib=1"."""

    page_size: int = 4096
    use_xor: bool = False
    zlib_level: int = 0

    @classmethod
    def parse(cls, spec: str) -> "DeltaSettings":
        out = cls()
        for part in filter(None, (p.strip() for p in spec.split(";"))):
            if part.startswith("pages="):
                out.page_size = int(part.split("=", 1)[1])
            elif part == "xor":
                out.use_xor = True
            elif part.startswith("zlib="):
                out.zlib_level = int(part.split("=", 1)[1])
        return out


def _dirty_runs(flags: np.ndarray) -> list[tuple[int, int]]:
    """Consecutive dirty pages coalesced into (first_page, n_pages)."""
    idx = np.where(flags)[0]
    if idx.size == 0:
        return []
    breaks = np.where(np.diff(idx) > 1)[0]
    starts = np.concatenate([[idx[0]], idx[breaks + 1]])
    ends = np.concatenate([idx[breaks], [idx[-1]]])
    return [(int(s), int(e - s + 1)) for s, e in zip(starts, ends)]


def serialize_delta(settings: DeltaSettings, old: "bytes | np.ndarray",
                    new: "bytes | np.ndarray") -> bytes:
    """Encode new relative to old (arrays skip the bytes-conversion
    copy). The dirty scan is one native/vectorized pass and consecutive
    dirty pages emit as single runs, so sparse deltas over big images
    cost ~a memcmp, not a Python loop."""
    # Arrays pass through without the bytes-conversion copy
    old_arr = (old.reshape(-1).view(np.uint8) if isinstance(old, np.ndarray)
               else np.frombuffer(old, dtype=np.uint8))
    new_arr = (new.reshape(-1).view(np.uint8) if isinstance(new, np.ndarray)
               else np.frombuffer(new, dtype=np.uint8))
    ps = settings.page_size
    n = new_arr.size

    body = bytearray()
    from faabric_tpu.util.dirty import page_flags

    for first_page, n_pages in _dirty_runs(page_flags(old_arr, new_arr,
                                                      ps)):
        off = first_page * ps
        end = min((first_page + n_pages) * ps, n)
        # XOR needs old coverage; split a run at the old-size boundary
        xor_end = min(end, old_arr.size) if settings.use_xor else off
        if settings.use_xor and xor_end > off:
            payload = np.bitwise_xor(new_arr[off:xor_end],
                                     old_arr[off:xor_end]).tobytes()
            body += struct.pack("<BQQ", CMD_DELTA_XOR, off, len(payload))
            body += payload
            off = xor_end
        if off < end:
            payload = new_arr[off:end].tobytes()
            body += struct.pack("<BQQ", CMD_DELTA_OVERWRITE, off,
                                len(payload))
            body += payload
    body += struct.pack("<B", CMD_END)

    out = bytearray()
    out += struct.pack("<BQ", CMD_TOTAL_SIZE, n)
    if settings.zlib_level > 0:
        compressed = zlib.compress(bytes(body), settings.zlib_level)
        out += struct.pack("<BQ", CMD_ZLIB_COMMANDS, len(compressed))
        out += compressed
    else:
        out += body
    return bytes(out)


def apply_delta(delta: bytes, old: "bytes | np.ndarray",
                out: "np.ndarray | None" = None) -> np.ndarray:
    """Reconstruct new from old + delta, returning a uint8 array.

    Cost model (reference src/util/delta.cpp applyDelta writes straight
    into the destination buffer; this matches it):
      - default: ONE pass to build the base image (empty + copy of old,
        zero-fill only for growth), then O(delta) patching — no trailing
        ``tobytes`` copy.
      - ``out=`` a preallocated uint8 array of the right size: the base
        copy lands there (steady-state memcpy, no allocation/page-fault
        cost on the hot freeze/thaw path).
      - ``out`` aliasing ``old`` (patch the resident image in place):
        the base copy is skipped entirely — apply is O(delta).
    """
    pos = 0
    cmd, total = struct.unpack_from("<BQ", delta, pos)
    if cmd != CMD_TOTAL_SIZE:
        raise ValueError("Delta stream must start with TOTAL_SIZE")
    pos += struct.calcsize("<BQ")

    cmd = delta[pos]
    if cmd == CMD_ZLIB_COMMANDS:
        (_, comp_len) = struct.unpack_from("<BQ", delta, pos)
        pos += struct.calcsize("<BQ")
        body = zlib.decompress(delta[pos:pos + comp_len])
    else:
        body = delta[pos:]

    old_arr = (old.reshape(-1).view(np.uint8) if isinstance(old, np.ndarray)
               else np.frombuffer(old, dtype=np.uint8))
    common = min(total, old_arr.size)
    if out is None:
        out = np.empty(total, dtype=np.uint8)
        out[:common] = old_arr[:common]
        if total > common:
            out[common:] = 0
    else:
        out = out.reshape(-1).view(np.uint8)
        if out.size != total:
            raise ValueError(
                f"out buffer is {out.size} bytes, delta target is {total}")
        if np.shares_memory(out, old_arr):
            # In-place patch: out already IS the old image (XOR payloads
            # are new^old at their offsets, so patching over old content
            # is exactly right; overwrites don't read it at all)
            if total > common:
                out[common:] = 0
        else:
            out[:common] = old_arr[:common]
            if total > common:
                out[common:] = 0

    pos = 0
    while True:
        cmd = body[pos]
        if cmd == CMD_END:
            break
        _, off, length = struct.unpack_from("<BQQ", body, pos)
        pos += struct.calcsize("<BQQ")
        payload = np.frombuffer(body[pos:pos + length], dtype=np.uint8)
        pos += length
        if cmd == CMD_DELTA_OVERWRITE:
            out[off:off + length] = payload
        elif cmd == CMD_DELTA_XOR:
            np.bitwise_xor(out[off:off + length], payload,
                           out=out[off:off + length])
        else:
            raise ValueError(f"Unknown delta command {cmd}")
    return out
