"""Standalone snapshot delta encoding.

Reference analog: include/faabric/util/delta.h:10-52 and
src/util/delta.cpp (272 lines): page-granular compare, optional
XOR-with-old, optional compression, command-stream format. The reference
uses zstd; zlib is what this image bakes in, and the config string keeps
the same shape (``pages=4096;xor;zlib=1``).

Commands: TOTAL_SIZE, ZLIB_COMPRESSED_COMMANDS, DELTA_OVERWRITE,
DELTA_XOR, END — one byte each, lengths/offsets u64 little-endian.
"""

from __future__ import annotations

import dataclasses
import struct
import zlib

import numpy as np

CMD_TOTAL_SIZE = 1
CMD_ZLIB_COMMANDS = 2
CMD_DELTA_OVERWRITE = 3
CMD_DELTA_XOR = 4
CMD_END = 5


@dataclasses.dataclass
class DeltaSettings:
    """Parsed from e.g. "pages=4096;xor;zlib=1"."""

    page_size: int = 4096
    use_xor: bool = False
    zlib_level: int = 0

    @classmethod
    def parse(cls, spec: str) -> "DeltaSettings":
        out = cls()
        for part in filter(None, (p.strip() for p in spec.split(";"))):
            if part.startswith("pages="):
                out.page_size = int(part.split("=", 1)[1])
            elif part == "xor":
                out.use_xor = True
            elif part.startswith("zlib="):
                out.zlib_level = int(part.split("=", 1)[1])
        return out


def serialize_delta(settings: DeltaSettings, old: bytes, new: bytes) -> bytes:
    """Encode new relative to old."""
    old_arr = np.frombuffer(old, dtype=np.uint8)
    new_arr = np.frombuffer(new, dtype=np.uint8)
    ps = settings.page_size

    body = bytearray()
    n = len(new)
    for off in range(0, n, ps):
        end = min(off + ps, n)
        new_page = new_arr[off:end]
        old_page = old_arr[off:min(end, old_arr.size)]
        if old_page.size == new_page.size and np.array_equal(old_page, new_page):
            continue
        if settings.use_xor and old_page.size == new_page.size:
            payload = np.bitwise_xor(new_page, old_page).tobytes()
            cmd = CMD_DELTA_XOR
        else:
            payload = new_page.tobytes()
            cmd = CMD_DELTA_OVERWRITE
        body += struct.pack("<BQQ", cmd, off, len(payload))
        body += payload
    body += struct.pack("<B", CMD_END)

    out = bytearray()
    out += struct.pack("<BQ", CMD_TOTAL_SIZE, n)
    if settings.zlib_level > 0:
        compressed = zlib.compress(bytes(body), settings.zlib_level)
        out += struct.pack("<BQ", CMD_ZLIB_COMMANDS, len(compressed))
        out += compressed
    else:
        out += body
    return bytes(out)


def apply_delta(delta: bytes, old: bytes) -> bytes:
    """Reconstruct new from old + delta."""
    pos = 0
    cmd, total = struct.unpack_from("<BQ", delta, pos)
    if cmd != CMD_TOTAL_SIZE:
        raise ValueError("Delta stream must start with TOTAL_SIZE")
    pos += struct.calcsize("<BQ")

    cmd = delta[pos]
    if cmd == CMD_ZLIB_COMMANDS:
        (_, comp_len) = struct.unpack_from("<BQ", delta, pos)
        pos += struct.calcsize("<BQ")
        body = zlib.decompress(delta[pos:pos + comp_len])
    else:
        body = delta[pos:]

    out = np.zeros(total, dtype=np.uint8)
    old_arr = np.frombuffer(old, dtype=np.uint8)
    out[:min(total, old_arr.size)] = old_arr[:min(total, old_arr.size)]

    pos = 0
    while True:
        cmd = body[pos]
        if cmd == CMD_END:
            break
        _, off, length = struct.unpack_from("<BQQ", body, pos)
        pos += struct.calcsize("<BQQ")
        payload = np.frombuffer(body[pos:pos + length], dtype=np.uint8)
        pos += length
        if cmd == CMD_DELTA_OVERWRITE:
            out[off:off + length] = payload
        elif cmd == CMD_DELTA_XOR:
            out[off:off + length] = np.bitwise_xor(out[off:off + length],
                                                   payload)
        else:
            raise ValueError(f"Unknown delta command {cmd}")
    return out.tobytes()
