from faabric_tpu.util.config import get_system_config, SystemConfig
from faabric_tpu.util.gids import generate_gid
from faabric_tpu.util.testing import (
    set_mock_mode,
    is_mock_mode,
    set_test_mode,
    is_test_mode,
)

__all__ = [
    "get_system_config",
    "SystemConfig",
    "generate_gid",
    "set_mock_mode",
    "is_mock_mode",
    "set_test_mode",
    "is_test_mode",
]
