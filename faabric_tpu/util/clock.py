"""Wall-clock and timing helpers (reference: include/faabric/util/timing.h).

The PROF_START/PROF_END macros' ``prof`` context manager now delegates
into the telemetry span tracer (faabric_tpu/telemetry/tracer.py): every
``prof`` label becomes a ``prof/<label>`` span, so legacy call sites
show up in the Chrome trace and the Prometheus-era summaries without
change. ``prof_summary`` (the TRACE_ALL analog) returns the tracer's
text summary — enabled via FAABRIC_TRACING=1 or the legacy
FAABRIC_SELF_TRACING=1.
"""

from __future__ import annotations

import time


def get_global_clock_epoch() -> float:
    return time.time()


def epoch_millis() -> int:
    return int(time.time() * 1000)


def now() -> float:
    return time.monotonic()


def prof(label: str):
    """Timing bracket; a no-op singleton while tracing is disabled."""
    from faabric_tpu.telemetry import tracer

    return tracer.span("prof", label)


def prof_summary() -> str:
    from faabric_tpu.telemetry import tracer

    return tracer.text_summary()


def prof_reset() -> None:
    from faabric_tpu.telemetry import tracer

    tracer.reset_tracing()


def is_tracing_enabled() -> bool:
    from faabric_tpu.telemetry import tracer

    return tracer.tracing_enabled()
