"""Wall-clock and timing helpers (reference: include/faabric/util/timing.h).

PROF_START/PROF_END macros become the ``prof`` context manager; totals are
accumulated per label and dumped with ``prof_summary`` (TRACE_ALL analog,
enabled via env FAABRIC_SELF_TRACING=1).
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from collections import defaultdict

_ENABLED = os.environ.get("FAABRIC_SELF_TRACING", "0") == "1"
_totals: dict[str, float] = defaultdict(float)
_counts: dict[str, int] = defaultdict(int)
_lock = threading.Lock()


def get_global_clock_epoch() -> float:
    return time.time()


def epoch_millis() -> int:
    return int(time.time() * 1000)


def now() -> float:
    return time.monotonic()


@contextlib.contextmanager
def prof(label: str):
    if not _ENABLED:
        yield
        return
    start = time.monotonic()
    try:
        yield
    finally:
        elapsed = time.monotonic() - start
        with _lock:
            _totals[label] += elapsed
            _counts[label] += 1


def prof_summary() -> str:
    with _lock:
        lines = ["--- PROF summary ---"]
        for label in sorted(_totals):
            lines.append(
                f"{label:<40} total={_totals[label]*1000:.2f}ms n={_counts[label]}"
            )
        return "\n".join(lines)


def prof_reset() -> None:
    with _lock:
        _totals.clear()
        _counts.clear()


def is_tracing_enabled() -> bool:
    return _ENABLED
