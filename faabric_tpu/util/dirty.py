"""Dirty-page tracking over executor memory.

Reference analog: include/faabric/util/dirty.h:24-52 and
src/util/dirty.cpp (917 lines) — there mprotect/SIGSEGV, soft-dirty PTEs
or userfaultfd over mmap'd guest memory. Executor memory here is host
numpy buffers (HBM state is snapshotted via device→host transfer), so
tracking is comparison-based:

- ``compare``: keep a baseline copy, vectorised page compare (numpy).
- ``native``: same baseline, memcmp per page in C++ (util/native.py).
- ``hash``: per-page 64-bit universal-hash baseline — 8 bytes per 4 KiB
  page (~1/512 the memory of a full copy), vectorised blockwise.
- ``none``: every page reported dirty (the reference's fallback).

Same interface as the reference: global + thread-local start/stop, page
flags out. Thread-local tracking lets each executor thread report only
ITS writes (reference threadLocalDirtyRegions).
"""

from __future__ import annotations

import threading

from typing import Optional

import numpy as np

from faabric_tpu.util.config import get_system_config
from faabric_tpu.util.logging import get_logger

logger = get_logger(__name__)

PAGE_SIZE = 4096


def n_pages(size: int) -> int:
    return (size + PAGE_SIZE - 1) // PAGE_SIZE


def _as_array(mem) -> np.ndarray:
    return np.frombuffer(mem, dtype=np.uint8)


def page_flags(old: np.ndarray, new: np.ndarray,
               page_size: int = PAGE_SIZE) -> np.ndarray:
    """Dirty flags per page of ``new`` vs ``old``: one native memcmp pass
    when the C++ helper is available (numpy reshape-compare otherwise),
    partial trailing page included, pages past ``old`` (growth) dirty by
    definition. Shared by the dirty trackers and the delta codec."""
    from faabric_tpu.util.native import get_pagediff_lib

    n = new.size
    n_pages_total = (n + page_size - 1) // page_size
    flags = np.zeros(n_pages_total, dtype=bool)
    common = min(old.size, n)
    common_pages = (common + page_size - 1) // page_size

    lib = get_pagediff_lib()
    if common and lib is not None:
        raw = np.zeros(common_pages, dtype=np.uint8)
        old_c = np.ascontiguousarray(old[:common])
        new_c = np.ascontiguousarray(new[:common])
        lib.diff_pages(old_c.ctypes.data, new_c.ctypes.data, common,
                       page_size, raw.ctypes.data)
        flags[:common_pages] = raw.astype(bool)
    elif common:
        whole = common // page_size
        if whole:
            flags[:whole] = (
                new[:whole * page_size].reshape(-1, page_size)
                != old[:whole * page_size].reshape(-1, page_size)
            ).any(axis=1)
        if whole * page_size < common:
            flags[whole] = not np.array_equal(
                new[whole * page_size:common], old[whole * page_size:common])
    if n > old.size:
        flags[old.size // page_size:] = True
    return flags


def hint_page_indices(region_hints, total_pages: int) -> np.ndarray:
    """Page indices covered by (offset, length) byte extents, clipped to
    the image."""
    mask = np.zeros(total_pages, dtype=bool)
    for off, length in region_hints:
        if length <= 0:
            continue
        first = off // PAGE_SIZE
        last = (off + length - 1) // PAGE_SIZE
        mask[max(0, first):min(total_pages, last + 1)] = True
    return np.where(mask)[0]


class DirtyTracker:
    """``region_hints`` (list of (offset, length) byte extents) is an
    opt-in contract that the tracked task only writes inside those
    extents — trackers then baseline/compare just the hinted pages, so
    bracketing cost scales with the declared write set instead of the
    whole image (the comparison-tracking answer to the reference's
    fault-driven precision, dirty.cpp:306-412). Writes outside the hints
    are NOT detected in hint mode."""

    mode = "base"

    def start_tracking(self, mem, region_hints=None) -> None:
        raise NotImplementedError

    def stop_tracking(self, mem) -> None:
        pass

    def get_dirty_pages(self, mem) -> np.ndarray:
        """Bool flags per page since start_tracking."""
        raise NotImplementedError

    def start_thread_local_tracking(self, mem, region_hints=None) -> None:
        pass

    def stop_thread_local_tracking(self, mem) -> None:
        pass

    def get_thread_local_dirty_pages(self, mem) -> np.ndarray:
        return self.get_dirty_pages(mem)


def _paged_view(arr: np.ndarray, idx: np.ndarray) -> np.ndarray:
    """(len(idx), PAGE_SIZE) copy of the selected pages, zero-padding the
    image's trailing partial page."""
    pages = n_pages(arr.size)
    out = np.zeros((idx.size, PAGE_SIZE), dtype=np.uint8)
    whole = idx[idx < arr.size // PAGE_SIZE]
    if whole.size:
        grid = arr[:(arr.size // PAGE_SIZE) * PAGE_SIZE].reshape(
            -1, PAGE_SIZE)
        out[:whole.size] = grid[whole]
    if idx.size > whole.size:  # trailing partial page selected
        lo = (pages - 1) * PAGE_SIZE
        out[-1, :arr.size - lo] = arr[lo:]
    return out


class CompareTracker(DirtyTracker):
    """Baseline copy + vectorised compare; with region hints only the
    hinted pages are copied and compared."""

    mode = "compare"

    def __init__(self) -> None:
        self._baseline: Optional[np.ndarray] = None
        self._hint_idx: Optional[np.ndarray] = None
        self._tls = threading.local()

    def _snapshot(self, mem, region_hints):
        arr = _as_array(mem)
        if region_hints is None:
            return arr.copy(), None
        idx = hint_page_indices(region_hints, n_pages(arr.size))
        return _paged_view(arr, idx), idx

    def start_tracking(self, mem, region_hints=None) -> None:
        self._baseline, self._hint_idx = self._snapshot(mem, region_hints)

    def _diff(self, baseline: np.ndarray, mem,
              hint_idx: Optional[np.ndarray] = None) -> np.ndarray:
        cur = _as_array(mem)
        size = cur.size
        flags = np.zeros(n_pages(size), dtype=bool)
        if hint_idx is not None:
            live = hint_idx[hint_idx < flags.size]
            rows = _paged_view(cur, live)
            flags[live] = (rows != baseline[:live.size]).any(axis=1)
            return flags
        # Memory may have grown since the baseline was taken: pages beyond
        # the baseline are dirty by definition
        cmp_size = min(size, baseline.size)
        cmp_pages = cmp_size // PAGE_SIZE
        if cmp_pages:
            flags[:cmp_pages] = (
                cur[:cmp_pages * PAGE_SIZE].reshape(-1, PAGE_SIZE)
                != baseline[:cmp_pages * PAGE_SIZE].reshape(-1, PAGE_SIZE)
            ).any(axis=1)
        # Trailing partial page plus anything beyond the baseline
        if cmp_pages * PAGE_SIZE < cmp_size:
            flags[cmp_pages] = not np.array_equal(
                cur[cmp_pages * PAGE_SIZE:cmp_size],
                baseline[cmp_pages * PAGE_SIZE:cmp_size])
        if size > baseline.size:
            flags[baseline.size // PAGE_SIZE:] = True
        return flags

    def get_dirty_pages(self, mem) -> np.ndarray:
        if self._baseline is None:
            return np.zeros(0, dtype=bool)
        return self._diff(self._baseline, mem, self._hint_idx)

    def start_thread_local_tracking(self, mem, region_hints=None) -> None:
        self._tls.baseline, self._tls.hint_idx = self._snapshot(
            mem, region_hints)

    def get_thread_local_dirty_pages(self, mem) -> np.ndarray:
        baseline = getattr(self._tls, "baseline", None)
        if baseline is None:
            return np.zeros(0, dtype=bool)
        return self._diff(baseline, mem,
                          getattr(self._tls, "hint_idx", None))


class NativeCompareTracker(CompareTracker):
    """Baseline copy + C++ memcmp per page; falls back to numpy."""

    mode = "native"

    def _diff(self, baseline: np.ndarray, mem,
              hint_idx: Optional[np.ndarray] = None) -> np.ndarray:
        if hint_idx is not None:
            # Hinted diffs are already O(hinted pages) in numpy
            return super()._diff(baseline, mem, hint_idx)
        return page_flags(baseline, _as_array(mem))


# Random per-word-position multipliers for the vectorised page hash: a
# page's hash is the dot product of its 512 uint64 WORDS with this vector
# mod 2^64 (multiply-shift universal family). Hashing words instead of
# bytes reads the page as-is — no 8× astype widening — which makes the
# bracket ~8× cheaper (measured 2.4 s → ~0.3 s per 128 MiB image).
_HASH_RNG = np.random.RandomState(0x5EED)
_WORDS_PER_PAGE = PAGE_SIZE // 8
_HASH_MULT = _HASH_RNG.randint(1, 2**63 - 1, _WORDS_PER_PAGE,
                               dtype=np.uint64) | np.uint64(1)
_HASH_BLOCK_PAGES = 8192  # bound the intermediate product buffer


class HashTracker(DirtyTracker):
    """Per-page 64-bit baseline hash — 8 bytes per 4 KiB page instead of
    a full copy. Hashing is a vectorised blockwise dot product (no per-page Python
    loop): this brackets every executor task, so it must not dwarf the
    guest work."""

    mode = "hash"

    def __init__(self) -> None:
        self._hashes: Optional[np.ndarray] = None
        self._hint_idx: Optional[np.ndarray] = None
        self._tls = threading.local()

    @staticmethod
    def _page_hashes(mem, hint_idx: Optional[np.ndarray] = None
                     ) -> np.ndarray:
        arr = _as_array(mem)
        if hint_idx is not None:
            grid = _paged_view(arr, hint_idx).view(np.uint64)
            with np.errstate(over="ignore"):
                return (grid * _HASH_MULT).sum(axis=1)
        pages = n_pages(arr.size)
        pad = pages * PAGE_SIZE - arr.size
        if pad:
            arr = np.concatenate([arr, np.zeros(pad, np.uint8)])
        if not arr.flags.c_contiguous:
            arr = np.ascontiguousarray(arr)
        grid = arr.view(np.uint64).reshape(pages, _WORDS_PER_PAGE)
        out = np.empty(pages, dtype=np.uint64)
        with np.errstate(over="ignore"):
            for lo in range(0, pages, _HASH_BLOCK_PAGES):
                hi = min(pages, lo + _HASH_BLOCK_PAGES)
                out[lo:hi] = (grid[lo:hi] * _HASH_MULT).sum(axis=1)
        return out

    @staticmethod
    def _compare(old: Optional[np.ndarray], mem,
                 hint_idx: Optional[np.ndarray] = None) -> np.ndarray:
        if old is None:
            return np.zeros(0, dtype=bool)
        if hint_idx is not None:
            flags = np.zeros(n_pages(_as_array(mem).size), dtype=bool)
            live = hint_idx[hint_idx < flags.size]
            cur = HashTracker._page_hashes(mem, live)
            flags[live] = cur != old[:live.size]
            return flags
        cur = HashTracker._page_hashes(mem)
        flags = np.ones(cur.size, dtype=bool)  # pages beyond baseline dirty
        m = min(cur.size, old.size)
        flags[:m] = cur[:m] != old[:m]
        return flags

    def start_tracking(self, mem, region_hints=None) -> None:
        self._hint_idx = (None if region_hints is None else
                          hint_page_indices(region_hints,
                                            n_pages(_as_array(mem).size)))
        self._hashes = self._page_hashes(mem, self._hint_idx)

    def get_dirty_pages(self, mem) -> np.ndarray:
        return self._compare(self._hashes, mem, self._hint_idx)

    def start_thread_local_tracking(self, mem, region_hints=None) -> None:
        self._tls.hint_idx = (None if region_hints is None else
                              hint_page_indices(
                                  region_hints,
                                  n_pages(_as_array(mem).size)))
        self._tls.hashes = self._page_hashes(mem, self._tls.hint_idx)

    def get_thread_local_dirty_pages(self, mem) -> np.ndarray:
        return self._compare(getattr(self._tls, "hashes", None), mem,
                             getattr(self._tls, "hint_idx", None))


class NoneTracker(DirtyTracker):
    """Everything dirty (reference dirty.h:194-225)."""

    mode = "none"

    def __init__(self) -> None:
        self._size = 0

    def start_tracking(self, mem, region_hints=None) -> None:
        self._size = _as_array(mem).size

    def get_dirty_pages(self, mem) -> np.ndarray:
        return np.ones(n_pages(_as_array(mem).size), dtype=bool)


def _os_to_image_flags(os_flags: np.ndarray, page_off: int,
                       n_img: int) -> np.ndarray:
    """Map per-OS-page dirty flags to IMAGE pages when the buffer start
    is not page-aligned (malloc'd numpy buffers rarely are): image page
    j overlaps OS pages j and j+1, so it is dirty if either is."""
    if page_off == 0:
        return os_flags[:n_img].astype(bool)
    padded = np.zeros(n_img + 1, dtype=bool)
    padded[:min(os_flags.size, n_img + 1)] = \
        os_flags[:n_img + 1].astype(bool)
    return padded[:n_img] | padded[1:n_img + 1]


class SegvTracker(DirtyTracker):
    """Write-protection fault tracking — the reference's headline
    precision mode (src/util/dirty.cpp segfault tracker): the image is
    mprotect'd read-only at start; the first write to each page faults
    into a C++ SIGSEGV handler (native/segv_tracker.cpp) that records
    the page and restores write access. O(dirty) — no baseline copy, no
    per-bracket memory scan; a 128 MiB image with 3 dirty pages costs 3
    faults, not a 128 MiB compare.

    Kernel-interface caveat (same as the reference's): writes into the
    protected range from the KERNEL side (recv_into, read() into the
    buffer) return EFAULT instead of faulting — guest code writing
    through userspace (numpy ops, memoryviews) is the supported shape.

    Thread-local tracking reports every page dirtied since tracking
    began (page-fault attribution is per-process, not per-thread); the
    THREADS merge path ORs per-thread sets, so over-reporting is
    content-correct and merge-safe.

    ``region_hints`` narrow the protected range to the hinted pages —
    fewer protected pages, but writes outside the hints are undetected
    (the same contract as the comparison trackers)."""

    mode = "segv"

    def _get_lib(self):
        from faabric_tpu.util.native import get_segv_lib

        return get_segv_lib()

    def __init__(self) -> None:
        lib = self._get_lib()
        if lib is None:
            raise RuntimeError(f"{self.mode} dirty tracking unavailable "
                               "(kernel or native build)")
        self._start_fn = getattr(lib, f"{self.mode}_start")
        self._stop_fn = getattr(lib, f"{self.mode}_stop")
        self._region_ids: list[int] = []
        self._os_flags: Optional[np.ndarray] = None
        self._addr = 0
        self._size = 0
        self._page_off = 0

    def start_tracking(self, mem, region_hints=None) -> None:
        arr = _as_array(mem)
        self._addr = arr.ctypes.data
        self._size = arr.size
        start_al = self._addr & ~(PAGE_SIZE - 1)
        self._page_off = self._addr - start_al
        end_al = -(-(self._addr + self._size) // PAGE_SIZE) * PAGE_SIZE
        n_os = (end_al - start_al) // PAGE_SIZE
        self._os_flags = np.zeros(n_os, dtype=np.uint8)
        self._region_ids = []
        if region_hints is not None:
            # Protect only runs of OS pages covering the hinted extents
            img_idx = hint_page_indices(region_hints, n_pages(self._size))
            os_mask = np.zeros(n_os, dtype=bool)
            for j in img_idx:
                os_mask[j] = True
                if self._page_off and j + 1 < n_os:
                    os_mask[j + 1] = True
            runs = _mask_runs(os_mask)
        else:
            runs = [(0, n_os)]
        for lo, count in runs:
            rid = self._start_fn(
                start_al + lo * PAGE_SIZE, count,
                self._os_flags.ctypes.data + lo)
            if rid < 0:
                for r in self._region_ids:
                    self._stop_fn(r)
                self._region_ids = []
                raise RuntimeError(f"{self.mode} start failed ({rid}) — "
                                   "unprotectable mapping?")
            self._region_ids.append(rid)

    def stop_tracking(self, mem) -> None:
        for rid in self._region_ids:
            self._stop_fn(rid)
        self._region_ids = []

    def get_dirty_pages(self, mem) -> np.ndarray:
        arr = _as_array(mem)
        if self._os_flags is None:
            return np.zeros(0, dtype=bool)
        if arr.ctypes.data != self._addr:
            # Buffer reallocated (growth copies into a new allocation):
            # every page of the new buffer is dirty by definition
            return np.ones(n_pages(arr.size), dtype=bool)
        n_img = n_pages(self._size)
        flags = _os_to_image_flags(self._os_flags, self._page_off, n_img)
        if arr.size > self._size:  # in-place growth: new pages dirty
            out = np.ones(n_pages(arr.size), dtype=bool)
            out[:n_img] = flags
            return out
        return flags

    # Per-thread attribution is impossible with process-wide faults;
    # report the full dirty set (merge-safe, see class docstring)
    def start_thread_local_tracking(self, mem, region_hints=None) -> None:
        pass

    def stop_thread_local_tracking(self, mem) -> None:
        pass

    def get_thread_local_dirty_pages(self, mem) -> np.ndarray:
        return self.get_dirty_pages(mem)

    def __del__(self):  # noqa: D105 — protections must not outlive us
        try:
            self.stop_tracking(None)
        except Exception:  # noqa: BLE001
            pass


class UffdTracker(SegvTracker):
    """userfaultfd write-protect tracking — the reference's
    uffd-thread-wp mode (src/util/dirty.cpp uffd impls,
    include/faabric/util/dirty.h:124-192): same O(dirty) fault-per-page
    cost model as SIGSEGV tracking, but faults are ordinary events
    consumed by ONE native thread (native/uffd_tracker.cpp) instead of a
    process-wide signal handler — no async-signal-safety constraints and
    no interaction with other SIGSEGV users (libtpu, faulthandler).
    Kernel-side writes into the range (read(2)/recv into the buffer)
    fault-and-resolve normally instead of failing EFAULT, which the
    segv mode cannot offer. Needs kernel >= 5.7 uffd-wp; unavailable
    kernels fall down the ladder (uffd -> segv -> native)."""

    mode = "uffd"

    def _get_lib(self):
        from faabric_tpu.util.native import get_uffd_lib

        return get_uffd_lib()


def _mask_runs(mask: np.ndarray) -> list:
    """Consecutive True runs of a bool mask as (start, count) pairs."""
    idx = np.where(mask)[0]
    if idx.size == 0:
        return []
    splits = np.where(np.diff(idx) > 1)[0] + 1
    return [(int(g[0]), int(g.size))
            for g in np.split(idx, splits)]


# ---------------------------------------------------------------------------
# Soft-dirty PTEs (reference src/util/dirty.cpp softpte tracker)
# ---------------------------------------------------------------------------

_SOFTPTE_LOCK = threading.Lock()
_SOFTPTE_SESSIONS: list = []  # live _SoftPTESession objects
_softpte_probe: Optional[bool] = None


def _pagemap_softdirty(addr: int, size: int) -> np.ndarray:
    """Soft-dirty bit (pagemap bit 55) per OS page over [addr, addr+size)."""
    first = addr >> 12
    n = ((addr + size - 1) >> 12) - first + 1
    with open("/proc/self/pagemap", "rb") as f:
        f.seek(first * 8)
        data = f.read(n * 8)
    words = np.frombuffer(data, dtype=np.uint64)
    return ((words >> np.uint64(55)) & np.uint64(1)).astype(bool)


def softpte_available() -> bool:
    """One-time probe: CONFIG_MEM_SOFT_DIRTY kernels set pagemap bit 55
    on the first write after a clear_refs(4). Containers and custom
    kernels often ship without it — then the probe write succeeds but
    the bit never appears, and softpte mode must fall back."""
    global _softpte_probe
    with _SOFTPTE_LOCK:
        if _softpte_probe is not None:
            return _softpte_probe
        try:
            probe = np.ones(PAGE_SIZE * 4, np.uint8)  # faulted-in pages
            with open("/proc/self/clear_refs", "w") as f:
                f.write("4")
            probe[PAGE_SIZE * 2] = 7
            bits = _pagemap_softdirty(probe.ctypes.data, probe.size)
            _softpte_probe = bool(bits.any())
        except OSError:
            _softpte_probe = False
        if not _softpte_probe:
            # Debug, not info: make_dirty_tracker already warns once per
            # (mode, fallback) when the ladder actually falls back —
            # surfacing the probe result here too printed the same
            # fallback twice back-to-back in every bench/worker log
            logger.debug("Soft-dirty PTEs not functional on this kernel; "
                         "DIRTY_TRACKING_MODE=softpte falls back to segv/"
                         "native")
        return _softpte_probe


class _SoftPTESession:
    """One tracked image. clear_refs resets soft-dirty bits for the
    WHOLE process, so starting any session first folds the current bits
    of every other live session into its accumulator — sessions never
    lose writes to each other's clears."""

    def __init__(self, addr: int, size: int) -> None:
        self.addr = addr
        self.size = size
        n_os = ((addr + size - 1) >> 12) - (addr >> 12) + 1
        self.accum = np.zeros(n_os, dtype=bool)

    def fold_current(self) -> None:
        self.accum |= _pagemap_softdirty(self.addr, self.size)

    def dirty_os_pages(self) -> np.ndarray:
        return self.accum | _pagemap_softdirty(self.addr, self.size)


class SoftPTETracker(DirtyTracker):
    """Kernel soft-dirty PTE tracking (reference dirty.cpp softpte
    tracker): clear_refs(4) write-protects every PTE; the kernel sets
    pagemap bit 55 on the first write to each page. O(dirty) faults at
    write time + an 8-bytes-per-page pagemap read at query time — no
    baseline copy, no image scan. Requires CONFIG_MEM_SOFT_DIRTY
    (``softpte_available()``); ``make_dirty_tracker`` falls back to the
    segv tracker (or native compare) where the kernel lacks it.

    Like the segv tracker, fault attribution is process-wide, so
    thread-local queries report the full dirty set (merge-safe)."""

    mode = "softpte"

    def __init__(self) -> None:
        if not softpte_available():
            raise RuntimeError("soft-dirty PTEs not available")
        self._sess: Optional[_SoftPTESession] = None
        self._page_off = 0

    def start_tracking(self, mem, region_hints=None) -> None:
        arr = _as_array(mem)
        sess = _SoftPTESession(arr.ctypes.data, arr.size)
        self._page_off = arr.ctypes.data & (PAGE_SIZE - 1)
        with _SOFTPTE_LOCK:
            # Everyone else banks their bits before we clear them
            for other in _SOFTPTE_SESSIONS:
                other.fold_current()
            with open("/proc/self/clear_refs", "w") as f:
                f.write("4")
            if self._sess in _SOFTPTE_SESSIONS:
                _SOFTPTE_SESSIONS.remove(self._sess)
            _SOFTPTE_SESSIONS.append(sess)
        self._sess = sess

    def stop_tracking(self, mem) -> None:
        with _SOFTPTE_LOCK:
            if self._sess in _SOFTPTE_SESSIONS:
                _SOFTPTE_SESSIONS.remove(self._sess)
        self._sess = None

    def get_dirty_pages(self, mem) -> np.ndarray:
        arr = _as_array(mem)
        if self._sess is None:
            return np.zeros(0, dtype=bool)
        if arr.ctypes.data != self._sess.addr:
            return np.ones(n_pages(arr.size), dtype=bool)
        with _SOFTPTE_LOCK:
            os_flags = self._sess.dirty_os_pages()
        n_img = n_pages(self._sess.size)
        flags = _os_to_image_flags(os_flags, self._page_off, n_img)
        if arr.size > self._sess.size:
            out = np.ones(n_pages(arr.size), dtype=bool)
            out[:n_img] = flags
            return out
        return flags

    def start_thread_local_tracking(self, mem, region_hints=None) -> None:
        pass

    def stop_thread_local_tracking(self, mem) -> None:
        pass

    def get_thread_local_dirty_pages(self, mem) -> np.ndarray:
        return self.get_dirty_pages(mem)

    def __del__(self):  # noqa: D105
        try:
            self.stop_tracking(None)
        except Exception:  # noqa: BLE001
            pass


_TRACKERS = {
    "compare": CompareTracker,
    "native": NativeCompareTracker,
    "hash": HashTracker,
    "none": NoneTracker,
    "segv": SegvTracker,
    "softpte": SoftPTETracker,
    "uffd": UffdTracker,
}

_FALLBACK_WARNED: set = set()


def make_dirty_tracker(mode: str | None = None) -> DirtyTracker:
    mode = mode or get_system_config().dirty_tracking_mode
    cls = _TRACKERS.get(mode)
    if cls is None:
        raise ValueError(f"Unknown dirty tracking mode: {mode}")
    # Kernel-assisted modes degrade gracefully: softpte → segv → native.
    # This ladder is an intentional robustness addition — the reference
    # (dirty.cpp getDirtyTracker) throws on an unavailable mode instead.
    # dict.fromkeys dedupes so mode='segv' doesn't construct SegvTracker
    # twice before falling back.
    for fallback in dict.fromkeys((cls, SegvTracker, NativeCompareTracker)):
        try:
            return fallback()
        except RuntimeError as e:
            key = (mode, fallback.mode)
            if key not in _FALLBACK_WARNED:
                _FALLBACK_WARNED.add(key)
                logger.warning("Dirty mode %s unavailable (%s); "
                               "falling back", fallback.mode, e)
    return NativeCompareTracker()
