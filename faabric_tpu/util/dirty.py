"""Dirty-page tracking over executor memory.

Reference analog: include/faabric/util/dirty.h:24-52 and
src/util/dirty.cpp (917 lines) — there mprotect/SIGSEGV, soft-dirty PTEs
or userfaultfd over mmap'd guest memory. Executor memory here is host
numpy buffers (HBM state is snapshotted via device→host transfer), so
tracking is comparison-based:

- ``compare``: keep a baseline copy, vectorised page compare (numpy).
- ``native``: same baseline, memcmp per page in C++ (util/native.py).
- ``hash``: per-page 64-bit universal-hash baseline — 8 bytes per 4 KiB
  page (~1/512 the memory of a full copy), vectorised blockwise.
- ``none``: every page reported dirty (the reference's fallback).

Same interface as the reference: global + thread-local start/stop, page
flags out. Thread-local tracking lets each executor thread report only
ITS writes (reference threadLocalDirtyRegions).
"""

from __future__ import annotations

import threading

from typing import Optional

import numpy as np

from faabric_tpu.util.config import get_system_config
from faabric_tpu.util.logging import get_logger

logger = get_logger(__name__)

PAGE_SIZE = 4096


def n_pages(size: int) -> int:
    return (size + PAGE_SIZE - 1) // PAGE_SIZE


def _as_array(mem) -> np.ndarray:
    return np.frombuffer(mem, dtype=np.uint8)


class DirtyTracker:
    mode = "base"

    def start_tracking(self, mem) -> None:
        raise NotImplementedError

    def stop_tracking(self, mem) -> None:
        pass

    def get_dirty_pages(self, mem) -> np.ndarray:
        """Bool flags per page since start_tracking."""
        raise NotImplementedError

    def start_thread_local_tracking(self, mem) -> None:
        pass

    def stop_thread_local_tracking(self, mem) -> None:
        pass

    def get_thread_local_dirty_pages(self, mem) -> np.ndarray:
        return self.get_dirty_pages(mem)


class CompareTracker(DirtyTracker):
    """Baseline copy + vectorised compare."""

    mode = "compare"

    def __init__(self) -> None:
        self._baseline: Optional[np.ndarray] = None
        self._tls = threading.local()

    def start_tracking(self, mem) -> None:
        self._baseline = _as_array(mem).copy()

    def _diff(self, baseline: np.ndarray, mem) -> np.ndarray:
        cur = _as_array(mem)
        size = cur.size
        # Memory may have grown since the baseline was taken: pages beyond
        # the baseline are dirty by definition
        flags = np.zeros(n_pages(size), dtype=bool)
        cmp_size = min(size, baseline.size)
        cmp_pages = cmp_size // PAGE_SIZE
        if cmp_pages:
            flags[:cmp_pages] = (
                cur[:cmp_pages * PAGE_SIZE].reshape(-1, PAGE_SIZE)
                != baseline[:cmp_pages * PAGE_SIZE].reshape(-1, PAGE_SIZE)
            ).any(axis=1)
        # Trailing partial page plus anything beyond the baseline
        if cmp_pages * PAGE_SIZE < cmp_size:
            flags[cmp_pages] = not np.array_equal(
                cur[cmp_pages * PAGE_SIZE:cmp_size],
                baseline[cmp_pages * PAGE_SIZE:cmp_size])
        if size > baseline.size:
            flags[baseline.size // PAGE_SIZE:] = True
        return flags

    def get_dirty_pages(self, mem) -> np.ndarray:
        if self._baseline is None:
            return np.zeros(0, dtype=bool)
        return self._diff(self._baseline, mem)

    def start_thread_local_tracking(self, mem) -> None:
        self._tls.baseline = _as_array(mem).copy()

    def get_thread_local_dirty_pages(self, mem) -> np.ndarray:
        baseline = getattr(self._tls, "baseline", None)
        if baseline is None:
            return np.zeros(0, dtype=bool)
        return self._diff(baseline, mem)


class NativeCompareTracker(CompareTracker):
    """Baseline copy + C++ memcmp per page; falls back to numpy."""

    mode = "native"

    def _diff(self, baseline: np.ndarray, mem) -> np.ndarray:
        from faabric_tpu.util.native import get_pagediff_lib

        lib = get_pagediff_lib()
        cur = _as_array(mem)
        if lib is None:
            return super()._diff(baseline, mem)
        cmp_size = min(cur.size, baseline.size)
        flags = np.zeros(n_pages(cur.size), dtype=np.uint8)
        if cmp_size:
            cur_c = np.ascontiguousarray(cur[:cmp_size])
            base_c = np.ascontiguousarray(baseline[:cmp_size])
            lib.diff_pages(base_c.ctypes.data, cur_c.ctypes.data, cmp_size,
                           PAGE_SIZE, flags.ctypes.data)
        out = flags.astype(bool)
        # Pages past the baseline (memory grew mid-batch) are dirty by
        # definition — mirrors CompareTracker._diff
        if cur.size > baseline.size:
            out[baseline.size // PAGE_SIZE:] = True
        return out


# Random per-byte-position multipliers for the vectorised page hash: a
# page's hash is the dot product of its bytes with this vector mod 2^64 —
# a universal hash family, so two different pages collide with probability
# ~2^-64. One shared vector per process.
_HASH_RNG = np.random.RandomState(0x5EED)
_HASH_MULT = _HASH_RNG.randint(1, 2**63 - 1, PAGE_SIZE,
                               dtype=np.uint64) | np.uint64(1)
_HASH_BLOCK_PAGES = 4096  # bound the widened intermediate to ~128 MiB


class HashTracker(DirtyTracker):
    """Per-page 64-bit baseline hash — 8 bytes per 4 KiB page instead of
    a full copy. Hashing is a vectorised blockwise dot product (no per-page Python
    loop): this brackets every executor task, so it must not dwarf the
    guest work."""

    mode = "hash"

    def __init__(self) -> None:
        self._hashes: Optional[np.ndarray] = None
        self._tls = threading.local()

    @staticmethod
    def _page_hashes(mem) -> np.ndarray:
        arr = _as_array(mem)
        pages = n_pages(arr.size)
        pad = pages * PAGE_SIZE - arr.size
        if pad:
            arr = np.concatenate([arr, np.zeros(pad, np.uint8)])
        grid = arr.reshape(pages, PAGE_SIZE)
        out = np.empty(pages, dtype=np.uint64)
        with np.errstate(over="ignore"):
            for lo in range(0, pages, _HASH_BLOCK_PAGES):
                hi = min(pages, lo + _HASH_BLOCK_PAGES)
                block = grid[lo:hi].astype(np.uint64)
                out[lo:hi] = (block * _HASH_MULT).sum(axis=1)
        return out

    @staticmethod
    def _compare(old: Optional[np.ndarray], mem) -> np.ndarray:
        if old is None:
            return np.zeros(0, dtype=bool)
        cur = HashTracker._page_hashes(mem)
        flags = np.ones(cur.size, dtype=bool)  # pages beyond baseline dirty
        m = min(cur.size, old.size)
        flags[:m] = cur[:m] != old[:m]
        return flags

    def start_tracking(self, mem) -> None:
        self._hashes = self._page_hashes(mem)

    def get_dirty_pages(self, mem) -> np.ndarray:
        return self._compare(self._hashes, mem)

    def start_thread_local_tracking(self, mem) -> None:
        self._tls.hashes = self._page_hashes(mem)

    def get_thread_local_dirty_pages(self, mem) -> np.ndarray:
        return self._compare(getattr(self._tls, "hashes", None), mem)


class NoneTracker(DirtyTracker):
    """Everything dirty (reference dirty.h:194-225)."""

    mode = "none"

    def __init__(self) -> None:
        self._size = 0

    def start_tracking(self, mem) -> None:
        self._size = _as_array(mem).size

    def get_dirty_pages(self, mem) -> np.ndarray:
        return np.ones(n_pages(_as_array(mem).size), dtype=bool)


_TRACKERS = {
    "compare": CompareTracker,
    "native": NativeCompareTracker,
    "hash": HashTracker,
    "none": NoneTracker,
}


def make_dirty_tracker(mode: str | None = None) -> DirtyTracker:
    mode = mode or get_system_config().dirty_tracking_mode
    cls = _TRACKERS.get(mode)
    if cls is None:
        raise ValueError(f"Unknown dirty tracking mode: {mode}")
    return cls()
