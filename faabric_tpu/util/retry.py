"""Retry policy + per-peer circuit breaker.

Replaces the transport layer's ad-hoc retry-once: a ``RetryPolicy``
names the whole discipline (attempt budget, exponential backoff with
jitter, breaker thresholds) so the planner's requeue backoff and the
RPC clients share one schedule implementation, and failure propagation
stays *bounded* — a peer that keeps failing trips its breaker and
subsequent calls fail immediately instead of re-paying connect/timeout
latency (the fabric-lib "peer failure is a first-class, bounded-latency
event" stance, arXiv:2510.27656).

Jitter is drawn from a policy-owned ``random.Random`` so tests can seed
it; by default it decorrelates retry storms across peers.
"""

from __future__ import annotations

import random
import threading
import time


class CircuitBreaker:
    """Per-peer failure gate: CLOSED → (threshold consecutive failures)
    → OPEN → (reset_after elapses) → HALF_OPEN → one trial call →
    CLOSED on success / OPEN on failure.

    ``allow()`` is asked before an attempt; ``record_success`` /
    ``record_failure`` report its outcome. While OPEN, ``allow()`` is an
    immediate False — the caller fails fast without touching the
    network."""

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"

    def __init__(self, threshold: int = 5, reset_after: float = 5.0,
                 clock=time.monotonic) -> None:
        if threshold < 1:
            raise ValueError("breaker threshold must be >= 1")
        self.threshold = threshold
        self.reset_after = reset_after
        self._clock = clock
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._trial_in_flight = False

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open_locked()
            return self._state

    @property
    def failures(self) -> int:
        """Consecutive failures since the last success (observability
        surfaces read this; unlike allow() it never consumes the
        half-open trial slot)."""
        with self._lock:
            return self._failures

    def _maybe_half_open_locked(self) -> None:
        if (self._state == self.OPEN
                and self._clock() - self._opened_at >= self.reset_after):
            self._state = self.HALF_OPEN
            self._trial_in_flight = False

    def allow(self) -> bool:
        with self._lock:
            self._maybe_half_open_locked()
            if self._state == self.CLOSED:
                return True
            if self._state == self.HALF_OPEN and not self._trial_in_flight:
                self._trial_in_flight = True  # exactly one concurrent trial
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self._state = self.CLOSED
            self._failures = 0
            self._trial_in_flight = False

    def record_failure(self) -> None:
        with self._lock:
            if self._state == self.HALF_OPEN:
                # Failed trial: straight back to OPEN, fresh timer
                self._state = self.OPEN
                self._opened_at = self._clock()
                self._trial_in_flight = False
                return
            self._failures += 1
            if self._failures >= self.threshold:
                self._state = self.OPEN
                self._opened_at = self._clock()


class RetryPolicy:
    """Attempt budget + exponential backoff with jitter + breaker
    parameters, as one named object."""

    def __init__(self, max_attempts: int = 2, backoff: float = 0.05,
                 multiplier: float = 2.0, max_backoff: float = 2.0,
                 jitter: float = 0.2, breaker_threshold: int = 5,
                 breaker_reset: float = 5.0,
                 rng: random.Random | None = None) -> None:
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.max_attempts = max_attempts
        self.backoff = backoff
        self.multiplier = multiplier
        self.max_backoff = max_backoff
        self.jitter = jitter
        self.breaker_threshold = breaker_threshold
        self.breaker_reset = breaker_reset
        self._rng = rng or random.Random()

    def delay(self, attempt: int) -> float:
        """Sleep before retry number ``attempt + 1`` (0-based: delay(0)
        separates attempts 1 and 2). Exponential, capped, jittered to
        ±jitter fraction."""
        base = min(self.backoff * (self.multiplier ** attempt),
                   self.max_backoff)
        if self.jitter <= 0:
            return base
        return base * (1.0 + self.jitter * (2.0 * self._rng.random() - 1.0))

    def schedule(self) -> list[float]:
        """The full backoff schedule (max_attempts - 1 sleeps)."""
        return [self.delay(i) for i in range(self.max_attempts - 1)]

    def sleep(self, attempt: int) -> None:
        d = self.delay(attempt)
        if d > 0:
            time.sleep(d)

    def new_breaker(self, clock=time.monotonic) -> CircuitBreaker:
        return CircuitBreaker(threshold=self.breaker_threshold,
                              reset_after=self.breaker_reset, clock=clock)


def default_transport_retry_policy() -> RetryPolicy:
    """The RPC clients' policy, env-tunable (defaults reproduce the old
    retry-once behaviour plus a short decorrelating backoff)."""
    import os

    def _f(name: str, default: float) -> float:
        try:
            return float(os.environ.get(name, default))
        except ValueError:
            return default

    return RetryPolicy(
        max_attempts=max(1, int(_f("TRANSPORT_RETRY_ATTEMPTS", 2))),
        backoff=_f("TRANSPORT_RETRY_BACKOFF", 0.05),
        breaker_threshold=max(1, int(_f("TRANSPORT_BREAKER_THRESHOLD", 6))),
        breaker_reset=_f("TRANSPORT_BREAKER_RESET", 5.0),
    )
