"""Execution graphs (reference: include/faabric/util/ExecGraph.h:19-48,
src/util/ExecGraph.cpp).

Call trees are reconstructed from chained message ids recorded in planner
results, exported as JSON via the planner REST API (GET_EXEC_GRAPH).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any

from faabric_tpu.proto import Message


@dataclasses.dataclass
class ExecGraphNode:
    msg: Message
    children: list["ExecGraphNode"] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class ExecGraph:
    root: ExecGraphNode

    def count_nodes(self) -> int:
        def _count(node: ExecGraphNode) -> int:
            return 1 + sum(_count(c) for c in node.children)

        return _count(self.root)

    def to_dict(self) -> dict[str, Any]:
        def _node(n: ExecGraphNode) -> dict[str, Any]:
            return {"msg": n.msg.to_dict(),
                    "timing": node_timing(n.msg),
                    "chained": [_node(c) for c in n.children]}

        return {"root": _node(self.root)}

    def to_json(self) -> str:
        return json.dumps(self.to_dict())


def node_timing(msg: Message) -> dict[str, float]:
    """Per-node duration summary (milliseconds) from the timing the
    executor attaches to each result (executor.py _run_task): ``queue``
    is task-queue wait, ``exec`` the guest run, ``wall`` message creation
    to finish — the planner-observed end-to-end latency."""
    out: dict[str, float] = {}
    details = msg.int_exec_graph_details
    if "queue_us" in details:
        out["queue_ms"] = round(details["queue_us"] / 1000.0, 3)
    if "exec_us" in details:
        out["exec_ms"] = round(details["exec_us"] / 1000.0, 3)
    if msg.timestamp and msg.finish_timestamp:
        out["wall_ms"] = round(
            max(0.0, msg.finish_timestamp - msg.timestamp) * 1000.0, 3)
    return out


def log_chained_function(parent: Message, chained_msg_id: int) -> None:
    """Record a chained call on the parent message (reference ExecGraph.h:46)."""
    if chained_msg_id not in parent.chained_msg_ids:
        parent.chained_msg_ids.append(chained_msg_id)


def get_chained_functions(msg: Message) -> list[int]:
    return list(msg.chained_msg_ids)


def build_exec_graph(get_result, root_msg_id: int, app_id: int) -> ExecGraph:
    """Build the graph by following chained ids. ``get_result(app_id, msg_id)``
    must return the result ``Message`` (the planner provides this)."""

    def _build(msg_id: int) -> ExecGraphNode:
        msg = get_result(app_id, msg_id)
        node = ExecGraphNode(msg=msg)
        for child_id in msg.chained_msg_ids:
            node.children.append(_build(child_id))
        return node

    return ExecGraph(root=_build(root_msg_id))
