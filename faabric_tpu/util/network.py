"""Network helpers (reference: include/faabric/util/network.h)."""

from __future__ import annotations

import os
import socket

LOCALHOST = "127.0.0.1"


def get_primary_ip_for_this_host() -> str:
    override = os.environ.get("OVERRIDE_HOST_IP")
    if override:
        return override
    try:
        # UDP connect to a public address picks the primary interface without
        # sending any packet.
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("8.8.8.8", 80))
            return s.getsockname()[0]
    except OSError:
        return LOCALHOST


def is_local_ip(ip: str) -> bool:
    """True when ``ip`` names THIS machine (loopback or the primary
    interface address). Used to gate same-machine fast paths (shm rings,
    ring allreduce)."""
    if ip.startswith("127.") or ip == "localhost":
        return True
    try:
        return ip == get_primary_ip_for_this_host()
    except OSError:
        return False


def get_free_port() -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("", 0))
        return s.getsockname()[1]


# The framework's listener plan spans 8003..~30000 (canonical ports
# 8003-8014, the per-host MPI pool up to 8532, all shifted by multi-host
# alias offsets up to ~21000). The kernel's ephemeral range is NOT
# guaranteed to start above that — containers commonly run with
# ip_local_port_range = 16000 65535 — so a plain connect() can squat a
# future listener's port for the pooled connection's whole lifetime and
# fail that server's bind with EADDRINUSE. Client dials therefore pin
# their SOURCE port above the plan.
SAFE_CLIENT_PORT_MIN = 30500
SAFE_CLIENT_PORT_MAX = 60000


def safe_create_connection(address: tuple[str, int],
                           timeout: float | None = None) -> socket.socket:
    """``socket.create_connection`` with the local port drawn from
    [SAFE_CLIENT_PORT_MIN, SAFE_CLIENT_PORT_MAX) so outgoing connections
    never collide with the listener plan. Falls back to a plain
    ephemeral connect if the safe range is (improbably) exhausted."""
    import random

    for _ in range(20):
        port = random.randrange(SAFE_CLIENT_PORT_MIN, SAFE_CLIENT_PORT_MAX)
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.settimeout(timeout)
            s.bind(("", port))
            s.connect(address)
            return s
        except OSError as e:
            s.close()
            import errno as _errno

            if e.errno in (_errno.EADDRINUSE, _errno.EADDRNOTAVAIL):
                continue  # unlucky draw: that port is taken
            raise
    return socket.create_connection(address, timeout)
