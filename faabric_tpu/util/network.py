"""Network helpers (reference: include/faabric/util/network.h)."""

from __future__ import annotations

import os
import socket

LOCALHOST = "127.0.0.1"


def get_primary_ip_for_this_host() -> str:
    override = os.environ.get("OVERRIDE_HOST_IP")
    if override:
        return override
    try:
        # UDP connect to a public address picks the primary interface without
        # sending any packet.
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("8.8.8.8", 80))
            return s.getsockname()[0]
    except OSError:
        return LOCALHOST


def is_local_ip(ip: str) -> bool:
    """True when ``ip`` names THIS machine (loopback or the primary
    interface address). Used to gate same-machine fast paths (shm rings,
    ring allreduce)."""
    if ip.startswith("127.") or ip == "localhost":
        return True
    try:
        return ip == get_primary_ip_for_this_host()
    except OSError:
        return False


def get_free_port() -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("", 0))
        return s.getsockname()[1]
