"""Env-var driven system configuration.

TPU-native analog of the reference SystemConfig singleton
(include/faabric/util/config.h:12-70, src/util/config.cpp:19-97): a
re-readable (``reset()``) process-wide config sourced from environment
variables, printable for debugging, with test overrides.
"""

from __future__ import annotations

import dataclasses
import os
import threading


def _env(name: str, default: str) -> str:
    return os.environ.get(name, default)


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


@dataclasses.dataclass
class SystemConfig:
    # Logging
    log_level: str = "info"
    log_file: str = "off"

    # State
    state_mode: str = "inmemory"  # inmemory | file (shm) | redis
    state_dir: str = "/dev/shm/faabric_tpu_state"
    # Synchronous backups per in-memory state key (ISSUE 19). 1 = every
    # key gets a planner-placed backup host and masters forward dirty
    # chunks before acking; 0 = seed-era single-master semantics (no
    # backups, no epochs on the wire, no fencing).
    state_replicas: int = 1
    # THREADS batches whose snapshots declare merge regions promise their
    # writes stay inside them: trackers then baseline/compare only those
    # pages (writes outside the hints go undetected — opt-in)
    dirty_region_hints: bool = False
    redis_state_host: str = "redis"
    redis_queue_host: str = "redis"
    redis_port: int = 6379

    # Scheduling
    batch_scheduler_mode: str = "bin-pack"  # bin-pack | compact | spot
    # Gang-schedule MPI batches (ISSUE 9): bin-pack consults the world's
    # prospective Topology and prefers FILLING a host with the world's
    # ranks (best fit among hosts that hold the whole remainder) before
    # spilling — fewest hosts, co-located ranks, so the hierarchical
    # collectives get their shm tier. Off → the capacity-blind
    # larger-first order also applies to MPI worlds.
    gang_schedule_mpi: bool = True
    override_cpu_count: int = 0
    override_free_cpu_start: int = 0
    default_mpi_world_size: int = 5

    # Timeouts (seconds)
    global_message_timeout: float = 60.0
    bound_timeout: float = 30.0
    reaper_interval_secs: float = 30.0

    # Endpoint
    endpoint_interface: str = ""
    endpoint_host: str = ""
    endpoint_port: int = 8080
    endpoint_num_threads: int = 4

    # RPC server worker threads per plane
    function_server_threads: int = 2
    state_server_threads: int = 2
    snapshot_server_threads: int = 2
    point_to_point_server_threads: int = 8

    # Dirty tracking: the reference uses mprotect/SIGSEGV, soft-dirty PTEs or
    # userfaultfd on guest memory (src/util/dirty.cpp). Executor memory here
    # is host numpy / device HBM, so tracking is hash-page compare ("hash"),
    # full compare ("compare"), native C++ page compare ("native"), or "none"
    # (everything dirty).
    dirty_tracking_mode: str = "hash"
    diffing_mode: str = "xor"
    delta_snapshot_encoding: str = "pages=4096;xor;zlib=1"

    # Planner
    planner_host: str = "localhost"
    planner_port: int = 8011
    # Hosts expire if they miss keep-alives for this long (reference
    # PlannerConfig.hostTimeout; workers re-register every half-timeout)
    planner_host_timeout: float = 30.0
    # Recovery: per-app requeue budget when a host dies or a dispatch
    # fails, and the base of the exponential requeue backoff
    planner_max_requeues: int = 3
    planner_requeue_backoff: float = 0.2
    # Crash safety (ISSUE 4): directory for the planner's write-ahead
    # journal (empty → journaling disabled, allocation-free no-op), the
    # fsync batching interval, the record count that triggers snapshot
    # compaction, and how long a restarted planner waits for hosts to
    # re-register before requeueing their replayed in-flight messages
    # (0 → defaults to planner_host_timeout)
    planner_journal_dir: str = ""
    planner_journal_fsync_interval: float = 0.05
    planner_journal_compact_records: int = 20000
    planner_reconcile_grace: float = 0.0
    # High-QPS invocation ingress (ISSUE 8): batched scheduling tick
    # period; admission-queue bound (messages); per-source credit cap
    # (outstanding queued messages per source before that source sheds);
    # and how long a queued invocation may wait for capacity before it
    # is failed back to the caller
    planner_tick_ms: float = 5.0
    ingress_queue_max: int = 20000
    ingress_source_credits: int = 8192
    ingress_queue_timeout: float = 30.0

    # MPI fault propagation: while a recv on a watched (MPI) group
    # blocks, the expected sender's host is probed every this many
    # seconds; a refused connection aborts the world within ~one probe
    # interval instead of hanging to the socket timeout
    mpi_abort_check_seconds: float = 2.0

    # Transport
    serialisation: str = "json"

    # Device / mesh
    mesh_device_kind: str = "auto"  # auto | tpu | cpu

    @classmethod
    def from_env(cls) -> "SystemConfig":
        """Build a config populated from the environment. A plain
        ``SystemConfig(...)`` keeps its constructor arguments / dataclass
        defaults untouched (explicit kwargs are never silently overwritten
        by the environment)."""
        conf = cls()
        conf.reset()
        return conf

    def reset(self) -> None:
        """Re-read every knob from the environment."""
        self.log_level = _env("LOG_LEVEL", "info")
        self.log_file = _env("LOG_FILE", "off")

        self.state_mode = _env("STATE_MODE", "inmemory")
        self.state_dir = _env("STATE_DIR", "/dev/shm/faabric_tpu_state")
        self.state_replicas = _env_int("FAABRIC_STATE_REPLICAS", 1)
        self.redis_state_host = _env("REDIS_STATE_HOST", "redis")
        self.redis_queue_host = _env("REDIS_QUEUE_HOST", "redis")
        self.redis_port = _env_int("REDIS_PORT", 6379)

        self.batch_scheduler_mode = _env("BATCH_SCHEDULER_MODE", "bin-pack")
        self.gang_schedule_mpi = _env(
            "FAABRIC_GANG_SCHEDULE", "1").lower() not in ("0", "false", "off")
        self.override_cpu_count = _env_int("OVERRIDE_CPU_COUNT", 0)
        self.override_free_cpu_start = _env_int("OVERRIDE_FREE_CPU_START", 0)
        self.default_mpi_world_size = _env_int("DEFAULT_MPI_WORLD_SIZE", 5)

        self.global_message_timeout = _env_int("GLOBAL_MESSAGE_TIMEOUT", 60000) / 1000.0
        self.bound_timeout = _env_int("BOUND_TIMEOUT", 30000) / 1000.0
        self.reaper_interval_secs = _env_int("REAPER_INTERVAL_SECS", 30)

        self.endpoint_interface = _env("ENDPOINT_INTERFACE", "")
        self.endpoint_host = _env("ENDPOINT_HOST", "")
        self.endpoint_port = _env_int("ENDPOINT_PORT", 8080)
        self.endpoint_num_threads = _env_int("ENDPOINT_NUM_THREADS", 4)

        self.function_server_threads = _env_int("FUNCTION_SERVER_THREADS", 2)
        self.state_server_threads = _env_int("STATE_SERVER_THREADS", 2)
        self.snapshot_server_threads = _env_int("SNAPSHOT_SERVER_THREADS", 2)
        self.point_to_point_server_threads = _env_int("POINT_TO_POINT_SERVER_THREADS", 8)

        # native (C++ memcmp) brackets a 128 MiB image in ~75 ms vs
        # compare ~170 ms and hash ~300 ms (bench.py extras.dirty_tracker);
        # hash still wins when baseline MEMORY matters (8 B/page)
        self.dirty_tracking_mode = _env("DIRTY_TRACKING_MODE", "native")
        self.dirty_region_hints = _env("DIRTY_REGION_HINTS", "0") in (
            "1", "true", "on")
        self.diffing_mode = _env("DIFFING_MODE", "xor")
        self.delta_snapshot_encoding = _env(
            "DELTA_SNAPSHOT_ENCODING", "pages=4096;xor;zlib=1"
        )

        self.planner_host = _env("PLANNER_HOST", "localhost")
        self.planner_port = _env_int("PLANNER_PORT", 8011)
        self.planner_host_timeout = _env_float("PLANNER_HOST_TIMEOUT", 30.0)
        self.planner_max_requeues = _env_int("PLANNER_MAX_REQUEUES", 3)
        self.planner_requeue_backoff = _env_float(
            "PLANNER_REQUEUE_BACKOFF", 0.2)
        self.planner_journal_dir = _env("FAABRIC_PLANNER_JOURNAL_DIR", "")
        self.planner_journal_fsync_interval = _env_float(
            "FAABRIC_PLANNER_JOURNAL_FSYNC_INTERVAL", 0.05)
        self.planner_journal_compact_records = _env_int(
            "FAABRIC_PLANNER_JOURNAL_COMPACT_RECORDS", 20000)
        self.planner_reconcile_grace = _env_float(
            "FAABRIC_PLANNER_RECONCILE_GRACE", 0.0)
        self.planner_tick_ms = _env_float("FAABRIC_PLANNER_TICK_MS", 5.0)
        self.ingress_queue_max = _env_int("FAABRIC_INGRESS_QUEUE_MAX", 20000)
        self.ingress_source_credits = _env_int(
            "FAABRIC_INGRESS_SOURCE_CREDITS", 8192)
        self.ingress_queue_timeout = _env_float(
            "FAABRIC_INGRESS_QUEUE_TIMEOUT", 30.0)
        self.mpi_abort_check_seconds = _env_float(
            "MPI_ABORT_CHECK_SECONDS", 2.0)

        self.serialisation = _env("SERIALISATION", "json")
        self.mesh_device_kind = _env("MESH_DEVICE_KIND", "auto")

    def print(self) -> str:
        lines = ["--- System config ---"]
        for f in dataclasses.fields(self):
            lines.append(f"{f.name:<32}{getattr(self, f.name)}")
        out = "\n".join(lines)
        return out

    def get_usable_cores(self) -> int:
        if self.override_cpu_count > 0:
            return self.override_cpu_count
        return os.cpu_count() or 1


_conf: SystemConfig | None = None
_conf_lock = threading.Lock()


def get_system_config() -> SystemConfig:
    global _conf
    if _conf is None:
        with _conf_lock:
            if _conf is None:
                _conf = SystemConfig.from_env()
    return _conf
