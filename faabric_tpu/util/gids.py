"""Process-unique id generation (reference: include/faabric/util/gids.h:6).

Ids are unique within a cluster with high probability: a per-process random
48-bit base plus a monotonically increasing counter, so they are also
monotonic within a process (useful for seqnums and result ordering).
"""

from __future__ import annotations

import itertools
import random
import threading

_lock = threading.Lock()
_base: int | None = None
_counter = itertools.count(1)


def _ensure_base() -> int:
    global _base
    if _base is None:
        with _lock:
            if _base is None:
                _base = random.getrandbits(48) << 20
    return _base


def generate_gid() -> int:
    """Return a process-unique positive integer id."""
    base = _ensure_base()
    return base + next(_counter)


def reset_gids() -> None:
    """Testing hook: re-randomise the base."""
    global _base, _counter
    with _lock:
        _base = None
        _counter = itertools.count(1)
