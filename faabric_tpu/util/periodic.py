"""Periodic background work (reference:
include/faabric/util/PeriodicBackgroundThread.h).

Base for the scheduler's executor reaper and the planner keep-alive thread:
``start(interval)`` runs ``do_work()`` every interval seconds until
``stop()``; stop wakes the sleeper immediately via an event rather than
waiting out the interval.
"""

from __future__ import annotations

import threading

from faabric_tpu.util.logging import get_logger

logger = get_logger(__name__)


class PeriodicBackgroundThread:
    # Subclasses set this to a ``subsystem/role`` name (ISSUE 18 thread
    # naming convention) so profiler / lockcheck attribution is
    # readable; the class-name fallback keeps foreign subclasses legal.
    thread_name: str | None = None

    def __init__(self) -> None:
        self._thread: threading.Thread | None = None
        self._stop_event = threading.Event()
        self.interval: float = 0.0

    # Virtual — subclasses implement the periodic work.
    def do_work(self) -> None:
        raise NotImplementedError

    # Optional hook run on stop (reference tidyUp()).
    def tidy_up(self) -> None:
        pass

    def start(self, interval_seconds: float) -> None:
        if self._thread is not None:
            # A previously stuck thread that has since drained can be
            # reclaimed; a live one means we're already running.
            if self._thread.is_alive():
                return
            self._thread = None
            self.tidy_up()
        self.interval = interval_seconds
        self._stop_event.clear()
        self._thread = threading.Thread(
            target=self._loop,
            name=self.thread_name or f"{type(self).__name__}-periodic",
            daemon=True,
        )
        self._thread.start()

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop_event.set()
        self._thread.join(timeout=5.0)
        if self._thread.is_alive():
            # do_work is stuck; keep the thread registered (a later start()
            # is a no-op) and skip tidy_up, which could release resources
            # the stuck work is still using. The stop event stays set, so
            # the loop exits as soon as do_work returns.
            logger.warning(
                "%s did not stop within timeout; leaving thread to drain",
                type(self).__name__,
            )
            return
        self._thread = None
        self.tidy_up()

    def _loop(self) -> None:
        while not self._stop_event.wait(self.interval):
            try:
                self.do_work()
            except Exception:  # noqa: BLE001 — periodic work must not die
                logger.exception("%s periodic work failed", type(self).__name__)
