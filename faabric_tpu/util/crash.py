"""Fatal-signal crash handler (reference include/faabric/util/crash.h:10-16
— there a native stack-trace printer; here faulthandler, which dumps every
thread's Python stack on SIGSEGV/SIGFPE/SIGABRT/SIGBUS and on demand via
SIGUSR1)."""

from __future__ import annotations

import faulthandler
import signal
import sys

_installed = False


def install_crash_handler() -> None:
    global _installed
    if _installed:
        return
    _installed = True
    faulthandler.enable(file=sys.stderr, all_threads=True)
    try:
        # Live-dump without dying: kill -USR1 <pid> prints all stacks
        faulthandler.register(signal.SIGUSR1, file=sys.stderr,
                              all_threads=True)
    except (AttributeError, ValueError):  # pragma: no cover — non-POSIX
        pass
