"""Byte-twiddling helpers (reference include/faabric/util/bytes.h —
unaligned typed reads/writes, value↔bytes conversion)."""

from __future__ import annotations

import struct
from typing import Any

import numpy as np

_FMT = {
    "i32": "<i", "u32": "<I", "i64": "<q", "u64": "<Q",
    "f32": "<f", "f64": "<d", "u8": "<B",
}


def read_value(buf, offset: int, kind: str) -> Any:
    """Unaligned typed read from any buffer-protocol object."""
    fmt = _FMT[kind]
    return struct.unpack_from(fmt, buf, offset)[0]


def write_value(buf, offset: int, kind: str, value) -> None:
    struct.pack_into(_FMT[kind], buf, offset, value)


def value_to_bytes(kind: str, value) -> bytes:
    return struct.pack(_FMT[kind], value)


def bytes_to_array(data: bytes, dtype=np.uint8) -> np.ndarray:
    return np.frombuffer(data, dtype=dtype).copy()


def array_to_bytes(arr: np.ndarray) -> bytes:
    return np.ascontiguousarray(arr).tobytes()


def format_byte_size(n: int) -> str:
    """Human-readable size (reference's str helpers)."""
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{n} B"
        n /= 1024
    return f"{n} B"  # pragma: no cover
