"""Logging setup (reference: include/faabric/util/logging.h, spdlog).

``LOG_LEVEL`` / ``LOG_FILE`` env vars control level and sink.
"""

from __future__ import annotations

import logging
import os
import sys

_LEVELS = {
    "trace": logging.DEBUG,
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warn": logging.WARNING,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "critical": logging.CRITICAL,
    "off": logging.CRITICAL + 10,
}

_initialised = False


def init_logging() -> None:
    global _initialised
    if _initialised:
        return
    level = _LEVELS.get(os.environ.get("LOG_LEVEL", "info").lower(), logging.INFO)
    log_file = os.environ.get("LOG_FILE", "off")
    handlers: list[logging.Handler] = []
    if log_file not in ("", "off"):
        handlers.append(logging.FileHandler(log_file))
    else:
        handlers.append(logging.StreamHandler(sys.stderr))
    logging.basicConfig(
        level=level,
        format="%(asctime)s [%(levelname).1s] %(name)s: %(message)s",
        handlers=handlers,
    )
    _initialised = True


def get_logger(name: str) -> logging.Logger:
    init_logging()
    return logging.getLogger(name)
