"""Executor-memory allocation helpers.

Reference analog: include/faabric/util/memory.h:78-130 — there mmap
private/shared/virtual reservations and memfd-backed snapshots. Executor
memory here is numpy buffers (the device analog transfers HBM↔host via
jax), so the equivalents are page-aligned allocation, reserve-then-claim
growth, and shared memory via ``multiprocessing.shared_memory``.
"""

from __future__ import annotations

import threading

from multiprocessing import shared_memory
from typing import Optional

import numpy as np

from faabric_tpu.util.dirty import PAGE_SIZE, n_pages


def page_align_up(size: int) -> int:
    return n_pages(size) * PAGE_SIZE


def is_page_aligned(offset: int) -> bool:
    return offset % PAGE_SIZE == 0


def allocate_buffer(size: int) -> np.ndarray:
    """Zeroed page-rounded buffer (the mmap-private analog)."""
    return np.zeros(page_align_up(size), dtype=np.uint8)


class VirtualBuffer:
    """Reserve max, claim forward (reference claimVirtualMemory): a buffer
    whose usable size grows monotonically up to a fixed reservation —
    growth never reallocates or moves data."""

    def __init__(self, max_size: int, initial_size: int = 0) -> None:
        self.max_size = page_align_up(max_size)
        if page_align_up(initial_size) > self.max_size:
            raise ValueError(
                f"Initial size {initial_size} exceeds reservation "
                f"{self.max_size}")
        self._backing = np.zeros(self.max_size, dtype=np.uint8)
        self._claimed = page_align_up(initial_size)

    @property
    def size(self) -> int:
        return self._claimed

    def claim(self, new_size: int) -> np.ndarray:
        new_size = page_align_up(new_size)
        if new_size > self.max_size:
            raise ValueError(
                f"Claim {new_size} exceeds reservation {self.max_size}")
        self._claimed = max(self._claimed, new_size)
        return self.view()

    def view(self) -> np.ndarray:
        return self._backing[:self._claimed]


class SharedBuffer:
    """Cross-process shared memory region (the MAP_SHARED analog) backed
    by ``multiprocessing.shared_memory``."""

    def __init__(self, size: int, name: Optional[str] = None,
                 create: bool = True) -> None:
        size = page_align_up(size)
        self._shm = shared_memory.SharedMemory(name=name, create=create,
                                               size=size)
        self.name = self._shm.name
        self.array = np.frombuffer(self._shm.buf, dtype=np.uint8)
        self._closed = False

    def close(self, unlink: bool = False) -> None:
        """Idempotent and never raises for live external views: a mapping
        still pinned by caller-held numpy views goes to a graveyard that
        later close() calls (and atexit) drain once the views die —
        otherwise SharedMemory.__del__ rattles off BufferError at
        interpreter-decided destruction order. ``unlink`` removes the
        name immediately either way (POSIX allows unlink while mapped)."""
        _drain_shm_graveyard()
        if self._closed:
            return
        self._closed = True
        self.array = None  # drop our own view
        if unlink:
            try:
                self._shm.unlink()
            except FileNotFoundError:  # pragma: no cover
                pass
        try:
            self._shm.close()
        except BufferError:
            with _SHM_GRAVEYARD_LOCK:
                _SHM_GRAVEYARD.append(self._shm)
        self._shm = None


# Mappings whose close() found live external views; kept referenced so
# their __del__ can't fire early, retried as views die. Mutated from
# every rank thread's close() — all access under the lock (and entries
# are drained by one thread at a time, so no double-close).
_SHM_GRAVEYARD: list = []
_SHM_GRAVEYARD_LOCK = threading.Lock()


def _drain_shm_graveyard() -> None:
    with _SHM_GRAVEYARD_LOCK:
        kept = []
        for shm in _SHM_GRAVEYARD:
            try:
                shm.close()
            except BufferError:
                kept.append(shm)
        _SHM_GRAVEYARD[:] = kept


def _graveyard_atexit() -> None:  # pragma: no cover — interpreter exit
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        _drain_shm_graveyard()


import atexit  # noqa: E402  (registration belongs next to the graveyard)

atexit.register(_graveyard_atexit)
