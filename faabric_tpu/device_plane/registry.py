"""Device registration and mesh resolution for one MPI world.

The handshake (fabric-lib arXiv:2510.27656 gives the shape: peers
register their local memory/device handles, exchange them once, and
every peer independently validates the resulting connectivity before
any zero-copy path activates):

1. every rank resolves its OWN device — the planner-assigned chip
   carried in the PTP mappings by default, or an explicit override —
   and registers it with the world;
2. one host-path allgather moves each rank's ``(rank, global device
   id, jax process index)`` row to every participant (the only wire
   exchange; collectives after activation never touch the host
   planes);
3. every participant runs the SAME deterministic validation over the
   full row set (``resolve_mesh``). The plane activates only when the
   whole rank set resolves onto distinct devices of ONE mesh whose
   process split matches the world's host split; any violation raises
   :class:`MeshMismatch` and the world stays on the host ladder.

Because step 3 is a pure function of data every rank holds after the
allgather, all processes reach the identical activate/fall-back
verdict with no further coordination — the property that keeps the
dispatch ladder from desyncing across ranks.
"""

from __future__ import annotations

import numpy as np

from faabric_tpu.util.logging import get_logger

logger = get_logger(__name__)

# Pure functions over allgathered rows — no shared mutable state
GUARDS: dict = {}

# One handshake row per rank: [rank, global device id, process index]
ROW_FIELDS = 3


class DevicePlaneFallback(RuntimeError):
    """Route this collective (and, once raised from activation or a
    backend failure, every later one) back to the host ladder."""


class MeshMismatch(DevicePlaneFallback):
    """The registered rank→device set does not resolve to one mesh."""


def registration_row(rank: int, device) -> np.ndarray:
    """This rank's handshake row. ``device`` is a jax Device or None
    (no resolvable device — the row still travels so every peer reaches
    the same MeshMismatch verdict instead of hanging the handshake)."""
    if device is None:
        return np.array([rank, -1, -1], dtype=np.int64)
    return np.array([rank, int(device.id), int(device.process_index)],
                    dtype=np.int64)


def resolve_local_device(world, rank: int):
    """Default registration: the planner-assigned chip of ``rank``
    (decision ``device_ids`` riding the PTP mappings), mapped onto this
    process's jax devices the same way local_devices_for_ids does —
    per-host indexes wrap modulo the local device count. None when the
    placement carries no device or the backend has none."""
    import jax

    try:
        dev_id = world.device_for_rank(rank)
    except Exception:  # noqa: BLE001 — stub brokers without device maps
        return None
    if dev_id is None or dev_id < 0:
        return None
    local = jax.local_devices()
    if not local:
        return None
    return local[dev_id % len(local)]


def resolve_mesh(rows: np.ndarray, size: int, local_ranks,
                 process_index: int) -> list:
    """Validate the allgathered registration rows and return the mesh's
    device list in rank order.

    ``local_ranks`` is the rank set THIS world object serves (the
    broker's host split); ``process_index`` this process's jax process
    id. Deterministic in its inputs: every process computes the same
    verdict from the same rows, differing only in which ranks it calls
    local — and the cross-check below makes those two splits agree or
    the whole plane refuses.
    """
    import jax

    rows = np.asarray(rows).reshape(-1, ROW_FIELDS)
    if rows.shape[0] != size:
        raise MeshMismatch(
            f"handshake returned {rows.shape[0]} rows for a "
            f"{size}-rank world")
    by_rank: dict[int, tuple[int, int]] = {}
    for r, dev_id, pidx in rows.tolist():
        if r in by_rank:
            raise MeshMismatch(f"rank {r} registered twice")
        by_rank[int(r)] = (int(dev_id), int(pidx))
    if sorted(by_rank) != list(range(size)):
        raise MeshMismatch(
            f"rank set {sorted(by_rank)[:8]}... is not 0..{size - 1}")

    dev_ids = [by_rank[r][0] for r in range(size)]
    if any(d < 0 for d in dev_ids):
        missing = [r for r in range(size) if by_rank[r][0] < 0]
        raise MeshMismatch(f"ranks {missing[:8]} registered no device")
    if len(set(dev_ids)) != size:
        raise MeshMismatch(
            f"device ids {dev_ids[:8]}... alias a chip across ranks")

    by_global_id = {d.id: d for d in jax.devices()}
    devices = []
    local_ranks = set(local_ranks)
    for r in range(size):
        dev_id, claimed_pidx = by_rank[r]
        dev = by_global_id.get(dev_id)
        if dev is None:
            raise MeshMismatch(
                f"rank {r}'s device {dev_id} is not in this backend's "
                f"global device set ({len(by_global_id)} devices)")
        if dev.process_index != claimed_pidx:
            raise MeshMismatch(
                f"rank {r} claims device {dev_id} on process "
                f"{claimed_pidx}, backend says {dev.process_index}")
        # The world's host split and the mesh's process split must be
        # the SAME partition: a rank this world object serves must own
        # an addressable chip (or the rendezvous could never build its
        # shard), and a remote rank's chip must NOT be addressable here
        # (two simulated hosts sharing one process would each see only
        # part of the shard set a single-controller array needs)
        if (dev.process_index == process_index) != (r in local_ranks):
            raise MeshMismatch(
                f"rank {r}: host split (local={r in local_ranks}) "
                f"disagrees with device process split "
                f"(process {dev.process_index} vs {process_index})")
        devices.append(dev)
    return devices
