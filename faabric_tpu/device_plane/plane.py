"""The device collective plane: compiled, donated-buffer collectives.

The fourth rung of the MPI dispatch ladder (shm → tcp → device): when a
world's ranks all resolved onto devices of one JAX mesh (registry.py),
allreduce / allgather / reduce_scatter run as ONE compiled XLA program
over that mesh instead of chunk-pipelined host rings — on TPU the
collective rides ICI scheduled by XLA; on this container's CPU backend
the same code runs over virtual devices (cross-process via the gloo
collectives layer), which is what the tests and bench drive today.

Execution model (multi-controller SPMD): rank threads of one process
rendezvous per collective — each deposits its buffer, the LAST arriver
becomes the executor: it assembles the global array
(``make_array_from_single_device_arrays``), runs the cached compiled
executable, and hands each local rank the addressable shard of its own
device. Worlds spanning processes run the identical program in every
process, exactly like jax's multi-process SPMD model — no cross-process
bytes ever touch the host shm/tcp planes.

Device-resident payloads (ISSUE 15): a deposit that is already a
**committed single-device jax.Array on its rank's registered chip**
skips ``device_put`` entirely, and when every local deposit is resident
the round executes a **zero-host-copy** program: inputs are used in
place in HBM, the input is NOT donated (the callers still own their
arrays — jax arrays are immutable, so MPI's reuse-after-call contract
holds by construction), and each rank's result is returned as the
addressable shard still on its device. Host rounds keep the PR 10
shape: ``device_put`` in (donated — XLA may reuse the buffer), shard
readback out. Mixed-residency rounds stage the resident deposits to
host (one counted copy each) and run the host shape — correctness over
performance for the asymmetric edge case. Every host↔device byte either
path moves is stamped on the ``faabric_device_copy_*`` counters
(copies.py), so "zero host bytes AND zero host copies for a
device-resident allreduce" is an asserted invariant, not a claim.

Executables are cached per (kind, op, elems, dtype, resident) — the
residency flag keys the cache because the resident program differs in
donation/aliasing — and compilation is surfaced as a ``phase=compile``
span plus per-plane hit/compile/compile-ms stats on ``summary()`` and
``GET /topology`` (first-call latency spikes are attributable).

``ring_permute`` is the p2p stream primitive for device worlds: every
rank's payload lands on its ring neighbour's chip in one compiled step
(Pallas ``make_async_remote_copy`` on TPU, ``jax.lax.ppermute``
elsewhere — pallas_ring.py), the building block the schedule runner's
``device-ring`` execution target drives.

Failure contract: eligibility is a pure function of (shape, dtype, op)
plus the activation verdict — residency deliberately does NOT affect
it — so every rank of every process picks the same rung. A backend
error while executing disables the plane and raises
:class:`DevicePlaneFallback`, which MpiWorld catches to re-run the
collective on the host ladder (staging device-resident inputs to host
with one explicit counted copy). Caveat (documented in
docs/data_plane.md): the backend collective is itself synchronous
across processes, so a mid-collective backend failure surfaces in every
process; an error that somehow struck ONE process only would leave the
others waiting in the backend until its own timeout.
"""

from __future__ import annotations

import os
import threading
import time
import warnings
import weakref

import numpy as np

from faabric_tpu.device_plane.copies import D2H, H2D, count_copy
from faabric_tpu.device_plane.registry import DevicePlaneFallback
from faabric_tpu.mpi.types import MpiOp, UserOp
from faabric_tpu.telemetry import (
    get_collective_profiler,
    get_comm_matrix,
    get_metrics,
    get_perf_store,
    span,
)
from faabric_tpu.util.logging import get_logger

logger = get_logger(__name__)

# XLA backends without donation support (CPU) warn per executable; the
# donation is an optimization contract, not a correctness one — keep the
# logs quiet where it cannot be honoured (TPU honours it).
warnings.filterwarnings(
    "ignore", message=".*donated buffers were not usable.*")

# A rank thread waiting for its rendezvous peers (same process, same
# collective) — generous: peers are sibling threads, not the network,
# but a loaded 2-core container can park a thread for seconds
DEVICE_PLANE_TIMEOUT_S = float(
    os.environ.get("FAABRIC_DEVICE_PLANE_TIMEOUT", "120"))

_ALLREDUCE_OPS = (MpiOp.SUM, MpiOp.MAX, MpiOp.MIN, MpiOp.PROD)

_metrics = get_metrics()
_COLLECTIVES = {
    kind: _metrics.counter(
        "faabric_device_plane_collectives_total",
        "Collectives executed on the device plane (per rank)", op=kind)
    for kind in ("allreduce", "allgather", "reduce_scatter",
                 "ring_permute")}
_COMPILES = _metrics.counter(
    "faabric_device_plane_compiles_total",
    "Device-plane executable cache misses (compilations)")
_FALLBACKS = _metrics.counter(
    "faabric_device_plane_fallbacks_total",
    "Device plane disables (collectives re-routed to the host ladder)")
# ISSUE 12: compile/execute phases fold into the collective profiler
# (critical-path decomposition shows compile-storm rounds next to the
# steady state) and executed payloads feed the device-plane link profile
_PROFILER = get_collective_profiler()
_PERF = get_perf_store()

# Live planes of this process (observability: GET /topology and the
# worker telemetry block list their summaries). WeakSet — a destroyed
# world's plane must not be pinned alive by the scrape surface.
_PLANES: "weakref.WeakSet[DevicePlane]" = weakref.WeakSet()
_PLANES_LOCK = threading.Lock()


def is_device_payload(data) -> bool:
    """Duck-typed "is this a jax.Array" check that never imports jax
    and never materializes the buffer: numpy first (the common case),
    then the two attributes every jax Array carries and no ndarray
    does. Used by MpiWorld's dispatch entries on EVERY collective call,
    so it must stay allocation-free."""
    return (not isinstance(data, np.ndarray)
            and hasattr(data, "sharding")
            and hasattr(data, "addressable_shards"))


def device_planes_summary() -> list[dict]:
    """Summaries of this process's live planes (telemetry surface)."""
    with _PLANES_LOCK:
        planes = list(_PLANES)
    out = []
    for p in planes:
        try:
            out.append(p.summary())
        except Exception:  # noqa: BLE001 — scrape must not throw
            pass
    out.sort(key=lambda s: s.get("world_id", 0))
    return out


class _Round:
    """One rendezvous: the local rank threads of one collective call.
    Internally synchronized by the owning plane's lock + the ready
    event; fields are written before ready.set() and read after."""

    __slots__ = ("deposits", "results", "error", "ready")

    def __init__(self) -> None:
        self.deposits: dict[int, tuple] = {}  # rank → (key, buf, resident)
        self.results: dict[int, object] | None = None
        self.error: BaseException | None = None
        self.ready = threading.Event()


class DevicePlane:
    """Compiled collectives bound to one world's resolved mesh."""

    # Rendezvous state and the disable verdict mutate under _lock from
    # N rank threads; the executable cache under its own leaf lock (the
    # executor holds it across a compile — seconds — which must not
    # block peers' deposits for the NEXT round).
    GUARDS = {
        "_rounds": "_lock",
        "_rank_seq": "_lock",
        "_disabled": "_lock",
        "_cache": "_cache_lock",
        "_cache_hits": "_cache_lock",
        "_cache_compiles": "_cache_lock",
        "_compile_ms": "_cache_lock",
    }

    def __init__(self, world_id: int, devices, local_ranks,
                 topology_gen: int, axis_name: str = "ranks") -> None:
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        self.world_id = world_id
        self.devices = list(devices)          # rank i ↔ devices[i]
        self.n = len(self.devices)
        self.local_ranks = tuple(sorted(local_ranks))
        self.n_local = len(self.local_ranks)
        self.topology_gen = topology_gen
        self.axis = axis_name
        self.mesh = Mesh(np.array(self.devices), (axis_name,))
        self._in_sharding = NamedSharding(self.mesh, P(axis_name, None))
        self._rank_of_device = {d: r for r, d in enumerate(self.devices)}
        self._jax = jax

        self._lock = threading.Lock()
        self._rounds: dict[int, _Round] = {}
        self._rank_seq: dict[int, int] = {}
        self._disabled: str | None = None
        self._cache_lock = threading.Lock()
        self._cache: dict[tuple, object] = {}
        self._cache_hits = 0
        self._cache_compiles = 0
        self._compile_ms = 0.0
        with _PLANES_LOCK:
            _PLANES.add(self)

    # ------------------------------------------------------------------
    # Eligibility / residency / fallback ladder
    # ------------------------------------------------------------------
    def eligible(self, kind: str, arr, op=None) -> bool:
        """Pure function of (activation verdict, shape, dtype, op):
        every rank of every process derives the same rung. Ineligible
        shapes take the host ladder with no device-plane involvement.
        ``arr`` may be a numpy array OR a jax.Array — only its
        shape/dtype are consulted, never its bytes (a jax input must
        not be materialized to answer an eligibility question)."""
        with self._lock:
            if self._disabled is not None:
                return False
        size = int(getattr(arr, "size", 0))
        try:
            dtype = np.dtype(arr.dtype)
        except (AttributeError, TypeError):
            return False
        # Exact int folds and IEEE float reductions compile; bool,
        # complex, structured (MINLOC pairs) and object dtypes do not
        if size == 0 or dtype.kind not in "iuf":
            return False
        # Canonicalization guard: with jax_enable_x64 off (this repo
        # never enables it) device_put silently DOWNCASTS 64-bit
        # buffers to 32-bit — wrong result dtype and overflow-corrupt
        # sums past 2^31. Payloads whose canonical jax dtype differs
        # from their numpy dtype keep the exact host ladder. (The x64
        # flag, like every ladder input, must agree across the world's
        # processes — it is process-global jax config. jax.Array inputs
        # pass by construction: they already hold canonical dtypes.)
        if self._jax.dtypes.canonicalize_dtype(dtype) != dtype:
            return False
        if isinstance(op, UserOp):
            return False  # arbitrary python folds cannot compile
        if kind == "allreduce":
            return op in _ALLREDUCE_OPS
        if kind == "reduce_scatter":
            return op == MpiOp.SUM and size % self.n == 0
        if kind in ("allgather", "ring_permute"):
            return op is None
        return False

    def resident(self, rank: int, arr) -> bool:
        """True when ``arr`` is a committed single-device jax.Array
        living on ``rank``'s registered chip — the zero-copy deposit
        shape. Residency is an EXECUTION property, never an eligibility
        one: ranks may disagree on it without desyncing the ladder."""
        if not is_device_payload(arr):
            return False
        try:
            if not getattr(arr, "committed", False):
                return False
            if not arr.is_fully_addressable:
                return False
            devs = arr.sharding.device_set
            if len(devs) != 1:
                return False
            (dev,) = devs
        except Exception:  # noqa: BLE001 — exotic array types → host
            return False
        return 0 <= rank < self.n and dev == self.devices[rank]

    def disable(self, reason: str) -> None:
        """One-way: after any backend error / rendezvous breakdown the
        plane routes everything to the host ladder (re-activation means
        a fresh handshake on the next topology generation)."""
        with self._lock:
            if self._disabled is not None:
                return
            self._disabled = reason
        _FALLBACKS.inc()
        logger.warning("Device plane (world %s) disabled: %s",
                       self.world_id, reason)

    @property
    def disabled_reason(self) -> str | None:
        with self._lock:
            return self._disabled

    # ------------------------------------------------------------------
    # Collectives (MpiWorld-facing; per-rank buffers in and out — numpy
    # or device-resident jax arrays; result residency follows input)
    # ------------------------------------------------------------------
    def allreduce(self, rank: int, data, op: MpiOp = MpiOp.SUM):
        out = self._collective("allreduce", rank, data, op)
        return out.reshape(data.shape)

    def allgather(self, rank: int, data):
        return self._collective("allgather", rank, data, None)

    def reduce_scatter(self, rank: int, data, op: MpiOp = MpiOp.SUM):
        return self._collective("reduce_scatter", rank, data, op)

    def ring_permute(self, rank: int, data, shift: int = 1):
        """The p2p stream primitive: every rank's payload lands on rank
        ``(rank + shift) % n`` in ONE compiled mesh step — Pallas
        ``make_async_remote_copy`` over ICI on TPU, ``lax.ppermute``
        elsewhere (pallas_ring.py). Returns the payload of rank
        ``(rank - shift) % n``; result residency follows input."""
        shift = int(shift) % self.n
        if shift == 0:
            return data
        out = self._collective("ring_permute", rank, data, shift)
        return out.reshape(data.shape)

    # ------------------------------------------------------------------
    def _collective(self, kind: str, rank: int, data, op):
        resident = self.resident(rank, data)
        if resident:
            flat = data.reshape(-1)  # on-device; no host materialization
        else:
            if is_device_payload(data):
                # An eligible jax.Array the plane cannot prove resident
                # (uncommitted, foreign chip): materializing it here IS
                # a device→host transfer — stamp it like every other
                # boundary crossing (the every-copy-counted contract)
                count_copy(D2H, int(data.nbytes), "staging")
            flat = np.ascontiguousarray(np.asarray(data).reshape(-1))
        if kind == "ring_permute":
            op_code = int(op)  # the shift rides the op slot of the key
        else:
            op_code = int(op) if op is not None else -1
        key = (kind, op_code, int(flat.size), str(flat.dtype))
        with self._lock:
            if self._disabled is not None:
                raise DevicePlaneFallback(self._disabled)
            if rank not in self.local_ranks:
                raise DevicePlaneFallback(
                    f"rank {rank} is not local to this plane")
            # Collectives are globally ordered per world, so each
            # rank's Nth device collective belongs to rendezvous N
            seq = self._rank_seq.get(rank, 0)
            self._rank_seq[rank] = seq + 1
            rnd = self._rounds.get(seq)
            if rnd is None:
                rnd = _Round()
                self._rounds[seq] = rnd
            rnd.deposits[rank] = (key, flat, resident)
            last = len(rnd.deposits) == self.n_local

        if last:
            try:
                rnd.results = self._execute(kind, key, rnd.deposits)
            except BaseException as e:  # noqa: BLE001 — delivered to
                # every waiting peer below; backend errors additionally
                # disable the plane so later collectives skip the rung
                if not isinstance(e, DevicePlaneFallback):
                    self.disable(f"backend error: {e!r}")
                    e = DevicePlaneFallback(
                        f"device collective failed: {e!r}")
                rnd.error = e
            with self._lock:
                self._rounds.pop(seq, None)
            rnd.ready.set()
        else:
            while not rnd.ready.wait(DEVICE_PLANE_TIMEOUT_S):
                with self._lock:
                    gathered = len(rnd.deposits) == self.n_local
                if gathered:
                    # Every local rank deposited — the executor is
                    # running (a first-shape compile or the backend
                    # collective itself can outlast the window). Keep
                    # waiting, exactly like a blocked host collective:
                    # a timing out here would desync this rank from the
                    # executor, which WILL return a device result. The
                    # executor's own failure path sets error + ready.
                    continue
                # Peers genuinely missing: a local rank never entered
                # this collective — protocol breakdown, not slowness
                with self._lock:
                    self._rounds.pop(seq, None)
                self.disable(
                    f"rendezvous timeout: round {seq} gathered "
                    f"{len(rnd.deposits)}/{self.n_local} local ranks")
                raise DevicePlaneFallback(
                    "device-plane rendezvous timeout")

        if rnd.error is not None:
            raise rnd.error
        _COLLECTIVES[kind].inc()
        # Truthful accounting: this rank's contribution entered the
        # device plane (ring-neighbour attribution in mesh rank order;
        # the host planes saw none of it)
        get_comm_matrix().record(rank, (rank + 1) % self.n, "device",
                                 int(flat.nbytes))
        return rnd.results[rank]

    # ------------------------------------------------------------------
    def _execute(self, kind: str, key: tuple,
                 deposits: dict[int, tuple]) -> dict:
        """Executor body (one thread per process per round): global
        array assembly → compiled run → per-rank shard handout. An
        all-resident round assembles the callers' HBM shards in place,
        compiles WITHOUT donation (callers keep their arrays) and hands
        each rank its device shard back — zero host↔device copies. Host
        rounds keep the PR 10 shape (device_put in, donated run,
        readback out), every copy counted."""
        jax = self._jax
        for r, (k, _buf, _res) in deposits.items():
            if k != key:
                raise RuntimeError(  # protocol desync — NOT a fallback
                    f"device-plane rendezvous mismatch: rank {r} "
                    f"deposited {k}, executor saw {key}")
        kind_, op_code, m, dtype = key
        all_resident = all(res for (_k, _b, res) in deposits.values())

        shards = []
        for r, (_k, buf, res) in sorted(deposits.items()):
            if all_resident:
                shards.append(buf[None])  # on-device reshape to (1, m)
                continue
            if res:
                # Mixed-residency round: the resident deposit takes the
                # explicit staging copy and rides the host shape
                buf = np.asarray(buf)
                count_copy(D2H, int(buf.nbytes), "staging")
            count_copy(H2D, int(buf.nbytes), "input")
            shards.append(jax.device_put(buf[None], self.devices[r]))
        x = jax.make_array_from_single_device_arrays(
            (self.n, m), self._in_sharding, shards)
        executor_rank = min(deposits)

        exe_key = key + (all_resident,)
        with self._cache_lock:
            compiled = self._cache.get(exe_key)
            if compiled is not None:
                self._cache_hits += 1
        if compiled is None:
            # Rounds are sequential per plane (a rank cannot enter round
            # N+1 before round N released it), so one executor compiles
            # at a time — the lock only orders the publish
            _COMPILES.inc()
            t0 = time.monotonic()
            with span("mpi.phase", "compile", phase="compile",
                      world=self.world_id, kind=kind, elems=m,
                      dtype=dtype, resident=all_resident):
                jfn = self._build(kind, op_code, donate=not all_resident)
                compiled = jfn.lower(x).compile()
            elapsed = time.monotonic() - t0
            _PROFILER.record_phase(self.world_id, kind, executor_rank,
                                   "compile", elapsed)
            with self._cache_lock:
                self._cache[exe_key] = compiled
                self._cache_compiles += 1
                self._compile_ms += elapsed * 1e3

        t0 = time.monotonic()
        with span("mpi.phase", "execute", phase="execute",
                  world=self.world_id, kind=kind, elems=m, dtype=dtype,
                  resident=all_resident):
            y = compiled(x)
            out = self._distribute(kind, y, all_resident)
        elapsed = time.monotonic() - t0
        _PROFILER.record_phase(self.world_id, kind, executor_rank,
                               "execute", elapsed)
        # The whole mesh's payload moved through the device plane in
        # this one execute — a per-mesh rate, not a per-point link
        total_bytes = sum(buf.nbytes for _k, buf, _r in deposits.values())
        _PERF.observe("mesh", "device", total_bytes, elapsed)
        return out

    def _build(self, kind: str, op_code: int, donate: bool = True):
        """The jitted program for one (kind, op): a shard_map whose
        body is the single jax.lax collective. ``donate`` aliases the
        input buffer into the output (host rounds own their device_put
        inputs); resident rounds must NOT donate — the callers still
        hold the input arrays."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from faabric_tpu.parallel.collectives import shard_map_compat

        axis = self.axis
        check_vma = None
        if kind == "allreduce":
            op = MpiOp(op_code)
            prim = {MpiOp.SUM: jax.lax.psum, MpiOp.MAX: jax.lax.pmax,
                    MpiOp.MIN: jax.lax.pmin}.get(op)
            if prim is not None:
                def f(shard):  # (1, m) → (1, m), every row the reduction
                    return prim(shard, axis)
            else:  # PROD: gather + fold (no pprod primitive)
                def f(shard):
                    g = jax.lax.all_gather(shard[0], axis, tiled=False)
                    return jnp.prod(g, axis=0,
                                    keepdims=True).astype(shard.dtype)
            out_spec = P(axis, None)
        elif kind == "reduce_scatter":
            def f(shard):  # (1, n·k) → (1, k)
                return jax.lax.psum_scatter(shard, axis,
                                            scatter_dimension=1,
                                            tiled=True)
            out_spec = P(axis, None)
        elif kind == "allgather":
            def f(shard):  # (1, k) → (n·k,) replicated
                return jax.lax.all_gather(shard[0], axis, tiled=True)
            out_spec = P()
            # Replicated output the static check cannot infer — the
            # same version-portable disable parallel/collectives.py uses
            check_vma = False
        elif kind == "ring_permute":
            from faabric_tpu.device_plane.pallas_ring import permute_body

            # op_code carries the shift; the body is the Pallas
            # remote-copy kernel on TPU, lax.ppermute elsewhere
            f = permute_body(self.mesh, axis, op_code)
            out_spec = P(axis, None)
        else:
            raise RuntimeError(f"unknown device collective {kind}")

        fn = shard_map_compat(f, mesh=self.mesh,
                              in_specs=P(axis, None),
                              out_specs=out_spec, check_vma=check_vma)
        return jax.jit(fn, donate_argnums=(0,) if donate else ())

    def _distribute(self, kind: str, y, resident: bool) -> dict:
        """Per-rank results from the output's addressable shards. A
        resident round hands each rank its device shard (still in HBM —
        an immutable jax array, the JAX-native result contract); a host
        round reads back private writable host copies (MPI result
        semantics), each readback counted."""
        if resident:
            out: dict[int, object] = {}
            for s in y.addressable_shards:
                r = self._rank_of_device.get(s.device)
                if r is None:
                    continue
                out[r] = s.data if kind == "allgather" else s.data[0]
            missing = [r for r in self.local_ranks if r not in out]
            if missing:
                raise RuntimeError(
                    f"output shards missing for local ranks {missing}")
            return out
        if kind == "allgather":
            # Replicated output: one readback, one private copy per rank
            full = np.array(y.addressable_shards[0].data)
            count_copy(D2H, int(full.nbytes), "readback")
            return {r: (full if i == 0 else full.copy())
                    for i, r in enumerate(self.local_ranks)}
        out = {}
        for s in y.addressable_shards:
            r = self._rank_of_device.get(s.device)
            if r is not None:
                host = np.array(s.data)[0]
                count_copy(D2H, int(host.nbytes), "readback")
                out[r] = host
        missing = [r for r in self.local_ranks if r not in out]
        if missing:
            raise RuntimeError(
                f"output shards missing for local ranks {missing}")
        return out

    def summary(self) -> dict:
        """Observability snapshot (tests / debugging endpoints /
        ``GET /topology``)."""
        from faabric_tpu.device_plane.copies import device_copy_totals

        with self._cache_lock:
            cached = sorted(str(k) for k in self._cache)
            cache_stats = {
                "entries": len(self._cache),
                "hits": self._cache_hits,
                "compiles": self._cache_compiles,
                "compile_ms_total": round(self._compile_ms, 3),
            }
        return {
            "world_id": self.world_id,
            "size": self.n,
            "local_ranks": list(self.local_ranks),
            "platform": self.devices[0].platform if self.devices else "",
            "topology_gen": self.topology_gen,
            "disabled": self.disabled_reason,
            "cached_executables": cached,
            "executable_cache": cache_stats,
            # PROCESS-wide host<->device copy accounting (copies.py) —
            # named so a consumer summing across listed planes cannot
            # mistake it for a per-plane figure and double-count
            "process_device_copies": device_copy_totals(),
        }
