"""Pallas ring-permute p2p: ``make_async_remote_copy`` as a stream primitive.

The device plane's collectives (plane.py) are single compiled XLA
programs — good for reductions, but the schedule compiler
(mpi/schedule_compile.py) also lowers collectives into *step programs*
whose wire legs are pure neighbour permutes (the ``allgather.ring``
family: n−1 rounds of "send my block right, receive the left
neighbour's"). On a device world those legs should never touch the host
planes: this module provides

- :func:`permute_body` — the per-shard body of the compiled
  ``ring_permute`` program. On TPU it is a Pallas kernel driving
  ``pltpu.make_async_remote_copy`` chip→chip over ICI (SNIPPETS.md
  [1–3]; the async-RDMA pattern from the Pallas distributed guide):
  source ref in ANY/HBM memory space, one send + one receive DMA
  semaphore, logical neighbour addressing — the bytes go straight from
  HBM to the neighbour's HBM without staging through VMEM-sized
  compute. Everywhere else (this container's CPU backend) the same
  signature lowers to ``jax.lax.ppermute``, so dispatch, eligibility,
  caching and numerics are all exercised today and the kernel lights up
  unchanged when the TPU tunnel grants devices.
- :class:`DeviceRingTarget` — a schedule-runner **execution target**
  (mpi/schedule.py ``register_step_target``): when a verified
  schedule's phase is annotated ``target="device-ring"`` and the
  world's device plane is active, the runner hands the phase's
  SEND/RECV steps here and each permute round executes as ONE
  ``DevicePlane.ring_permute`` mesh step instead of 2(n−1) host
  messages. Declines (returns None) on any structural or eligibility
  mismatch — the host steps then run untouched, which is the fallback
  the CPU tests pin.

Knob: ``FAABRIC_PALLAS_RING`` (default on) disables both the kernel
selection and the execution target; like every ladder knob it must
agree across the world's processes.

Selftest: ``python -m faabric_tpu.device_plane.pallas_ring --selftest``
validates the permute numerics on whatever backend is granted and
exercises the REAL Pallas kernel when that backend is TPU; with no TPU
it reports the skip explicitly and exits 0 fast (the CI hook's
fast-fail contract).
"""

from __future__ import annotations

import functools
import os

import numpy as np

from faabric_tpu.util.logging import get_logger

logger = get_logger(__name__)


def pallas_ring_enabled() -> bool:
    return os.environ.get("FAABRIC_PALLAS_RING", "1").lower() \
        not in ("0", "false", "off")


def mesh_on_tpu(mesh) -> bool:
    devs = mesh.devices.reshape(-1)
    return bool(devs.size) and devs[0].platform == "tpu"


# ---------------------------------------------------------------------------
# The kernel
# ---------------------------------------------------------------------------
def _pallas_permute_call(shard, axis: str, shift: int, n: int):
    """One ring hop as a Pallas TPU kernel: the whole (1, m) shard DMAs
    from this chip's HBM into the ``shift``-right neighbour's output
    buffer via ``make_async_remote_copy`` (ANY memory space: no VMEM
    round-trip, the DMA engine streams HBM→ICI→HBM)."""
    import jax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kernel(input_ref, output_ref, send_sem, recv_sem):
        my_id = jax.lax.axis_index(axis)
        dst = jax.lax.rem(my_id + shift, n)
        rdma = pltpu.make_async_remote_copy(
            src_ref=input_ref,
            dst_ref=output_ref,
            send_sem=send_sem,
            recv_sem=recv_sem,
            device_id=(dst,),
            device_id_type=pltpu.DeviceIdType.MESH,
        )
        rdma.start()
        rdma.wait()

    # Version-portable compiler params: the class was renamed
    # TPUCompilerParams → CompilerParams across pallas releases
    params_cls = (getattr(pltpu, "CompilerParams", None)
                  or getattr(pltpu, "TPUCompilerParams", None))
    kwargs = {}
    if params_cls is not None:
        kwargs["compiler_params"] = params_cls(has_side_effects=True,
                                               collective_id=0)
    any_space = getattr(pltpu, "ANY", None) or pl.ANY
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=0,
        in_specs=[pl.BlockSpec(memory_space=any_space)],
        out_specs=pl.BlockSpec(memory_space=any_space),
        scratch_shapes=[pltpu.SemaphoreType.DMA] * 2,
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(shard.shape, shard.dtype),
        grid_spec=grid_spec,
        **kwargs,
    )(shard)


def permute_body(mesh, axis: str, shift: int):
    """The per-shard body DevicePlane compiles for ``ring_permute``:
    rank r's shard lands on rank (r + shift) % n. Pallas remote-copy on
    TPU meshes (knob-gated), ``lax.ppermute`` everywhere else — the
    SAME contract, so tests on the CPU backend pin the numerics the
    kernel must reproduce."""
    import jax

    n = int(mesh.devices.size)
    shift = int(shift) % n
    if pallas_ring_enabled() and mesh_on_tpu(mesh):
        return functools.partial(_pallas_permute_call, axis=axis,
                                 shift=shift, n=n)
    perm = [(i, (i + shift) % n) for i in range(n)]

    def f(shard):  # (1, m) → (1, m): the left neighbour's payload
        return jax.lax.ppermute(shard, axis, perm)

    return f


def ring_backend(mesh) -> str:
    """Which implementation ``permute_body`` selects for this mesh —
    observability for summaries and the selftest report."""
    if pallas_ring_enabled() and mesh_on_tpu(mesh):
        return "pallas"
    return "xla"


# ---------------------------------------------------------------------------
# Schedule-runner execution target
# ---------------------------------------------------------------------------
class DeviceRingTarget:
    """Executes an annotated permute phase on the device plane.

    ``try_run`` returns the number of leading steps it executed, or
    None to decline (the runner then executes the phase's host steps
    unchanged). The verdict must be world-symmetric or ranks desync:
    every input it consults — the spec annotation, the step structure,
    the payload dtype/size, the plane's activation — is identical on
    every rank for a verified permute schedule (the plane's activation
    verdict is world-agreed by the registration handshake, and a
    mid-phase plane disable surfaces symmetrically in every process,
    after which ALL ranks resume the remaining pairs on the host path).
    """

    name = "device-ring"

    def try_run(self, world, rank: int, sched, phase: str, steps,
                env: dict, resolver):
        if not pallas_ring_enabled():
            return None
        if not sched.spec.get("ring_uniform"):
            return None
        plane = world.device_plane()
        if plane is None or plane.n != world.size:
            return None
        pairs = self._parse_pairs(steps, rank, world.size)
        if not pairs:
            return None
        # Single-key legs only (a multi-key leg would need host
        # concatenation — decline and let the host steps run), and
        # eligibility from the FIRST pair's payload dtype: later pairs'
        # send keys are filled by earlier recvs DURING execution, and
        # the ring_uniform contract makes their dtype/size identical
        if any(len(s.keys) != 1 or len(r.keys) != 1
               for s, r, _ in pairs):
            return None
        first = env.get(pairs[0][0].keys[0])
        if first is None or not plane.eligible("ring_permute", first,
                                               None):
            return None

        from faabric_tpu.device_plane.registry import DevicePlaneFallback

        done = 0
        for send_st, recv_st, shift in pairs:
            payload = env[send_st.keys[0]]
            if not isinstance(payload, np.ndarray) \
                    and not hasattr(payload, "sharding"):
                payload = np.asarray(payload)
            try:
                out = plane.ring_permute(rank, payload.reshape(-1),
                                         shift)
            except DevicePlaneFallback:
                # Symmetric mid-phase disable: every rank's pair k
                # fails together; the runner finishes steps[done:] on
                # the host path
                logger.warning(
                    "device-ring target fell back to host steps at "
                    "pair %d/%d (world %s)", done // 2, len(pairs),
                    world.id)
                return done if done else None
            env[recv_st.keys[0]] = out.reshape(-1)
            done += 2
        return done

    @staticmethod
    def _parse_pairs(steps, rank: int, n: int):
        """Decompose a phase group into (send, recv, shift) permute
        pairs; [] when the structure is not a pure uniform-shift ring
        (any FOLD/COPY, odd step count, inconsistent neighbours)."""
        from faabric_tpu.mpi.schedule import RECV, SEND

        if len(steps) < 2 or len(steps) % 2:
            return []
        pairs = []
        for i in range(0, len(steps), 2):
            s, r = steps[i], steps[i + 1]
            if s.op != SEND or r.op != RECV:
                return []
            shift = (s.peer - rank) % n
            if shift == 0 or (rank - r.peer) % n != shift:
                return []
            pairs.append((s, r, shift))
        return pairs


def ensure_registered() -> None:
    """Idempotently register the target (module import does this; the
    schedule runner's lazy lookup calls it as a fallback)."""
    from faabric_tpu.mpi.schedule import get_registered_target, \
        register_step_target

    if get_registered_target(DeviceRingTarget.name) is None:
        register_step_target(DeviceRingTarget())


# Import-time registration: the device_plane package __init__ imports
# this module, so touching the plane at all arms the target; the
# schedule runner's get_step_target lazily imports it as the fallback.
try:
    ensure_registered()
except Exception:  # noqa: BLE001 — registration is an optimization
    logger.exception("device-ring target registration failed")


# ---------------------------------------------------------------------------
# Selftest (CI hook: slow-marked test + manual TPU validation)
# ---------------------------------------------------------------------------
def selftest(verbose: bool = True) -> dict:
    """Validate the ring-permute contract on the granted backend.

    Always: compile ``permute_body`` over the local mesh and check the
    permute numerics for several shifts/dtypes. On TPU that IS the
    Pallas ``make_async_remote_copy`` kernel; elsewhere the XLA
    fallback runs and the report says so explicitly (fast, clean — no
    tunnel dial, no hang)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from faabric_tpu.parallel.collectives import shard_map_compat

    devs = jax.local_devices()
    n = min(4, len(devs))
    report = {
        "platform": devs[0].platform if devs else "none",
        "n_devices": n,
        "backend": None,
        "checked": 0,
        "tpu_kernel": False,
    }
    if n < 2:
        report["backend"] = "skipped"
        if verbose:
            print("pallas_ring selftest: SKIP — fewer than 2 devices "
                  f"granted (platform={report['platform']})")
        return report
    mesh = Mesh(np.array(devs[:n]), ("ranks",))
    report["backend"] = ring_backend(mesh)
    report["tpu_kernel"] = report["backend"] == "pallas"
    sharding = NamedSharding(mesh, P("ranks", None))
    for dtype in (np.int32, np.float32):
        for shift in (1, n - 1):
            shards = [jax.device_put(
                np.full((1, 128), r + 1, dtype), devs[r])
                for r in range(n)]
            x = jax.make_array_from_single_device_arrays(
                (n, 128), sharding, shards)
            body = permute_body(mesh, "ranks", shift)
            fn = jax.jit(shard_map_compat(
                body, mesh=mesh, in_specs=P("ranks", None),
                out_specs=P("ranks", None)))
            y = np.asarray(fn(x))
            for r in range(n):
                src = (r - shift) % n
                expect = np.full(128, src + 1, dtype)
                if not np.array_equal(y[r], expect):
                    raise AssertionError(
                        f"ring_permute shift={shift} dtype={dtype}: "
                        f"rank {r} got {y[r][:4]}, want {expect[:4]}")
            report["checked"] += 1
    if verbose:
        tag = ("Pallas make_async_remote_copy kernel" if
               report["tpu_kernel"] else
               "XLA ppermute fallback (no TPU granted — the Pallas "
               "kernel is untested on this backend)")
        print(f"pallas_ring selftest: OK — {report['checked']} "
              f"permutes verified via {tag} on "
              f"{report['platform']}x{n}")
    return report


def _main(argv) -> int:
    if "--selftest" not in argv:
        print(__doc__)
        return 2
    # The selftest must be runnable standalone: pin the CPU backend
    # unless the caller explicitly granted something else — the image's
    # sitecustomize would otherwise dial the (minutes-slow,
    # single-claimant) TPU tunnel on import
    if "JAX_PLATFORMS" not in os.environ:
        os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    try:
        report = selftest(verbose=True)
    except Exception as e:  # noqa: BLE001 — CLI surface
        print(f"pallas_ring selftest: FAILED — {e!r}")
        return 1
    return 0 if report["backend"] is not None else 1


if __name__ == "__main__":
    import sys

    sys.exit(_main(sys.argv[1:]))
