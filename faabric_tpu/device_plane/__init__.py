"""Device-native collective plane (ISSUE 10 / ROADMAP item 3).

The fourth transport rung of the MPI dispatch ladder (shm → tcp →
device): worlds whose ranks all resolve onto devices of one JAX mesh
run allreduce / allgather / reduce_scatter as compiled donated-buffer
XLA programs over that mesh — ICI on TPU, the gloo CPU collectives
layer in this container — instead of bouncing device-resident data
through the host planes.

- :mod:`registry` — the registration handshake: per-rank device
  registration, the one-shot allgather exchange, and the deterministic
  mesh-resolution verdict (``MeshMismatch`` → host ladder).
- :mod:`plane` — :class:`DevicePlane`: the per-world rendezvous
  executor, the (kind, op, elems, dtype, resident)-keyed
  compiled-executable cache, the residency-aware zero-host-copy path
  for committed ``jax.Array`` deposits (ISSUE 15), the
  eligibility/fallback ladder, ``ring_permute``, and the
  ``plane=device`` comm-matrix + ``phase=compile|execute`` telemetry.
- :mod:`copies` — host↔device copy accounting
  (``faabric_device_copy_*``): the auditable surface behind the
  "zero host copies for a device-resident collective" invariant.
- :mod:`pallas_ring` — the ring-permute p2p primitive: a Pallas
  ``make_async_remote_copy`` kernel on TPU, ``lax.ppermute``
  elsewhere, plus the ``device-ring`` schedule-runner execution
  target.

Entry point: ``MpiWorld.activate_device_plane(rank, ...)`` — a
collective call every rank makes once after the world forms (and after
any migration remap); see docs/data_plane.md.
"""

import faabric_tpu.device_plane.pallas_ring  # noqa: F401 — registers
# the device-ring schedule execution target at package import
from faabric_tpu.device_plane.copies import (
    count_copy,
    device_copy_totals,
    reset_device_copy_totals,
)
from faabric_tpu.device_plane.plane import (
    DEVICE_PLANE_TIMEOUT_S,
    DevicePlane,
    device_planes_summary,
    is_device_payload,
)
from faabric_tpu.device_plane.registry import (
    DevicePlaneFallback,
    MeshMismatch,
    registration_row,
    resolve_local_device,
    resolve_mesh,
)

__all__ = [
    "DEVICE_PLANE_TIMEOUT_S",
    "DevicePlane",
    "DevicePlaneFallback",
    "MeshMismatch",
    "count_copy",
    "device_copy_totals",
    "device_planes_summary",
    "is_device_payload",
    "registration_row",
    "reset_device_copy_totals",
    "resolve_local_device",
    "resolve_mesh",
]
