"""Device-native collective plane (ISSUE 10 / ROADMAP item 3).

The fourth transport rung of the MPI dispatch ladder (shm → tcp →
device): worlds whose ranks all resolve onto devices of one JAX mesh
run allreduce / allgather / reduce_scatter as compiled donated-buffer
XLA programs over that mesh — ICI on TPU, the gloo CPU collectives
layer in this container — instead of bouncing device-resident data
through the host planes.

- :mod:`registry` — the registration handshake: per-rank device
  registration, the one-shot allgather exchange, and the deterministic
  mesh-resolution verdict (``MeshMismatch`` → host ladder).
- :mod:`plane` — :class:`DevicePlane`: the per-world rendezvous
  executor, the (kind, op, elems, dtype)-keyed compiled-executable
  cache with input donation, the eligibility/fallback ladder, and the
  ``plane=device`` comm-matrix + ``phase=compile|execute`` telemetry.

Entry point: ``MpiWorld.activate_device_plane(rank, ...)`` — a
collective call every rank makes once after the world forms (and after
any migration remap); see docs/data_plane.md.
"""

from faabric_tpu.device_plane.plane import (
    DEVICE_PLANE_TIMEOUT_S,
    DevicePlane,
)
from faabric_tpu.device_plane.registry import (
    DevicePlaneFallback,
    MeshMismatch,
    registration_row,
    resolve_local_device,
    resolve_mesh,
)

__all__ = [
    "DEVICE_PLANE_TIMEOUT_S",
    "DevicePlane",
    "DevicePlaneFallback",
    "MeshMismatch",
    "registration_row",
    "resolve_local_device",
    "resolve_mesh",
]
