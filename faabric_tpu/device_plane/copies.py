"""Host↔device copy accounting (ISSUE 15).

"Zero host copies for a device-resident collective" is the tentpole
invariant of the device-resident array plane — and an invariant nobody
counts is a claim, not a property. Every byte the device plane (and the
HBM state/snapshot tier riding on it) moves across the host↔device
boundary is stamped here, in BOTH directions, tagged with why it moved:

- ``h2d`` / ``input``      — a host contribution placed onto its chip
  before a compiled collective (the PR 10 path; the cost the
  device-resident path exists to delete);
- ``d2h`` / ``readback``   — a collective result pulled back to a host
  buffer (ditto);
- ``d2h`` / ``staging``    — the *explicit* fallback copy: a
  device-resident payload that could not ride the device rung
  (ineligible op/dtype, inactive plane, mixed-residency round) staged
  to host exactly once before the host ladder runs;
- ``h2d`` / ``state``, ``d2h`` / ``state`` — HBM state-handle
  materialization (state/device_handle.py);
- ``d2h`` / ``snapshot``, ``h2d`` / ``snapshot`` — device-snapshot page
  flags/diffs/restores (snapshot/device_snapshot.py).

Two surfaces: the global metrics registry
(``faabric_device_copy_total`` / ``faabric_device_copy_bytes_total``
with ``direction``+``reason`` labels, so ``/metrics`` exports them) and
an always-on process-local totals table read by
``DevicePlane.summary()``, bench sections and the zero-copy assertions
— counting must not vanish when ``FAABRIC_METRICS=0`` flips the
registry handles to no-ops, or the invariant becomes untestable in
metrics-off runs.
"""

from __future__ import annotations

import threading

from faabric_tpu.telemetry import get_metrics

H2D = "h2d"
D2H = "d2h"

_metrics = get_metrics()

# (direction, reason) → (count handle, bytes handle); created lazily so
# only reasons that actually fire appear in the exposition
_handles: dict = {}
_handles_lock = threading.Lock()

# Always-on local totals: (direction, reason) → [count, bytes]
_totals: dict = {}
_totals_lock = threading.Lock()


def count_copy(direction: str, nbytes: int, reason: str) -> None:
    """Stamp one host↔device transfer of ``nbytes`` bytes."""
    key = (direction, reason)
    pair = _handles.get(key)
    if pair is None:
        with _handles_lock:
            pair = _handles.get(key)
            if pair is None:
                pair = (
                    _metrics.counter(
                        "faabric_device_copy_total",
                        "Host<->device transfers performed by the device "
                        "plane / HBM state tier",
                        direction=direction, reason=reason),
                    _metrics.counter(
                        "faabric_device_copy_bytes_total",
                        "Bytes moved across the host<->device boundary "
                        "by the device plane / HBM state tier",
                        direction=direction, reason=reason),
                )
                _handles[key] = pair
    pair[0].inc()
    pair[1].inc(int(nbytes))
    with _totals_lock:
        t = _totals.get(key)
        if t is None:
            t = _totals[key] = [0, 0]
        t[0] += 1
        t[1] += int(nbytes)


def device_copy_totals() -> dict:
    """Process-wide snapshot: per-(direction, reason) counts/bytes plus
    roll-ups — what ``DevicePlane.summary()``, bench sections and the
    zero-copy tests read."""
    with _totals_lock:
        rows = {f"{d}.{r}": {"count": t[0], "bytes": t[1]}
                for (d, r), t in _totals.items()}
        count = sum(t[0] for t in _totals.values())
        nbytes = sum(t[1] for t in _totals.values())
    return {"count": count, "bytes": nbytes, "by_reason": rows}


def reset_device_copy_totals() -> None:
    """Test hook: zero the local totals (metrics counters are monotonic
    and stay — tests diff those via snapshots instead)."""
    with _totals_lock:
        _totals.clear()
