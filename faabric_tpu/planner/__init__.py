"""Cluster control plane (reference src/planner)."""

from faabric_tpu.planner.planner import Planner, PlannerHost, get_planner
from faabric_tpu.planner.server import PlannerCalls, PlannerServer
from faabric_tpu.planner.client import (
    PlannerClient,
    clear_mock_planner_calls,
    get_mock_batch_calls,
    get_mock_set_results,
)
from faabric_tpu.planner.journal import (
    NULL_JOURNAL,
    JournalCorrupt,
    PlannerJournal,
    open_planner_journal,
)

__all__ = [
    "JournalCorrupt",
    "NULL_JOURNAL",
    "Planner",
    "PlannerCalls",
    "PlannerClient",
    "PlannerHost",
    "PlannerJournal",
    "PlannerServer",
    "clear_mock_planner_calls",
    "get_mock_batch_calls",
    "get_mock_set_results",
    "get_planner",
    "open_planner_journal",
]
