"""Planner RPC server (ports 8011/8012).

Reference analog: src/planner/PlannerServer.cpp (249 lines), call enum
include/faabric/planner/PlannerApi.h:207-224.
"""

from __future__ import annotations

import enum
import threading

from faabric_tpu.batch_scheduler.decision import SchedulingDecision
from faabric_tpu.planner.planner import get_planner
from faabric_tpu.proto import (
    ber_from_wire,
    messages_from_wire,
    messages_to_wire,
)
from faabric_tpu.transport.common import PLANNER_ASYNC_PORT, PLANNER_SYNC_PORT
from faabric_tpu.transport.message import TransportMessage
from faabric_tpu.transport.server import MessageEndpointServer, handler_response
from faabric_tpu.util.config import get_system_config
from faabric_tpu.util.logging import get_logger
from faabric_tpu.util.periodic import PeriodicBackgroundThread

logger = get_logger(__name__)


class _ExpiryReaper(PeriodicBackgroundThread):
    """Drives host expiry (and therefore failure RECOVERY) on a clock.
    Expiry is otherwise lazy — piggybacked on scheduling and host
    listings — so a dead worker's in-flight messages would only be
    requeued when some client happened to poke the planner."""

    thread_name = "planner/reaper"

    def __init__(self, planner) -> None:
        super().__init__()
        self.planner = planner

    def do_work(self) -> None:
        self.planner.expire_hosts()


class PlannerCalls(enum.IntEnum):
    NO_CALL = 0
    PING = 1
    REGISTER_HOST = 2
    REMOVE_HOST = 3
    GET_AVAILABLE_HOSTS = 4
    SET_MESSAGE_RESULT = 5
    GET_MESSAGE_RESULT = 6
    GET_BATCH_RESULTS = 7
    GET_SCHEDULING_DECISION = 8
    GET_NUM_MIGRATIONS = 9
    CALL_BATCH = 10
    PRELOAD_SCHEDULING_DECISION = 11
    CLAIM_STATE_MASTER = 12
    DROP_STATE_MASTER = 13
    CHECK_MIGRATION = 14
    JOIN_DEVICE_PLANE = 15
    # Degraded-mode drain (ISSUE 4): a worker that buffered results
    # while the planner was down flushes them in one SYNC call after
    # rejoin — unlike the fire-and-forget async result push, the
    # response confirms delivery so the worker can clear its queue
    FLUSH_RESULTS = 16
    # Out-of-band group-abort relay (ISSUE 6): a host that aborts an
    # MPI world but cannot reach some of its peers (network partition —
    # the direct abort broadcast rides the very link that just died)
    # hands the planner the unreachable hosts; the planner's links are
    # independent of the worker-pair link, so the far side learns of
    # the abort in bounded time instead of waiting out a socket timeout
    RELAY_GROUP_ABORT = 17
    # High-QPS submission (ISSUE 8): enqueue an invocation into the
    # ingress admission queue and return IMMEDIATELY with the admission
    # verdict — the scheduling tick batches it; results flow back
    # through the normal result plane. Unlike CALL_BATCH the response
    # does not carry a decision, so thousands of submissions per second
    # never serialize behind scheduling.
    SUBMIT_BATCH = 18


class PlannerServer(MessageEndpointServer):
    """Planner RPC + the planner's SnapshotServer (the reference
    planner_server binary runs both, src/planner/planner_server.cpp:9-43)."""

    def __init__(self, port_offset: int = 0, n_threads: int = 4) -> None:
        super().__init__(
            PLANNER_ASYNC_PORT + port_offset,
            PLANNER_SYNC_PORT + port_offset,
            label="planner-server",
            n_threads=n_threads,
        )
        self.planner = get_planner()

        from faabric_tpu.snapshot.remote import SnapshotServer

        self.snapshot_server = SnapshotServer(
            self.planner.snapshot_registry, host="planner",
            port_offset=port_offset)
        self.expiry_reaper = _ExpiryReaper(self.planner)

    def start(self) -> None:
        from faabric_tpu.telemetry import set_process_label

        set_process_label("planner")
        from faabric_tpu.faults import set_fault_identity

        set_fault_identity("planner")
        # Re-arm the (possibly previously stopped) ingress coordinator
        # before the transport can deliver submissions
        self.planner.ingress.start()
        super().start()
        self.snapshot_server.start()
        # Check at quarter-timeout: worst-case detection latency stays
        # well inside one extra keep-alive period
        timeout = get_system_config().planner_host_timeout
        self.expiry_reaper.start(max(0.5, timeout / 4.0))
        # Time-series ring (ISSUE 14): control-plane gauges sampled
        # continuously so the doctor sees TRENDS (queue growth,
        # capacity exhaustion), not instants. Shared sampler thread —
        # stop() releases this server's share.
        from faabric_tpu.telemetry import get_timeseries, start_sampler

        ring = get_timeseries()
        planner = self.planner
        self._ring_series = {
            "ingress_depth": lambda: planner.ingress.admission.depth(),
            "ingress_shed_total":
                lambda: planner.ingress.admission.shed_total(),
            "free_slots": planner.free_slot_watermark,
            "tick_ms": planner.ingress.last_tick_ms,
            "result_backlog": planner.result_backlog,
            "in_flight_msgs": planner.in_flight_message_count,
            "results_total": planner.results_total,
        }
        for name, fn in self._ring_series.items():
            ring.register(name, fn)
        start_sampler()
        # Balance marker: stop() must release ONLY the sampler share
        # this start() took — an unmatched stop (failed start, double
        # stop) would otherwise drop the refcount under a co-resident
        # runtime and silently halt its sampling
        self._sampling = True
        # Continuous CPU profiler (ISSUE 18): always-on stack sampler
        # feeding GET /profile. Same refcount discipline as above.
        from faabric_tpu.telemetry import start_profiler

        start_profiler()
        self._profiling = True

    def stop(self) -> None:
        from faabric_tpu.telemetry import get_timeseries, stop_sampler

        if getattr(self, "_sampling", False):
            self._sampling = False
            stop_sampler()
        if getattr(self, "_profiling", False):
            self._profiling = False
            from faabric_tpu.telemetry import stop_profiler

            stop_profiler()
        # Unregister what start() registered: leftover closures would
        # pin this planner alive and keep a surviving in-process
        # sampler polling a stopped server's locks. fn-matched, so a
        # co-resident server that re-registered over us keeps its rows.
        ring = get_timeseries()
        for name, fn in getattr(self, "_ring_series", {}).items():
            ring.unregister(name, fn)
        self._ring_series = {}
        self.expiry_reaper.stop()
        self.snapshot_server.stop()
        # Stop the ingress tick thread BEFORE the transport: queued
        # invocations resolve as unschedulable rather than dispatching
        # into a closing server
        self.planner.ingress.stop()
        super().stop()
        # Clean stop: drain the write-behind buffer, fsync, and release
        # the journal fd + drain thread (in-process start/stop cycles
        # must not accumulate either)
        self.planner.close_journal()

    # ------------------------------------------------------------------
    def do_async_recv(self, msg: TransportMessage) -> None:
        if msg.code == int(PlannerCalls.SET_MESSAGE_RESULT):
            # Single ("msg") or a coalesced frame ("msgs", ISSUE 8):
            # workers batch results that complete while a push RPC is
            # already in flight
            dicts = msg.header.get("msgs") or [msg.header["msg"]]
            results = messages_from_wire(dicts, msg.payload)
            self.planner.set_message_results(results)
        elif msg.code == int(PlannerCalls.RELAY_GROUP_ABORT):
            self._relay_group_abort(int(msg.header["group_id"]),
                                    str(msg.header.get("reason", "")),
                                    list(msg.header.get("hosts", [])))
        else:
            logger.warning("Unknown async planner call %d", msg.code)

    @staticmethod
    def _relay_group_abort(group_id: int, reason: str,
                           hosts: list) -> None:
        """Fan the abort out to the hosts the originator could not
        reach, on a thread per relay batch (network I/O must not hold a
        server worker hostage to a slow peer)."""
        from faabric_tpu.telemetry import flight_record

        flight_record("abort_relayed", group=group_id, reason=reason,
                      n_hosts=len(hosts))
        logger.warning("Relaying abort of group %d to %s: %s", group_id,
                       hosts, reason)

        def relay():
            from faabric_tpu.transport.ptp_remote import PointToPointClient

            for host in hosts:
                try:
                    client = PointToPointClient(host)
                    try:
                        client.abort_group(group_id,
                                           f"{reason} (planner relay)")
                    finally:
                        client.close()
                except Exception:  # noqa: BLE001 — a host dead to the
                    # planner too is handled by keep-alive expiry
                    logger.debug("Abort relay of group %d to %s failed",
                                 group_id, host, exc_info=True)

        threading.Thread(target=relay, name=f"planner/abort-relay@{group_id}",
                         daemon=True).start()

    # ------------------------------------------------------------------
    def do_sync_recv(self, msg: TransportMessage) -> TransportMessage:
        code = msg.code
        h = msg.header

        if code == int(PlannerCalls.PING):
            return handler_response(header={"pong": True})

        if code == int(PlannerCalls.REGISTER_HOST):
            # "known" tells a keep-alive caller whether the planner had
            # this host BEFORE the call: False on a keep-alive means the
            # host expired (or the planner restarted) — the worker
            # rejoins with overwrite=True (planner/client.py)
            known = self.planner.is_host_registered(h["host"])
            timeout = self.planner.register_host(
                h["host"], h["slots"], h.get("n_devices", 0),
                overwrite=h.get("overwrite", False))
            return handler_response(header={"host_timeout": timeout,
                                            "known": known,
                                            "boot": self.planner.boot_id})

        if code == int(PlannerCalls.REMOVE_HOST):
            self.planner.remove_host(h["host"])
            return handler_response()

        if code == int(PlannerCalls.GET_AVAILABLE_HOSTS):
            hosts = self.planner.get_available_hosts()
            return handler_response(header={"hosts": [
                {"ip": x.ip, "slots": x.slots, "used_slots": x.used_slots,
                 "n_devices": x.n_devices} for x in hosts]})

        if code == int(PlannerCalls.GET_MESSAGE_RESULT):
            result = self.planner.get_message_result(
                h["app_id"], h["msg_id"], h.get("host", ""))
            if result is None:
                return handler_response(header={"found": False})
            dicts, tail = messages_to_wire([result])
            return handler_response(header={"found": True, "msg": dicts[0]},
                                    payload=tail)

        if code == int(PlannerCalls.GET_BATCH_RESULTS):
            status = self.planner.get_batch_results(h["app_id"])
            dicts, tail = messages_to_wire(status.message_results)
            return handler_response(header={
                "app_id": status.app_id,
                "finished": status.finished,
                "expected_num_messages": status.expected_num_messages,
                "messages": dicts,
            }, payload=tail)

        if code == int(PlannerCalls.GET_SCHEDULING_DECISION):
            decision = self.planner.get_scheduling_decision(h["app_id"])
            if decision is None:
                return handler_response(header={"found": False})
            return handler_response(header={"found": True,
                                            "decision": decision.to_dict()})

        if code == int(PlannerCalls.GET_NUM_MIGRATIONS):
            return handler_response(
                header={"num_migrations": self.planner.get_num_migrations()})

        if code == int(PlannerCalls.JOIN_DEVICE_PLANE):
            spec = self.planner.join_device_plane(h["host"],
                                                  h["n_processes"])
            if spec is None:
                return handler_response(header={"found": False})
            return handler_response(header={"found": True, "spec": spec})

        if code == int(PlannerCalls.CALL_BATCH):
            req = ber_from_wire(msg.header["ber"], msg.payload)
            # Through the ingress (ISSUE 8): a lone call takes the
            # immediate cutover (classic call_batch latency); concurrent
            # callers batch into one scheduling tick. Ineligible
            # requests (MPI/THREADS/migrations/scale) pass straight
            # through. A shed maps to NOT_ENOUGH_SLOTS on this plane —
            # the REST surface is where 429 + Retry-After lives. The
            # queue wait is capped at ~2 ticks: this sync plane has a
            # small worker pool, and a full cluster must keep answering
            # NOT_ENOUGH_SLOTS promptly (pre-ingress semantics) instead
            # of parking server threads that keep-alives need.
            from faabric_tpu.batch_scheduler.decision import (
                not_enough_slots_decision,
            )
            from faabric_tpu.ingress import IngressShedError
            from faabric_tpu.util.config import get_system_config

            wait_s = max(0.05, get_system_config().planner_tick_ms / 250)
            try:
                decision = self.planner.ingress.submit(
                    req, source=h.get("host", ""), timeout=wait_s)
            except IngressShedError:
                decision = not_enough_slots_decision()
            return handler_response(header={"decision": decision.to_dict()})

        if code == int(PlannerCalls.SUBMIT_BATCH):
            from faabric_tpu.ingress import IngressShedError
            from faabric_tpu.proto import bers_from_wire

            reqs = bers_from_wire(h, msg.payload)
            try:
                self.planner.ingress.submit_many(reqs,
                                                 source=h.get("host", ""))
            except IngressShedError as e:
                return handler_response(header={
                    "accepted": False, "retry_after": e.retry_after,
                    "reason": e.reason})
            return handler_response(header={"accepted": True})

        if code == int(PlannerCalls.CHECK_MIGRATION):
            decision = self.planner.check_migration(h["app_id"])
            if decision is None:
                return handler_response(header={"found": False})
            return handler_response(header={"found": True,
                                            "decision": decision.to_dict()})

        if code == int(PlannerCalls.CLAIM_STATE_MASTER):
            master, backup, epoch = self.planner.claim_state_master(
                h["user"], h["key"], h["host"])
            return handler_response(header={"master": master,
                                            "backup": backup,
                                            "epoch": epoch})

        if code == int(PlannerCalls.DROP_STATE_MASTER):
            self.planner.drop_state_master(h["user"], h["key"])
            return handler_response()

        if code == int(PlannerCalls.FLUSH_RESULTS):
            msgs = messages_from_wire(h.get("msgs", []), msg.payload)
            for result in msgs:
                # set_message_result is first-write-wins, so a flush
                # retried after a half-delivered attempt is harmless
                self.planner.set_message_result(result)
            logger.info("Flushed %d buffered result(s) from %s",
                        len(msgs), h.get("host", "?"))
            return handler_response(header={"accepted": len(msgs)})

        if code == int(PlannerCalls.PRELOAD_SCHEDULING_DECISION):
            decision = SchedulingDecision.from_dict(h["decision"])
            self.planner.preload_scheduling_decision(decision)
            return handler_response()

        raise ValueError(f"Unknown sync planner call {code}")
