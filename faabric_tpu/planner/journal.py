"""Planner write-ahead journal: crash safety for the cluster's brain.

The planner (planner.py) is the faithful reproduction of faabric's
centralized controller — and therefore its last hard single point of
failure: host registry, in-flight scheduling decisions, message results
and the state-master directory all live in process memory. This module
makes every durable planner mutation an append to an on-disk journal so
a restarted planner replays itself back to the pre-crash state
(tolerating a torn final record), then reconciles with the hosts that
re-register (planner.py `_reconcile_after_restart`). The design stance
matches PR 2's: control-plane failure is a bounded blip, not an outage.

On-disk layout (``FAABRIC_PLANNER_JOURNAL_DIR``):

- ``planner.journal`` — 16-byte header (``FTPJRNL1`` magic + 8-byte
  random generation id), then length-prefixed records::

      [u32 payload_len][u32 crc32(payload)][payload: JSON]

  Each payload is ``{"k": kind, "ts": wall_seconds, ...fields}``.

  Two durability classes (classic WAL group commit):

  * ``append_durable`` — scheduling-class mutations (``app_update``,
    host membership, state masters, freeze/reset). Encoded and written
    inline in one ``os.write``: the record reaches the kernel before
    the call returns, so a SIGKILL of the planner cannot lose a
    decision it already acted on.
  * ``append`` — the hot path (``result`` records). Buffered and
    drained by a writer thread every fsync interval; the append itself
    is a lock + list push (~0.5 µs), keeping the journal's
    set_message_result overhead well under the 5 % budget. A crash can
    lose at most one drain interval of results — which is safe by
    construction: every result the planner loses is still inside some
    worker's recent-results window (planner/client.py), and the
    rejoin-after-restart path re-delivers it through the confirmed
    FLUSH_RESULTS call.

  A durable append drains the buffer first, so file order always
  matches mutation order. fsync is batched on
  ``FAABRIC_PLANNER_JOURNAL_FSYNC_INTERVAL`` — protection against
  whole-machine (not process) crashes.

- ``planner.snapshot.json`` — periodic compaction target: the full
  planner state plus ``(generation, offset)`` of the journal at
  snapshot time. Replay loads the snapshot, then applies journal
  records from ``offset`` when the generations match (crash between
  the two compaction renames) or from the top of the fresh journal
  when they don't. Compaction itself is crash-safe: snapshot is
  written tmp+fsync+rename first, then the journal is swapped for a
  fresh-generation file the same way.

Torn tail: a crash mid-append leaves a record whose length prefix,
payload, or CRC doesn't check out at EOF. Replay stops at the last
valid record; reopening for append truncates the torn bytes so the
next record starts clean. A CRC mismatch anywhere is treated the same
way — records after a corrupt one are unreachable (lengths chain), so
the honest contract is "replay the longest valid prefix".

With ``FAABRIC_PLANNER_JOURNAL_DIR`` unset, ``open_planner_journal()``
returns the shared ``NULL_JOURNAL`` whose ``enabled`` is False —
call sites gate on that bool, so the disabled hot path is one
attribute load + branch, no allocation.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import time
import zlib
from typing import Any, Optional

from faabric_tpu.telemetry import get_metrics
from faabric_tpu.util.logging import get_logger

logger = get_logger(__name__)

MAGIC = b"FTPJRNL1"
GENERATION_BYTES = 8
HEADER_LEN = len(MAGIC) + GENERATION_BYTES  # 16
_REC_HDR = struct.Struct("<II")  # payload length, crc32(payload)
# A single record larger than this is rejected as corrupt rather than
# attempted: a garbage length prefix must not trigger a giant read
MAX_RECORD_BYTES = 64 << 20

JOURNAL_FILE = "planner.journal"
SNAPSHOT_FILE = "planner.snapshot.json"

_metrics = get_metrics()
_APPENDS = _metrics.counter(
    "faabric_planner_journal_appends_total",
    "Records appended to the planner write-ahead journal")
_APPEND_BYTES = _metrics.counter(
    "faabric_planner_journal_bytes_total",
    "Bytes appended to the planner write-ahead journal")
_FSYNCS = _metrics.counter(
    "faabric_planner_journal_fsyncs_total",
    "Batched fsyncs of the planner journal")
_COMPACTIONS = _metrics.counter(
    "faabric_planner_journal_compactions_total",
    "Snapshot compactions of the planner journal")
_GROUP_COMMITS = _metrics.counter(
    "faabric_planner_journal_group_commits_total",
    "Group-commit records appended (one per scheduling tick that "
    "journaled)")
_GROUP_SUBRECORDS = _metrics.counter(
    "faabric_planner_journal_group_subrecords_total",
    "Scheduling-class records coalesced inside group commits")
_REPLAYED = _metrics.counter(
    "faabric_planner_journal_replayed_records_total",
    "Journal records applied during planner restart replay")
_SIZE = _metrics.gauge(
    "faabric_planner_journal_size_bytes",
    "Current on-disk size of the planner journal file")


class JournalCorrupt(Exception):
    """A structurally invalid journal (bad magic/header) — distinct from
    a torn tail, which replay tolerates silently."""


# ---------------------------------------------------------------------------
# Record codec (module-level so tests and journaldump share it)
# ---------------------------------------------------------------------------
def encode_record(kind: str, fields: dict[str, Any],
                  ts: float | None = None) -> bytes:
    """One wire record: length + CRC header and the JSON payload in a
    single buffer (appended with one ``os.write``)."""
    payload = json.dumps(
        {"k": kind, "ts": time.time() if ts is None else ts, **fields},
        separators=(",", ":"), default=str).encode()
    return _REC_HDR.pack(len(payload), zlib.crc32(payload)) + payload


def decode_records(data: bytes, offset: int = 0
                   ) -> tuple[list[dict[str, Any]], int, bool]:
    """Decode records from ``data[offset:]``.

    Returns ``(records, valid_end, torn)``: the longest valid prefix of
    records, the byte offset just past the last valid record, and
    whether trailing bytes were rejected (short header, short payload,
    CRC mismatch, or undecodable JSON — all treated as a torn tail)."""
    records: list[dict[str, Any]] = []
    pos = offset
    end = len(data)
    while pos < end:
        if end - pos < _REC_HDR.size:
            return records, pos, True
        length, crc = _REC_HDR.unpack_from(data, pos)
        body_start = pos + _REC_HDR.size
        if length > MAX_RECORD_BYTES or body_start + length > end:
            return records, pos, True
        payload = data[body_start:body_start + length]
        if zlib.crc32(payload) != crc:
            return records, pos, True
        try:
            rec = json.loads(payload)
        except ValueError:
            return records, pos, True
        records.append(rec)
        pos = body_start + length
    return records, pos, False


# ---------------------------------------------------------------------------
class NullJournal:
    """Shared no-op stand-in while journaling is disabled. Call sites
    gate on ``enabled`` so the disabled path allocates nothing."""

    __slots__ = ()
    enabled = False
    since_compact = 0
    compact_records = 0

    def append(self, kind: str, fields: dict[str, Any]) -> None:
        pass

    def append_durable(self, kind: str, fields: dict[str, Any]) -> None:
        pass

    def append_group(self, records) -> None:
        pass

    def flush(self) -> None:
        pass

    def compact(self, state: dict[str, Any]) -> None:
        pass

    def replay(self) -> tuple[None, list, dict]:
        return None, [], {"enabled": False}

    def stats(self) -> dict[str, Any]:
        return {"enabled": False}

    def close(self) -> None:
        pass


NULL_JOURNAL = NullJournal()


class PlannerJournal:
    """Append-only, fsync-batched journal over one directory.

    Thread-safe; the planner calls ``append`` under its own lock so the
    journal order IS the state-mutation order, but the internal lock
    keeps the file consistent for out-of-band callers (healthz stats,
    tests)."""

    enabled = True
    DRAIN_BACKPRESSURE = 1024

    def __init__(self, directory: str, fsync_interval: float = 0.05,
                 compact_records: int = 20000) -> None:
        self.directory = directory
        self.fsync_interval = max(0.0, fsync_interval)
        self.compact_records = max(1, compact_records)
        self._lock = threading.RLock()
        os.makedirs(directory, exist_ok=True)
        self._journal_path = os.path.join(directory, JOURNAL_FILE)
        self._snapshot_path = os.path.join(directory, SNAPSHOT_FILE)

        self._fd = os.open(self._journal_path,
                           os.O_RDWR | os.O_CREAT, 0o644)
        data = self._read_all()
        if not data:
            self._generation = os.urandom(GENERATION_BYTES)
            os.write(self._fd, MAGIC + self._generation)
            self._size = HEADER_LEN
            self.records = 0
        else:
            if len(data) < HEADER_LEN or data[:len(MAGIC)] != MAGIC:
                raise JournalCorrupt(
                    f"{self._journal_path}: bad magic/header")
            self._generation = data[len(MAGIC):HEADER_LEN]
            recs, valid_end, torn = decode_records(data, HEADER_LEN)
            if torn:
                logger.warning(
                    "Journal %s has a torn tail: truncating %d byte(s) "
                    "after %d valid record(s)", self._journal_path,
                    len(data) - valid_end, len(recs))
                os.ftruncate(self._fd, valid_end)
            os.lseek(self._fd, valid_end, os.SEEK_SET)
            self._size = valid_end
            self.records = len(recs)
        self.since_compact = self.records
        self.compactions = 0
        self._dirty = False
        self._last_fsync = time.monotonic()
        self._last_append = 0.0
        # Write-behind buffer for hot-path (result) records: (kind,
        # fields, ts) tuples encoded and written by the drain thread.
        # Callers hand over fields dicts they never mutate afterwards.
        self._buffer: list[tuple[str, dict, float]] = []
        self._drain_wake = threading.Event()
        self._drain_stop = False
        self._drain_thread: threading.Thread | None = None
        _SIZE.set(self._size)

    # ------------------------------------------------------------------
    def _read_all(self) -> bytes:
        os.lseek(self._fd, 0, os.SEEK_SET)
        chunks = []
        while True:
            chunk = os.read(self._fd, 1 << 20)
            if not chunk:
                return b"".join(chunks)
            chunks.append(chunk)

    @property
    def generation(self) -> str:
        return self._generation.hex()

    @property
    def size_bytes(self) -> int:
        return self._size

    # ------------------------------------------------------------------
    def append(self, kind: str, fields) -> None:
        """Hot-path append: push onto the write-behind buffer (a lock +
        list push) and let the drain thread encode + write within one
        fsync interval. ``fields`` is a dict — or a zero-arg callable
        returning one, evaluated at drain time so even the dict build
        (e.g. ``Message.to_dict``) stays off the hot path; either way
        the underlying data must not mutate after hand-over. Loss
        window on SIGKILL: one drain interval — acceptable ONLY for
        records something upstream re-delivers (results: the workers'
        recent-window flush); everything else goes through
        ``append_durable``."""
        with self._lock:
            self._buffer.append((kind, fields, time.time()))
            self.records += 1
            self.since_compact += 1
            if self._drain_thread is None or not self._drain_thread.is_alive():
                self._start_drain_thread_locked()
            backpressure = len(self._buffer) >= self.DRAIN_BACKPRESSURE
        if backpressure:
            # Normally the drain's interval timer does the work — waking
            # it per append would context-switch every result and
            # serialize the "batched" writes. Only a large backlog
            # forces an early drain.
            self._drain_wake.set()
        _APPENDS.inc()

    def append_durable(self, kind: str, fields: dict[str, Any]) -> None:
        """Scheduling-class append: encoded and written inline — the
        record reaches the kernel (survives a process kill) before this
        returns. Drains the buffer first so file order is mutation
        order. fsync stays batched."""
        buf = encode_record(kind, fields)
        with self._lock:
            self._drain_buffer_locked()
            self._write_locked(buf)
            self.records += 1
            self.since_compact += 1
        _APPENDS.inc()
        _APPEND_BYTES.inc(len(buf))
        _SIZE.set(self._size)

    def append_group(self, records: list[tuple[str, dict]]) -> None:
        """Group commit (ISSUE 8): coalesce one scheduling tick's worth
        of scheduling-class records into ONE journal record —

            {"k": "group", "n": N, "recs": [{"k": kind, ...}, ...]}

        — written with a single ``os.write`` inside one fsync boundary.
        The record-level CRC makes the group atomic on replay: a torn
        group tail drops the WHOLE tick (no partial application), which
        is safe because every sub-record describes state the planner
        only acts on after this call returns. Durability class matches
        ``append_durable`` (in the kernel before the planner dispatches;
        a machine crash can lose at most one fsync interval)."""
        if not records:
            return
        ts = time.time()
        subs = [{"k": kind, "ts": ts, **fields} for kind, fields in records]
        buf = encode_record("group", {"n": len(subs), "recs": subs}, ts=ts)
        with self._lock:
            self._drain_buffer_locked()
            self._write_locked(buf)
            self.records += 1
            # Compaction pressure tracks the coalesced content, not the
            # on-disk record count — a group of 500 app_updates is 500
            # records' worth of replay work
            self.since_compact += len(subs)
        _APPENDS.inc()
        _GROUP_COMMITS.inc()
        _GROUP_SUBRECORDS.inc(len(subs))
        _APPEND_BYTES.inc(len(buf))
        _SIZE.set(self._size)

    def _write_locked(self, buf: bytes) -> None:
        if self._fd < 0:
            # Closed (clean shutdown) — a late append must not blow up
            # the caller; the record is dropped with a trace
            logger.warning("Journal %s is closed; dropping %d byte(s)",
                           self._journal_path, len(buf))
            return
        os.write(self._fd, buf)
        self._size += len(buf)
        self._dirty = True
        self._last_append = time.monotonic()
        if self._last_append - self._last_fsync >= self.fsync_interval:
            self._fsync_locked()

    def _drain_buffer_locked(self) -> None:
        if not self._buffer:
            return
        batch, self._buffer = self._buffer, []
        parts = []
        for kind, fields, ts in batch:
            try:
                parts.append(encode_record(
                    kind, fields() if callable(fields) else fields, ts=ts))
            except Exception:  # noqa: BLE001 — one unencodable record
                # must not sink the whole batch
                logger.exception("Dropping unencodable journal record %r",
                                 kind)
        buf = b"".join(parts)
        if not buf:
            return
        try:
            self._write_locked(buf)
        except OSError:
            # Transient write failure (ENOSPC, EIO): put the batch back
            # so nothing is lost — the next drain/durable append retries
            self._buffer[:0] = batch
            raise
        _APPEND_BYTES.inc(len(buf))
        _SIZE.set(self._size)

    def _start_drain_thread_locked(self) -> None:
        self._drain_stop = False
        t = threading.Thread(target=self._drain_loop,
                             name="planner/journal-drain", daemon=True)
        self._drain_thread = t
        t.start()

    def _drain_loop(self) -> None:
        interval = max(0.005, self.fsync_interval or 0.05)
        while True:
            self._drain_wake.wait(interval)
            self._drain_wake.clear()
            try:
                with self._lock:
                    if self._fd < 0:
                        return
                    self._drain_buffer_locked()
                    if self._dirty and (time.monotonic() - self._last_fsync
                                        >= self.fsync_interval):
                        self._fsync_locked()
                    if self._drain_stop and not self._buffer:
                        return
            except Exception:  # noqa: BLE001 — the drain thread must
                # outlive transient fs errors; the failed batch was
                # re-queued and retries next interval
                logger.exception("Journal drain failed; retrying")

    def _fsync_locked(self) -> None:
        try:
            os.fsync(self._fd)
        except OSError:  # pragma: no cover — e.g. fs without fsync
            pass
        self._dirty = False
        self._last_fsync = time.monotonic()
        _FSYNCS.inc()

    def flush(self) -> None:
        with self._lock:
            self._drain_buffer_locked()
            if self._dirty:
                self._fsync_locked()

    # ------------------------------------------------------------------
    def compact(self, state: dict[str, Any]) -> None:
        """Fold the journal into a snapshot of ``state``.

        Crash-safe ordering: (1) snapshot written tmp+fsync+rename,
        stamped with the CURRENT journal (generation, offset) — a crash
        here replays snapshot + the same journal tail, idempotently;
        (2) a fresh-generation journal replaces the old one the same
        way — after which the stale snapshot offset no longer matches
        and replay starts from the fresh journal's top."""
        with self._lock:
            self._drain_buffer_locked()
            self._fsync_locked()
            body = {
                "version": 1,
                "ts": time.time(),
                "journal_generation": self.generation,
                "journal_offset": self._size,
                "records_folded": self.records,
                "state": state,
            }
            tmp = self._snapshot_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(body, f, default=str)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._snapshot_path)

            new_gen = os.urandom(GENERATION_BYTES)
            jtmp = self._journal_path + ".tmp"
            nfd = os.open(jtmp, os.O_RDWR | os.O_CREAT | os.O_TRUNC, 0o644)
            os.write(nfd, MAGIC + new_gen)
            os.fsync(nfd)
            os.replace(jtmp, self._journal_path)
            os.close(self._fd)
            self._fd = nfd
            self._generation = new_gen
            self._size = HEADER_LEN
            self.records = 0
            self.since_compact = 0
            self.compactions += 1
            self._dirty = False
            self._last_fsync = time.monotonic()
        _COMPACTIONS.inc()
        _SIZE.set(self._size)
        logger.info("Journal compacted into %s (%d records folded)",
                    self._snapshot_path, body["records_folded"])

    # ------------------------------------------------------------------
    def replay(self) -> tuple[Optional[dict], list[dict], dict]:
        """Load ``(snapshot_state, records, meta)`` from disk.

        ``snapshot_state`` is the compacted state dict (or None),
        ``records`` the valid journal records to apply after it, and
        ``meta`` describes what happened (counts, torn tail, skipped
        offset) for healthz / flight records."""
        with self._lock:
            self.flush()
            snapshot, records, meta = load_journal_dir(self.directory)
        meta["records"] = len(records)
        _REPLAYED.inc(len(records))
        return snapshot, records, meta

    # ------------------------------------------------------------------
    def stats(self) -> dict[str, Any]:
        with self._lock:
            now = time.monotonic()
            return {
                "enabled": True,
                "directory": self.directory,
                "sizeBytes": self._size,
                "records": self.records,
                "bufferedRecords": len(self._buffer),
                "sinceCompactRecords": self.since_compact,
                "compactions": self.compactions,
                "generation": self.generation,
                "dirty": self._dirty,
                "lastFsyncAgeSeconds": round(now - self._last_fsync, 3),
                "fsyncIntervalSeconds": self.fsync_interval,
                "compactThresholdRecords": self.compact_records,
            }

    def close(self) -> None:
        with self._lock:
            if self._fd < 0:
                return
            self._drain_stop = True
            self._drain_buffer_locked()
            if self._dirty:
                self._fsync_locked()
            os.close(self._fd)
            self._fd = -1
        self._drain_wake.set()  # unblock the drain thread so it exits


# ---------------------------------------------------------------------------
def load_journal_dir(directory: str
                     ) -> tuple[Optional[dict], list[dict], dict]:
    """Read a journal directory without opening it for append (shared by
    ``PlannerJournal.replay`` and the journaldump CLI).

    Returns ``(snapshot_state, records, meta)``."""
    snapshot_path = os.path.join(directory, SNAPSHOT_FILE)
    journal_path = os.path.join(directory, JOURNAL_FILE)

    snapshot_state = None
    snap_gen, snap_offset = "", HEADER_LEN
    meta: dict[str, Any] = {"snapshot": False, "torn": False,
                            "skipped_bytes": 0}
    try:
        with open(snapshot_path) as f:
            snap = json.load(f)
        snapshot_state = snap.get("state") or {}
        snap_gen = snap.get("journal_generation", "")
        snap_offset = int(snap.get("journal_offset", HEADER_LEN))
        meta["snapshot"] = True
        meta["snapshot_ts"] = snap.get("ts")
        meta["records_folded"] = snap.get("records_folded", 0)
    except FileNotFoundError:
        pass
    except (OSError, ValueError) as e:
        # A corrupt snapshot plus an intact journal cannot be safely
        # combined (the journal tail assumes the snapshot's state) —
        # surface loudly, recover nothing from the snapshot
        logger.error("Journal snapshot %s unreadable: %s", snapshot_path, e)
        meta["snapshot_error"] = str(e)

    records: list[dict[str, Any]] = []
    try:
        with open(journal_path, "rb") as f:
            data = f.read()
    except FileNotFoundError:
        return snapshot_state, records, meta
    if not data:
        return snapshot_state, records, meta
    if len(data) < HEADER_LEN or data[:len(MAGIC)] != MAGIC:
        raise JournalCorrupt(f"{journal_path}: bad magic/header")
    generation = data[len(MAGIC):HEADER_LEN].hex()
    start = HEADER_LEN
    if snapshot_state is not None and snap_gen == generation:
        # Crash between the two compaction renames: the snapshot already
        # folds the journal up to its recorded offset
        start = min(max(snap_offset, HEADER_LEN), len(data))
        meta["skipped_bytes"] = start - HEADER_LEN
    records, valid_end, torn = decode_records(data, start)
    meta["torn"] = torn
    meta["torn_bytes"] = len(data) - valid_end if torn else 0
    meta["generation"] = generation
    return snapshot_state, records, meta


def open_planner_journal(directory: str | None = None
                         ) -> PlannerJournal | NullJournal:
    """The planner's journal per config: a real journal when
    ``FAABRIC_PLANNER_JOURNAL_DIR`` (or ``directory``) names a path,
    otherwise the shared no-op."""
    from faabric_tpu.util.config import get_system_config

    conf = get_system_config()
    d = directory if directory is not None else conf.planner_journal_dir
    if not d:
        return NULL_JOURNAL
    return PlannerJournal(
        d, fsync_interval=conf.planner_journal_fsync_interval,
        compact_records=conf.planner_journal_compact_records)
